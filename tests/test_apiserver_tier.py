"""The real-apiserver verification tier (SURVEY.md §4, BASELINE config 2).

The reference proves its engine against a real kube-apiserver (envtest,
upgrade_suit_test.go:77-82).  Here the equivalent boundary is
``k8s.apiserver.KubeApiServer``: every call crosses a real HTTP socket,
gets serialized to Kubernetes wire JSON, parsed back, and executed with
apiserver semantics.  Two layers of proof:

- a **conformance suite** parametrized over FakeCluster and
  RestClient-over-apiserver: both must exhibit identical verb semantics
  (a FakeCluster behavior the wire tier can't reproduce is a bug in one
  of them);
- the **full e2e rolling upgrade driven through RestClient** — the
  engine, drain helper and probers run unchanged over HTTP.
"""

from __future__ import annotations

import pytest

from k8s_operator_libs_tpu.api import DrainSpec, TPUUpgradePolicySpec
from k8s_operator_libs_tpu.k8s import (
    FakeCluster,
    KubeApiServer,
    KubeConfig,
    NotFoundError,
    RestClient,
)
from k8s_operator_libs_tpu.k8s.client import (
    ConflictError,
    EvictionBlockedError,
)
from k8s_operator_libs_tpu.upgrade import (
    ClusterUpgradeStateManager,
    UpgradeKeys,
    UpgradeState,
)
from tests.fixtures import DRIVER_LABELS, NAMESPACE, ClusterFixture
from tests.test_upgrade_state import FakeProber

KEYS = UpgradeKeys()


@pytest.fixture(params=["fake", "rest"])
def tier(request):
    """(client, store): same FakeCluster semantics, optionally reached
    through the full HTTP round trip."""
    store = FakeCluster()
    if request.param == "fake":
        yield store, store
        return
    server = KubeApiServer(store)
    server.start()
    client = RestClient(KubeConfig(host=server.host), timeout_s=10.0)
    try:
        yield client, store
    finally:
        server.stop()


# --- conformance: node verbs -------------------------------------------------


def test_node_get_list_and_patches(tier):
    client, store = tier
    fx = ClusterFixture(store, KEYS)
    fx.node("n1", labels={"pool": "a"})
    fx.node("n2", labels={"pool": "b"})

    node = client.get_node("n1", cached=False)
    assert node.name == "n1" and node.labels["pool"] == "a"
    assert {n.name for n in client.list_nodes()} == {"n1", "n2"}
    assert [n.name for n in client.list_nodes(label_selector="pool=a")] == [
        "n1"
    ]

    client.patch_node_labels("n1", {"x": "1", "pool": None})
    labels = client.get_node("n1", cached=False).labels
    assert labels.get("x") == "1" and "pool" not in labels

    client.patch_node_annotations("n1", {"note": "hi"})
    assert client.get_node("n1", cached=False).annotations["note"] == "hi"
    client.patch_node_annotations("n1", {"note": None})
    assert "note" not in client.get_node("n1", cached=False).annotations

    client.set_node_unschedulable("n1", True)
    assert client.get_node("n1", cached=False).spec.unschedulable
    client.set_node_unschedulable("n1", False)
    assert not client.get_node("n1", cached=False).spec.unschedulable

    with pytest.raises(NotFoundError):
        client.get_node("missing", cached=False)
    with pytest.raises(NotFoundError):
        client.patch_node_labels("missing", {"a": "b"})


# --- conformance: pod verbs --------------------------------------------------


def test_pod_list_delete_evict(tier):
    client, store = tier
    fx = ClusterFixture(store, KEYS)
    n1 = fx.node("n1")
    n2 = fx.node("n2")
    ds = fx.daemon_set(hash_suffix="h1", revision=1)
    driver = fx.driver_pod(n1, ds, hash_suffix="h1")
    wl = fx.workload_pod(n1, labels={"app": "train"})
    fx.workload_pod(n2, labels={"app": "train"})

    pods = client.list_pods(node_name="n1")
    assert {p.name for p in pods} == {driver.name, wl.name}
    # Owner references survive the wire (the engine's DS-ownership match).
    got_driver = client.get_pod(NAMESPACE, driver.name)
    assert got_driver.metadata.owner_references[0].uid == ds.metadata.uid
    assert (
        got_driver.labels["controller-revision-hash"] == "h1"
    )

    by_label = client.list_pods(label_selector="app=train")
    assert len(by_label) == 2

    client.delete_pod("default", wl.name)
    with pytest.raises(NotFoundError):
        client.get_pod("default", wl.name)

    blocked = fx.workload_pod(n2, labels={"app": "pdb"})
    store.set_eviction_blocked(blocked.namespace, blocked.name, True)
    with pytest.raises(EvictionBlockedError):
        client.evict_pod(blocked.namespace, blocked.name)
    store.set_eviction_blocked(blocked.namespace, blocked.name, False)
    client.evict_pod(blocked.namespace, blocked.name)
    with pytest.raises(NotFoundError):
        client.get_pod(blocked.namespace, blocked.name)


# --- conformance: daemonsets + revisions --------------------------------------


def test_daemonset_and_revision_verbs(tier):
    client, store = tier
    fx = ClusterFixture(store, KEYS)
    ds = fx.daemon_set(hash_suffix="h1", revision=1)
    fx.driver_pod(fx.node("n1"), ds, hash_suffix="h1")

    listed = client.list_daemon_sets(
        namespace=NAMESPACE, match_labels=DRIVER_LABELS
    )
    assert [d.name for d in listed] == [ds.name]
    # The engine's completeness guard reads status over the wire
    # (upgrade_state.go:243-246).
    assert listed[0].status.desired_number_scheduled == 1
    assert listed[0].metadata.uid == ds.metadata.uid

    got = client.get_daemon_set(NAMESPACE, ds.name)
    assert got.spec.selector.match_labels == DRIVER_LABELS

    revs = client.list_controller_revisions(
        namespace=NAMESPACE, label_selector="app=libtpu-driver"
    )
    assert len(revs) == 1 and revs[0].revision == 1

    with pytest.raises(ConflictError):
        client.create_daemon_set(got)
    with pytest.raises(NotFoundError):
        client.get_daemon_set(NAMESPACE, "missing")

    got.spec.template.labels["v"] = "2"
    updated = client.update_daemon_set(got)
    assert updated.spec.template.labels["v"] == "2"
    # Server-owned fields preserved across the update round trip.
    assert (
        client.get_daemon_set(NAMESPACE, ds.name).metadata.uid
        == ds.metadata.uid
    )


# --- the e2e rolling upgrade, engine -> RestClient -> HTTP -> apiserver ------


def test_full_rolling_upgrade_through_rest_client():
    """BASELINE config 2: the complete slice-atomic roll with every engine
    call crossing the HTTP wire (reference analogue: the whole
    upgrade_state_test.go suite runs against envtest's real apiserver)."""
    store = FakeCluster()
    server = KubeApiServer(store)
    server.start()
    try:
        client = RestClient(KubeConfig(host=server.host), timeout_s=10.0)
        fx = ClusterFixture(store, KEYS)
        ds = fx.daemon_set(hash_suffix="h1", revision=1)
        slice_a = fx.tpu_slice("pool-a", hosts=2)
        slice_b = fx.tpu_slice("pool-b", hosts=2)
        nodes = slice_a + slice_b
        for n in nodes:
            fx.driver_pod(n, ds, hash_suffix="h1")
            fx.workload_pod(n, labels={"app": "train"})
        fx.bump_daemon_set_template(ds, "h2", revision=2)
        fx.auto_recreate_driver_pods(ds, "h2")

        mgr = ClusterUpgradeStateManager(
            client, keys=KEYS, poll_interval_s=0.01, poll_timeout_s=2.0
        )
        mgr.with_validation_enabled(FakeProber(healthy=True))
        policy = TPUUpgradePolicySpec(
            auto_upgrade=True,
            max_parallel_upgrades=1,
            drain_spec=DrainSpec(enable=True, timeout_second=5),
        )

        for _ in range(60):
            state = mgr.build_state(NAMESPACE, DRIVER_LABELS)
            mgr.apply_state(state, policy)
            assert mgr.wait_for_async_work()
            # Slice atomicity over the wire.
            for names in ([n.name for n in slice_a],
                          [n.name for n in slice_b]):
                states = {
                    client.get_node(nm, cached=False).labels.get(
                        KEYS.state_label, ""
                    )
                    for nm in names
                }
                assert len(states) == 1, f"slice split: {states}"
            if all(
                client.get_node(n.name, cached=False).labels.get(
                    KEYS.state_label
                )
                == UpgradeState.DONE.value
                for n in nodes
            ):
                break
        else:
            raise AssertionError(
                "upgrade did not converge through the REST tier"
            )

        for n in nodes:
            pods = [
                p
                for p in client.list_pods(node_name=n.name)
                if p.labels.get("app") == DRIVER_LABELS["app"]
            ]
            assert len(pods) == 1
            assert pods[0].labels["controller-revision-hash"] == "h2"
            assert not client.get_node(n.name, cached=False).spec.unschedulable
        # The engine really did its work over HTTP.
        assert sum(client.stats.values()) > 100
    finally:
        server.stop()
