"""Multi-artifact upgrade DAGs (`artifacts/`, docs/multi-artifact-dags.md).

Covers the subsystem end to end on the fake tier:

- DAG structural validation at admission (cycles, dangling edges,
  skew conflicts, unsatisfiable version constraints) rejecting the
  policy through the classic ``ValidationError`` path;
- a 3-artifact pinned-order stack rolling under ONE cordon/drain
  window per node with ONE budget charge per group, restart order
  respecting the topology;
- seeded fuzz over random DAG shapes x {lockstep, pinned-order}
  asserting the same invariants hold for arbitrary stacks;
- reverse-topological rollback events when a mid-stack artifact
  crash-loops, and durable resume at the correct artifact step when a
  fresh controller adopts a half-stepped stack;
- size-1 parity: a one-item ``artifacts`` stanza produces the exact
  transition multiset and write counts of the classic path;
- the network-path gate holding an artifact's step (one Warning per
  hold episode) until the prober passes.
"""

import random

import pytest

from k8s_operator_libs_tpu.api import IntOrString, TPUUpgradePolicySpec
from k8s_operator_libs_tpu.api.v1alpha1 import (
    ArtifactDAGSpec,
    ArtifactEdgeSpec,
    ArtifactSpec,
    ValidationError,
)
from k8s_operator_libs_tpu.artifacts.dag import (
    ArtifactDAG,
    ArtifactDAGError,
    artifact_dag_of,
    constraint_satisfied,
)
from k8s_operator_libs_tpu.artifacts.gates import (
    GateResult,
    NetworkPathGateProber,
)
from k8s_operator_libs_tpu.k8s import FakeCluster
from k8s_operator_libs_tpu.k8s.objects import ContainerStatus
from k8s_operator_libs_tpu.upgrade import (
    ClusterUpgradeStateManager,
    UpgradeKeys,
    UpgradeState,
)
from k8s_operator_libs_tpu.upgrade.sharded import BudgetLedger
from k8s_operator_libs_tpu.upgrade.util import EventRecorder
from tests.fixtures import DRIVER_LABELS, NAMESPACE, ClusterFixture

KEYS = UpgradeKeys()

NET_LABELS = {"app": "tpu-network-driver"}
PLUGIN_LABELS = {"app": "tpu-device-plugin"}


def _spec(names_labels, edges, gates=None):
    """ArtifactDAGSpec from [(name, labels)] + [(before, after, skew)]."""
    gates = gates or {}
    return ArtifactDAGSpec(
        items=[
            ArtifactSpec(
                name=name,
                match_labels=dict(labels),
                target_version="1.0.0",
                gate=gates.get(name, ""),
            )
            for name, labels in names_labels
        ],
        edges=[
            ArtifactEdgeSpec(before=b, after=a, skew=s) for b, a, s in edges
        ],
    )


def _policy(artifacts=None, **kw):
    kw.setdefault("auto_upgrade", True)
    kw.setdefault("max_parallel_upgrades", 0)
    kw.setdefault("max_unavailable", IntOrString("100%"))
    kw.setdefault("unavailability_unit", "slice")
    return TPUUpgradePolicySpec(artifacts=artifacts, **kw)


# -- DAG structural validation -----------------------------------------------


class TestDagValidation:
    ITEMS = [("a", {"app": "a"}), ("b", {"app": "b"}), ("c", {"app": "c"})]

    def test_pinned_order_cycle_rejected(self):
        spec = _spec(
            self.ITEMS,
            [
                ("a", "b", "pinned-order"),
                ("b", "c", "pinned-order"),
                ("c", "a", "pinned-order"),
            ],
        )
        with pytest.raises(ArtifactDAGError, match="cycle"):
            ArtifactDAG.from_spec(spec).validate()

    def test_lockstep_condensation_catches_mixed_cycle(self):
        # a <-> b lockstep-connected, plus a pinned-order edge entering
        # and leaving the component: a cycle of the condensed graph.
        spec = _spec(
            self.ITEMS,
            [
                ("a", "b", "lockstep"),
                ("b", "c", "pinned-order"),
                ("c", "a", "pinned-order"),
            ],
        )
        with pytest.raises(ArtifactDAGError, match="cycle"):
            ArtifactDAG.from_spec(spec).validate()

    def test_pinned_order_inside_lockstep_component_rejected(self):
        spec = _spec(
            self.ITEMS[:2],
            [("a", "b", "lockstep"), ("a", "b", "pinned-order")],
        )
        with pytest.raises(ArtifactDAGError, match="conflicting skew"):
            ArtifactDAG.from_spec(spec).validate()

    def test_dangling_edge_rejected(self):
        spec = _spec(self.ITEMS[:2], [("a", "ghost", "pinned-order")])
        with pytest.raises(ArtifactDAGError, match="dangling"):
            ArtifactDAG.from_spec(spec).validate()

    def test_self_edge_rejected(self):
        spec = _spec(self.ITEMS[:2], [("a", "a", "pinned-order")])
        with pytest.raises(ArtifactDAGError, match="self-edge"):
            ArtifactDAG.from_spec(spec).validate()

    def test_unknown_skew_and_gate_rejected(self):
        spec = _spec(self.ITEMS[:2], [("a", "b", "sideways")])
        with pytest.raises(ArtifactDAGError, match="unknown skew"):
            ArtifactDAG.from_spec(spec).validate()
        spec = _spec(self.ITEMS[:2], [], gates={"a": "vibes"})
        with pytest.raises(ArtifactDAGError, match="unknown gate"):
            ArtifactDAG.from_spec(spec).validate()

    def test_unsatisfiable_constraint_rejected(self):
        spec = _spec(self.ITEMS[:2], [])
        spec.items[0].target_version = "2.17.0"
        spec.edges = [
            ArtifactEdgeSpec(before="a", after="b", requires=">=2.18.0")
        ]
        with pytest.raises(ArtifactDAGError, match="unsatisfiable"):
            ArtifactDAG.from_spec(spec).validate()

    def test_duplicate_name_rejected(self):
        spec = _spec([("a", {"app": "a"}), ("a", {"app": "a2"})], [])
        with pytest.raises(ArtifactDAGError, match="duplicate"):
            ArtifactDAG.from_spec(spec).validate()

    def test_policy_validate_rejects_invalid_dag(self):
        # The engine never sees an invalid stack: the classic
        # ValidationError admission path carries the DAG error.
        spec = _spec(
            self.ITEMS[:2],
            [("a", "b", "pinned-order"), ("b", "a", "pinned-order")],
        )
        with pytest.raises(ValidationError, match="artifacts:.*cycle"):
            _policy(artifacts=spec).validate()

    def test_levels_and_orders(self):
        spec = _spec(
            self.ITEMS,
            [("a", "b", "pinned-order"), ("b", "c", "lockstep")],
        )
        dag = ArtifactDAG.from_spec(spec)
        dag.validate()
        assert dag.levels() == {"a": 1, "b": 2, "c": 2}
        assert dag.serialized_steps() == 2
        assert dag.topo_order() == ["a", "b", "c"]
        assert dag.rollback_order() == ["c", "b", "a"]
        assert dag.primary() == "a"

    def test_all_lockstep_collapses_to_one_step(self):
        spec = _spec(
            self.ITEMS, [("a", "b", "lockstep"), ("b", "c", "lockstep")]
        )
        dag = ArtifactDAG.from_spec(spec)
        dag.validate()
        assert dag.serialized_steps() == 1

    def test_size_one_dag_is_classic_path(self):
        assert artifact_dag_of(_policy()) is None
        one = _spec([("driver", DRIVER_LABELS)], [])
        assert artifact_dag_of(_policy(artifacts=one)) is None

    def test_constraint_grammar(self):
        assert constraint_satisfied(">=2.18.0", "2.18.0")
        assert constraint_satisfied("", "anything")
        assert not constraint_satisfied("<2.0", "2.0.1")
        assert constraint_satisfied("2.18.0", "2.18.0")  # bare = exact
        assert not constraint_satisfied("!=1.4.0", "1.4.0")


# -- fake-tier stack rolls ---------------------------------------------------


class _StackEnv:
    """A fleet where every node carries one pod per artifact, every
    DaemonSet's template already bumped to its -v2 revision."""

    def __init__(self, names_labels, n_slices=2, hosts=2, recreate=None):
        self.cluster = FakeCluster()
        self.fx = ClusterFixture(self.cluster, KEYS)
        self.names_labels = list(names_labels)
        recreate = recreate or {}
        self.dss = {}
        self.nodes = []
        primary_name = self.names_labels[0][0]
        for name, labels in self.names_labels:
            if dict(labels) == dict(DRIVER_LABELS):
                ds = self.fx.daemon_set(hash_suffix=f"{name}-v1", revision=1)
            else:
                ds = self.fx.daemon_set(
                    name=f"{name}-ds",
                    hash_suffix=f"{name}-v1",
                    revision=1,
                    labels=dict(labels),
                )
            self.dss[name] = ds
        for i in range(n_slices):
            for n in self.fx.tpu_slice(f"pool-{i}", hosts=hosts):
                self.nodes.append(n)
                for name, _ in self.names_labels:
                    pod_name = (
                        None if name == primary_name else f"{name}-{n.name}"
                    )
                    self.fx.driver_pod(
                        n,
                        self.dss[name],
                        hash_suffix=f"{name}-v1",
                        name=pod_name,
                    )
        for name, _ in self.names_labels:
            self.fx.bump_daemon_set_template(
                self.dss[name], f"{name}-v2", revision=2
            )
            hook = recreate.get(name)
            if hook is None:
                self.fx.auto_recreate_driver_pods(self.dss[name], f"{name}-v2")
            else:
                hook(self, self.dss[name], f"{name}-v2")
        self.events = EventRecorder()
        self.mgr = ClusterUpgradeStateManager(
            self.cluster,
            keys=KEYS,
            poll_interval_s=0.005,
            poll_timeout_s=2.0,
            event_recorder=self.events,
        )
        # Restart order per node: the sequence of artifact pod deletes.
        self.deletes: dict[str, list[str]] = {}
        self.delete_counts: dict[tuple[str, str], int] = {}
        label_to_name = {
            frozenset(labels.items()): name
            for name, labels in self.names_labels
        }
        orig_delete = self.cluster.delete_pod

        def watch_delete(namespace, name, **kw):
            pod = self.cluster.get_pod(namespace, name)
            art = label_to_name.get(
                frozenset(
                    (k, v)
                    for k, v in pod.labels.items()
                    if k != "controller-revision-hash"
                )
            )
            if art is not None and pod.spec.node_name:
                node = pod.spec.node_name
                self.deletes.setdefault(node, []).append(art)
                key = (node, art)
                self.delete_counts[key] = self.delete_counts.get(key, 0) + 1
            return orig_delete(namespace, name, **kw)

        self.cluster.delete_pod = watch_delete

        self.cordons: dict[str, int] = {}
        orig_unsched = self.cluster.set_node_unschedulable

        def watch_unsched(name, unschedulable):
            if unschedulable:
                self.cordons[name] = self.cordons.get(name, 0) + 1
            return orig_unsched(name, unschedulable)

        self.cluster.set_node_unschedulable = watch_unsched

    def install_counting_ledger(self, n_groups):
        ledger = BudgetLedger()
        ledger.configure(
            total_units=n_groups,
            max_parallel=0,
            max_unavailable=n_groups,
            unit="slice",
        )
        charges: dict[str, int] = {}
        orig_claim = ledger.try_claim

        def counting_claim(group_id, cost, **kw):
            held = ledger.holds(group_id)
            ok = orig_claim(group_id, cost, **kw)
            if ok and not held:
                charges[group_id] = charges.get(group_id, 0) + 1
            return ok

        ledger.try_claim = counting_claim
        self.mgr.budget_ledger = ledger
        return charges

    def node_states(self):
        return {
            self.cluster.get_node(n.name, cached=False).labels.get(
                KEYS.state_label, ""
            )
            for n in self.nodes
        }

    def tick(self, policy):
        state = self.mgr.build_state(NAMESPACE, DRIVER_LABELS, policy)
        self.mgr.apply_state(state, policy)
        assert self.mgr.wait_for_async_work(30.0)

    def roll(self, policy, max_ticks=60, want=UpgradeState.DONE):
        for _ in range(max_ticks):
            self.tick(policy)
            if self.node_states() == {want.value}:
                return
        raise AssertionError(
            f"did not converge to {want.value} in {max_ticks} ticks; "
            f"states: {self.node_states()}"
        )

    def assert_pods_current(self, names=None):
        for name, labels in self.names_labels:
            if names is not None and name not in names:
                continue
            sel = ",".join(f"{k}={v}" for k, v in labels.items())
            pods = self.cluster.list_pods(
                namespace=NAMESPACE, label_selector=sel
            )
            assert pods, f"artifact {name}: no pods"
            for p in pods:
                assert (
                    p.labels["controller-revision-hash"] == f"{name}-v2"
                ), f"artifact {name}: pod {p.name} on old revision"


THREE_STACK = [
    ("driver", DRIVER_LABELS),
    ("net", NET_LABELS),
    ("plugin", PLUGIN_LABELS),
]
THREE_EDGES = [
    ("driver", "net", "pinned-order"),
    ("net", "plugin", "pinned-order"),
]


class TestMultiArtifactRoll:
    def test_pinned_order_stack_one_window_topological(self):
        env = _StackEnv(THREE_STACK)
        policy = _policy(artifacts=_spec(THREE_STACK, THREE_EDGES))
        policy.validate()
        charges = env.install_counting_ledger(n_groups=2)
        env.roll(policy)
        env.assert_pods_current()
        # ONE cordon window per node, ONE budget charge per group.
        assert set(env.cordons.values()) == {1}
        assert len(env.cordons) == len(env.nodes)
        assert set(charges.values()) == {1}
        assert len(charges) == 2
        # Each artifact's pod restarted exactly once, in topo order.
        for node in env.nodes:
            seq = env.deletes[node.name]
            assert seq == ["driver", "net", "plugin"], seq
        # Later steps were withheld while the cursor sat earlier.
        assert env.mgr.artifact_skew_holds["net"] >= 1
        assert env.mgr.artifact_skew_holds["plugin"] >= 1
        # Shared window avoided (artifacts - 1) windows per node.
        assert env.mgr.artifact_window_savings == len(env.nodes) * 2
        # No node ever left schedulable=False behind.
        for n in env.nodes:
            assert not env.cluster.get_node(n.name).spec.unschedulable

    def test_lockstep_stack_restarts_in_one_step(self):
        env = _StackEnv(THREE_STACK)
        edges = [
            ("driver", "net", "lockstep"),
            ("net", "plugin", "lockstep"),
        ]
        policy = _policy(artifacts=_spec(THREE_STACK, edges))
        policy.validate()
        env.roll(policy)
        env.assert_pods_current()
        # One restart step: nothing is ever held back.
        assert env.mgr.artifact_skew_holds == {}
        assert set(env.cordons.values()) == {1}
        # Every artifact restarted exactly once per node (no thrash).
        for node in env.nodes:
            assert sorted(env.deletes[node.name]) == [
                "driver",
                "net",
                "plugin",
            ]

    def test_progress_gauge_tracks_mid_roll(self):
        env = _StackEnv(THREE_STACK, n_slices=1, hosts=2)
        policy = _policy(artifacts=_spec(THREE_STACK, THREE_EDGES))
        saw_partial = False
        for _ in range(60):
            env.tick(policy)
            prog = env.mgr.artifact_progress
            if prog:
                assert set(prog) <= {"driver", "net", "plugin"}
                for synced, total in prog.values():
                    assert 0 <= synced <= total
                if any(s < t for s, t in prog.values()):
                    saw_partial = True
            if env.node_states() == {UpgradeState.DONE.value}:
                break
        else:
            raise AssertionError("no convergence")
        assert saw_partial


# -- seeded fuzz -------------------------------------------------------------


def _random_dag(rng):
    """Random 2-4 artifact stack with random forward edges: always a
    valid DAG (edges only point from lower to higher item index).  The
    PRIMARY artifact (first in topological order — the one the engine
    maps onto the classic driver DaemonSet) gets the driver labels,
    whichever item the edge shape makes it."""
    n = rng.randint(2, 4)
    names_labels = [(f"art{i}", {"app": f"art{i}"}) for i in range(n)]
    edges = []
    for j in range(1, n):
        # Each artifact depends on at least one earlier one: keeps the
        # stack connected so ordering is actually exercised.
        deps = rng.sample(range(j), rng.randint(1, j))
        for i in deps:
            skew = rng.choice(["lockstep", "pinned-order"])
            edges.append((f"art{i}", f"art{j}", skew))
    dag = ArtifactDAG.from_spec(_spec(names_labels, edges))
    try:
        dag.validate()
    except ArtifactDAGError:
        # A transitive lockstep component caught a pinned-order edge
        # inside it (the admission-rejected conflicting-skew shape):
        # draw again — deterministic given the rng.
        return _random_dag(rng)
    primary = dag.primary()
    names_labels = [
        (name, dict(DRIVER_LABELS) if name == primary else labels)
        for name, labels in names_labels
    ]
    return names_labels, edges


@pytest.mark.parametrize("seed", [7, 23, 61])
def test_fuzz_random_dags_hold_window_invariants(seed):
    rng = random.Random(seed)
    for _trial in range(3):
        names_labels, edges = _random_dag(rng)
        spec = _spec(names_labels, edges)
        policy = _policy(artifacts=spec)
        policy.validate()
        dag = artifact_dag_of(policy)
        assert dag is not None
        levels = dag.levels()

        env = _StackEnv(names_labels, n_slices=2, hosts=2)
        charges = env.install_counting_ledger(n_groups=2)
        env.roll(policy)
        env.assert_pods_current()

        # One cordon window per node, one budget charge per group,
        # each artifact restarted at most once per node.
        assert set(env.cordons.values()) == {1}
        assert len(env.cordons) == len(env.nodes)
        assert set(charges.values()) == {1}
        assert set(env.delete_counts.values()) == {1}
        # Restart sequence respects the topology: steps never decrease.
        for node in env.nodes:
            seq = env.deletes[node.name]
            assert len(seq) == len(names_labels)
            step_seq = [levels[a] for a in seq]
            assert step_seq == sorted(step_seq), (
                f"seed {seed}: node {node.name} restarted {seq} "
                f"(steps {step_seq}) against levels {levels}"
            )
        assert env.mgr.artifact_window_savings == len(env.nodes) * (
            len(names_labels) - 1
        )


# -- rollback ----------------------------------------------------------------


def _crash_recreate(env, ds, hash_suffix):
    """Recreate hook: pods come back on the TARGET revision but
    crash-looping (Ready=False, restart_count over the failing
    threshold) — the synced-but-failing rollback trigger."""
    from k8s_operator_libs_tpu.k8s.objects import (
        ObjectMeta,
        Pod,
        PodSpec,
        PodStatus,
    )

    cluster = env.cluster

    def hook(pod):
        selector = ds.spec.selector.match_labels
        if not all(pod.labels.get(k) == v for k, v in selector.items()):
            return
        if not pod.metadata.owner_references:
            return
        if pod.metadata.owner_references[0].uid != ds.metadata.uid:
            return
        labels = dict(selector)
        labels["controller-revision-hash"] = hash_suffix
        cluster.create_pod(
            Pod(
                metadata=ObjectMeta(
                    name=pod.name,
                    namespace=pod.namespace,
                    labels=labels,
                    owner_references=list(pod.metadata.owner_references),
                ),
                spec=PodSpec(node_name=pod.spec.node_name),
                status=PodStatus(
                    phase="Running",
                    container_statuses=[
                        ContainerStatus(ready=False, restart_count=12)
                    ],
                ),
            )
        )

    cluster.on_pod_deleted(hook)


class TestRollback:
    def test_crash_looping_artifact_unwinds_in_reverse_topo_order(self):
        env = _StackEnv(
            THREE_STACK,
            n_slices=1,
            hosts=2,
            recreate={"net": _crash_recreate},
        )
        policy = _policy(artifacts=_spec(THREE_STACK, THREE_EDGES))
        policy.validate()
        env.roll(policy, want=UpgradeState.FAILED)
        # plugin never restarted: the stack failed at the net step.
        for node in env.nodes:
            assert env.deletes[node.name] == ["driver", "net"]
        assert env.mgr.artifact_rollbacks_total == 1
        rollbacks = [
            e for e in env.events.events if e.reason == "ArtifactRollback"
        ]
        steps = [
            e for e in env.events.events if e.reason == "ArtifactRollbackStep"
        ]
        assert len(rollbacks) == 1
        assert rollbacks[0].event_type == "Warning"
        assert "'net'" in rollbacks[0].message
        # Unwind is reverse topological over the REACHED prefix only:
        # net first, then driver; plugin (never reached) is absent.
        assert len(steps) == 2
        assert "'net'" in steps[0].message
        assert "'driver'" in steps[1].message
        assert all("plugin" not in s.message for s in steps)


# -- chaos: controller dies mid-stack ----------------------------------------


def test_fresh_controller_resumes_at_correct_artifact_step():
    env = _StackEnv(THREE_STACK, n_slices=1, hosts=2)
    policy = _policy(artifacts=_spec(THREE_STACK, THREE_EDGES))
    policy.validate()

    # Drive until the driver artifact restarted but the stack is not
    # done — the controller "crashes" mid-DAG.
    for _ in range(60):
        env.tick(policy)
        if any(
            seq and seq[0] == "driver" for seq in env.deletes.values()
        ) and env.node_states() != {UpgradeState.DONE.value}:
            break
    else:
        raise AssertionError("never reached a mid-stack point")
    mid_deletes = {n: list(s) for n, s in env.deletes.items()}
    assert env.node_states() != {UpgradeState.DONE.value}

    # A FRESH manager (no in-memory state carried over) adopts the
    # fleet: the artifact cursor derives from observed pod hashes.
    env.mgr = ClusterUpgradeStateManager(
        env.cluster,
        keys=KEYS,
        poll_interval_s=0.005,
        poll_timeout_s=2.0,
        event_recorder=env.events,
    )
    env.roll(policy)
    env.assert_pods_current()
    # Resume continued, never re-ran: each artifact restarted exactly
    # once per node across BOTH controller incarnations, and the full
    # per-node sequence still respects the topology.
    assert set(env.delete_counts.values()) == {1}
    for node, seq in env.deletes.items():
        assert seq == ["driver", "net", "plugin"], (node, seq)
        # The pre-crash prefix is a prefix of the final sequence.
        assert seq[: len(mid_deletes.get(node, []))] == mid_deletes.get(
            node, []
        )


# -- size-1 parity -----------------------------------------------------------


def _parity_roll(artifacts):
    env = _StackEnv([("driver", DRIVER_LABELS)], n_slices=2, hosts=2)
    policy = _policy(artifacts=artifacts)
    policy.validate()
    transitions: list[tuple[str, str]] = []

    def watch(name, labels):
        if labels and KEYS.state_label in labels:
            transitions.append((name, labels[KEYS.state_label]))

    orig_pl = env.cluster.patch_node_labels
    orig_pm = env.cluster.patch_node_metadata
    env.cluster.patch_node_labels = lambda n, p: (watch(n, p), orig_pl(n, p))[
        1
    ]

    def pm(name, labels=None, annotations=None, field_manager=None):
        watch(name, labels)
        return orig_pm(
            name,
            labels=labels,
            annotations=annotations,
            field_manager=field_manager,
        )

    env.cluster.patch_node_metadata = pm
    write_verbs = (
        "patch_node",
        "delete_pod",
        "evict_pod",
        "update_pod",
        "create_pod",
        "create_event",
    )
    base = {v: env.cluster.stats.get(v, 0) for v in write_verbs}
    env.roll(policy)
    writes = {
        v: env.cluster.stats.get(v, 0) - base[v] for v in write_verbs
    }
    return sorted(transitions), writes, [e.reason for e in env.events.events]


def test_size_one_dag_transition_multiset_and_writes_match_classic():
    """A one-item artifacts stanza IS the classic path: identical
    per-node transition multiset, identical write-verb counts,
    identical event reasons."""
    classic_tr, classic_writes, classic_events = _parity_roll(None)
    one = _spec([("driver", DRIVER_LABELS)], [])
    dag_tr, dag_writes, dag_events = _parity_roll(one)
    assert dag_tr == classic_tr
    assert dag_writes == classic_writes
    assert dag_events == classic_events
    # And the engine's artifact machinery never engaged.
    assert classic_writes["delete_pod"] == dag_writes["delete_pod"]


# -- network-path gate -------------------------------------------------------


class _HoldThenPassProber:
    def __init__(self):
        self.passed = False
        self.calls = 0

    def probe(self, group, artifact_name):
        self.calls += 1
        if self.passed:
            return GateResult(True, "dcn, ici verified")
        return GateResult(False, "ici link down on port 3")


class TestNetworkGate:
    def test_gate_holds_stack_then_releases(self):
        env = _StackEnv(THREE_STACK, n_slices=1, hosts=2)
        spec = _spec(
            THREE_STACK, THREE_EDGES, gates={"net": "network-path"}
        )
        policy = _policy(artifacts=spec)
        policy.validate()
        prober = _HoldThenPassProber()
        env.mgr.artifact_gate_prober = prober

        held_ticks = 0
        for _ in range(60):
            env.tick(policy)
            if env.mgr.artifact_gate_holds.get("net", 0) > 0:
                held_ticks += 1
            if held_ticks >= 3:
                break
        assert env.mgr.artifact_gate_holds["net"] >= 3
        # The plugin step never ran while the gate held.
        for seq in env.deletes.values():
            assert "plugin" not in seq
        # One Warning per hold EPISODE, not per pass.
        holds = [
            e for e in env.events.events if e.reason == "ArtifactGateHeld"
        ]
        assert len(holds) == 1
        assert holds[0].event_type == "Warning"
        assert "ici link down" in holds[0].message

        prober.passed = True
        env.roll(policy)
        env.assert_pods_current()
        holds_after = env.mgr.artifact_gate_holds["net"]
        env.tick(policy)
        assert env.mgr.artifact_gate_holds["net"] == holds_after
        # Verdict cache: once passed, completed groups drop gate state.
        assert env.mgr._artifact_gate_ok == set()

    def test_prober_fail_closed_on_probe_error(self):
        def exploding_runner():
            raise RuntimeError("transport down")

        prober = NetworkPathGateProber(runner=exploding_runner)
        verdict = prober.probe(type("G", (), {"id": "g"})(), "net")
        assert not verdict.passed
        assert "probe error" in verdict.detail

    def test_prober_reports_failing_checks(self):
        class _Check:
            def __init__(self, name, ok, detail=""):
                self.name = name
                self.ok = ok
                self.detail = detail

        prober = NetworkPathGateProber(
            runner=lambda: [
                _Check("dcn_reachability", True),
                _Check("ici_link_state", False, "port 3 down"),
            ]
        )
        verdict = prober.probe(type("G", (), {"id": "g"})(), "net")
        assert not verdict.passed
        assert "ici_link_state" in verdict.detail
        assert verdict.checks == {
            "dcn_reachability": True,
            "ici_link_state": False,
        }
