"""A consumer operator embedding the upgrade library — the reference's
primary usage shape (its consumers, the GPU/Network Operators, own the
reconcile loop and wire their own policy source and validation).

This example manages a fictional "mydriver" DaemonSet with:

- its own policy source (here: a dict; in a real operator, your CRD),
- a custom validation prober (here: "driver pod publishes a ready file
  marker annotation" — the moral equivalent of the reference's consumers
  pointing ValidationManager at their nvidia-smi validation pod),
- its own reconcile cadence.

Run against a real cluster (kubeconfig from $KUBECONFIG or
~/.kube/config, in-cluster service account when deployed):

    python examples/consumer_operator.py --interval 30

or exercise it hermetically (what tests/test_example.py does) by passing
a FakeCluster through ``run_reconcile_loop(client, ...)``.
"""

from __future__ import annotations

import argparse
import time

from k8s_operator_libs_tpu.api import TPUUpgradePolicySpec
from k8s_operator_libs_tpu.health.slice_prober import ProbeResult
from k8s_operator_libs_tpu.upgrade import (
    ClusterUpgradeStateManager,
    UpgradeKeys,
)

DRIVER_NAME = "mydriver"
NAMESPACE = "mydriver-system"
DRIVER_LABELS = {"app": f"{DRIVER_NAME}-driver"}
READY_MARKER = "example.com/mydriver-validated"


class MarkerProber:
    """Consumer-supplied validation: a slice passes when every host's
    node carries the READY_MARKER annotation (your driver's readiness
    probe would publish it).  Same duck type as NodeReportProber."""

    def probe(self, group) -> ProbeResult:
        missing = [
            n.name
            for n in group.nodes
            if n.annotations.get(READY_MARKER) != "true"
        ]
        if missing:
            return ProbeResult(
                False, f"awaiting validation marker on: {', '.join(missing)}"
            )
        return ProbeResult(True, f"all {group.size()} host(s) validated")


def build_manager(client) -> ClusterUpgradeStateManager:
    keys = UpgradeKeys(driver_name=DRIVER_NAME, domain="example.com")
    mgr = ClusterUpgradeStateManager(client, keys=keys)
    mgr.with_validation_enabled(MarkerProber())
    # Your workload pods, not DaemonSets, get evicted before the upgrade.
    mgr.with_pod_deletion_enabled(lambda pod: not pod.is_daemonset_pod())
    return mgr


def load_policy() -> TPUUpgradePolicySpec:
    """In a real operator this comes from your CRD spec."""
    return TPUUpgradePolicySpec.from_dict(
        {
            "autoUpgrade": True,
            "maxParallelUpgrades": 1,
            "maxUnavailable": "25%",
            "podDeletion": {"force": False, "timeoutSeconds": 300},
            "drain": {"enable": True, "timeoutSeconds": 300},
            # The library's TPU health gate is replaced by MarkerProber,
            # so the built-in gate knobs are left enabled-by-default.
        }
    )


def run_reconcile_loop(
    client,
    interval_s: float = 30.0,
    max_passes: int | None = None,
    leader_elect: bool = False,
    elector=None,
) -> None:
    """The consumer-owned loop: snapshot, tick, sleep — identical shape
    to a controller-runtime Reconcile with a resync period.

    ``leader_elect`` shows the HA pattern for a consumer running 2+
    replicas: only the Lease holder reconciles, everyone else stands by
    (the same library protocol the bundled controller uses)."""
    if leader_elect and elector is None:
        from k8s_operator_libs_tpu.k8s.leader import (
            LeaderElector,
            ensure_lease_kind,
        )

        ensure_lease_kind(client)  # no-op on a real apiserver
        elector = LeaderElector(
            client, namespace=NAMESPACE, name=f"{DRIVER_NAME}-operator"
        )
    mgr = build_manager(client)
    policy = load_policy()
    passes = 0
    while max_passes is None or passes < max_passes:
        if elector is not None and not elector.acquire_or_renew():
            time.sleep(min(elector.retry_period_s, interval_s))
            continue
        state = mgr.build_state(NAMESPACE, DRIVER_LABELS, policy)
        # Re-check right before the mutating phase: a build_state that
        # outlives the 10 s renew deadline must not cordon/drain
        # concurrently with a successor that already took over (the
        # controller's ``_still_leading`` guard, in example form).
        if elector is not None and not elector.acquire_or_renew():
            continue
        mgr.apply_state(state, policy)
        mgr.wait_for_async_work()
        print(
            f"pass {passes}: managed={mgr.get_total_managed_nodes(state)} "
            f"in-progress={mgr.get_upgrades_in_progress(state)} "
            f"done={mgr.get_upgrades_done(state)} "
            f"failed={mgr.get_upgrades_failed(state)}"
        )
        passes += 1
        if max_passes is None:
            renewing_sleep(elector, interval_s)
    if elector is not None:
        elector.release()  # clean handover to the standby replica


def renewing_sleep(elector, seconds: float) -> None:
    """Sleep in retry-period chunks, renewing the Lease between chunks.

    A plain ``time.sleep(interval_s)`` would forfeit the lease every
    pass (interval 30 s > the 15 s default term) and ping-pong
    leadership with the standby; this mirrors the bundled controller's
    ``_wait``."""
    deadline = time.monotonic() + seconds
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            return
        chunk = remaining
        if elector is not None:
            chunk = min(chunk, elector.retry_period_s)
        time.sleep(chunk)
        if elector is not None:
            elector.acquire_or_renew()


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--interval", type=float, default=30.0)
    parser.add_argument(
        "--leader-elect",
        action="store_true",
        help="run 2+ replicas safely: only the Lease holder reconciles",
    )
    args = parser.parse_args()
    from k8s_operator_libs_tpu.k8s import get_default_client

    run_reconcile_loop(
        get_default_client(),
        interval_s=args.interval,
        leader_elect=args.leader_elect,
    )


if __name__ == "__main__":
    main()
