"""Hot-path regression guard for the informer-backed cached reconcile.

``make bench-guard`` runs this standalone (no accelerator, no jax
device work — the engine + FakeCluster only): it builds the 256-node
steady-state pool from the scale pin (tests/test_scale.py), syncs an
Informer, drives reconcile ticks through a CachedKubeClient, and FAILS
if the measured ``api_requests_per_tick`` regresses above the pinned
ceiling.  The cache serves every read in steady state, so the true
value is 0.0; the ceiling leaves no room for a per-node GET (256/tick)
or a per-tick LIST (>= 4/tick) to sneak back into the hot path.

bench.py imports ``measure()`` for its ``cached_reconcile`` stage so
the nightly artifact records the same numbers this gate enforces.
"""

from __future__ import annotations

import json
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)
sys.path.insert(0, os.path.join(_ROOT, "tests"))

N_SLICES = 16
HOSTS_PER_SLICE = 16
TICKS = 5
# Average API round trips per steady-state tick through the cached
# client.  Pinned, not aspirational: the scale pin asserts exactly 0
# reads over 3 ticks, so anything above this ceiling is a reintroduced
# relist or per-node GET, never noise.
API_PER_TICK_CEILING = 0.5


def measure(
    slices: int = N_SLICES,
    hosts: int = HOSTS_PER_SLICE,
    ticks: int = TICKS,
) -> dict:
    """One steady-state cached-reconcile measurement; returns the
    artifact dict (also embedded in BENCH_DETAILS.json by bench.py)."""
    from k8s_operator_libs_tpu.api import (
        DrainSpec,
        IntOrString,
        TPUUpgradePolicySpec,
    )
    from k8s_operator_libs_tpu.k8s import FakeCluster
    from k8s_operator_libs_tpu.k8s.informer import (
        CachedKubeClient,
        Informer,
    )
    from k8s_operator_libs_tpu.upgrade import (
        ClusterUpgradeStateManager,
        UpgradeKeys,
        UpgradeState,
    )

    from fixtures import ClusterFixture, DRIVER_LABELS, NAMESPACE

    keys = UpgradeKeys()
    cluster = FakeCluster()
    fx = ClusterFixture(cluster, keys)
    ds = fx.daemon_set(hash_suffix="v1", revision=1)
    # Already-rolled pool: every node done, every pod at the current
    # revision — the state a controller sits in 99% of its life.
    for i in range(slices):
        for n in fx.tpu_slice(
            f"pool-{i:02d}", hosts=hosts, state=UpgradeState.DONE
        ):
            fx.driver_pod(n, ds, hash_suffix="v1")
    policy = TPUUpgradePolicySpec(
        auto_upgrade=True,
        max_parallel_upgrades=4,
        max_unavailable=IntOrString("25%"),
        drain_spec=DrainSpec(enable=True, timeout_second=5),
    )

    informer = Informer(cluster)
    cached = CachedKubeClient(cluster, informer=informer)
    mgr = ClusterUpgradeStateManager(cached, keys=keys)
    sync_before = sum(cluster.stats.values())
    informer.sync()
    sync_requests = sum(cluster.stats.values()) - sync_before

    before = sum(cluster.stats.values())
    for _ in range(ticks):
        state = mgr.build_state(NAMESPACE, DRIVER_LABELS, policy)
        mgr.apply_state(state, policy)
        if not mgr.wait_for_async_work(10.0):
            raise RuntimeError("async upgrade work did not drain")
    total = sum(cluster.stats.values()) - before

    return {
        "nodes": slices * hosts,
        "ticks": ticks,
        "sync_api_requests": sync_requests,
        "api_requests_total": total,
        "api_requests_per_tick": round(total / ticks, 3),
        "cache_hits": informer.stats["cache_hits"],
        "cache_misses": informer.stats["cache_misses"],
        "ceiling_per_tick": API_PER_TICK_CEILING,
    }


def main() -> int:
    result = measure()
    ok = result["api_requests_per_tick"] <= API_PER_TICK_CEILING
    result["ok"] = ok
    print(json.dumps(result, sort_keys=True))
    if not ok:
        print(
            "bench-guard FAIL: steady-state cached reconcile issued "
            f"{result['api_requests_per_tick']} API requests/tick at "
            f"{result['nodes']} nodes (ceiling "
            f"{API_PER_TICK_CEILING}) — a relist or per-node GET is "
            "back in the hot path",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
