"""Hot-path regression guard for the informer-backed cached reconcile,
the sharded dirty-set reconcile, and the fused probe battery.

``make bench-guard`` runs this standalone (no accelerator needed — the
probe stage runs on jax's virtual CPU mesh).  The core stages:

1. **Cached reconcile** (256 nodes): builds the steady-state pool from
   the scale pin (tests/test_scale.py), syncs an Informer, drives full
   reconcile ticks through a CachedKubeClient, and FAILS if the
   measured ``api_requests_per_tick`` regresses above the pinned
   ceiling.  The cache serves every read in steady state, so the true
   value is 0.0; the ceiling leaves no room for a per-node GET
   (256/tick) or a per-tick LIST (>= 4/tick) to sneak back in.

2. **Sharded dirty-set reconcile** (4096 nodes): seeds a
   ShardedReconciler from one full resync, then pins
   tick-cost-is-O(changed): idle ticks must walk exactly 0 pools and
   issue 0 API requests, idle p99 tick latency must stay under its
   ceiling, and a single watch delta must make the next tick walk
   exactly 1 pool (never the fleet).

3. **Incremental O(delta) reconcile** (100,000 nodes): seeds the
   materialized pool view and the copy-on-write snapshot path at fleet
   scale, then pins the whole read path: the full-resync
   view-vs-build_state audit reports 0 mismatches, idle ticks walk 0
   pools at 0 API requests, one watch delta reconciles exactly 1 pool
   *from the view* under a fixed ceiling, ``snapshot()`` reuses
   identity (zero full-map deep copies) under its build ceiling, and
   peak RSS stays inside a budget sized so one retained eager copy of
   the 200k-object fleet would blow through it.

4. **Fused probe battery** (8-device CPU mesh): runs the single-dispatch
   battery cold then warm and pins the compile-cache contract — the
   second run of the same topology MUST be a cache hit, the warm battery
   must finish under its per-node ceiling, and the full async validation
   gate (stamp -> healthy verdict through ValidationManager +
   LocalDeviceProber) must clear one slice under its wall-time ceiling.

bench.py imports ``measure()`` / ``measure_sharded()`` /
``measure_incremental()`` for its ``cached_reconcile`` /
``sharded_reconcile`` / ``incremental_100k`` stages so the nightly
artifact records the same numbers this gate enforces; its
``fused_battery`` artifact records the same cache-hit/warm-time
contract from the production-size battery on the real backend
(``measure_probe_battery()`` here re-pins it on a CPU mesh so CI needs
no accelerator).
"""

from __future__ import annotations

import json
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)
sys.path.insert(0, os.path.join(_ROOT, "tests"))

N_SLICES = 16
HOSTS_PER_SLICE = 16
TICKS = 5
# Average API round trips per steady-state tick through the cached
# client.  Pinned, not aspirational: the scale pin asserts exactly 0
# reads over 3 ticks, so anything above this ceiling is a reintroduced
# relist or per-node GET, never noise.
API_PER_TICK_CEILING = 0.5

# Sharded stage: the 4096-node pin.
SHARDED_N_SLICES = 256
SHARDED_HOSTS_PER_SLICE = 16
SHARDED_IDLE_TICKS = 200
# An idle dirty tick checks an empty queue and returns — O(µs).  The
# ceiling is 3+ orders of magnitude above that so only a real
# regression (an O(fleet) walk back in the idle path) can trip it,
# never scheduler noise.
SHARDED_IDLE_P99_CEILING_S = 0.05
# One dirty pool = one scoped build (16 nodes) + one scoped apply; a
# second of wall-clock means the scoped path regressed to O(fleet).
SHARDED_ACTIVE_TICK_CEILING_S = 1.0

# Incremental-view stage: the 100k-node O(delta) pin — 24x the sharded
# fleet, seeded through ONE full resync with the materialized view
# attached.  Pins: idle ticks walk exactly 0 pools and issue exactly 0
# API requests; one watch delta walks exactly 1 pool and the view (not
# a scoped rebuild) serves it (matview_hits >= 1) under the active
# ceiling; rebuilding the cluster-wide snapshot after a store write is
# SHALLOW (structure-shared COW — the eager per-object deepcopy this
# replaced costs seconds at 200k objects, the ceiling admits only the
# two dict copies); an unchanged store returns the IDENTICAL cached
# snapshot object; the resync view-vs-build_state audit reports 0
# mismatches; and process peak RSS stays inside its budget.
INC_N_SLICES = 6250
INC_HOSTS_PER_SLICE = 16  # 6250 x 16 = 100,000 nodes
INC_IDLE_TICKS = 50
# Same idle discipline as the sharded stage: an empty dirty queue is
# O(µs) regardless of fleet size — the ceiling only trips on an
# O(fleet) walk returning to the idle path.
INC_IDLE_P99_CEILING_S = 0.05
# One dirty pool = one 16-row view materialization + one scoped apply.
# Fleet size must NOT appear in this number: that is the whole pin.
INC_ACTIVE_TICK_CEILING_S = 1.0
# Unscoped snapshot rebuild after a store version bump: two shallow
# dict copies (100k nodes + 100k pods) plus shared kind maps.  The
# pre-COW eager snapshot deep-copied every object — seconds, not
# milliseconds — so the ceiling is the regression tripwire.
INC_SNAPSHOT_BUILD_CEILING_S = 0.5
# Peak RSS for the whole stage (fixture fleet + apiserver history +
# informer store + view rows + one full-resync materialization).
# Measured ~1.9 GiB standalone, ~2.4 GiB when the stage runs last in
# the full suite (ru_maxrss inherits the earlier fixtures' high-water
# mark); the budget leaves headroom for neither an extra retained copy
# of the 200k-object fleet (the eager-snapshot regression) nor a
# per-node deep copy creeping back into the view.
INC_RSS_CEILING_MIB = 4096

# Probe-battery stage: CPU-sized battery (the pins are about CACHING
# and dispatch-count, which are size-independent — real-hardware sizes
# would just melt a CI box).
BATTERY_MATMUL_N = 256
BATTERY_HBM_MIB = 4
BATTERY_ALLREDUCE_ELEMS = 1 << 14
# Warm fused battery per node — the tentpole number: node 2..N of a
# topology pays a single XLA dispatch, never a recompile.  A breach
# means the topology key churned (cache miss) or the battery grew a
# second dispatch.
BATTERY_WARM_CEILING_S = 1.0
# Full async validation gate (stamp -> healthy) for one slice with a
# warm compile cache, including worker-thread handoff latency.
VALIDATION_WALL_CEILING_S = 10.0

# Elastic-roll stage: 4 operator slices mapped onto the 8-device CPU
# mesh (2 devices per slice), rolled end-to-end through the negotiation
# protocol with a live ElasticCanaryRunner.
ELASTIC_N_SLICES = 4
# The canary steps every few ms and a precompiled resize costs ~one
# step, so across a WHOLE 4-slice roll the longest inter-step gap stays
# at canary-step granularity.  The ceiling is ~100 canary steps — tight
# enough that any drain fallback (the job parked while pods restart,
# seconds at minimum) or a resize that recompiles trips it, loose
# enough for CI scheduler noise.  Downtime is reported as 0.00 s only
# when the gap stays under it.
ELASTIC_GAP_CEILING_S = 0.5

# Write-hygiene stage: the write-plane pins.  An active 256-node roll
# coalesces every node transition (state label + its companion clock
# annotations) into one metadata patch, so the budget per observed
# state transition is the patch itself plus at most one scheduling
# write (cordon/uncordon rides the same budget).  Anything above 2
# means the plane stopped coalescing or a producer is writing around
# it.
WH_N_SLICES = 16
WH_HOSTS_PER_SLICE = 16
WH_WRITES_PER_TRANSITION_CEILING = 2.0
# A 4096-node sharded fleet with no dirty pools must issue exactly 0
# API writes per idle tick — suppression is a pin, not a target.
WH_IDLE_TICKS = 50
# Storm of identical events (same object/reason/message inside one
# aggregation window) must collapse at least 10:1 into count-carrying
# publishes.
WH_EVENT_STORM = 50
WH_EVENT_COLLAPSE_FLOOR = 10.0

# Planner stage: the predictive-planning pins.  A 4096-node
# mixed-generation fleet must plan in under a second with exactly zero
# API write verbs (planning is analytic — any write means a side effect
# crept into the read path), and on a smaller mixed fleet the digital
# twin (the REAL engine on a cloned cluster + accelerated clock) must
# reproduce the analytic wave schedule exactly.
PLANNER_N_SLICES = 256
PLANNER_HOSTS_PER_SLICE = 16
PLAN_WALL_CEILING_S = 1.0
PLANNER_TWIN_N_SLICES = 12
PLANNER_TWIN_HOSTS = 4

# Packed-admission stage: the plan-guided FFD pins.  A mixed-SIZE
# 256-node fleet under a node-unit budget that no slice size divides
# (5): greedy id-order admission strands budget whenever a 4-host slice
# follows the 1-host slices (4 > residual 1), while packed
# (first-fit-decreasing off the anchored plan) pairs a quad with a
# single every wave.  Packed must beat greedy STRICTLY on both the
# analytic wave count and the live-engine roll, the engine's packed
# admission schedule must agree with the analytic packed plan exactly,
# and neither mode may ever leave affordable pending work on the table
# (budget_idle_ticks == 0).
PACKED_N_SINGLES = 56
PACKED_N_QUADS = 50  # 56*1 + 50*4 = 256 nodes
PACKED_BUDGET_NODES = 5
PACKED_PARALLEL = 8
PACKED_TWIN_SINGLES = 4
PACKED_TWIN_QUADS = 4

# Tracing stage: the observe-only pins.  The same 256-node active roll
# run twice — recorder off, recorder on — must show < 5% p99 tick
# overhead (the taps are O(1) dict work at existing choke points, so
# anything above the ceiling is a new allocation or lock on the hot
# path); the traced roll must complete into ONE connected span tree
# with zero open spans whose critical-path buckets sum to the measured
# makespan within 1% (the attribution walk charges every second
# exactly once); a 4096-node idle sharded fleet with tracing on must
# still walk 0 pools and issue 0 writes; and a black-box trigger storm
# must stay under the spool byte cap (oldest-first deletion) while
# still dumping.
TRACING_N_SLICES = 16
TRACING_HOSTS_PER_SLICE = 16
TRACING_OVERHEAD_CEILING_PCT = 5.0
# Absolute grace on the p99 comparison: two runs of identical
# in-process work still differ by a few ms of scheduler/GC jitter,
# which at ~tens-of-ms ticks would drown a genuine 5% signal.
TRACING_OVERHEAD_GRACE_S = 0.005
# A roll is ~30 ticks, so its p99 is effectively its single slowest
# tick; comparing one roll per leg makes the pin a coin-flip on
# scheduler jitter.  Each leg runs this many times and the pin takes
# the MIN p99 per leg (the timeit estimator: noise only ever inflates
# a measurement, so the floor is the code's structural cost).  Five
# reps (~5 s each pair) keeps the floor honest even on a loaded CI
# box, where with three reps every rep of one leg can still catch a
# GC pause in its single slowest tick.
TRACING_TIMING_REPS = 5
TRACING_BUCKET_TOLERANCE_PCT = 1.0
TRACING_IDLE_TICKS = 25
TRACING_STORM_TRIGGERS = 100
TRACING_SPOOL_CAP_BYTES = 64 * 1024

# Telemetry stage: the fleet-health pins.  (1) Verdict correctness on a
# 256-node mixed-generation fleet whose histories arrive through the
# durable-adoption path: exactly one node injected 25% below its
# generation's median must be flagged within one roll's worth of
# batteries, and the other 255 (carrying realistic ±0.8% jitter) must
# produce ZERO false positives.  (2) Write parity on a live roll: the
# same roll with the telemetry plane attached and detached must issue
# an IDENTICAL total API write-verb count — per-node history rides the
# existing combined transition patch, never its own write — while still
# persisting a non-empty ring annotation on every node.
TELEMETRY_GENERATIONS = [
    ("tpu-v4-podslice", "pool-v4", 240.0),
    ("tpu-v5-lite-podslice", "pool-v5e", 360.0),
    ("tpu-v6e-slice", "pool-v6e", 880.0),
]
TELEMETRY_N_NODES = 256
# The injected straggler runs at this fraction of its generation's
# median (25% below — the acceptance scenario).
TELEMETRY_STRAGGLER_FRACTION = 0.75
TELEMETRY_ROLL_SLICES = 4
TELEMETRY_ROLL_HOSTS = 4

# Federation stage: the partition-tolerance pins.  Three 256-node
# member clusters (64 slices x 4 hosts each) roll one global policy
# through the FederationCoordinator; mid-roll one non-canary cluster
# is partitioned (every API verb fails) for a 20-tick window.  Pins:
# the coordinator must mark it skipped on every window tick, issue
# ZERO mutating API verbs against it for the whole window, record
# ZERO global-budget violations over the entire roll, and still
# converge all three clusters to upgrade-done after the heal.  The
# durable store must stay phase-proportional: its write count is
# capped well below the tick count (state is persisted on phase
# edges, never per tick).
FED_N_CLUSTERS = 3
FED_SLICES_PER_CLUSTER = 64
FED_HOSTS_PER_SLICE = 4
FED_PARTITION_TICKS = 20
FED_STORE_WRITE_CEILING = 8
FED_MAX_TICKS = 600

# Multi-artifact stage: the shared-window pins.  The same 256-node
# fleet (64 slices x 4 hosts, every node carrying a driver + network
# driver + device-plugin pod) is rolled twice — once under a classic
# single-DaemonSet policy, once under a 3-artifact pinned-order stack
# (driver -> net -> plugin) — and the stack roll must amortize ONE
# window per node: exactly 1 cordon and 1 drain-window entry per node,
# exactly 1 BudgetLedger charge per slice group for the whole stack,
# and the per-verb API write delta versus the classic roll must be
# EXACTLY the two extra artifacts' own pod restarts (2 deletes + the
# DS-controller's 2 recreates per node) — zero extra node patches,
# events, or any other write verb per additional artifact.
MULTI_ART_N_SLICES = 64
MULTI_ART_HOSTS_PER_SLICE = 4
MULTI_ART_EXTRA_ARTIFACTS = 2  # net + plugin ride the driver's window
MULTI_ART_MAX_TICKS = 400


def measure(
    slices: int = N_SLICES,
    hosts: int = HOSTS_PER_SLICE,
    ticks: int = TICKS,
) -> dict:
    """One steady-state cached-reconcile measurement; returns the
    artifact dict (also embedded in BENCH_DETAILS.json by bench.py)."""
    from k8s_operator_libs_tpu.api import (
        DrainSpec,
        IntOrString,
        TPUUpgradePolicySpec,
    )
    from k8s_operator_libs_tpu.k8s import FakeCluster
    from k8s_operator_libs_tpu.k8s.informer import (
        CachedKubeClient,
        Informer,
    )
    from k8s_operator_libs_tpu.upgrade import (
        ClusterUpgradeStateManager,
        UpgradeKeys,
        UpgradeState,
    )

    from fixtures import ClusterFixture, DRIVER_LABELS, NAMESPACE

    keys = UpgradeKeys()
    cluster = FakeCluster()
    fx = ClusterFixture(cluster, keys)
    ds = fx.daemon_set(hash_suffix="v1", revision=1)
    # Already-rolled pool: every node done, every pod at the current
    # revision — the state a controller sits in 99% of its life.
    for i in range(slices):
        for n in fx.tpu_slice(
            f"pool-{i:02d}", hosts=hosts, state=UpgradeState.DONE
        ):
            fx.driver_pod(n, ds, hash_suffix="v1")
    policy = TPUUpgradePolicySpec(
        auto_upgrade=True,
        max_parallel_upgrades=4,
        max_unavailable=IntOrString("25%"),
        drain_spec=DrainSpec(enable=True, timeout_second=5),
    )

    informer = Informer(cluster)
    cached = CachedKubeClient(cluster, informer=informer)
    mgr = ClusterUpgradeStateManager(cached, keys=keys)
    sync_before = sum(cluster.stats.values())
    informer.sync()
    sync_requests = sum(cluster.stats.values()) - sync_before

    before = sum(cluster.stats.values())
    for _ in range(ticks):
        state = mgr.build_state(NAMESPACE, DRIVER_LABELS, policy)
        mgr.apply_state(state, policy)
        if not mgr.wait_for_async_work(10.0):
            raise RuntimeError("async upgrade work did not drain")
    total = sum(cluster.stats.values()) - before

    return {
        "nodes": slices * hosts,
        "ticks": ticks,
        "sync_api_requests": sync_requests,
        "api_requests_total": total,
        "api_requests_per_tick": round(total / ticks, 3),
        "cache_hits": informer.stats["cache_hits"],
        "cache_misses": informer.stats["cache_misses"],
        "ceiling_per_tick": API_PER_TICK_CEILING,
    }


def measure_sharded(
    slices: int = SHARDED_N_SLICES,
    hosts: int = SHARDED_HOSTS_PER_SLICE,
    idle_ticks: int = SHARDED_IDLE_TICKS,
) -> dict:
    """Tick-cost-is-O(changed) measurement at 4096 nodes; returns the
    artifact dict (also embedded in BENCH_DETAILS.json by bench.py)."""
    import time

    from k8s_operator_libs_tpu.api import (
        DrainSpec,
        IntOrString,
        TPUUpgradePolicySpec,
    )
    from k8s_operator_libs_tpu.k8s import FakeCluster
    from k8s_operator_libs_tpu.k8s.client import WatchEvent
    from k8s_operator_libs_tpu.k8s.informer import (
        CachedKubeClient,
        Informer,
    )
    from k8s_operator_libs_tpu.upgrade import (
        ClusterUpgradeStateManager,
        UpgradeKeys,
        UpgradeState,
    )
    from k8s_operator_libs_tpu.upgrade.sharded import ShardedReconciler

    from fixtures import ClusterFixture, DRIVER_LABELS, NAMESPACE

    keys = UpgradeKeys()
    cluster = FakeCluster()
    fx = ClusterFixture(cluster, keys)
    ds = fx.daemon_set(hash_suffix="v1", revision=1)
    for i in range(slices):
        for n in fx.tpu_slice(
            f"pool-{i:03d}", hosts=hosts, state=UpgradeState.DONE
        ):
            fx.driver_pod(n, ds, hash_suffix="v1")
    policy = TPUUpgradePolicySpec(
        auto_upgrade=True,
        max_parallel_upgrades=4,
        max_unavailable=IntOrString("25%"),
        drain_spec=DrainSpec(enable=True, timeout_second=5),
    )

    informer = Informer(
        cluster, pod_namespace=NAMESPACE, pod_match_labels=DRIVER_LABELS
    )
    cached = CachedKubeClient(cluster, informer=informer)
    mgr = ClusterUpgradeStateManager(cached, keys=keys)
    informer.sync()
    sharded = ShardedReconciler(mgr, NAMESPACE, DRIVER_LABELS, shards=4)
    try:
        # Seed: exactly one full resync (registry + ledger), then the
        # controller would only ever run dirty ticks until the next
        # resync interval.
        t0 = time.monotonic()
        state = mgr.build_state(NAMESPACE, DRIVER_LABELS, policy)
        started = sharded.observe_full_state(state, policy, started=t0)
        mgr.apply_state(state, policy)
        sharded.complete_full_resync(started)
        seed_resync_s = time.monotonic() - t0

        api_before = sum(cluster.stats.values())
        idle_walked = 0
        idle_durations: list[float] = []
        for _ in range(idle_ticks):
            report = sharded.tick(policy)
            idle_walked += report.pools_walked
            idle_durations.append(report.duration_s)
        idle_api = sum(cluster.stats.values()) - api_before
        idle_durations.sort()
        p50 = idle_durations[len(idle_durations) // 2]
        p99 = idle_durations[int(len(idle_durations) * 0.99)]

        # One watch delta on one node: the next tick must walk exactly
        # that node's pool and nothing else.
        node = cluster.get_node("pool-000-w0", cached=False)
        sharded.handle_event(WatchEvent("MODIFIED", "Node", node, 1))
        t0 = time.monotonic()
        report = sharded.tick(policy)
        active_tick_s = time.monotonic() - t0
        if not sharded.wait_idle(30.0):
            raise RuntimeError("sharded reconcile did not drain")
    finally:
        sharded.shutdown()

    return {
        "nodes": slices * hosts,
        "pools": slices,
        "seed_resync_s": round(seed_resync_s, 3),
        "idle_ticks": idle_ticks,
        "idle_pools_walked_total": idle_walked,
        "idle_api_requests_total": idle_api,
        "idle_p50_tick_s": round(p50, 6),
        "idle_p99_tick_s": round(p99, 6),
        "active_pools_walked": report.pools_walked,
        "active_tick_s": round(active_tick_s, 4),
        "idle_p99_ceiling_s": SHARDED_IDLE_P99_CEILING_S,
        "active_tick_ceiling_s": SHARDED_ACTIVE_TICK_CEILING_S,
    }


def measure_incremental(
    slices: int = INC_N_SLICES,
    hosts: int = INC_HOSTS_PER_SLICE,
    idle_ticks: int = INC_IDLE_TICKS,
) -> dict:
    """O(delta) reconcile at 100,000 nodes through the materialized
    view + COW snapshots; returns the artifact dict (also embedded in
    BENCH_DETAILS.json by bench.py)."""
    import resource
    import time

    from k8s_operator_libs_tpu.api import (
        DrainSpec,
        IntOrString,
        TPUUpgradePolicySpec,
    )
    from k8s_operator_libs_tpu.k8s import FakeCluster
    from k8s_operator_libs_tpu.k8s.client import WatchEvent
    from k8s_operator_libs_tpu.k8s.informer import (
        CachedKubeClient,
        Informer,
    )
    from k8s_operator_libs_tpu.k8s.objects import (
        ContainerStatus,
        ObjectMeta,
        OwnerReference,
        Pod,
        PodPhase,
        PodSpec,
        PodStatus,
    )
    from k8s_operator_libs_tpu.upgrade import (
        ClusterUpgradeStateManager,
        UpgradeKeys,
        UpgradeState,
    )
    from k8s_operator_libs_tpu.upgrade.sharded import ShardedReconciler

    from fixtures import ClusterFixture, DRIVER_LABELS, NAMESPACE

    keys = UpgradeKeys()
    cluster = FakeCluster()
    fx = ClusterFixture(cluster, keys)
    ds = fx.daemon_set(hash_suffix="v1", revision=1)
    selector = dict(ds.spec.selector.match_labels)
    t0 = time.monotonic()
    for i in range(slices):
        for n in fx.tpu_slice(
            f"pool-{i:04d}", hosts=hosts, state=UpgradeState.DONE
        ):
            # fixtures.driver_pod read-modify-writes the DaemonSet once
            # per pod — 100k updates of one object just to build the
            # fixture.  Create the pod directly and settle the DS
            # status in ONE write below.
            labels = dict(selector)
            labels["controller-revision-hash"] = "v1"
            meta = ObjectMeta(
                name=f"driver-{n.name}",
                namespace=ds.namespace,
                labels=labels,
            )
            meta.owner_references = [
                OwnerReference(
                    name=ds.name, uid=ds.metadata.uid, kind="DaemonSet"
                )
            ]
            cluster.create_pod(
                Pod(
                    metadata=meta,
                    spec=PodSpec(node_name=n.name),
                    status=PodStatus(
                        phase=PodPhase.RUNNING,
                        container_statuses=[
                            ContainerStatus(ready=True, restart_count=0)
                        ],
                    ),
                )
            )
    ds.status.desired_number_scheduled = slices * hosts
    cluster.update_daemon_set(ds)
    fleet_build_s = time.monotonic() - t0

    policy = TPUUpgradePolicySpec(
        auto_upgrade=True,
        max_parallel_upgrades=4,
        max_unavailable=IntOrString("25%"),
        drain_spec=DrainSpec(enable=True, timeout_second=5),
    )

    # The seed resync at this scale takes longer than the default
    # freshness bound; the stage pins tick cost, not staleness policy.
    informer = Informer(
        cluster,
        pod_namespace=NAMESPACE,
        pod_match_labels=DRIVER_LABELS,
        max_staleness_s=600.0,
    )
    cached = CachedKubeClient(cluster, informer=informer)
    mgr = ClusterUpgradeStateManager(cached, keys=keys)
    t0 = time.monotonic()
    informer.sync()
    sync_s = time.monotonic() - t0
    sharded = ShardedReconciler(mgr, NAMESPACE, DRIVER_LABELS, shards=4)
    try:
        # Seed: exactly one full resync.  observe_full_state audits the
        # materialized view against this build and reseeds it from a
        # COW snapshot — the audit must be clean on an untouched fleet.
        t0 = time.monotonic()
        state = mgr.build_state(NAMESPACE, DRIVER_LABELS, policy)
        started = sharded.observe_full_state(state, policy, started=t0)
        mgr.apply_state(state, policy)
        sharded.complete_full_resync(started)
        seed_resync_s = time.monotonic() - t0
        diff_mismatches = sharded.stats.get("matview_diff_mismatches", 0)

        # COW snapshot pins.  A store version bump invalidates the
        # cached snapshot; the rebuild must be shallow (two dict copies
        # + shared kind maps), and an untouched store must return the
        # IDENTICAL object, not an equal one.
        node = cluster.get_node("pool-0000-w0", cached=False)
        informer.handle_event(
            WatchEvent(
                "MODIFIED", "Node", node, node.metadata.resource_version
            )
        )
        t0 = time.monotonic()
        snap1 = informer.snapshot()
        snapshot_build_s = time.monotonic() - t0
        snapshot_reused = informer.snapshot() is snap1
        snapshot_shared = bool(getattr(snap1, "shared", False))

        api_before = sum(cluster.stats.values())
        idle_walked = 0
        idle_durations: list[float] = []
        for _ in range(idle_ticks):
            report = sharded.tick(policy)
            idle_walked += report.pools_walked
            idle_durations.append(report.duration_s)
        idle_api = sum(cluster.stats.values()) - api_before
        idle_durations.sort()
        p50 = idle_durations[len(idle_durations) // 2]
        p99 = idle_durations[int(len(idle_durations) * 0.99)]

        # One watch delta, fed the way the controller feeds it: informer
        # ingest (the view applies it in O(1)) + dirty-pool routing.
        # The next tick must walk exactly that pool, and the view — not
        # a scoped build_state — must serve it.
        node = cluster.get_node(
            f"pool-{slices // 2:04d}-w{hosts // 2}", cached=False
        )
        ev = WatchEvent(
            "MODIFIED", "Node", node, node.metadata.resource_version
        )
        t0 = time.monotonic()
        informer.handle_event(ev)
        delta_apply_s = time.monotonic() - t0
        sharded.handle_event(ev)
        hits_before = sharded.stats.get("matview_hits", 0)
        t0 = time.monotonic()
        report = sharded.tick(policy)
        active_tick_s = time.monotonic() - t0
        if not sharded.wait_idle(60.0):
            raise RuntimeError("incremental reconcile did not drain")
        matview_hits = sharded.stats.get("matview_hits", 0) - hits_before
        view_stats = (
            sharded.matview.snapshot_stats()
            if sharded.matview is not None
            else {}
        )
    finally:
        sharded.shutdown()

    # ru_maxrss is KiB on Linux, bytes on macOS.
    maxrss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    peak_rss_mib = (
        maxrss / 1024 if sys.platform != "darwin" else maxrss / 2**20
    )

    return {
        "nodes": slices * hosts,
        "pools": slices,
        "fleet_build_s": round(fleet_build_s, 3),
        "sync_s": round(sync_s, 3),
        "seed_resync_s": round(seed_resync_s, 3),
        "resync_diff_mismatches": diff_mismatches,
        "snapshot_build_s": round(snapshot_build_s, 6),
        "snapshot_reused": snapshot_reused,
        "snapshot_shared": snapshot_shared,
        "idle_ticks": idle_ticks,
        "idle_pools_walked_total": idle_walked,
        "idle_api_requests_total": idle_api,
        "idle_p50_tick_s": round(p50, 6),
        "idle_p99_tick_s": round(p99, 6),
        "delta_apply_s": round(delta_apply_s, 6),
        "active_pools_walked": report.pools_walked,
        "active_tick_s": round(active_tick_s, 4),
        "matview_hits": matview_hits,
        "matview_rows": view_stats.get("rows", 0),
        "matview_pools": view_stats.get("pools", 0),
        "matview_interned_strings": view_stats.get("interned_strings", 0),
        "peak_rss_mib": round(peak_rss_mib, 1),
        "idle_p99_ceiling_s": INC_IDLE_P99_CEILING_S,
        "active_tick_ceiling_s": INC_ACTIVE_TICK_CEILING_S,
        "snapshot_build_ceiling_s": INC_SNAPSHOT_BUILD_CEILING_S,
        "rss_ceiling_mib": INC_RSS_CEILING_MIB,
    }


def measure_probe_battery() -> dict:
    """Cold/warm fused-battery + async-gate measurement on the virtual
    CPU mesh; returns the artifact dict (also embedded in
    BENCH_DETAILS.json by bench.py)."""
    import time

    # Keep the unfused fallback (if the battery ever falls back here)
    # from escalating its sustained-measurement loops on a busy CI box.
    os.environ.setdefault("K8S_TPU_PROBE_MIN_TIME_S", "0.01")
    from k8s_operator_libs_tpu import hostenv

    hostenv.pin_current_process_to_cpu(default_host_device_count=8)

    from k8s_operator_libs_tpu.health import fused
    from k8s_operator_libs_tpu.health.probes import run_host_probe

    sizes = dict(
        matmul_n=BATTERY_MATMUL_N,
        hbm_mib=BATTERY_HBM_MIB,
        allreduce_elems=BATTERY_ALLREDUCE_ELEMS,
    )
    fused.reset_battery_cache()
    t0 = time.monotonic()
    cold_checks = run_host_probe(fused=True, **sizes)
    cold_s = time.monotonic() - t0
    # Second node of the same topology: identical key, zero compile.
    t0 = time.monotonic()
    warm_checks = run_host_probe(fused=True, **sizes)
    warm_s = time.monotonic() - t0
    stats = fused.battery_stats()
    warm_hit = any(
        c.metrics.get("battery_cache_hit") == 1.0 for c in warm_checks
    )

    # Async pipelined gate: wall-clock from validation stamp to healthy
    # verdict for one slice, probed on a worker thread (warm cache).
    from k8s_operator_libs_tpu.health.slice_prober import LocalDeviceProber
    from k8s_operator_libs_tpu.k8s import FakeCluster
    from k8s_operator_libs_tpu.upgrade import UpgradeKeys
    from k8s_operator_libs_tpu.upgrade.node_state_provider import (
        NodeUpgradeStateProvider,
    )
    from k8s_operator_libs_tpu.upgrade.types import (
        NodeUpgradeState,
        UpgradeGroup,
    )
    from k8s_operator_libs_tpu.upgrade.validation_manager import (
        ValidationManager,
    )

    from fixtures import make_node

    keys = UpgradeKeys()
    cluster = FakeCluster()
    cluster.create_node(make_node("bench-val-0"))
    provider = NodeUpgradeStateProvider(
        cluster, keys, poll_interval_s=0.005, poll_timeout_s=5.0
    )
    vm = ValidationManager(
        cluster,
        provider,
        keys,
        prober=LocalDeviceProber(fused=True, **sizes),
        timeout_seconds=60,
    )
    gate_passed = False
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
        fresh = cluster.get_node("bench-val-0", cached=False)
        group = UpgradeGroup(
            id="bench-slice", members=[NodeUpgradeState(node=fresh)]
        )
        if vm.validate(group):
            gate_passed = True
            break
        time.sleep(0.01)
    vm.wait_idle(10.0)
    validation_wall_s = vm.validation_wall_s.get("bench-slice", -1.0)

    return {
        "devices": 8,
        "cold_s": round(cold_s, 4),
        "warm_s": round(warm_s, 4),
        "warm_cache_hit": warm_hit,
        "compile_cache_hits": stats["compile_cache_hits"],
        "compile_cache_misses": stats["compile_cache_misses"],
        "fallbacks": stats["fallbacks"],
        "checks_ok": all(c.ok for c in cold_checks)
        and all(c.ok for c in warm_checks),
        "gate_passed": gate_passed,
        "validation_wall_s": round(validation_wall_s, 4),
        "warm_ceiling_s": BATTERY_WARM_CEILING_S,
        "validation_wall_ceiling_s": VALIDATION_WALL_CEILING_S,
    }


def measure_elastic(
    accept: bool = True, devices=None, pin_cpu: bool = True
) -> dict:
    """One end-to-end elastic roll; returns the artifact dict (also
    embedded in BENCH_DETAILS.json by bench.py).

    ``accept=True`` rolls every slice through the negotiation protocol
    with a live ElasticCanaryRunner answering offers and measures the
    canary's longest inter-step gap across the whole roll — the
    zero-downtime headline.  ``accept=False`` declines every offer and
    verifies the roll still completes end-to-end on the drain path.

    Standalone (bench-guard) the stage pins the process to the 8-device
    virtual CPU mesh; bench.py passes its real ``devices`` with
    ``pin_cpu=False`` (pinning would repoint the whole bench process)."""
    import time

    os.environ.setdefault("K8S_TPU_PROBE_MIN_TIME_S", "0.01")
    if pin_cpu:
        from k8s_operator_libs_tpu import hostenv

        hostenv.pin_current_process_to_cpu(default_host_device_count=8)

    import jax

    from k8s_operator_libs_tpu.api import (
        DrainSpec,
        ElasticCoordinationSpec,
        IntOrString,
        TPUUpgradePolicySpec,
    )
    from k8s_operator_libs_tpu.coordination import (
        RunnerElasticRuntime,
        WorkloadCoordinator,
    )
    from k8s_operator_libs_tpu.k8s import FakeCluster
    from k8s_operator_libs_tpu.upgrade import (
        ClusterUpgradeStateManager,
        UpgradeKeys,
        UpgradeState,
    )
    from k8s_operator_libs_tpu.workloads.canary import (
        CanaryConfig,
        ElasticCanaryRunner,
    )

    from fixtures import ClusterFixture, DRIVER_LABELS, NAMESPACE
    from test_upgrade_state import FakeProber

    keys = UpgradeKeys()
    cluster = FakeCluster()
    fx = ClusterFixture(cluster, keys)
    ds = fx.daemon_set(hash_suffix="v1", revision=1)
    slice_ids = [f"pool-{i}" for i in range(ELASTIC_N_SLICES)]
    slice_nodes = {}
    for sid in slice_ids:
        nodes = fx.tpu_slice(sid, hosts=1)
        slice_nodes[sid] = [n.name for n in nodes]
        for n in nodes:
            fx.driver_pod(n, ds, hash_suffix="v1")
    fx.bump_daemon_set_template(ds, "v2", revision=2)
    fx.auto_recreate_driver_pods(ds, "v2")

    mgr = ClusterUpgradeStateManager(
        cluster, keys=keys, poll_interval_s=0.005, poll_timeout_s=2.0
    )
    mgr.with_validation_enabled(FakeProber(healthy=True))
    policy = TPUUpgradePolicySpec(
        auto_upgrade=True,
        max_parallel_upgrades=1,
        max_unavailable=IntOrString("25%"),
        unavailability_unit="slice",
        drain_spec=DrainSpec(enable=True, timeout_second=5),
        elastic=ElasticCoordinationSpec(
            enable=True, offer_timeout_second=60, rejoin_timeout_second=60
        ),
    )

    devs = list(devices) if devices is not None else list(jax.devices())
    cfg = CanaryConfig(
        vocab=64, d_model=32, n_heads=2, n_layers=1, d_ff=64,
        seq_len=16, batch=8,
    )
    runner = ElasticCanaryRunner(
        cfg, devices=devs, n_slices=ELASTIC_N_SLICES, seed=0
    )
    coordinator = WorkloadCoordinator(
        cluster,
        keys,
        "bench-canary",
        slice_nodes,
        RunnerElasticRuntime(
            runner, {sid: i for i, sid in enumerate(slice_ids)}
        ),
        accept_policy=lambda sid: accept,
    )
    coordinator.register()

    for _ in range(4):  # warmup: compiles stay out of the gap window
        runner.run_step()
    runner.reset_timing()

    all_names = [nm for names in slice_nodes.values() for nm in names]
    converged = False
    for _ in range(200):
        state = mgr.build_state(NAMESPACE, DRIVER_LABELS, policy)
        mgr.apply_state(state, policy)
        if not mgr.wait_for_async_work(10.0):
            raise RuntimeError("async upgrade work did not drain")
        coordinator.poll_once()
        for _ in range(3):
            runner.run_step()
        if all(
            cluster.get_node(nm).labels.get(keys.state_label)
            == UpgradeState.DONE.value
            for nm in all_names
        ):
            converged = True
            break
    max_gap_s = runner.max_gap_seconds(until=time.monotonic())
    perf = runner.perf_summary()
    # Downtime at canary-step granularity: a gap under the ceiling is
    # the normal step cadence (resize included), not an interruption.
    downtime_s = 0.0 if max_gap_s <= ELASTIC_GAP_CEILING_S else max_gap_s

    leftover_excluded = sum(
        1
        for nm in all_names
        if cluster.get_node(nm).annotations.get(
            keys.elastic_excluded_annotation
        )
        == "true"
    )
    return {
        "variant": "accept" if accept else "decline",
        "slices": ELASTIC_N_SLICES,
        "devices": len(devs),
        "physical_partition": runner.physical,
        "converged": converged,
        "downtime_s": round(downtime_s, 2),
        "max_gap_s": round(max_gap_s, 4),
        "median_step_s": perf.get("median_step_s", 0.0),
        "canary_steps": len(runner.step_times),
        "negotiations": dict(mgr.elastic_negotiations),
        "resizes": dict(mgr.elastic_resizes),
        "runner_resizes": len(runner.resize_events),
        "leftover_excluded": leftover_excluded,
        "gap_ceiling_s": ELASTIC_GAP_CEILING_S,
    }


def measure_heterogeneous(max_ticks: int = 400) -> dict:
    """Heterogeneous-fleet stage: one CR rolls a v4 + v5e + v6e pool mix
    under a serial fleet budget; returns the artifact dict (also
    embedded in BENCH_DETAILS.json by bench.py).

    The pins: (1) admission is oldest-generation-first (the v4 canary
    enters the roll before v5e, v5e before v6e); (2) a pool outside its
    maintenance window makes ZERO state transitions and holds ZERO
    budget — the other pools must spend it while the held pool waits —
    and once the window opens the whole fleet converges."""
    import time

    from k8s_operator_libs_tpu.api import (
        IntOrString,
        TPUUpgradePolicySpec,
    )
    from k8s_operator_libs_tpu.api.v1alpha1 import (
        MaintenanceWindowSpec,
        PoolSpec,
    )
    from k8s_operator_libs_tpu.k8s import FakeCluster
    from k8s_operator_libs_tpu.upgrade import (
        ClusterUpgradeStateManager,
        UpgradeKeys,
    )
    from k8s_operator_libs_tpu.upgrade.consts import (
        GKE_TPU_ACCELERATOR_LABEL,
    )

    from fixtures import ClusterFixture, DRIVER_LABELS, NAMESPACE

    keys = UpgradeKeys()
    cluster = FakeCluster()
    fx = ClusterFixture(cluster, keys)
    ds = fx.daemon_set(hash_suffix="v1", revision=1)
    pools = {
        "v4": "tpu-v4-podslice",
        "v5e": "tpu-v5-lite-podslice",
        "v6e": "tpu-v6e-slice",
    }
    slices = {
        gen: fx.tpu_slice(
            f"{gen}-0", hosts=2, topology="2x2x2", accelerator=accel
        )
        for gen, accel in pools.items()
    }
    for nodes in slices.values():
        for n in nodes:
            fx.driver_pod(n, ds, hash_suffix="v1")
    fx.bump_daemon_set_template(ds, "v2", revision=2)
    fx.auto_recreate_driver_pods(ds, "v2")

    # The v6e pool's window is closed until the two older pools finish
    # (a 1-minute window half an hour away fails closed now).
    closed_cron = f"{(time.gmtime().tm_min + 30) % 60} * * * *"
    policy = TPUUpgradePolicySpec(
        auto_upgrade=True,
        max_parallel_upgrades=1,
        max_unavailable=IntOrString(1),
        unavailability_unit="slice",
        pools=[
            PoolSpec(
                name=gen,
                node_selector={GKE_TPU_ACCELERATOR_LABEL: accel},
                maintenance_window=(
                    MaintenanceWindowSpec(cron=closed_cron)
                    if gen == "v6e"
                    else None
                ),
            )
            for gen, accel in pools.items()
        ],
    )
    policy.validate()
    mgr = ClusterUpgradeStateManager(
        cluster, keys=keys, poll_interval_s=0.005, poll_timeout_s=2.0
    )

    held_nodes = {n.name for n in slices["v6e"]}
    held_transitions = 0
    orig_patch = cluster.patch_node_labels

    def watch_patch(name, patch):
        nonlocal held_transitions
        if keys.state_label in patch and name in held_nodes:
            held_transitions += 1
        return orig_patch(name, patch)

    cluster.patch_node_labels = watch_patch

    def pool_states(gen):
        return {
            cluster.get_node(n.name, cached=False).labels.get(
                keys.state_label, ""
            )
            for n in slices[gen]
        }

    settled = {"", "upgrade-required", "upgrade-done"}
    first_admit: dict[str, int] = {}
    transitions_while_closed = held_cordons_while_closed = 0
    window_opened = False
    converged = False
    t0 = time.monotonic()
    for tick in range(max_ticks):
        state = mgr.build_state(NAMESPACE, DRIVER_LABELS, policy)
        mgr.apply_state(state, policy)
        mgr.wait_for_async_work(30.0)
        states = {gen: pool_states(gen) for gen in pools}
        for gen, st in states.items():
            if st - settled and gen not in first_admit:
                first_admit[gen] = tick
        if not window_opened:
            transitions_while_closed = held_transitions
            held_cordons_while_closed += sum(
                1
                for n in slices["v6e"]
                if cluster.get_node(n.name, cached=False).spec.unschedulable
            )
            if (
                states["v4"] == {"upgrade-done"}
                and states["v5e"] == {"upgrade-done"}
            ):
                policy.pools[2].maintenance_window = MaintenanceWindowSpec(
                    cron="* * * * *"
                )
                window_opened = True
        if all(st == {"upgrade-done"} for st in states.values()):
            converged = True
            break
    wall_s = time.monotonic() - t0

    order = sorted(first_admit, key=first_admit.get)
    return {
        "stage": "heterogeneous",
        "pools": len(pools),
        "nodes": sum(len(ns) for ns in slices.values()),
        "converged": converged,
        "window_opened": window_opened,
        "ticks": tick + 1,
        "wall_s": round(wall_s, 3),
        "first_admit_ticks": first_admit,
        "admission_order": order,
        "oldest_first": order[:2] == ["v4", "v5e"],
        "held_transitions_while_closed": transitions_while_closed,
        "held_cordons_while_closed": held_cordons_while_closed,
        "window_held_groups_peak": 1 if window_opened else 0,
    }


def measure_write_hygiene(
    slices: int = WH_N_SLICES,
    hosts: int = WH_HOSTS_PER_SLICE,
    idle_slices: int = SHARDED_N_SLICES,
    idle_hosts: int = SHARDED_HOSTS_PER_SLICE,
    idle_ticks: int = WH_IDLE_TICKS,
    storm: int = WH_EVENT_STORM,
) -> dict:
    """Write-plane hygiene measurement; returns the artifact dict (also
    embedded in BENCH_DETAILS.json by bench.py).

    Three sub-pins: an active 256-node roll stays within the
    writes-per-transition budget (coalescing works), a 4096-node
    sharded idle tick issues exactly 0 writes (suppression works), and
    an identical-event storm collapses >= 10:1 (aggregation works)."""
    import time

    from k8s_operator_libs_tpu.api import (
        DrainSpec,
        IntOrString,
        TPUUpgradePolicySpec,
    )
    from k8s_operator_libs_tpu.k8s import FakeCluster
    from k8s_operator_libs_tpu.k8s.informer import (
        CachedKubeClient,
        Informer,
    )
    from k8s_operator_libs_tpu.k8s.writeplan import WritePlan
    from k8s_operator_libs_tpu.upgrade import (
        ClusterUpgradeStateManager,
        UpgradeKeys,
        UpgradeState,
    )
    from k8s_operator_libs_tpu.upgrade.sharded import ShardedReconciler

    from fixtures import ClusterFixture, DRIVER_LABELS, NAMESPACE

    def _node_writes(cluster) -> int:
        # Every node patch variant on the fake (labels, annotations,
        # combined metadata, cordon/uncordon) ticks the same verb.
        return int(cluster.stats.get("patch_node", 0))

    def _all_writes(cluster) -> int:
        return int(
            sum(
                v
                for k, v in cluster.stats.items()
                if str(k)
                .lower()
                .startswith(
                    ("patch", "create", "delete", "evict", "update", "post", "put")
                )
            )
        )

    # -- 1. active roll: writes per observed node state transition -----
    keys = UpgradeKeys()
    cluster = FakeCluster()
    fx = ClusterFixture(cluster, keys)
    ds = fx.daemon_set(hash_suffix="v1", revision=1)
    nodes = []
    for i in range(slices):
        for n in fx.tpu_slice(f"pool-{i:02d}", hosts=hosts):
            fx.driver_pod(n, ds, hash_suffix="v1")
            nodes.append(n.name)
    fx.bump_daemon_set_template(ds, "v2", revision=2)
    fx.auto_recreate_driver_pods(ds, "v2")
    policy = TPUUpgradePolicySpec(
        auto_upgrade=True,
        max_parallel_upgrades=4,
        max_unavailable=IntOrString("25%"),
        drain_spec=DrainSpec(enable=True, timeout_second=5),
    )
    informer = Informer(
        cluster, pod_namespace=NAMESPACE, pod_match_labels=DRIVER_LABELS
    )
    cached = CachedKubeClient(cluster, informer=informer)
    mgr = ClusterUpgradeStateManager(cached, keys=keys)
    informer.sync()

    def _states() -> dict:
        return {
            name: cluster.get_node(name, cached=False).labels.get(
                keys.state_label, ""
            )
            for name in nodes
        }

    transitions_total = 0
    node_writes_total = 0
    worst_ratio = 0.0
    ticks_run = 0
    deadline = time.monotonic() + 120.0
    while time.monotonic() < deadline:
        before_states = _states()
        before_writes = _node_writes(cluster)
        state = mgr.build_state(NAMESPACE, DRIVER_LABELS, policy)
        mgr.apply_state(state, policy)
        if not mgr.wait_for_async_work(30.0):
            raise RuntimeError("async upgrade work did not drain")
        after_states = _states()
        tick_writes = _node_writes(cluster) - before_writes
        tick_transitions = sum(
            1
            for name in nodes
            if after_states[name] != before_states[name]
        )
        transitions_total += tick_transitions
        node_writes_total += tick_writes
        ticks_run += 1
        if tick_transitions:
            worst_ratio = max(worst_ratio, tick_writes / tick_transitions)
        if all(
            s == UpgradeState.DONE.value for s in after_states.values()
        ):
            break
    else:
        raise RuntimeError("active roll did not converge inside 120 s")
    roll_ratio = node_writes_total / max(1, transitions_total)
    plan = getattr(mgr, "write_plan", None)
    counters = dict(plan.counters()) if plan is not None else {}

    # -- 2. sharded idle fleet: exactly zero writes per tick -----------
    idle_cluster = FakeCluster()
    idle_fx = ClusterFixture(idle_cluster, keys)
    idle_ds = idle_fx.daemon_set(hash_suffix="v1", revision=1)
    for i in range(idle_slices):
        for n in idle_fx.tpu_slice(
            f"pool-{i:03d}", hosts=idle_hosts, state=UpgradeState.DONE
        ):
            idle_fx.driver_pod(n, idle_ds, hash_suffix="v1")
    idle_informer = Informer(
        idle_cluster, pod_namespace=NAMESPACE, pod_match_labels=DRIVER_LABELS
    )
    idle_cached = CachedKubeClient(idle_cluster, informer=idle_informer)
    idle_mgr = ClusterUpgradeStateManager(idle_cached, keys=keys)
    idle_informer.sync()
    sharded = ShardedReconciler(idle_mgr, NAMESPACE, DRIVER_LABELS, shards=4)
    try:
        t0 = time.monotonic()
        state = idle_mgr.build_state(NAMESPACE, DRIVER_LABELS, policy)
        started = sharded.observe_full_state(state, policy, started=t0)
        idle_mgr.apply_state(state, policy)
        sharded.complete_full_resync(started)
        writes_before = _all_writes(idle_cluster)
        for _ in range(idle_ticks):
            sharded.tick(policy)
        idle_writes = _all_writes(idle_cluster) - writes_before
        if not sharded.wait_idle(30.0):
            raise RuntimeError("sharded reconcile did not drain")
    finally:
        sharded.shutdown()

    # -- 3. identical-event storm collapses through the aggregator -----
    storm_cluster = FakeCluster()
    storm_plan = WritePlan(storm_cluster)
    event = {
        "type": "Warning",
        "reason": "UpgradeFailed",
        "message": "drain timed out",
        "involvedObject": {"kind": "Node", "name": "pool-00-w0"},
        "source": {"component": "tpu-upgrade-controller"},
    }
    for _ in range(storm):
        storm_plan.stage_event(NAMESPACE, dict(event))
        storm_plan.flush_events()
    storm_plan.flush_events(force=True)
    published = int(storm_cluster.stats.get("create_event", 0))
    collapse_ratio = storm / max(1, published)

    return {
        "nodes": slices * hosts,
        "roll_ticks": ticks_run,
        "roll_transitions": transitions_total,
        "roll_node_writes": node_writes_total,
        "roll_writes_per_transition": round(roll_ratio, 3),
        "roll_worst_tick_writes_per_transition": round(worst_ratio, 3),
        "writes_suppressed": int(counters.get("suppressed", 0)),
        "writes_coalesced_keys": int(counters.get("coalesced_keys", 0)),
        "conflict_replays": int(counters.get("conflict_replays", 0)),
        "idle_nodes": idle_slices * idle_hosts,
        "idle_ticks": idle_ticks,
        "idle_writes_total": idle_writes,
        "event_storm": storm,
        "events_published": published,
        "event_collapse_ratio": round(collapse_ratio, 1),
        "writes_per_transition_ceiling": WH_WRITES_PER_TRANSITION_CEILING,
        "event_collapse_floor": WH_EVENT_COLLAPSE_FLOOR,
    }


def measure_planner(
    slices: int = PLANNER_N_SLICES,
    hosts: int = PLANNER_HOSTS_PER_SLICE,
    twin_slices: int = PLANNER_TWIN_N_SLICES,
    twin_hosts: int = PLANNER_TWIN_HOSTS,
) -> dict:
    """Predictive-planning measurement; returns the artifact dict (also
    embedded in BENCH_DETAILS.json by bench.py).

    Sub-pins: a 4096-node mixed-generation plan lands under the wall
    ceiling with exactly 0 API write verbs, and the digital twin's
    actual admission schedule agrees with the analytic plan exactly
    (wave count and node->wave assignment) on a smaller mixed fleet."""
    import time

    from k8s_operator_libs_tpu.api import (
        DrainSpec,
        IntOrString,
        TPUUpgradePolicySpec,
    )
    from k8s_operator_libs_tpu.k8s import FakeCluster
    from k8s_operator_libs_tpu.planning import plan_roll, run_twin
    from k8s_operator_libs_tpu.upgrade import (
        ClusterUpgradeStateManager,
        UpgradeKeys,
        UpgradeState,
    )

    from fixtures import ClusterFixture, DRIVER_LABELS, NAMESPACE

    generations = [
        "tpu-v4-podslice",
        "tpu-v4-podslice",
        "tpu-v5-lite-podslice",
        "tpu-v6e-slice",
    ]

    def _writes(cluster) -> int:
        return int(
            sum(
                v
                for k, v in cluster.stats.items()
                if str(k)
                .lower()
                .startswith(
                    ("patch", "create", "delete", "evict", "update", "post", "put")
                )
            )
        )

    def _mixed_fleet(n_slices, n_hosts):
        keys = UpgradeKeys()
        cluster = FakeCluster()
        fx = ClusterFixture(cluster, keys)
        ds = fx.daemon_set(hash_suffix="v1", revision=1)
        for i in range(n_slices):
            nodes = fx.tpu_slice(
                f"pool-{i:03d}",
                hosts=n_hosts,
                state=UpgradeState.DONE,
                accelerator=generations[i % len(generations)],
            )
            for n in nodes:
                fx.driver_pod(n, ds, hash_suffix="v1")
        fx.bump_daemon_set_template(ds, "v2", revision=2)
        fx.auto_recreate_driver_pods(ds, "v2")
        return keys, cluster

    policy = TPUUpgradePolicySpec(
        auto_upgrade=True,
        max_parallel_upgrades=4,
        max_unavailable=IntOrString(4),
        drain_spec=DrainSpec(enable=False),
    )

    # -- 1. plan wall time + write hygiene at 4096 nodes ---------------
    keys, cluster = _mixed_fleet(slices, hosts)
    manager = ClusterUpgradeStateManager(
        cluster, keys=keys, poll_interval_s=0.005, poll_timeout_s=2.0
    )
    state = manager.build_state(NAMESPACE, DRIVER_LABELS, policy)
    writes_before = _writes(cluster)
    t0 = time.perf_counter()
    plan = plan_roll(manager, state, policy)
    plan_wall_s = time.perf_counter() - t0
    plan_writes = _writes(cluster) - writes_before

    # -- 2. twin-vs-analytic wave agreement on a smaller fleet ---------
    twin_keys, twin_cluster = _mixed_fleet(twin_slices, twin_hosts)
    twin_policy = TPUUpgradePolicySpec(
        auto_upgrade=True,
        max_parallel_upgrades=2,
        max_unavailable=IntOrString(2),
        drain_spec=DrainSpec(enable=False),
    )
    twin_manager = ClusterUpgradeStateManager(
        twin_cluster,
        keys=twin_keys,
        poll_interval_s=0.005,
        poll_timeout_s=2.0,
    )
    twin_state = twin_manager.build_state(
        NAMESPACE, DRIVER_LABELS, twin_policy
    )
    analytic = plan_roll(twin_manager, twin_state, twin_policy)
    twin = run_twin(
        twin_cluster, NAMESPACE, DRIVER_LABELS, twin_policy, keys=twin_keys
    )

    return {
        "stage": "planner",
        "nodes": slices * hosts,
        "pending_groups": plan.pending_groups,
        "plan_waves": plan.wave_count,
        "plan_wall_s": round(plan_wall_s, 4),
        "plan_writes": plan_writes,
        "wall_ceiling_s": PLAN_WALL_CEILING_S,
        "twin_nodes": twin_slices * twin_hosts,
        "twin_converged": twin.converged,
        "analytic_waves": analytic.wave_count,
        "twin_waves": twin.wave_count,
        "node_wave_agrees": twin.node_wave == analytic.node_wave,
    }


def measure_packed_admission(
    n_singles: int = PACKED_N_SINGLES,
    n_quads: int = PACKED_N_QUADS,
    twin_singles: int = PACKED_TWIN_SINGLES,
    twin_quads: int = PACKED_TWIN_QUADS,
) -> dict:
    """Plan-guided admission packing measurement; returns the artifact
    dict (also embedded in BENCH_DETAILS.json by bench.py).

    Two fleets, one shape: 1-host slices named to sort BEFORE 4-host
    slices under the greedy id order, rolled under a node-unit budget
    of 5.  Greedy admits singles first and strands 1-4 budget units
    whenever a quad heads the residual; packed (FFD off the anchored
    plan) pairs {4,1} every wave.  Stage 1 compares analytic plans at
    256 nodes; stage 2 rolls the small fleet through the REAL engine
    (digital twin) in both modes and cross-checks the packed engine's
    admission schedule against the analytic packed plan."""
    from k8s_operator_libs_tpu.api import (
        DrainSpec,
        IntOrString,
        PlanningSpec,
        TPUUpgradePolicySpec,
    )
    from k8s_operator_libs_tpu.k8s import FakeCluster
    from k8s_operator_libs_tpu.planning import plan_roll, run_twin
    from k8s_operator_libs_tpu.upgrade import (
        ClusterUpgradeStateManager,
        UpgradeKeys,
        UpgradeState,
    )

    from fixtures import ClusterFixture, DRIVER_LABELS, NAMESPACE

    def _writes(cluster) -> int:
        return int(
            sum(
                v
                for k, v in cluster.stats.items()
                if str(k)
                .lower()
                .startswith(
                    ("patch", "create", "delete", "evict", "update", "post", "put")
                )
            )
        )

    def _sized_fleet(singles, quads):
        keys = UpgradeKeys()
        cluster = FakeCluster()
        fx = ClusterFixture(cluster, keys)
        ds = fx.daemon_set(hash_suffix="v1", revision=1)
        for i in range(singles):
            # "a-" < "b-": greedy id order tries every single first.
            for n in fx.tpu_slice(
                f"a-solo-{i:03d}", hosts=1, state=UpgradeState.DONE
            ):
                fx.driver_pod(n, ds, hash_suffix="v1")
        for i in range(quads):
            for n in fx.tpu_slice(
                f"b-quad-{i:03d}", hosts=4, state=UpgradeState.DONE
            ):
                fx.driver_pod(n, ds, hash_suffix="v1")
        fx.bump_daemon_set_template(ds, "v2", revision=2)
        fx.auto_recreate_driver_pods(ds, "v2")
        return keys, cluster

    def _policy(mode):
        return TPUUpgradePolicySpec(
            auto_upgrade=True,
            max_parallel_upgrades=PACKED_PARALLEL,
            max_unavailable=IntOrString(PACKED_BUDGET_NODES),
            unavailability_unit="node",
            drain_spec=DrainSpec(enable=False),
            planning=PlanningSpec(admission_mode=mode),
        )

    # -- 1. analytic greedy vs packed at 256 nodes ---------------------
    keys, cluster = _sized_fleet(n_singles, n_quads)
    manager = ClusterUpgradeStateManager(
        cluster, keys=keys, poll_interval_s=0.005, poll_timeout_s=2.0
    )
    state = manager.build_state(NAMESPACE, DRIVER_LABELS, _policy("greedy"))
    writes_before = _writes(cluster)
    greedy_plan = plan_roll(manager, state, _policy("greedy"))
    packed_plan = plan_roll(manager, state, _policy("packed"))
    plan_writes = _writes(cluster) - writes_before

    # -- 2. live engine (digital twin) greedy vs packed ----------------
    tg_keys, tg_cluster = _sized_fleet(twin_singles, twin_quads)
    twin_greedy = run_twin(
        tg_cluster, NAMESPACE, DRIVER_LABELS, _policy("greedy"), keys=tg_keys
    )
    tp_keys, tp_cluster = _sized_fleet(twin_singles, twin_quads)
    twin_packed = run_twin(
        tp_cluster, NAMESPACE, DRIVER_LABELS, _policy("packed"), keys=tp_keys
    )
    # The analytic packed plan for the same small fleet — the engine's
    # actual admission schedule must reproduce it wave for wave.
    sp_keys, sp_cluster = _sized_fleet(twin_singles, twin_quads)
    sp_manager = ClusterUpgradeStateManager(
        sp_cluster, keys=sp_keys, poll_interval_s=0.005, poll_timeout_s=2.0
    )
    sp_state = sp_manager.build_state(
        NAMESPACE, DRIVER_LABELS, _policy("packed")
    )
    small_plan = plan_roll(sp_manager, sp_state, _policy("packed"))
    planned_waves = [sorted(w.group_ids) for w in small_plan.waves]
    engine_waves = [sorted(w) for w in twin_packed.waves]

    return {
        "stage": "packed_admission",
        "nodes": n_singles + 4 * n_quads,
        "budget_nodes": PACKED_BUDGET_NODES,
        "greedy_waves": greedy_plan.wave_count,
        "packed_waves": packed_plan.wave_count,
        "greedy_duration_s": round(greedy_plan.projected_duration_s, 1),
        "packed_duration_s": round(packed_plan.projected_duration_s, 1),
        "plan_writes": plan_writes,
        "twin_nodes": twin_singles + 4 * twin_quads,
        "engine_greedy_converged": twin_greedy.converged,
        "engine_packed_converged": twin_packed.converged,
        "engine_greedy_waves": twin_greedy.wave_count,
        "engine_packed_waves": twin_packed.wave_count,
        "engine_greedy_duration_s": round(
            twin_greedy.virtual_duration_s, 1
        ),
        "engine_packed_duration_s": round(
            twin_packed.virtual_duration_s, 1
        ),
        "engine_packed_mode": twin_packed.admission_mode,
        "engine_plan_wave_agrees": engine_waves == planned_waves,
        "packed_admitted": twin_packed.admission.get("packed_admitted", 0),
        "greedy_idle_ticks": twin_greedy.admission.get(
            "budget_idle_ticks", 0
        ),
        "packed_idle_ticks": twin_packed.admission.get(
            "budget_idle_ticks", 0
        ),
    }


def measure_tracing(
    slices: int = TRACING_N_SLICES,
    hosts: int = TRACING_HOSTS_PER_SLICE,
    idle_slices: int = SHARDED_N_SLICES,
    idle_hosts: int = SHARDED_HOSTS_PER_SLICE,
    idle_ticks: int = TRACING_IDLE_TICKS,
    storm: int = TRACING_STORM_TRIGGERS,
) -> dict:
    """Roll-tracing measurement; returns the artifact dict (also
    embedded in BENCH_DETAILS.json by bench.py).

    Four sub-pins: the recorder costs < 5% p99 on an active 256-node
    tick (observe-only means cheap, not just fail-open), the traced
    roll completes into one connected zero-open-span tree whose
    critical-path buckets sum to the makespan, a 4096-node idle sharded
    fleet stays 0-pools/0-writes with tracing on, and a trigger storm
    cannot blow the black-box spool past its byte cap."""
    import shutil
    import tempfile
    import time

    from k8s_operator_libs_tpu.api import (
        DrainSpec,
        IntOrString,
        TPUUpgradePolicySpec,
    )
    from k8s_operator_libs_tpu.k8s import FakeCluster
    from k8s_operator_libs_tpu.k8s.informer import (
        CachedKubeClient,
        Informer,
    )
    from k8s_operator_libs_tpu.obs.critical import analyze
    from k8s_operator_libs_tpu.obs.flightrec import FlightRecorder
    from k8s_operator_libs_tpu.obs.trace import KIND_ROLL
    from k8s_operator_libs_tpu.upgrade import (
        ClusterUpgradeStateManager,
        UpgradeKeys,
        UpgradeState,
    )
    from k8s_operator_libs_tpu.upgrade.sharded import ShardedReconciler

    from fixtures import ClusterFixture, DRIVER_LABELS, NAMESPACE

    policy = TPUUpgradePolicySpec(
        auto_upgrade=True,
        max_parallel_upgrades=4,
        max_unavailable=IntOrString("25%"),
        # Drain off keeps the ticks CPU-bound: async drain polls would
        # put wall-clock sleeps into both legs and drown the overhead
        # comparison in scheduler noise.
        drain_spec=DrainSpec(enable=False),
    )

    # -- 1+2. the same active roll, recorder off then on ---------------
    def _roll(enable_tracing: bool):
        keys = UpgradeKeys()
        cluster = FakeCluster()
        fx = ClusterFixture(cluster, keys)
        ds = fx.daemon_set(hash_suffix="v1", revision=1)
        names = []
        for i in range(slices):
            for n in fx.tpu_slice(f"pool-{i:02d}", hosts=hosts):
                fx.driver_pod(n, ds, hash_suffix="v1")
                names.append(n.name)
        fx.bump_daemon_set_template(ds, "v2", revision=2)
        fx.auto_recreate_driver_pods(ds, "v2")
        mgr = ClusterUpgradeStateManager(
            cluster,
            keys=keys,
            poll_interval_s=0.005,
            poll_timeout_s=2.0,
            enable_tracing=enable_tracing,
        )
        durations: list[float] = []
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            t0 = time.monotonic()
            state = mgr.build_state(NAMESPACE, DRIVER_LABELS, policy)
            mgr.apply_state(state, policy)
            if not mgr.wait_for_async_work(30.0):
                raise RuntimeError("async upgrade work did not drain")
            durations.append(time.monotonic() - t0)
            if all(
                cluster.get_node(n, cached=False).labels.get(
                    keys.state_label
                )
                == UpgradeState.DONE.value
                for n in names
            ):
                break
        else:
            raise RuntimeError("traced roll did not converge inside 120 s")
        # Settling ticks: the closing maybe_end_roll runs on the apply
        # pass AFTER the last async state flip lands.
        for _ in range(2):
            t0 = time.monotonic()
            state = mgr.build_state(NAMESPACE, DRIVER_LABELS, policy)
            mgr.apply_state(state, policy)
            mgr.wait_for_async_work(10.0)
            durations.append(time.monotonic() - t0)
        return mgr, durations

    def _p99(durations: list[float]) -> float:
        # First tick excluded: it pays process-wide lazy imports and
        # fixture first-touch, not steady-state tick cost.
        samples = durations[1:] if len(durations) > 4 else durations
        ordered = sorted(samples)
        return ordered[min(len(ordered) - 1, int(len(ordered) * 0.99))]

    # Interleaved repetitions, min-of-reps p99 per leg (see
    # TRACING_TIMING_REPS).  OFF leg first within each pair so one-time
    # import warmup lands on the baseline leg (never flatters tracing).
    # GC hygiene: by this point the earlier stages (JAX batteries, the
    # 4096-node fleets) have left a multi-GB heap behind, so every gen-2
    # collection the timing loop triggers pays a full traversal of THAT
    # heap — the leg that allocates more (tracing on, by design) eats
    # more of those pauses into its p99, turning heap size into fake
    # recorder overhead.  Parking the pre-existing heap in the permanent
    # generation keeps collections scoped to what the roll itself
    # allocates, which is exactly the structural cost the pin is about.
    import gc

    gc.collect()
    gc.freeze()
    try:
        reps_off: list[list[float]] = []
        reps_on: list[list[float]] = []
        for _ in range(TRACING_TIMING_REPS):
            _, t_off = _roll(False)
            mgr_on, t_on = _roll(True)
            reps_off.append(t_off)
            reps_on.append(t_on)
    finally:
        gc.unfreeze()
    ticks_off = min(reps_off, key=_p99)
    ticks_on = min(reps_on, key=_p99)
    p99_off = _p99(ticks_off)
    p99_on = _p99(ticks_on)
    overhead_pct = 100.0 * (p99_on - p99_off) / max(p99_off, 1e-9)

    rec = mgr_on.trace_recorder
    completed = rec.last_completed() if rec is not None else None
    spans = completed.spans if completed is not None else []
    span_ids = {s.span_id for s in spans}
    roots = [s for s in spans if s.parent_id is None]
    trace_connected = (
        bool(spans)
        and len(roots) == 1
        and roots[0].kind == KIND_ROLL
        and all(
            s.parent_id in span_ids
            for s in spans
            if s.parent_id is not None
        )
    )
    open_spans = sum(1 for s in spans if s.open)
    makespan = completed.makespan if completed is not None else 0.0
    attribution = analyze(completed) if completed is not None else None
    bucket_sum = (
        attribution.bucket_total() if attribution is not None else 0.0
    )
    bucket_err_pct = (
        100.0 * abs(bucket_sum - makespan) / max(makespan, 1e-9)
        if completed is not None
        else 100.0
    )

    # -- 3. idle sharded fleet with tracing on: still 0 pools, 0 writes
    def _all_writes(cluster) -> int:
        return int(
            sum(
                v
                for k, v in cluster.stats.items()
                if str(k)
                .lower()
                .startswith(
                    ("patch", "create", "delete", "evict", "update", "post", "put")
                )
            )
        )

    keys = UpgradeKeys()
    idle_cluster = FakeCluster()
    idle_fx = ClusterFixture(idle_cluster, keys)
    idle_ds = idle_fx.daemon_set(hash_suffix="v1", revision=1)
    for i in range(idle_slices):
        for n in idle_fx.tpu_slice(
            f"pool-{i:03d}", hosts=idle_hosts, state=UpgradeState.DONE
        ):
            idle_fx.driver_pod(n, idle_ds, hash_suffix="v1")
    idle_informer = Informer(
        idle_cluster, pod_namespace=NAMESPACE, pod_match_labels=DRIVER_LABELS
    )
    idle_cached = CachedKubeClient(idle_cluster, informer=idle_informer)
    idle_mgr = ClusterUpgradeStateManager(idle_cached, keys=keys)
    idle_tracing_enabled = idle_mgr.trace_recorder is not None
    idle_informer.sync()
    sharded = ShardedReconciler(idle_mgr, NAMESPACE, DRIVER_LABELS, shards=4)
    try:
        t0 = time.monotonic()
        state = idle_mgr.build_state(NAMESPACE, DRIVER_LABELS, policy)
        started = sharded.observe_full_state(state, policy, started=t0)
        idle_mgr.apply_state(state, policy)
        sharded.complete_full_resync(started)
        writes_before = _all_writes(idle_cluster)
        idle_walked = 0
        for _ in range(idle_ticks):
            idle_walked += sharded.tick(policy).pools_walked
        idle_writes = _all_writes(idle_cluster) - writes_before
        if not sharded.wait_idle(30.0):
            raise RuntimeError("sharded reconcile did not drain")
    finally:
        sharded.shutdown()

    # -- 4. black-box trigger storm stays under the spool byte cap -----
    spool_dir = tempfile.mkdtemp(prefix="bench-blackbox-")
    try:
        fr = FlightRecorder(
            spool_dir=spool_dir,
            spool_cap_bytes=TRACING_SPOOL_CAP_BYTES,
            throttle_s=0.0,  # un-throttled: the cap must hold alone
        )
        if rec is not None:
            fr.snapshot_providers["trace"] = rec.export
        for i in range(storm):
            fr.note("delta", node=f"pool-{i % slices:02d}-w0", seq=i)
            fr.trigger("infeasible", tick=i, detail="bench trigger storm")
        storm_dumps = sum(fr.dumps_total.values())
        spool_bytes = fr.spool_bytes()
        spool_files = len(fr.spool_files())
    finally:
        shutil.rmtree(spool_dir, ignore_errors=True)

    return {
        "nodes": slices * hosts,
        "roll_ticks_off": len(ticks_off),
        "roll_ticks_on": len(ticks_on),
        "p99_tick_off_s": round(p99_off, 6),
        "p99_tick_on_s": round(p99_on, 6),
        "mean_tick_off_s": round(sum(ticks_off) / len(ticks_off), 6),
        "mean_tick_on_s": round(sum(ticks_on) / len(ticks_on), 6),
        "overhead_pct": round(overhead_pct, 2),
        "trace_completed": completed is not None,
        "trace_spans": len(spans),
        "trace_connected": trace_connected,
        "trace_open_spans": open_spans,
        "trace_drops": rec.drops if rec is not None else -1,
        "trace_groups": (
            attribution.group_count if attribution is not None else 0
        ),
        "makespan_s": round(makespan, 6),
        "bucket_sum_s": round(bucket_sum, 6),
        "bucket_sum_error_pct": round(bucket_err_pct, 4),
        "buckets": (
            {k: round(v, 6) for k, v in attribution.buckets.items()}
            if attribution is not None
            else {}
        ),
        "idle_nodes": idle_slices * idle_hosts,
        "idle_ticks": idle_ticks,
        "idle_tracing_enabled": idle_tracing_enabled,
        "idle_pools_walked_total": idle_walked,
        "idle_writes_total": idle_writes,
        "storm_triggers": storm,
        "storm_dumps": storm_dumps,
        "storm_spool_files": spool_files,
        "spool_bytes": spool_bytes,
        "spool_cap_bytes": TRACING_SPOOL_CAP_BYTES,
        "overhead_ceiling_pct": TRACING_OVERHEAD_CEILING_PCT,
        "overhead_grace_s": TRACING_OVERHEAD_GRACE_S,
        "bucket_tolerance_pct": TRACING_BUCKET_TOLERANCE_PCT,
    }


def measure_telemetry(
    n_nodes: int = TELEMETRY_N_NODES,
    roll_slices: int = TELEMETRY_ROLL_SLICES,
    roll_hosts: int = TELEMETRY_ROLL_HOSTS,
) -> dict:
    """Fleet-health telemetry measurement; returns the artifact dict
    (also embedded in BENCH_DETAILS.json by bench.py).

    Two sub-pins.  (1) Verdict correctness at fleet scale: a 256-node
    mixed-generation fleet whose probe histories arrive through the
    durable-adoption path (ring annotations — the crash/handoff
    surface) plus ONE fresh battery must confirm exactly the node
    injected 25% below its generation's median and nobody else.
    (2) Write parity: an identical small roll with and without the
    telemetry plane attached must issue the SAME total count of API
    write verbs — the history ring rides the combined transition patch
    — while the telemetry leg still persists a non-empty ring
    annotation on every node."""
    import time

    from k8s_operator_libs_tpu.api import (
        DrainSpec,
        IntOrString,
        TPUUpgradePolicySpec,
    )
    from k8s_operator_libs_tpu.k8s import FakeCluster
    from k8s_operator_libs_tpu.obs.telemetry import (
        TelemetryPlane,
        format_ring,
        parse_ring,
    )
    from k8s_operator_libs_tpu.upgrade import (
        ClusterUpgradeStateManager,
        UpgradeKeys,
        UpgradeState,
    )
    from k8s_operator_libs_tpu.upgrade.consts import (
        GKE_TPU_ACCELERATOR_LABEL,
    )

    from fixtures import ClusterFixture, DRIVER_LABELS, NAMESPACE, make_node

    keys = UpgradeKeys()

    # -- 1. verdict pins on an adopted mixed-generation fleet ----------
    plane = TelemetryPlane()
    plane.annotation_key = keys.telemetry_history_annotation
    # Pre-crash batteries already on the durable ring: one short of
    # confirmation, so the single post-adoption battery is the decider.
    history = plane.confirm_batteries - 1

    def _sample(stats: dict, scale: float) -> dict:
        out = {k: v * scale for k, v in stats.items()}
        out["battery_execute_ms"] = 40.0 / scale
        return out

    def _jitter(node_idx: int, battery: int) -> float:
        # Deterministic ±0.8% spread so cohort MAD is realistic and
        # non-zero without pulling in random.
        return 1.0 + 0.004 * ((node_idx * 7 + battery * 3) % 5 - 2)

    fleet = []  # (name, generation, pool, baseline stats, straggler?)
    per_gen = -(-n_nodes // len(TELEMETRY_GENERATIONS))
    for gen, pool, tflops in TELEMETRY_GENERATIONS:
        stats = {"tflops": tflops, "gbps": tflops * 4.0}
        for i in range(per_gen):
            if len(fleet) >= n_nodes:
                break
            fleet.append(
                (f"{pool}-w{i:03d}", gen, pool, stats, len(fleet) == 0)
            )
    straggler_name = fleet[0][0]

    adopted = 0
    pools = {}
    for j, (name, gen, pool, stats, slow) in enumerate(fleet):
        ring = []
        for battery in range(history):
            scale = _jitter(j, battery)
            if slow:
                scale *= TELEMETRY_STRAGGLER_FRACTION
            ring.append(
                (battery + 1, 1000.0 + battery, _sample(stats, scale))
            )
        node = make_node(
            name,
            labels={GKE_TPU_ACCELERATOR_LABEL: gen},
            annotations={
                keys.telemetry_history_annotation: format_ring(ring)
            },
        )
        if plane.adopt_node(node):
            adopted += 1
        pools[name] = pool
    plane.seed_pools(pools)
    # One fresh battery after the hand-off: the straggler's
    # confirm_batteries-th consecutive slow sample.
    for j, (name, gen, pool, stats, slow) in enumerate(fleet):
        scale = _jitter(j, history)
        if slow:
            scale *= TELEMETRY_STRAGGLER_FRACTION
        plane.ingest(
            name, _sample(stats, scale), generation=gen, pool=pool
        )
    plane.recompute()
    status = plane.to_status()
    verdicts = status.get("stragglers") or []
    confirmed = sorted(v["node"] for v in verdicts)
    straggler_verdict = next(
        (v for v in verdicts if v["node"] == straggler_name), None
    )
    cohorts = (status.get("healthSummary") or {}).get("cohorts") or []

    # -- 2. write parity: the ring rides the combined patch ------------
    def _all_writes(cluster) -> int:
        return int(
            sum(
                v
                for k, v in cluster.stats.items()
                if str(k)
                .lower()
                .startswith(
                    ("patch", "create", "delete", "evict", "update", "post", "put")
                )
            )
        )

    # Raw-cluster reads + tight polls (the trace_roll.py harness): the
    # pod-restart wait sees the recreated driver pod immediately, so
    # both legs converge through the identical deterministic tick
    # sequence and the write totals are exactly comparable.
    policy = TPUUpgradePolicySpec(
        auto_upgrade=True,
        max_parallel_upgrades=4,
        max_unavailable=IntOrString("25%"),
        drain_spec=DrainSpec(enable=False),
    )
    leg_writes = {}
    rings_persisted = 0
    for enabled in (False, True):
        cluster = FakeCluster()
        fx = ClusterFixture(cluster, keys)
        ds = fx.daemon_set(hash_suffix="v1", revision=1)
        names = []
        for i in range(roll_slices):
            for n in fx.tpu_slice(f"tel-{i:02d}", hosts=roll_hosts):
                fx.driver_pod(n, ds, hash_suffix="v1")
                names.append(n.name)
        fx.bump_daemon_set_template(ds, "v2", revision=2)
        fx.auto_recreate_driver_pods(ds, "v2")
        mgr = ClusterUpgradeStateManager(
            cluster,
            keys=keys,
            poll_interval_s=0.005,
            poll_timeout_s=2.0,
            enable_telemetry=enabled,
        )
        if enabled:
            # One battery per node before the roll: every ring is dirty
            # and must reach its annotation on the transition patches
            # the roll stages anyway.
            for name in names:
                mgr.telemetry_plane.ingest(
                    name,
                    {"tflops": 459.0, "gbps": 1640.0},
                    generation="tpu-v5p-slice",
                )
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            state = mgr.build_state(NAMESPACE, DRIVER_LABELS, policy)
            mgr.apply_state(state, policy)
            if not mgr.wait_for_async_work(30.0):
                raise RuntimeError("async upgrade work did not drain")
            done = all(
                cluster.get_node(name, cached=False).labels.get(
                    keys.state_label, ""
                )
                == UpgradeState.DONE.value
                for name in names
            )
            if done:
                break
        else:
            raise RuntimeError(
                "telemetry parity roll did not converge inside 120 s"
            )
        leg_writes[enabled] = _all_writes(cluster)
        if enabled:
            rings_persisted = sum(
                1
                for name in names
                if parse_ring(
                    cluster.get_node(name, cached=False).annotations.get(
                        keys.telemetry_history_annotation
                    )
                )
            )

    return {
        "nodes": len(fleet),
        "generations": len(TELEMETRY_GENERATIONS),
        "cohorts": len(cohorts),
        "adopted": adopted,
        "straggler": straggler_name,
        "straggler_confirmed": straggler_verdict is not None,
        "straggler_z": (
            straggler_verdict["z"] if straggler_verdict else 0.0
        ),
        "straggler_score": (
            straggler_verdict["score"] if straggler_verdict else -1.0
        ),
        "straggler_streak": (
            straggler_verdict["streak"] if straggler_verdict else 0
        ),
        "confirmed": confirmed,
        "false_positives": len([n for n in confirmed if n != straggler_name]),
        "fresh_batteries_to_confirm": 1,
        "drops": plane.drops,
        "roll_nodes": roll_slices * roll_hosts,
        "writes_without_telemetry": leg_writes.get(False, -1),
        "writes_with_telemetry": leg_writes.get(True, -1),
        "extra_writes": leg_writes.get(True, -1) - leg_writes.get(False, -1),
        "rings_persisted": rings_persisted,
    }



def measure_federation(
    n_clusters: int = FED_N_CLUSTERS,
    slices: int = FED_SLICES_PER_CLUSTER,
    hosts: int = FED_HOSTS_PER_SLICE,
    partition_ticks: int = FED_PARTITION_TICKS,
) -> dict:
    """Federated-roll measurement; returns the artifact dict.

    One cluster per region past the canary; the canary region rolls
    first, promotes on a zero-length soak, then cluster "b" loses its
    WAN link for ``partition_ticks`` coordinator ticks while the rest
    of the fleet keeps rolling.  The numbers this returns are exactly
    the ones main() pins — see the FED_* constants."""
    from k8s_operator_libs_tpu.api import (
        DrainSpec,
        FederationCanarySpec,
        FederationClusterSpec,
        FederationSpec,
        IntOrString,
        TPUUpgradePolicySpec,
    )
    from k8s_operator_libs_tpu.federation import (
        ClusterRegistry,
        FederationCoordinator,
        FederationStateStore,
        ensure_federation_kind,
    )
    from k8s_operator_libs_tpu.federation.coordinator import (
        PHASE_DONE,
        PHASE_PROMOTED,
    )
    from k8s_operator_libs_tpu.k8s import FakeCluster
    from k8s_operator_libs_tpu.k8s.faults import FaultSchedule
    from k8s_operator_libs_tpu.k8s.retry import (
        CircuitBreaker,
        ResilientClient,
        RetryPolicy,
    )
    from k8s_operator_libs_tpu.upgrade import (
        ClusterUpgradeStateManager,
        UpgradeKeys,
        UpgradeState,
    )

    from fixtures import ClusterFixture, DRIVER_LABELS, NAMESPACE

    keys = UpgradeKeys()
    mutating = ("patch", "create", "update", "delete", "evict", "set_")

    def _writes(cluster) -> int:
        return int(
            sum(
                v
                for k, v in cluster.stats.items()
                if str(k).startswith(mutating)
            )
        )

    members = {}
    regions = {}
    for idx in range(n_clusters):
        name = chr(ord("a") + idx)
        region = f"r{idx + 1}"
        fake = FakeCluster()
        fx = ClusterFixture(fake, keys=keys)
        ds = fx.daemon_set()
        nodes = []
        for i in range(slices):
            slice_nodes = fx.tpu_slice(f"{name}-s{i:02d}", hosts=hosts)
            nodes.extend(slice_nodes)
            for node in slice_nodes:
                fx.driver_pod(node, ds)
        fx.bump_daemon_set_template(ds, "hash-2", revision=2)
        fx.auto_recreate_driver_pods(ds, "hash-2")
        client = ResilientClient(
            fake,
            retry_policy=RetryPolicy(
                max_attempts=2,
                base_backoff_s=0.0005,
                max_backoff_s=0.001,
                jitter=0.0,
            ),
            breaker=CircuitBreaker(failure_threshold=2, reset_timeout_s=0.0),
        )
        mgr = ClusterUpgradeStateManager(
            client, keys=keys, poll_interval_s=0.005, poll_timeout_s=2.0
        )
        members[name] = (fake, mgr, nodes)
        regions[name] = region

    policy = TPUUpgradePolicySpec(
        auto_upgrade=True,
        max_parallel_upgrades=16,
        max_unavailable=IntOrString("50%"),
        drain_spec=DrainSpec(enable=False),
        federation=FederationSpec(
            enable=True,
            clusters=[
                FederationClusterSpec(name=n, region=regions[n])
                for n in members
            ],
            canary=FederationCanarySpec(region="r1", soak_second=0),
            max_unavailable=IntOrString("50%"),
        ),
    )
    policy.validate()

    registry = ClusterRegistry(
        degraded_after=1, partitioned_after=2, heal_probes=1
    )
    for name, (fake, mgr, _nodes) in members.items():
        registry.add(name, regions[name], mgr.client, manager=mgr)
    store_client = FakeCluster()
    ensure_federation_kind(store_client)
    store = FederationStateStore(store_client, NAMESPACE)
    coord = FederationCoordinator(
        registry,
        policy,
        NAMESPACE,
        DRIVER_LABELS,
        store,
        identity="bench-fed",
        term=1,
        async_wait_s=10.0,
    )

    def _cluster_done(name) -> bool:
        fake, _mgr, nodes = members[name]
        return all(
            fake.get_node(n.name, cached=False).labels.get(
                keys.state_label, ""
            )
            == UpgradeState.DONE.value
            for n in nodes
        )

    target = members["b"][0]
    ticks = 0
    window_skips = 0
    window_writes = -1
    partitioned_at = -1
    healed_at = -1
    b_started_before_partition = False
    while ticks < FED_MAX_TICKS:
        summary = coord.tick()
        ticks += 1
        if partitioned_at < 0 and coord.phase in (
            PHASE_PROMOTED,
            PHASE_DONE,
        ):
            # Let the non-canary regions get genuinely mid-roll before
            # cutting the link.
            b_started_before_partition = any(
                target.get_node(n.name, cached=False).labels.get(
                    keys.state_label
                )
                for n in members["b"][2]
            )
            if b_started_before_partition and not _cluster_done("b"):
                target.fault_schedule = FaultSchedule().server_error("")
                writes_before = _writes(target)
                partitioned_at = ticks
        elif partitioned_at > 0 and healed_at < 0:
            if "b" in (summary.get("skippedPartitioned") or []):
                window_skips += 1
            if ticks - partitioned_at >= partition_ticks:
                window_writes = _writes(target) - writes_before
                target.fault_schedule = None
                healed_at = ticks
        if coord.phase == PHASE_DONE and all(
            _cluster_done(n) for n in members
        ):
            break

    return {
        "stage": "federation",
        "clusters": n_clusters,
        "nodes_per_cluster": slices * hosts,
        "nodes": n_clusters * slices * hosts,
        "ticks": ticks,
        "converged": coord.phase == PHASE_DONE
        and all(_cluster_done(n) for n in members),
        "partition_started": b_started_before_partition
        and partitioned_at > 0,
        "partition_window_ticks": (
            (healed_at - partitioned_at) if healed_at > 0 else -1
        ),
        "partition_window_skips": window_skips,
        "partition_window_writes": window_writes,
        "global_budget_violations": coord.global_ledger.violations,
        "global_budget_denials": coord.global_ledger.denials,
        "peak_global_unavailable": coord.global_ledger.peak_unavailable,
        "store_writes": store.writes,
        "partitions_detected": registry.stats.get("partitions", 0),
        "heals": registry.stats.get("heals", 0),
    }


# Write verbs compared between the classic and stack rolls.  Reads are
# deliberately absent: the pin is "no extra API *writes* per artifact",
# and read traffic is covered by the cached-reconcile stage.
MULTI_ART_WRITE_VERBS = (
    "patch_node",
    "delete_pod",
    "evict_pod",
    "update_pod",
    "create_pod",
    "create_event",
    "update_daemon_set",
    "create_node",
    "delete_node",
)


def _multi_artifact_roll(multi: bool) -> dict:
    """One 256-node roll on a fresh fleet: classic single-DaemonSet
    policy (``multi=False``) or the 3-artifact pinned-order stack
    (``multi=True``).  Both fleets carry identical objects — the
    network-driver and device-plugin pods exist (and their DaemonSets
    are bumped) either way, so the per-verb write counts differ only by
    what the stack itself does."""
    import time

    from k8s_operator_libs_tpu.api import IntOrString, TPUUpgradePolicySpec
    from k8s_operator_libs_tpu.api.v1alpha1 import (
        ArtifactDAGSpec,
        ArtifactEdgeSpec,
        ArtifactSpec,
    )
    from k8s_operator_libs_tpu.k8s import FakeCluster
    from k8s_operator_libs_tpu.upgrade import (
        ClusterUpgradeStateManager,
        UpgradeKeys,
    )
    from k8s_operator_libs_tpu.upgrade.consts import UpgradeState
    from k8s_operator_libs_tpu.upgrade.sharded import BudgetLedger

    from fixtures import ClusterFixture, DRIVER_LABELS, NAMESPACE

    net_labels = {"app": "tpu-network-driver"}
    plugin_labels = {"app": "tpu-device-plugin"}

    keys = UpgradeKeys()
    cluster = FakeCluster()
    fx = ClusterFixture(cluster, keys)
    driver_ds = fx.daemon_set(hash_suffix="v1", revision=1)
    net_ds = fx.daemon_set(
        name="tpu-net", hash_suffix="net-v1", revision=1, labels=net_labels
    )
    plugin_ds = fx.daemon_set(
        name="tpu-plugin",
        hash_suffix="plug-v1",
        revision=1,
        labels=plugin_labels,
    )
    nodes = []
    for i in range(MULTI_ART_N_SLICES):
        for n in fx.tpu_slice(f"pool-{i}", hosts=MULTI_ART_HOSTS_PER_SLICE):
            nodes.append(n)
            fx.driver_pod(n, driver_ds, hash_suffix="v1")
            fx.driver_pod(
                n, net_ds, hash_suffix="net-v1", name=f"net-{n.name}"
            )
            fx.driver_pod(
                n, plugin_ds, hash_suffix="plug-v1", name=f"plugin-{n.name}"
            )
    for ds, suffix in (
        (driver_ds, "v2"),
        (net_ds, "net-v2"),
        (plugin_ds, "plug-v2"),
    ):
        fx.bump_daemon_set_template(ds, suffix, revision=2)
        fx.auto_recreate_driver_pods(ds, suffix)

    artifacts = None
    if multi:
        artifacts = ArtifactDAGSpec(
            items=[
                ArtifactSpec(
                    name="driver",
                    match_labels=dict(DRIVER_LABELS),
                    target_version="2.18.0",
                ),
                ArtifactSpec(
                    name="net",
                    match_labels=dict(net_labels),
                    target_version="1.4.0",
                ),
                ArtifactSpec(
                    name="plugin",
                    match_labels=dict(plugin_labels),
                    target_version="0.9.2",
                ),
            ],
            edges=[
                ArtifactEdgeSpec(
                    before="driver",
                    after="net",
                    requires=">=2.18.0",
                    skew="pinned-order",
                ),
                ArtifactEdgeSpec(
                    before="net", after="plugin", skew="pinned-order"
                ),
            ],
        )
    policy = TPUUpgradePolicySpec(
        auto_upgrade=True,
        max_parallel_upgrades=0,
        max_unavailable=IntOrString("100%"),
        unavailability_unit="slice",
        artifacts=artifacts,
    )
    policy.validate()

    mgr = ClusterUpgradeStateManager(
        cluster, keys=keys, poll_interval_s=0.005, poll_timeout_s=5.0
    )

    # One BudgetLedger charge per group for the WHOLE stack: count
    # charge events (a grant to a group not currently holding one).
    ledger = BudgetLedger()
    ledger.configure(
        total_units=MULTI_ART_N_SLICES,
        max_parallel=0,
        max_unavailable=MULTI_ART_N_SLICES,
        unit="slice",
    )
    charges: dict[str, int] = {}
    orig_claim = ledger.try_claim

    def counting_claim(group_id, cost, **kw):
        held = ledger.holds(group_id)
        ok = orig_claim(group_id, cost, **kw)
        if ok and not held:
            charges[group_id] = charges.get(group_id, 0) + 1
        return ok

    ledger.try_claim = counting_claim
    mgr.budget_ledger = ledger

    cordons: dict[str, int] = {}
    orig_unsched = cluster.set_node_unschedulable

    def counting_unsched(name, unschedulable):
        if unschedulable:
            cordons[name] = cordons.get(name, 0) + 1
        return orig_unsched(name, unschedulable)

    cluster.set_node_unschedulable = counting_unsched

    # Drain-window entries: state-label writes flipping a node into the
    # drain state, on both label write paths (plain and coalesced).
    drain_value = UpgradeState.DRAIN_REQUIRED.value
    drains: dict[str, int] = {}

    def watch_labels(name, labels):
        if (labels or {}).get(keys.state_label) == drain_value:
            drains[name] = drains.get(name, 0) + 1

    orig_patch_labels = cluster.patch_node_labels
    orig_patch_meta = cluster.patch_node_metadata

    def counting_patch_labels(name, patch):
        watch_labels(name, patch)
        return orig_patch_labels(name, patch)

    def counting_patch_meta(
        name, labels=None, annotations=None, field_manager=None
    ):
        watch_labels(name, labels)
        return orig_patch_meta(
            name,
            labels=labels,
            annotations=annotations,
            field_manager=field_manager,
        )

    cluster.patch_node_labels = counting_patch_labels
    cluster.patch_node_metadata = counting_patch_meta

    write_base = {v: cluster.stats.get(v, 0) for v in MULTI_ART_WRITE_VERBS}
    t0 = time.monotonic()
    converged = False
    for tick in range(MULTI_ART_MAX_TICKS):
        state = mgr.build_state(NAMESPACE, DRIVER_LABELS, policy)
        mgr.apply_state(state, policy)
        mgr.wait_for_async_work(60.0)
        states = {
            cluster.get_node(n.name, cached=False).labels.get(
                keys.state_label, ""
            )
            for n in nodes
        }
        if states == {UpgradeState.DONE.value}:
            converged = True
            break
    wall_s = time.monotonic() - t0

    return {
        "converged": converged,
        "ticks": tick + 1,
        "wall_s": round(wall_s, 3),
        "nodes": len(nodes),
        "groups": MULTI_ART_N_SLICES,
        "cordons_per_node": sorted(set(cordons.values())) or [0],
        "nodes_cordoned": len(cordons),
        "drains_per_node": sorted(set(drains.values())) or [0],
        "nodes_drained": len(drains),
        "charges_per_group": sorted(set(charges.values())) or [0],
        "groups_charged": len(charges),
        "writes": {
            v: cluster.stats.get(v, 0) - write_base[v]
            for v in MULTI_ART_WRITE_VERBS
        },
        "window_savings": mgr.artifact_window_savings,
        "skew_holds": dict(mgr.artifact_skew_holds),
    }


def measure_multi_artifact() -> dict:
    """Multi-artifact stage: a 3-artifact pinned-order stack (driver ->
    net -> plugin) over a 256-node fleet must share ONE cordon/drain
    window and ONE budget charge per group, and its per-verb API write
    delta versus the identical classic roll must be exactly the extra
    artifacts' own pod restarts — nothing else."""
    classic = _multi_artifact_roll(multi=False)
    stack = _multi_artifact_roll(multi=True)
    delta = {
        v: stack["writes"][v] - classic["writes"][v]
        for v in MULTI_ART_WRITE_VERBS
    }
    extra_restarts = stack["nodes"] * MULTI_ART_EXTRA_ARTIFACTS
    return {
        "stage": "multi_artifact",
        "nodes": stack["nodes"],
        "groups": stack["groups"],
        "artifacts": 1 + MULTI_ART_EXTRA_ARTIFACTS,
        "converged": classic["converged"] and stack["converged"],
        "classic_ticks": classic["ticks"],
        "stack_ticks": stack["ticks"],
        "classic_wall_s": classic["wall_s"],
        "stack_wall_s": stack["wall_s"],
        "cordons_per_node": stack["cordons_per_node"],
        "nodes_cordoned": stack["nodes_cordoned"],
        "drains_per_node": stack["drains_per_node"],
        "nodes_drained": stack["nodes_drained"],
        "charges_per_group": stack["charges_per_group"],
        "groups_charged": stack["groups_charged"],
        "write_delta": {k: v for k, v in delta.items() if v},
        "expected_extra_pod_restarts": extra_restarts,
        "extra_writes_clean": delta
        == {
            **{v: 0 for v in MULTI_ART_WRITE_VERBS},
            # The stack restarts each extra artifact's pod once per
            # node; the fixture's DS-controller hook recreates it.
            "delete_pod": extra_restarts,
            "create_pod": extra_restarts,
        },
        "window_savings": stack["window_savings"],
        "skew_holds": stack["skew_holds"],
    }


def main() -> int:
    result = measure()
    ok = result["api_requests_per_tick"] <= API_PER_TICK_CEILING
    result["ok"] = ok
    print(json.dumps(result, sort_keys=True))
    if not ok:
        print(
            "bench-guard FAIL: steady-state cached reconcile issued "
            f"{result['api_requests_per_tick']} API requests/tick at "
            f"{result['nodes']} nodes (ceiling "
            f"{API_PER_TICK_CEILING}) — a relist or per-node GET is "
            "back in the hot path",
            file=sys.stderr,
        )
        return 1

    sharded = measure_sharded()
    failures = []
    if sharded["idle_pools_walked_total"] != 0:
        failures.append(
            f"idle ticks walked {sharded['idle_pools_walked_total']} "
            "pools (must be 0 — tick cost is no longer O(changed))"
        )
    if sharded["idle_api_requests_total"] != 0:
        failures.append(
            f"idle ticks issued {sharded['idle_api_requests_total']} "
            "API requests (must be 0)"
        )
    if sharded["idle_p99_tick_s"] > SHARDED_IDLE_P99_CEILING_S:
        failures.append(
            f"idle p99 tick latency {sharded['idle_p99_tick_s']}s > "
            f"ceiling {SHARDED_IDLE_P99_CEILING_S}s"
        )
    if sharded["active_pools_walked"] != 1:
        failures.append(
            f"one delta walked {sharded['active_pools_walked']} pools "
            "(must be exactly 1)"
        )
    if sharded["active_tick_s"] > SHARDED_ACTIVE_TICK_CEILING_S:
        failures.append(
            f"active tick took {sharded['active_tick_s']}s > ceiling "
            f"{SHARDED_ACTIVE_TICK_CEILING_S}s (scoped build regressed "
            "to O(fleet)?)"
        )
    sharded["ok"] = not failures
    print(json.dumps(sharded, sort_keys=True))
    if failures:
        for f in failures:
            print(
                f"bench-guard FAIL (sharded, {sharded['nodes']} nodes): "
                f"{f}",
                file=sys.stderr,
            )
        return 1

    battery = measure_probe_battery()
    failures = []
    if not battery["checks_ok"]:
        failures.append("fused battery produced a failing check")
    if battery["fallbacks"]:
        failures.append(
            f"fused battery fell back to unfused probes "
            f"{battery['fallbacks']} time(s)"
        )
    if not battery["warm_cache_hit"]:
        failures.append(
            "second same-topology battery missed the compile cache "
            "(topology key churned?)"
        )
    if battery["warm_s"] > BATTERY_WARM_CEILING_S:
        failures.append(
            f"warm fused battery took {battery['warm_s']}s > ceiling "
            f"{BATTERY_WARM_CEILING_S}s (recompile or extra dispatch in "
            "the warm path?)"
        )
    if not battery["gate_passed"]:
        failures.append("async validation gate never passed")
    elif battery["validation_wall_s"] > VALIDATION_WALL_CEILING_S:
        failures.append(
            f"validation gate wall-clock {battery['validation_wall_s']}s "
            f"> ceiling {VALIDATION_WALL_CEILING_S}s per slice"
        )
    battery["ok"] = not failures
    print(json.dumps(battery, sort_keys=True))
    if failures:
        for f in failures:
            print(f"bench-guard FAIL (battery): {f}", file=sys.stderr)
        return 1

    elastic = measure_elastic(accept=True)
    failures = []
    if not elastic["converged"]:
        failures.append("elastic roll did not converge to upgrade-done")
    if elastic["downtime_s"] != 0.0:
        failures.append(
            f"elastic roll downtime {elastic['downtime_s']}s != 0.00s "
            f"(max canary gap {elastic['max_gap_s']}s > ceiling "
            f"{ELASTIC_GAP_CEILING_S}s — a resize recompiled or the "
            "roll fell back to draining)"
        )
    if elastic["negotiations"].get("accept", 0) != ELASTIC_N_SLICES:
        failures.append(
            f"{elastic['negotiations']} accepted negotiations != "
            f"{ELASTIC_N_SLICES} slices"
        )
    if elastic["resizes"].get("down", 0) != ELASTIC_N_SLICES or elastic[
        "resizes"
    ].get("up", 0) != ELASTIC_N_SLICES:
        failures.append(
            f"resize counters {elastic['resizes']} != {ELASTIC_N_SLICES} "
            "down + up (a slice skipped the exclude/rejoin cycle)"
        )
    if elastic["leftover_excluded"]:
        failures.append(
            f"{elastic['leftover_excluded']} node(s) still carry the "
            "excluded marker after the roll"
        )
    elastic["ok"] = not failures
    print(json.dumps(elastic, sort_keys=True))
    if failures:
        for f in failures:
            print(f"bench-guard FAIL (elastic): {f}", file=sys.stderr)
        return 1

    fallback = measure_elastic(accept=False)
    failures = []
    if not fallback["converged"]:
        failures.append(
            "declined elastic roll did not complete on the drain path"
        )
    if fallback["negotiations"].get("decline", 0) != ELASTIC_N_SLICES:
        failures.append(
            f"{fallback['negotiations']} declined negotiations != "
            f"{ELASTIC_N_SLICES} slices"
        )
    if fallback["resizes"].get("down", 0) or fallback["resizes"].get("up", 0):
        failures.append(
            f"declined roll still resized the workload: "
            f"{fallback['resizes']}"
        )
    fallback["ok"] = not failures
    print(json.dumps(fallback, sort_keys=True))
    if failures:
        for f in failures:
            print(
                f"bench-guard FAIL (elastic fallback): {f}",
                file=sys.stderr,
            )
        return 1

    hetero = measure_heterogeneous()
    failures = []
    if not hetero["converged"]:
        failures.append(
            "mixed-generation roll did not converge to upgrade-done"
        )
    if not hetero["oldest_first"]:
        failures.append(
            f"admission order {hetero['admission_order']} is not "
            "oldest-generation-first (want v4 before v5e)"
        )
    if hetero["held_transitions_while_closed"]:
        failures.append(
            f"window-held pool made "
            f"{hetero['held_transitions_while_closed']} state "
            "transition(s) while its window was closed (must be 0)"
        )
    if hetero["held_cordons_while_closed"]:
        failures.append(
            f"window-held pool held budget while closed "
            f"({hetero['held_cordons_while_closed']} cordoned-node "
            "observations; must be 0)"
        )
    hetero["ok"] = not failures
    print(json.dumps(hetero, sort_keys=True))
    if failures:
        for f in failures:
            print(f"bench-guard FAIL (heterogeneous): {f}", file=sys.stderr)
        return 1

    hygiene = measure_write_hygiene()
    failures = []
    if (
        hygiene["roll_writes_per_transition"]
        > WH_WRITES_PER_TRANSITION_CEILING
    ):
        failures.append(
            f"active roll spent "
            f"{hygiene['roll_writes_per_transition']} node writes per "
            f"state transition (ceiling "
            f"{WH_WRITES_PER_TRANSITION_CEILING}) — the write plane "
            "stopped coalescing or a producer writes around it"
        )
    if hygiene["idle_writes_total"] != 0:
        failures.append(
            f"{hygiene['idle_ticks']} idle sharded ticks at "
            f"{hygiene['idle_nodes']} nodes issued "
            f"{hygiene['idle_writes_total']} API writes (must be "
            "exactly 0 — no-op suppression regressed)"
        )
    if hygiene["event_collapse_ratio"] < WH_EVENT_COLLAPSE_FLOOR:
        failures.append(
            f"identical-event storm collapsed only "
            f"{hygiene['event_collapse_ratio']}:1 (floor "
            f"{WH_EVENT_COLLAPSE_FLOOR}:1 — aggregation window broken)"
        )
    hygiene["ok"] = not failures
    print(json.dumps(hygiene, sort_keys=True))
    if failures:
        for f in failures:
            print(f"bench-guard FAIL (write hygiene): {f}", file=sys.stderr)
        return 1

    planner = measure_planner()
    failures = []
    if planner["plan_wall_s"] > PLAN_WALL_CEILING_S:
        failures.append(
            f"{planner['nodes']}-node plan took "
            f"{planner['plan_wall_s']}s (ceiling {PLAN_WALL_CEILING_S}s "
            "— the analytic planner picked up a per-node API call or "
            "quadratic scan)"
        )
    if planner["plan_writes"] != 0:
        failures.append(
            f"planning issued {planner['plan_writes']} API write "
            "verb(s) (must be exactly 0 — planning is read-only)"
        )
    if not planner["twin_converged"]:
        failures.append("digital twin did not converge to upgrade-done")
    if planner["twin_waves"] != planner["analytic_waves"]:
        failures.append(
            f"twin executed {planner['twin_waves']} wave(s) but the "
            f"analytic plan projected {planner['analytic_waves']} — "
            "the planner's admission model diverged from the engine"
        )
    if not planner["node_wave_agrees"]:
        failures.append(
            "twin node->wave assignment diverged from the analytic plan"
        )
    planner["ok"] = not failures
    print(json.dumps(planner, sort_keys=True))
    if failures:
        for f in failures:
            print(f"bench-guard FAIL (planner): {f}", file=sys.stderr)
        return 1

    packed = measure_packed_admission()
    failures = []
    if packed["packed_waves"] >= packed["greedy_waves"]:
        failures.append(
            f"packed plan took {packed['packed_waves']} wave(s) vs "
            f"greedy {packed['greedy_waves']} at {packed['nodes']} "
            "nodes (must be STRICTLY fewer — FFD stopped packing "
            "residual budget)"
        )
    if packed["packed_duration_s"] >= packed["greedy_duration_s"]:
        failures.append(
            f"packed plan projects {packed['packed_duration_s']}s vs "
            f"greedy {packed['greedy_duration_s']}s (must be strictly "
            "faster)"
        )
    if packed["plan_writes"] != 0:
        failures.append(
            f"planning issued {packed['plan_writes']} API write "
            "verb(s) (must be exactly 0 — planning is read-only)"
        )
    if not packed["engine_greedy_converged"]:
        failures.append("greedy engine roll did not converge")
    if not packed["engine_packed_converged"]:
        failures.append("packed engine roll did not converge")
    if packed["engine_packed_waves"] >= packed["engine_greedy_waves"]:
        failures.append(
            f"live engine rolled {packed['engine_packed_waves']} "
            f"packed wave(s) vs {packed['engine_greedy_waves']} greedy "
            "(must be strictly fewer — the engine is not following "
            "the plan)"
        )
    if packed["engine_packed_mode"] != "packed":
        failures.append(
            "engine admission never used the packed ordering (no "
            "fresh plan reached process_upgrade_required_groups)"
        )
    if not packed["engine_plan_wave_agrees"]:
        failures.append(
            "packed engine admission schedule diverged from the "
            "analytic packed plan's waves"
        )
    if packed["greedy_idle_ticks"] != 0 or packed["packed_idle_ticks"] != 0:
        failures.append(
            f"budget idle ticks with admissible pending work: greedy "
            f"{packed['greedy_idle_ticks']}, packed "
            f"{packed['packed_idle_ticks']} (must be exactly 0 — "
            "admission left affordable work on the table)"
        )
    packed["ok"] = not failures
    print(json.dumps(packed, sort_keys=True))
    if failures:
        for f in failures:
            print(
                f"bench-guard FAIL (packed admission): {f}",
                file=sys.stderr,
            )
        return 1

    tracing = measure_tracing()
    failures = []
    allowed_p99 = (
        tracing["p99_tick_off_s"]
        * (1.0 + TRACING_OVERHEAD_CEILING_PCT / 100.0)
        + TRACING_OVERHEAD_GRACE_S
    )
    if tracing["p99_tick_on_s"] > allowed_p99:
        failures.append(
            f"tracing-on p99 tick {tracing['p99_tick_on_s']}s vs off "
            f"{tracing['p99_tick_off_s']}s breaches the "
            f"{TRACING_OVERHEAD_CEILING_PCT}% overhead ceiling — an "
            "allocation or lock crept onto a hot-path tap"
        )
    if not tracing["trace_completed"]:
        failures.append(
            "the traced roll never produced a completed trace "
            "(maybe_end_roll did not close it)"
        )
    if not tracing["trace_connected"]:
        failures.append(
            f"completed trace is not one connected roll-rooted tree "
            f"({tracing['trace_spans']} spans)"
        )
    if tracing["trace_open_spans"] != 0:
        failures.append(
            f"completed trace still holds "
            f"{tracing['trace_open_spans']} open span(s)"
        )
    if tracing["trace_drops"] != 0:
        failures.append(
            f"recorder dropped {tracing['trace_drops']} record(s) "
            "during a 256-node roll (fail-open fired on the happy path)"
        )
    if tracing["bucket_sum_error_pct"] > TRACING_BUCKET_TOLERANCE_PCT:
        failures.append(
            f"critical-path buckets sum to {tracing['bucket_sum_s']}s "
            f"vs makespan {tracing['makespan_s']}s "
            f"({tracing['bucket_sum_error_pct']}% error > "
            f"{TRACING_BUCKET_TOLERANCE_PCT}% — the attribution walk "
            "double-charged or leaked an interval)"
        )
    if not tracing["idle_tracing_enabled"]:
        failures.append(
            "idle sharded manager was built without a trace recorder "
            "(the 0-pools/0-writes pin below would prove nothing)"
        )
    if tracing["idle_pools_walked_total"] != 0:
        failures.append(
            f"idle sharded ticks with tracing on walked "
            f"{tracing['idle_pools_walked_total']} pools (must be 0)"
        )
    if tracing["idle_writes_total"] != 0:
        failures.append(
            f"idle sharded ticks with tracing on issued "
            f"{tracing['idle_writes_total']} API writes (must be 0 — "
            "a trace anchor stopped riding an existing intent)"
        )
    if tracing["storm_dumps"] == 0:
        failures.append("trigger storm produced zero black-box dumps")
    if tracing["spool_bytes"] > TRACING_SPOOL_CAP_BYTES:
        failures.append(
            f"black-box spool holds {tracing['spool_bytes']} bytes "
            f"after the storm (cap {TRACING_SPOOL_CAP_BYTES} — "
            "oldest-first deletion regressed)"
        )
    tracing["ok"] = not failures
    print(json.dumps(tracing, sort_keys=True))
    if failures:
        for f in failures:
            print(f"bench-guard FAIL (tracing): {f}", file=sys.stderr)
        return 1

    telemetry = measure_telemetry()
    failures = []
    if telemetry["adopted"] != telemetry["nodes"]:
        failures.append(
            f"only {telemetry['adopted']}/{telemetry['nodes']} nodes "
            "re-seeded their history ring from the durable annotation "
            "on adoption"
        )
    if not telemetry["straggler_confirmed"]:
        failures.append(
            f"injected straggler {telemetry['straggler']} (25% below "
            "its generation's median) was not confirmed within one "
            "post-adoption battery"
        )
    if telemetry["false_positives"] != 0:
        failures.append(
            f"{telemetry['false_positives']} healthy node(s) flagged "
            f"as stragglers ({telemetry['confirmed']}) — must be "
            "exactly the injected one"
        )
    if telemetry["drops"] != 0:
        failures.append(
            f"telemetry plane swallowed {telemetry['drops']} error(s) "
            "(fail-open fired on the happy path)"
        )
    if telemetry["extra_writes"] != 0:
        failures.append(
            f"telemetry-enabled roll issued {telemetry['extra_writes']} "
            "extra API write verb(s) vs the telemetry-off roll (must "
            "be exactly 0 — the ring stopped riding the combined "
            "transition patch)"
        )
    if telemetry["rings_persisted"] != telemetry["roll_nodes"]:
        failures.append(
            f"only {telemetry['rings_persisted']}/"
            f"{telemetry['roll_nodes']} nodes hold a non-empty history "
            "ring annotation after the telemetry-enabled roll"
        )
    telemetry["ok"] = not failures
    print(json.dumps(telemetry, sort_keys=True))
    if failures:
        for f in failures:
            print(f"bench-guard FAIL (telemetry): {f}", file=sys.stderr)
        return 1

    federation = measure_federation()
    failures = []
    if not federation["partition_started"]:
        failures.append(
            "the partition window never opened mid-roll (cluster b "
            "finished or never started before the link cut) — the "
            "remaining pins would prove nothing"
        )
    if not federation["converged"]:
        failures.append(
            f"federated roll did not converge after "
            f"{federation['ticks']} ticks (fail-static resume broken?)"
        )
    if federation["partition_window_writes"] != 0:
        failures.append(
            f"coordinator issued {federation['partition_window_writes']} "
            "mutating API verb(s) against the partitioned cluster "
            "during the window (must be exactly 0 — fail-static means "
            "freeze, not retry)"
        )
    # Detection costs exactly one tick (probe failure -> Degraded,
    # engine failure -> Partitioned within that same pass); every
    # remaining window tick must report the cluster skipped.
    if (
        federation["partition_window_skips"]
        < federation["partition_window_ticks"] - 1
    ):
        failures.append(
            f"only {federation['partition_window_skips']}/"
            f"{federation['partition_window_ticks']} window ticks "
            "reported the partitioned cluster as skipped (at most one "
            "detection tick is allowed)"
        )
    if federation["global_budget_violations"] != 0:
        failures.append(
            f"{federation['global_budget_violations']} global-budget "
            "violation(s) (must be exactly 0 — a member charged past "
            "the global cap)"
        )
    if federation["store_writes"] > FED_STORE_WRITE_CEILING:
        failures.append(
            f"durable store took {federation['store_writes']} writes "
            f"over {federation['ticks']} ticks (ceiling "
            f"{FED_STORE_WRITE_CEILING} — state must persist on phase "
            "edges, never per tick)"
        )
    if federation["heals"] < 1:
        failures.append(
            "registry never recorded the heal (the ladder is stuck "
            "in Partitioned)"
        )
    federation["ok"] = not failures
    print(json.dumps(federation, sort_keys=True))
    if failures:
        for f in failures:
            print(f"bench-guard FAIL (federation): {f}", file=sys.stderr)
        return 1

    multi_artifact = measure_multi_artifact()
    failures = []
    if not multi_artifact["converged"]:
        failures.append(
            "a roll did not converge to upgrade-done "
            f"(classic {multi_artifact['classic_ticks']} ticks, stack "
            f"{multi_artifact['stack_ticks']} ticks)"
        )
    if multi_artifact["cordons_per_node"] != [1] or multi_artifact[
        "nodes_cordoned"
    ] != multi_artifact["nodes"]:
        failures.append(
            f"stack roll cordoned {multi_artifact['nodes_cordoned']} "
            f"node(s) {multi_artifact['cordons_per_node']} time(s) each "
            f"(must be every node exactly once — the shared window "
            "split)"
        )
    if multi_artifact["drains_per_node"] != [1] or multi_artifact[
        "nodes_drained"
    ] != multi_artifact["nodes"]:
        failures.append(
            f"stack roll entered the drain window "
            f"{multi_artifact['drains_per_node']} time(s) on "
            f"{multi_artifact['nodes_drained']} node(s) (must be every "
            "node exactly once)"
        )
    if multi_artifact["charges_per_group"] != [1] or multi_artifact[
        "groups_charged"
    ] != multi_artifact["groups"]:
        failures.append(
            f"stack roll charged {multi_artifact['groups_charged']} "
            f"group(s) {multi_artifact['charges_per_group']} time(s) "
            "each (must be one BudgetLedger charge per group for the "
            "whole stack)"
        )
    if not multi_artifact["extra_writes_clean"]:
        failures.append(
            f"write delta vs the classic roll is "
            f"{multi_artifact['write_delta']} (must be exactly "
            f"{multi_artifact['expected_extra_pod_restarts']} pod "
            "deletes + recreates — an extra artifact leaked node "
            "patches, events, or other writes)"
        )
    if multi_artifact["window_savings"] != (
        multi_artifact["nodes"] * MULTI_ART_EXTRA_ARTIFACTS
    ):
        failures.append(
            f"shared-window savings counter "
            f"{multi_artifact['window_savings']} != nodes x extra "
            f"artifacts ({multi_artifact['nodes']} x "
            f"{MULTI_ART_EXTRA_ARTIFACTS})"
        )
    multi_artifact["ok"] = not failures
    print(json.dumps(multi_artifact, sort_keys=True))
    if failures:
        for f in failures:
            print(
                f"bench-guard FAIL (multi-artifact): {f}", file=sys.stderr
            )
        return 1

    # Deliberately LAST: the 100k-node fixture churns ~2 GiB of heap,
    # and the arena fragmentation it leaves behind adds enough timing
    # variance to flip the tracing stage's 5% p99-overhead ceiling on a
    # 1-CPU runner.  Its own pins are counts, identities, and
    # generous-per-op ceilings, so stage ordering cannot flatter them.
    incremental = measure_incremental()
    failures = []
    if incremental["resync_diff_mismatches"] != 0:
        failures.append(
            f"full-resync audit found "
            f"{incremental['resync_diff_mismatches']} view-vs-build_state "
            "mismatch(es) (must be exactly 0 — the incremental apply "
            "path diverged from the authoritative build)"
        )
    if incremental["idle_pools_walked_total"] != 0:
        failures.append(
            f"idle ticks walked {incremental['idle_pools_walked_total']} "
            "pools (must be 0 — tick cost is no longer O(changed))"
        )
    if incremental["idle_api_requests_total"] != 0:
        failures.append(
            f"idle ticks issued {incremental['idle_api_requests_total']} "
            "API requests (must be 0)"
        )
    if incremental["idle_p99_tick_s"] > INC_IDLE_P99_CEILING_S:
        failures.append(
            f"idle p99 tick latency {incremental['idle_p99_tick_s']}s > "
            f"ceiling {INC_IDLE_P99_CEILING_S}s"
        )
    if incremental["active_pools_walked"] != 1:
        failures.append(
            f"one delta walked {incremental['active_pools_walked']} "
            "pools (must be exactly 1)"
        )
    if incremental["active_tick_s"] > INC_ACTIVE_TICK_CEILING_S:
        failures.append(
            f"active tick took {incremental['active_tick_s']}s > ceiling "
            f"{INC_ACTIVE_TICK_CEILING_S}s at {incremental['nodes']} "
            "nodes (fleet size leaked into the dirty path)"
        )
    if incremental["matview_hits"] < 1:
        failures.append(
            "the dirty pool was rebuilt via build_state instead of "
            "served from the materialized view (matview_hits == 0)"
        )
    if not incremental["snapshot_shared"]:
        failures.append(
            "informer snapshot is no longer a COW view "
            "(shared=False — the eager deep-copy snapshot is back)"
        )
    if incremental["snapshot_build_s"] > INC_SNAPSHOT_BUILD_CEILING_S:
        failures.append(
            f"snapshot rebuild took {incremental['snapshot_build_s']}s "
            f"> ceiling {INC_SNAPSHOT_BUILD_CEILING_S}s at "
            f"{incremental['nodes']} nodes (a per-object copy is back "
            "in snapshot construction)"
        )
    if not incremental["snapshot_reused"]:
        failures.append(
            "an unchanged store rebuilt its snapshot instead of "
            "returning the cached object (version clock broken)"
        )
    if incremental["peak_rss_mib"] > INC_RSS_CEILING_MIB:
        failures.append(
            f"peak RSS {incremental['peak_rss_mib']} MiB > budget "
            f"{INC_RSS_CEILING_MIB} MiB (the view or snapshot layer "
            "started copying objects it should only reference)"
        )
    incremental["ok"] = not failures
    print(json.dumps(incremental, sort_keys=True))
    if failures:
        for f in failures:
            print(
                f"bench-guard FAIL (incremental, "
                f"{incremental['nodes']} nodes): {f}",
                file=sys.stderr,
            )
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
