#!/usr/bin/env python
"""Profile one 256-node active-roll reconcile tick.

`make profile` — cProfile over a single build_state + apply_state pass
against a FakeCluster mid-roll (every slice pending upgrade), printing
the top 25 functions by cumulative time.  The first stop when
bench-guard's tick-cost pins regress: the hot path is the same one the
controller runs, minus the network.

`--memory` swaps the CPU profile for an allocation profile: tracemalloc
top-25 call sites by bytes allocated during the tick, plus the process
peak RSS — the first stop when bench-guard's `incremental_100k` RSS pin
regresses (e.g. the materialized-view layer starts copying objects it
should only reference).

Zero external dependencies; everything comes from the repo's own test
fixtures.
"""

from __future__ import annotations

import argparse
import cProfile
import os
import pstats
import resource
import sys
import tracemalloc

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)
sys.path.insert(0, os.path.join(_ROOT, "tests"))

N_SLICES = 64
HOSTS_PER_SLICE = 4  # 64 x 4 = 256 nodes
TOP_N = 25


def build_roll():
    """A 256-node mixed-generation fleet one template bump past DONE."""
    from k8s_operator_libs_tpu.api import (
        DrainSpec,
        IntOrString,
        TPUUpgradePolicySpec,
    )
    from k8s_operator_libs_tpu.k8s import FakeCluster
    from k8s_operator_libs_tpu.upgrade import (
        ClusterUpgradeStateManager,
        UpgradeKeys,
        UpgradeState,
    )

    from fixtures import ClusterFixture, DRIVER_LABELS, NAMESPACE

    generations = [
        "tpu-v4-podslice",
        "tpu-v4-podslice",
        "tpu-v5-lite-podslice",
        "tpu-v6e-slice",
    ]
    keys = UpgradeKeys()
    cluster = FakeCluster()
    fx = ClusterFixture(cluster, keys)
    ds = fx.daemon_set(hash_suffix="v1", revision=1)
    for i in range(N_SLICES):
        nodes = fx.tpu_slice(
            f"pool-{i:03d}",
            hosts=HOSTS_PER_SLICE,
            state=UpgradeState.DONE,
            accelerator=generations[i % len(generations)],
        )
        for n in nodes:
            fx.driver_pod(n, ds, hash_suffix="v1")
    fx.bump_daemon_set_template(ds, "v2", revision=2)
    fx.auto_recreate_driver_pods(ds, "v2")
    policy = TPUUpgradePolicySpec(
        auto_upgrade=True,
        max_parallel_upgrades=8,
        max_unavailable=IntOrString(8),
        drain_spec=DrainSpec(enable=False),
    )
    manager = ClusterUpgradeStateManager(
        cluster, keys=keys, poll_interval_s=0.005, poll_timeout_s=2.0
    )
    return manager, policy, NAMESPACE, DRIVER_LABELS


def tick(manager, policy, namespace, labels) -> None:
    """One full controller-shaped pass: snapshot, act, settle."""
    state = manager.build_state(namespace, labels, policy)
    manager.apply_state(state, policy)
    manager.wait_for_async_work()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--sort",
        default="cumulative",
        choices=["cumulative", "tottime", "calls"],
        help="pstats sort key (default: cumulative)",
    )
    parser.add_argument(
        "--top", type=int, default=TOP_N, help="rows to print"
    )
    parser.add_argument(
        "--memory",
        action="store_true",
        help="profile allocations (tracemalloc) instead of CPU time",
    )
    args = parser.parse_args(argv)

    manager, policy, namespace, labels = build_roll()
    # Warm pass outside the profile: first-touch costs (imports, fixture
    # lazy init) would otherwise drown the steady-state tick.
    tick(manager, policy, namespace, labels)

    if args.memory:
        return _memory_profile(args, manager, policy, namespace, labels)

    prof = cProfile.Profile()
    prof.enable()
    failure: Exception | None = None
    try:
        tick(manager, policy, namespace, labels)
    except Exception as e:  # noqa: BLE001 — report the partial profile
        failure = e
    finally:
        # Without the finally, a tick that raises leaves the profiler
        # enabled and every later frame (argparse teardown, interpreter
        # exit) pollutes the sample — and nothing at all gets printed.
        prof.disable()

    print(
        f"profile: one {N_SLICES * HOSTS_PER_SLICE}-node active-roll "
        f"tick (top {args.top} by {args.sort})"
    )
    if failure is not None:
        print(
            f"tick FAILED mid-profile ({failure!r}); partial profile "
            "up to the failure point:"
        )
    stats = pstats.Stats(prof, stream=sys.stdout)
    stats.strip_dirs().sort_stats(args.sort).print_stats(args.top)
    return 1 if failure is not None else 0


def _memory_profile(args, manager, policy, namespace, labels) -> int:
    """Allocation profile of one tick: top call sites by net bytes
    allocated (tracemalloc diff around the tick) + peak RSS."""
    tracemalloc.start(25)
    before = tracemalloc.take_snapshot()
    failure: Exception | None = None
    try:
        tick(manager, policy, namespace, labels)
    except Exception as e:  # noqa: BLE001 — report the partial profile
        failure = e
    after = tracemalloc.take_snapshot()
    _, traced_peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()

    print(
        f"memory profile: one {N_SLICES * HOSTS_PER_SLICE}-node "
        f"active-roll tick (top {args.top} call sites by net bytes)"
    )
    if failure is not None:
        print(
            f"tick FAILED mid-profile ({failure!r}); partial profile "
            "up to the failure point:"
        )
    for stat in after.compare_to(before, "lineno")[: args.top]:
        print(stat)
    # ru_maxrss is KiB on Linux, bytes on macOS.
    maxrss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    rss_mib = maxrss / 1024 if sys.platform != "darwin" else maxrss / 2**20
    print(f"tracemalloc peak during tick: {traced_peak / 2**20:.1f} MiB")
    print(f"process peak RSS: {rss_mib:.1f} MiB")
    return 1 if failure is not None else 0


if __name__ == "__main__":
    raise SystemExit(main())
