#!/usr/bin/env python
"""Render a fleet health report from the telemetry plane.

`make health-report` — the operator-facing view of obs/telemetry.py:
per-generation cohort baselines (median ± MAD of every measured probe
stat), the node health-score distribution, and any outliers/confirmed
stragglers.  Two sources:

- ``--metrics-url http://host:port/metrics`` reads a live controller's
  exposition (the same families the status CLI consumes:
  node_health_score, fleet_stragglers, probe_measured).
- default: builds a fake mixed-generation fleet, seeds a TelemetryPlane
  with synthetic probe histories (one injected straggler per
  generation), and reports on that — the quickest way to SEE what the
  telemetry plane produces without standing up a controller.

Zero external dependencies; the fake path uses only the repo itself.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

# Fake-fleet shape: (generation, pool, node count, baseline stats).
FAKE_COHORTS = [
    ("tpu-v4-podslice", "pool-a", 16, {"tflops": 240.0, "gbps": 980.0}),
    ("tpu-v5-lite-podslice", "pool-b", 16, {"tflops": 360.0, "gbps": 1400.0}),
    ("tpu-v6e-slice", "pool-c", 16, {"tflops": 880.0, "gbps": 3200.0}),
]
FAKE_BATTERIES = 4
# Injected straggler: last node of each cohort runs this fraction of
# its generation's baseline.
FAKE_STRAGGLER_FRACTION = 0.75

SCORE_BUCKETS = [(90.0, "90-100"), (75.0, "75-90"), (50.0, "50-75"),
                 (25.0, "25-50"), (0.0, "0-25")]


def build_fake_plane():
    """Seed a TelemetryPlane from a synthetic mixed-generation fleet."""
    from k8s_operator_libs_tpu.obs.telemetry import TelemetryPlane

    plane = TelemetryPlane()
    # Deterministic jitter so MAD is non-zero without pulling in random.
    for gen, pool, count, stats in FAKE_COHORTS:
        for battery in range(FAKE_BATTERIES):
            for i in range(count):
                scale = 1.0 + 0.004 * ((i * 7 + battery * 3) % 5 - 2)
                if i == count - 1:
                    scale *= FAKE_STRAGGLER_FRACTION
                sample = {k: v * scale for k, v in stats.items()}
                sample["battery_execute_ms"] = 40.0 / scale
                plane.ingest(
                    f"{gen.split('-')[1]}-{pool}-w{i}",
                    sample,
                    generation=gen,
                    pool=pool,
                )
    plane.recompute()
    return plane


def report_from_plane(plane) -> dict:
    """Shape a report dict from a live TelemetryPlane instance."""
    status = plane.to_status()
    view = plane.metrics_view()
    return {
        "cohorts": (status.get("healthSummary") or {}).get("cohorts") or [],
        "scores": view["scores"],
        "stragglers": status.get("stragglers") or [],
        "samples": view["samples_total"],
        "drops": view["drops"],
        "measured": {
            f"{check}/{stat}": val
            for (check, stat), val in sorted(view["measured"].items())
        },
    }


def report_from_metrics(metrics_url: str) -> dict:
    """Shape the same report from a controller's /metrics exposition."""
    from k8s_operator_libs_tpu.metrics import PREFIX
    from urllib.request import urlopen

    with urlopen(metrics_url, timeout=5) as resp:
        text = resp.read().decode()
    scores: dict[str, float] = {}
    measured: dict[str, float] = {}
    stragglers: list[dict] = []
    samples = drops = 0
    for line in text.splitlines():
        if line.startswith("#") or " " not in line:
            continue
        name, _, value = line.rpartition(" ")
        labels = ""
        if "{" in name:
            name, _, labels = name.partition("{")
        if not name.startswith(PREFIX + "_"):
            continue
        short = name[len(PREFIX) + 1 :]
        try:
            val = float(value)
        except ValueError:
            continue

        def label(key: str) -> str:
            part = labels.split(f'{key}="', 1)
            return part[1].split('"', 1)[0] if len(part) == 2 else ""

        if short == "node_health_score":
            scores[label("node")] = val
        elif short == "fleet_stragglers" and val:
            stragglers.append(
                {
                    "generation": label("generation"),
                    "pool": label("pool"),
                    "count": int(val),
                }
            )
        elif short == "probe_measured":
            measured[f"{label('check')}/{label('stat')}"] = val
        elif short == "telemetry_samples_total":
            samples = int(val)
        elif short == "telemetry_drops_total":
            drops = int(val)
    return {
        "cohorts": [],  # per-cohort baselines live on the CR, not /metrics
        "scores": scores,
        "stragglers": stragglers,
        "samples": samples,
        "drops": drops,
        "measured": measured,
    }


def render(report: dict) -> str:
    lines = []
    scores = report["scores"]
    lines.append(
        f"fleet health report: {len(scores)} node(s) scored | "
        f"{report['samples']} sample(s) ingested, "
        f"{report['drops']} drop(s)"
    )
    if report["cohorts"]:
        lines.append("")
        lines.append("per-generation baselines (median ± MAD):")
        for cohort in report["cohorts"]:
            stats = ", ".join(
                f"{stat} {b['median']:g}±{b['mad']:g}"
                for stat, b in sorted(cohort.get("baseline", {}).items())
            )
            lines.append(
                f"  {cohort['generation'] or '?':22s} "
                f"{cohort['pool'] or 'default':10s} "
                f"{cohort['nodes']:>3d} node(s)  {stats}"
            )
    if report["measured"]:
        lines.append("")
        lines.append("fleet-median measured stats (latest battery):")
        for key, val in sorted(report["measured"].items()):
            lines.append(f"  {key:36s} {val:g}")
    if scores:
        lines.append("")
        lines.append("score distribution:")
        total = len(scores)
        counts = {label: 0 for _, label in SCORE_BUCKETS}
        for s in scores.values():
            for floor, bucket_label in SCORE_BUCKETS:
                if s >= floor:
                    counts[bucket_label] += 1
                    break
        for _, bucket_label in SCORE_BUCKETS:
            n = counts[bucket_label]
            bar = "#" * max(1, round(40 * n / total)) if n else ""
            lines.append(f"  {bucket_label:>7s}  {n:>4d}  {bar}")
        worst = sorted(scores.items(), key=lambda kv: kv[1])[:5]
        outliers = [(n, s) for n, s in worst if s < 75.0]
        if outliers:
            lines.append("")
            lines.append("outliers (score < 75):")
            for node, score in outliers:
                lines.append(f"  {node:36s} {score:.1f}")
    if report["stragglers"]:
        lines.append("")
        lines.append("confirmed stragglers:")
        for s in report["stragglers"]:
            if "node" in s:
                lines.append(
                    f"  {s['node']:36s} "
                    f"{s.get('generation', '') or '?'}/"
                    f"{s.get('pool', '') or 'default'}  score "
                    f"{s.get('score', 0.0)}  z {s.get('z', 0.0)} on "
                    f"{s.get('worstStat', '')} over "
                    f"{s.get('streak', 0)} batteries"
                )
            else:
                lines.append(
                    f"  {s.get('generation', '') or '?'}/"
                    f"{s.get('pool', '') or 'default'}: "
                    f"{s.get('count', 0)} node(s)"
                )
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--metrics-url",
        default="",
        help="read a live controller's /metrics instead of the fake fleet",
    )
    parser.add_argument(
        "--json",
        action="store_true",
        help="emit the report dict as JSON instead of text",
    )
    args = parser.parse_args(argv)
    if args.metrics_url:
        report = report_from_metrics(args.metrics_url)
    else:
        report = report_from_plane(build_fake_plane())
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        print(render(report))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
