"""Chaos battery runner — `make chaos` entrypoint.

Runs each fault-injection battery as its own pytest process (so one
battery's crash — segfault, hang past the per-battery timeout, fixture
leak — cannot mask or poison the others), then prints a one-line-per-
battery summary table and exits nonzero if ANY battery failed.

The batteries, in dependency-light-to-heavy order:

* ``test_fault_tolerance.py`` — retry ladder, circuit breaker (incl.
  the concurrent half-open probe race), resilient client wiring.
* ``test_node_faults.py``    — mid-roll hardware loss, slice
  quarantine, eviction escalation.
* ``test_chaos.py``          — full rolls through API fault schedules,
  controller crash/adoption, fenced-writer abandonment.
* ``test_fuzz_invariants.py``— seed-parameterized randomized rolls
  with global invariant checks.
* ``test_federation.py``     — cross-cluster partitions, fail-static
  freeze/resume, canary holds, global budget hierarchy.

``PYTHONHASHSEED`` is pinned to 0 for every battery: the fuzz
scenarios are seed-parameterized already, so set iteration order is
the one remaining source of cross-run variation.
"""

from __future__ import annotations

import argparse
import os
import re
import subprocess
import sys
import time

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

BATTERIES = [
    "tests/test_fault_tolerance.py",
    "tests/test_node_faults.py",
    "tests/test_chaos.py",
    "tests/test_fuzz_invariants.py",
    "tests/test_federation.py",
]

# Per-battery wall-clock cap.  A hung battery (deadlocked half-open
# probe, stuck poll loop) should fail ITS row, not wedge the target.
BATTERY_TIMEOUT_S = 600

_COUNT = re.compile(r"(\d+) (passed|failed|error|errors|skipped|xfailed)")


def _tally(output: str) -> dict:
    """Fold pytest's final summary line into {outcome: count}."""
    counts: dict = {}
    for line in reversed(output.splitlines()):
        found = _COUNT.findall(line)
        if found and ("passed" in line or "failed" in line or "error" in line):
            for n, outcome in found:
                counts[outcome.rstrip("s")] = int(n)
            break
    return counts


def run_battery(path: str, extra_args: list) -> dict:
    env = dict(os.environ)
    env["PYTHONHASHSEED"] = "0"
    env.setdefault("JAX_PLATFORMS", "cpu")
    # No explicit -q: pyproject's addopts already passes one, and a
    # second would stack to -qq, which drops the "N passed" summary
    # line the table is built from.
    cmd = [
        sys.executable,
        "-m",
        "pytest",
        "-p",
        "no:cacheprovider",
        path,
        *extra_args,
    ]
    started = time.monotonic()
    try:
        proc = subprocess.run(
            cmd,
            cwd=_ROOT,
            env=env,
            capture_output=True,
            text=True,
            timeout=BATTERY_TIMEOUT_S,
        )
        rc = proc.returncode
        output = proc.stdout + proc.stderr
    except subprocess.TimeoutExpired as exc:
        rc = -1
        output = (exc.stdout or "") + (exc.stderr or "")
        output += f"\nTIMEOUT after {BATTERY_TIMEOUT_S}s"
    wall_s = time.monotonic() - started
    counts = _tally(output)
    return {
        "battery": os.path.basename(path),
        "rc": rc,
        "wall_s": wall_s,
        "passed": counts.get("passed", 0),
        "failed": counts.get("failed", 0) + counts.get("error", 0),
        "skipped": counts.get("skipped", 0),
        "output": output,
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument(
        "batteries",
        nargs="*",
        default=None,
        help="battery files to run (default: the full ladder)",
    )
    parser.add_argument(
        "-k",
        dest="keyword",
        default="",
        help="pytest -k expression forwarded to every battery",
    )
    args = parser.parse_args(argv)
    batteries = args.batteries or BATTERIES
    extra = ["-k", args.keyword] if args.keyword else []

    results = [run_battery(path, extra) for path in batteries]

    width = max(len(r["battery"]) for r in results)
    header = (
        f"{'battery':<{width}}  {'verdict':<7}  {'passed':>6}  "
        f"{'failed':>6}  {'skipped':>7}  {'wall':>7}"
    )
    print()
    print(header)
    print("-" * len(header))
    any_failed = False
    for r in results:
        # rc 5 = "no tests collected" (e.g. -k matched nothing): not a
        # failure of the battery itself.
        ok = r["rc"] in (0, 5) and r["failed"] == 0
        any_failed = any_failed or not ok
        verdict = "ok" if ok else ("TIMEOUT" if r["rc"] == -1 else "FAIL")
        print(
            f"{r['battery']:<{width}}  {verdict:<7}  {r['passed']:>6}  "
            f"{r['failed']:>6}  {r['skipped']:>7}  {r['wall_s']:>6.1f}s"
        )
    print("-" * len(header))
    total_passed = sum(r["passed"] for r in results)
    total_failed = sum(r["failed"] for r in results)
    print(
        f"{'total':<{width}}  {'FAIL' if any_failed else 'ok':<7}  "
        f"{total_passed:>6}  {total_failed:>6}"
    )
    if any_failed:
        # Replay the failing batteries' full output so the first
        # failure is diagnosable straight from the CI log.
        for r in results:
            if r["rc"] not in (0, 5) or r["failed"]:
                print(f"\n=== {r['battery']} (rc {r['rc']}) ===")
                print(r["output"])
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
