#!/usr/bin/env python
"""Line-coverage runner (reference parity: coverage in CI,
.github/workflows/ci.yaml:50-66 — pytest-cov/coverage.py are not
installable in every environment this repo builds in, so the gate ships
with the repo).

Uses ``sys.monitoring`` (PEP 669): the LINE callback DISABLEs each
location after its first hit, so steady-state overhead is near zero —
the full suite runs at roughly native speed.

Usage::

    python tools/cover.py [--threshold PCT] [--report] -- PYTEST_ARGS...

Runs pytest in-process under instrumentation, prints per-file and total
coverage for ``k8s_operator_libs_tpu``, and exits non-zero when total
coverage is below the threshold (or when the suite itself fails).
"""

from __future__ import annotations

import argparse
import os
import sys

PACKAGE = "k8s_operator_libs_tpu"
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG_DIR = os.path.join(REPO_ROOT, PACKAGE)
# ``python tools/cover.py`` puts tools/ on sys.path, not the repo root
# the test modules import from.
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

_hits: dict[str, set[int]] = {}


def _on_line(code, line):
    fname = code.co_filename
    if fname.startswith(PKG_DIR):
        _hits.setdefault(fname, set()).add(line)
    return sys.monitoring.DISABLE


def _executable_lines(path: str) -> set[int]:
    """All line numbers the compiler can attribute code to, from the
    compiled code object tree (matches what LINE events can report)."""
    with open(path, "rb") as f:
        src = f.read()
    try:
        top = compile(src, path, "exec")
    except SyntaxError:
        return set()
    lines: set[int] = set()
    stack = [top]
    while stack:
        code = stack.pop()
        for _, _, line in code.co_lines():
            if line is not None and line > 0:
                lines.add(line)
        for const in code.co_consts:
            if hasattr(const, "co_lines"):
                stack.append(const)
    return lines


def _ranges(lines: list[int]) -> str:
    """Compress [3,4,5,9] to '3-5, 9'."""
    out = []
    i = 0
    while i < len(lines):
        j = i
        while j + 1 < len(lines) and lines[j + 1] == lines[j] + 1:
            j += 1
        out.append(
            str(lines[i]) if i == j else f"{lines[i]}-{lines[j]}"
        )
        i = j + 1
    return ", ".join(out)


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--threshold", type=float, default=70.0)
    parser.add_argument(
        "--report", action="store_true", help="per-file detail"
    )
    parser.add_argument(
        "--missing",
        default="",
        metavar="SUBSTR",
        help="also print missed line numbers for files whose path "
        "contains SUBSTR",
    )
    parser.add_argument("pytest_args", nargs="*", default=[])
    args = parser.parse_args()

    tool = sys.monitoring.COVERAGE_ID
    sys.monitoring.use_tool_id(tool, "tpu-operator-cover")
    sys.monitoring.register_callback(
        tool, sys.monitoring.events.LINE, _on_line
    )
    sys.monitoring.set_events(tool, sys.monitoring.events.LINE)

    import pytest

    rc = pytest.main(args.pytest_args or ["tests/", "-q"])

    sys.monitoring.set_events(tool, 0)
    sys.monitoring.free_tool_id(tool)

    total_exec = 0
    total_hit = 0
    rows = []
    for root, dirs, files in os.walk(PKG_DIR):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for f in sorted(files):
            if not f.endswith(".py"):
                continue
            path = os.path.join(root, f)
            executable = _executable_lines(path)
            hit = _hits.get(path, set()) & executable
            total_exec += len(executable)
            total_hit += len(hit)
            pct = 100.0 * len(hit) / len(executable) if executable else 100.0
            rel = os.path.relpath(path, REPO_ROOT)
            rows.append((rel, pct, len(hit), len(executable)))
            missed = sorted(executable - hit)
            if args.missing and args.missing in rel and missed:
                print(f"{rel} missing: {_ranges(missed)}")

    if args.report:
        for rel, pct, hit, executable in rows:
            print(f"{rel:64s} {pct:6.1f}%  ({hit}/{executable})")
    total_pct = 100.0 * total_hit / total_exec if total_exec else 0.0
    print(
        f"TOTAL coverage: {total_pct:.1f}% "
        f"({total_hit}/{total_exec} lines, threshold {args.threshold:.0f}%)"
    )
    if rc != 0:
        print("cover: test suite FAILED", file=sys.stderr)
        return int(rc)
    if total_pct < args.threshold:
        print(
            f"cover: coverage {total_pct:.1f}% below threshold "
            f"{args.threshold:.0f}%",
            file=sys.stderr,
        )
        return 2
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
