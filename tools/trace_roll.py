#!/usr/bin/env python
"""Drive one fake-tier roll with tracing on and print the span tree.

`make trace` — rolls a small FakeCluster fleet end to end through the
real engine with the TraceRecorder enabled, then prints the completed
causal span tree (roll -> pool -> wave -> slice-group -> phase/wait)
and its critical-path makespan attribution.  The quickest way to SEE
what obs/trace.py + obs/critical.py produce without standing up a
controller; the same rendering the status CLI shows for a live roll.

Zero external dependencies; everything comes from the repo's own test
fixtures.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)
sys.path.insert(0, os.path.join(_ROOT, "tests"))

N_SLICES = 4
HOSTS_PER_SLICE = 4
ROLL_BUDGET_S = 120.0


def run_traced_roll(slices: int, hosts: int):
    """Roll a fresh fleet to upgrade-done; returns (manager, trace)."""
    from k8s_operator_libs_tpu.api import (
        DrainSpec,
        IntOrString,
        TPUUpgradePolicySpec,
    )
    from k8s_operator_libs_tpu.k8s import FakeCluster
    from k8s_operator_libs_tpu.upgrade import (
        ClusterUpgradeStateManager,
        UpgradeKeys,
        UpgradeState,
    )

    from fixtures import ClusterFixture, DRIVER_LABELS, NAMESPACE

    keys = UpgradeKeys()
    cluster = FakeCluster()
    fx = ClusterFixture(cluster, keys)
    ds = fx.daemon_set(hash_suffix="v1", revision=1)
    names = []
    for i in range(slices):
        for n in fx.tpu_slice(f"pool-{i:02d}", hosts=hosts):
            fx.driver_pod(n, ds, hash_suffix="v1")
            names.append(n.name)
    fx.bump_daemon_set_template(ds, "v2", revision=2)
    fx.auto_recreate_driver_pods(ds, "v2")
    policy = TPUUpgradePolicySpec(
        auto_upgrade=True,
        max_parallel_upgrades=2,
        max_unavailable=IntOrString("50%"),
        drain_spec=DrainSpec(enable=False),
    )
    manager = ClusterUpgradeStateManager(
        cluster, keys=keys, poll_interval_s=0.005, poll_timeout_s=2.0
    )
    deadline = time.monotonic() + ROLL_BUDGET_S
    while time.monotonic() < deadline:
        state = manager.build_state(NAMESPACE, DRIVER_LABELS, policy)
        manager.apply_state(state, policy)
        manager.wait_for_async_work(30.0)
        if all(
            cluster.get_node(n, cached=False).labels.get(keys.state_label)
            == UpgradeState.DONE.value
            for n in names
        ):
            break
    else:
        raise RuntimeError("roll did not converge inside its budget")
    # Settling ticks: the closing maybe_end_roll runs on the apply pass
    # AFTER the last async state flip lands.
    for _ in range(2):
        state = manager.build_state(NAMESPACE, DRIVER_LABELS, policy)
        manager.apply_state(state, policy)
        manager.wait_for_async_work(10.0)
    recorder = manager.trace_recorder
    trace = recorder.last_completed() if recorder is not None else None
    if trace is None:
        raise RuntimeError("roll completed but produced no trace")
    return manager, trace


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--slices", type=int, default=N_SLICES, help="slice-group count"
    )
    parser.add_argument(
        "--hosts", type=int, default=HOSTS_PER_SLICE, help="hosts per slice"
    )
    parser.add_argument(
        "--json",
        action="store_true",
        help="emit the makespanBreakdown block as JSON instead of text",
    )
    args = parser.parse_args(argv)

    from k8s_operator_libs_tpu.obs.critical import (
        analyze,
        makespan_breakdown,
        render_breakdown,
        render_tree,
    )

    _, trace = run_traced_roll(args.slices, args.hosts)
    attribution = analyze(trace)
    breakdown = makespan_breakdown(attribution)
    if args.json:
        print(json.dumps(breakdown, indent=2, sort_keys=True))
        return 0
    print(
        f"traced roll: {args.slices} slice(s) x {args.hosts} host(s), "
        f"{len(trace.spans)} spans"
    )
    print()
    print(render_tree(trace))
    print()
    print(render_breakdown(breakdown))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
