#!/usr/bin/env python
"""Self-contained linter (reference parity: golangci-lint gates CI,
.golangci.yaml:15 — no Python linter is installable in every environment
this repo builds in, so the gate ships with the repo).

Checks, all hard failures (exit 1):

- **syntax**: every file must parse;
- **F401 unused imports**: an imported name never referenced in the
  module (``# noqa`` / ``# noqa: F401`` on the import line exempts;
  ``__init__.py`` re-export surfaces rely on that, same as pyflakes);
- **F821 undefined names**: a name the compiler resolves as an implicit
  global that is neither a module global, a builtin, nor a wildcard
  import — the "typo in an error path" class golangci's typecheck
  catches (uses the real symtable, so comprehension/closure scopes
  resolve correctly);
- **E722 bare except**;
- **B006 mutable default arguments** (list/dict/set literals or calls).

Usage: ``python tools/lint.py PATH [PATH...]`` — directories recurse.
"""

from __future__ import annotations

import ast
import builtins
import os
import sys
import symtable
import tokenize


def _noqa_lines(path: str) -> dict[int, set[str]]:
    """line -> set of silenced codes ('*' = all) from ``# noqa`` comments."""
    out: dict[int, set[str]] = {}
    try:
        with tokenize.open(path) as f:
            tokens = tokenize.generate_tokens(f.readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                comment = tok.string
                if "noqa" not in comment.lower():
                    continue
                _, _, codes = comment.lower().partition("noqa")
                codes = codes.lstrip(":").strip()
                if codes:
                    out[tok.start[0]] = {
                        c.strip().upper()
                        for c in codes.replace(",", " ").split()
                    }
                else:
                    out[tok.start[0]] = {"*"}
    except (OSError, tokenize.TokenizeError, SyntaxError):
        pass
    return out


def _silenced(noqa: dict[int, set[str]], line: int, code: str) -> bool:
    codes = noqa.get(line)
    if not codes:
        return False
    # Codes may be pyflakes-style (F401) or prose ('F401 — re-export');
    # match on the bare code or a wildcard.
    return "*" in codes or any(code in c for c in codes)


class _Findings:
    def __init__(self) -> None:
        self.items: list[str] = []

    def add(self, path: str, line: int, code: str, msg: str) -> None:
        self.items.append(f"{path}:{line}: {code} {msg}")


def _module_scope_names(tree: ast.Module) -> set[str]:
    """Names bound at module scope (incl. conditional/try branches,
    walrus expressions anywhere in module-level statements, and
    match-case capture patterns)."""
    names: set[str] = set()

    def bind_target(t: ast.AST) -> None:
        for node in ast.walk(t):
            if isinstance(node, ast.Name):
                names.add(node.id)

    def bind_expressions(stmt: ast.stmt) -> None:
        """Walrus targets and match captures bind in the enclosing
        (module) scope wherever they appear in the statement — but not
        inside nested function/class bodies, whose walruses bind there."""
        for node in ast.walk(stmt):
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                continue  # ast.walk still descends; close enough — a
                # nested-scope walrus adding a module name is a
                # false-NEGATIVE for F821, never a false positive.
            if isinstance(node, ast.NamedExpr) and isinstance(
                node.target, ast.Name
            ):
                names.add(node.target.id)
            if isinstance(node, ast.MatchAs) and node.name:
                names.add(node.name)
            if isinstance(node, ast.MatchStar) and node.name:
                names.add(node.name)
            if isinstance(node, ast.MatchMapping) and node.rest:
                names.add(node.rest)

    def visit_body(body: list[ast.stmt]) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.Import, ast.ImportFrom)):
                for alias in stmt.names:
                    if alias.name == "*":
                        continue
                    names.add((alias.asname or alias.name).split(".")[0])
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef)):
                names.add(stmt.name)
            elif isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    bind_target(t)
            elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
                bind_target(stmt.target)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                bind_target(stmt.target)
                visit_body(stmt.body)
                visit_body(stmt.orelse)
            elif isinstance(stmt, (ast.If, ast.While)):
                visit_body(stmt.body)
                visit_body(stmt.orelse)
            elif isinstance(stmt, ast.Try):
                visit_body(stmt.body)
                for h in stmt.handlers:
                    if h.name:
                        names.add(h.name)
                    visit_body(h.body)
                visit_body(stmt.orelse)
                visit_body(stmt.finalbody)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    if item.optional_vars is not None:
                        bind_target(item.optional_vars)
                visit_body(stmt.body)
            elif isinstance(stmt, ast.Match):
                for case in stmt.cases:
                    visit_body(case.body)
            elif isinstance(stmt, ast.Delete):
                pass

    # One expression-binding pass over the top-level statements covers
    # every nested body (ast.walk is recursive); visit_body recursion
    # must not repeat it per nesting level.
    for stmt in tree.body:
        bind_expressions(stmt)
    visit_body(tree.body)
    return names


def _has_star_import(tree: ast.Module) -> bool:
    return any(
        isinstance(s, ast.ImportFrom)
        and any(a.name == "*" for a in s.names)
        for s in ast.walk(tree)
    )


def _check_unused_imports(
    path: str, tree: ast.Module, noqa: dict[int, set[str]], out: _Findings
) -> None:
    imported: dict[str, tuple[int, str]] = {}
    for stmt in ast.walk(tree):
        if isinstance(stmt, ast.Import):
            for alias in stmt.names:
                name = alias.asname or alias.name.split(".")[0]
                imported[name] = (stmt.lineno, alias.name)
        elif isinstance(stmt, ast.ImportFrom):
            if stmt.module == "__future__":
                continue  # compiler directive, not a binding to "use"
            for alias in stmt.names:
                if alias.name == "*":
                    continue
                name = alias.asname or alias.name
                imported[name] = (stmt.lineno, alias.name)
    used: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            root = node
            while isinstance(root, ast.Attribute):
                root = root.value
            if isinstance(root, ast.Name):
                used.add(root.id)
    # Names exported via a literal __all__ count as used.
    for stmt in tree.body:
        if (
            isinstance(stmt, ast.Assign)
            and any(
                isinstance(t, ast.Name) and t.id == "__all__"
                for t in stmt.targets
            )
            and isinstance(stmt.value, (ast.List, ast.Tuple))
        ):
            for elt in stmt.value.elts:
                if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                    used.add(elt.value)
    for name, (line, target) in sorted(imported.items()):
        if name in used or name == "_":
            continue
        if _silenced(noqa, line, "F401"):
            continue
        out.add(path, line, "F401", f"'{target}' imported but unused")


def _check_undefined_names(
    path: str, src: str, tree: ast.Module, noqa: dict[int, set[str]],
    out: _Findings,
) -> None:
    if _has_star_import(tree):
        return  # cannot resolve; same concession pyflakes makes
    module_names = _module_scope_names(tree)
    known = module_names | set(dir(builtins)) | {
        "__file__", "__name__", "__doc__", "__package__", "__spec__",
        "__loader__", "__builtins__", "__debug__", "__path__", "__class__",
    }
    try:
        table = symtable.symtable(src, path, "exec")
    except SyntaxError:
        return
    # Walk nested scopes; flag implicit globals unknown at module scope.
    # Line attribution: find a Name node matching in the scope's range.
    name_lines: dict[str, list[int]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            name_lines.setdefault(node.id, []).append(node.lineno)

    reported: set[tuple[str, int]] = set()

    def walk(scope: symtable.SymbolTable) -> None:
        for sym in scope.get_symbols():
            name = sym.get_name()
            if name in known or not sym.is_referenced():
                continue
            if sym.is_local() or sym.is_parameter() or sym.is_imported():
                continue
            if getattr(sym, "is_free", lambda: False)():
                continue
            if sym.is_declared_global() or sym.is_global():
                lines = name_lines.get(name, [scope.get_lineno()])
                line = lines[0]
                key = (name, line)
                if key in reported or _silenced(noqa, line, "F821"):
                    continue
                reported.add(key)
                out.add(path, line, "F821", f"undefined name '{name}'")
        for child in scope.get_children():
            walk(child)

    # Module scope itself: loads of unknown names.
    for sym in table.get_symbols():
        name = sym.get_name()
        if name in known or not sym.is_referenced():
            continue
        if sym.is_imported() or sym.is_assigned():
            continue
        lines = name_lines.get(name, [1])
        line = lines[0]
        if not _silenced(noqa, line, "F821"):
            key = (name, line)
            if key not in reported:
                reported.add(key)
                out.add(path, line, "F821", f"undefined name '{name}'")
    for child in table.get_children():
        walk(child)


def _check_misc(
    path: str, tree: ast.Module, noqa: dict[int, set[str]], out: _Findings
) -> None:
    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            if not _silenced(noqa, node.lineno, "E722"):
                out.add(path, node.lineno, "E722", "bare 'except:'")
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defaults = list(node.args.defaults) + [
                d for d in node.args.kw_defaults if d is not None
            ]
            for d in defaults:
                if isinstance(d, (ast.List, ast.Dict, ast.Set)):
                    if not _silenced(noqa, d.lineno, "B006"):
                        out.add(
                            path, d.lineno, "B006",
                            f"mutable default argument in '{node.name}'",
                        )


def lint_file(path: str, out: _Findings) -> None:
    try:
        with tokenize.open(path) as f:
            src = f.read()
    except (OSError, SyntaxError) as e:
        out.add(path, 0, "E902", f"cannot read: {e}")
        return
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        out.add(path, e.lineno or 0, "E999", f"syntax error: {e.msg}")
        return
    noqa = _noqa_lines(path)
    _check_unused_imports(path, tree, noqa, out)
    _check_undefined_names(path, src, tree, noqa, out)
    _check_misc(path, tree, noqa, out)


def main(argv: list[str]) -> int:
    paths: list[str] = []
    for arg in argv or ["."]:
        if os.path.isdir(arg):
            for root, dirs, files in os.walk(arg):
                dirs[:] = [
                    d for d in dirs
                    if d not in ("__pycache__", ".git", ".pytest_cache")
                ]
                paths.extend(
                    os.path.join(root, f) for f in files if f.endswith(".py")
                )
        elif arg.endswith(".py"):
            paths.append(arg)
    out = _Findings()
    for path in sorted(paths):
        lint_file(path, out)
    for item in out.items:
        print(item)
    print(
        f"lint: {len(paths)} files, {len(out.items)} finding(s)",
        file=sys.stderr,
    )
    return 1 if out.items else 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
