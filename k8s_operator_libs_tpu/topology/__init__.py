"""TPU slice topology model.

The structural analogue of long-context/sequence parallelism in the
reference's domain (SURVEY.md §5): which hosts form one ICI domain and must
therefore move through the upgrade state machine atomically.
"""

from k8s_operator_libs_tpu.topology.slices import (  # noqa: F401
    ACCELERATOR_CHIPS_PER_HOST,
    SliceInfo,
    discover_slices,
    hosts_for_topology,
    parse_topology,
)
