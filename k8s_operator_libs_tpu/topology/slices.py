"""Slice discovery from node labels, and slice-shape math.

New first-class component (SURVEY.md §2.3, §7 step 1): the reference has no
topology model — its schedulable unit is a node.  Here we read the public
GKE TPU node labels (``cloud.google.com/gke-tpu-topology``,
``gke-tpu-accelerator``, ``gke-tpu-worker-id``, ``gke-nodepool``) — or our
own fallback labels — and group nodes into ICI slices.  A multi-host slice
is one torus: cordoning or draining any host interrupts the collective for
every host, so the whole slice is the atomic upgrade unit.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import TYPE_CHECKING, Optional

from k8s_operator_libs_tpu.k8s.objects import Node

if TYPE_CHECKING:  # avoid a runtime cycle with the upgrade package
    from k8s_operator_libs_tpu.upgrade.util import UpgradeKeys

# GKE TPU node labels used for slice discovery (public GKE conventions).
# Canonical home is here; upgrade.consts re-exports them.
GKE_TPU_ACCELERATOR_LABEL = "cloud.google.com/gke-tpu-accelerator"
GKE_TPU_TOPOLOGY_LABEL = "cloud.google.com/gke-tpu-topology"
GKE_TPU_WORKER_ID_LABEL = "cloud.google.com/gke-tpu-worker-id"
GKE_NODEPOOL_LABEL = "cloud.google.com/gke-nodepool"
# Multi-slice training on GKE runs under JobSet; slices whose nodes carry
# the same jobset back one DCN data-parallel job and must never be down
# simultaneously (BASELINE config 5).  Used as the dcn-group fallback
# when our explicit dcn-group label is absent.  JobSet names are
# namespace-scoped, so the fallback combines namespace/name when the
# namespace label is present — two teams' same-named JobSets must not be
# merged into one DCN group.
JOBSET_NAME_LABEL = "jobset.sigs.k8s.io/jobset-name"
JOBSET_NAMESPACE_LABEL = "jobset.sigs.k8s.io/jobset-namespace"


def _jobset_dcn_group(labels: dict[str, str]) -> Optional[str]:
    name = labels.get(JOBSET_NAME_LABEL)
    if not name:
        return None
    ns = labels.get(JOBSET_NAMESPACE_LABEL)
    return f"{ns}/{name}" if ns else name

# Chips per host machine by GKE accelerator type (public machine shapes:
# v4/v5p hosts carry 4 chips; v5e and v6e hosts carry up to 8 but multi-host
# pod slices use 4-chip hosts for v5e 2x4+ topologies — we use the
# conservative per-host chip count for host-count math and allow explicit
# override via SliceTopologySpec.hosts_per_slice).
ACCELERATOR_CHIPS_PER_HOST = {
    "tpu-v4-podslice": 4,
    "tpu-v5p-slice": 4,
    "tpu-v5-lite-podslice": 4,
    "tpu-v5-lite-device": 8,  # single-host v5e
    "tpu-v6e-slice": 4,
    "tpu-v7x-slice": 4,
}
DEFAULT_CHIPS_PER_HOST = 4


def parse_topology(topology: str) -> tuple[int, ...]:
    """Parse ``"2x2x4"`` into dims; empty string -> ()."""
    if not topology:
        return ()
    try:
        dims = tuple(int(d) for d in topology.split("x"))
    except ValueError as e:
        raise ValueError(f"bad TPU topology string {topology!r}") from e
    if not dims or any(d <= 0 for d in dims):
        raise ValueError(f"bad TPU topology string {topology!r}")
    return dims


def chips_for_topology(topology: str) -> int:
    dims = parse_topology(topology)
    return math.prod(dims) if dims else 0


def hosts_for_topology(
    topology: str, accelerator: str = "", chips_per_host: int = 0
) -> int:
    """Expected host (node) count for a slice topology.

    ``chips_per_host`` > 0 overrides the accelerator table (explicit
    ``UpgradeKeys.chips_per_host_label`` on the nodes — sub-host v5e
    topologies and shapes the table doesn't know)."""
    chips = chips_for_topology(topology)
    if chips == 0:
        return 1
    per_host = chips_per_host or ACCELERATOR_CHIPS_PER_HOST.get(
        accelerator, DEFAULT_CHIPS_PER_HOST
    )
    return max(1, chips // per_host)


@dataclass
class SliceInfo:
    """Identity + shape of one ICI slice (one torus)."""

    slice_id: str
    accelerator: str = ""
    topology: str = ""
    expected_hosts: int = 1
    # Multi-slice (DCN) group this slice belongs to, if any: slices in the
    # same group back one data-parallel JobSet and must not be down
    # simultaneously (BASELINE config 5).
    dcn_group: Optional[str] = None
    # Explicit per-host chip count (chips_per_host_label); 0 = derive from
    # the accelerator table / topology.
    chips_per_host: int = 0

    @property
    def chips(self) -> int:
        return chips_for_topology(self.topology) or (
            self.expected_hosts * (self.chips_per_host or 4)
        )

    def host_chips(self) -> int:
        """Chips each host of this slice should enumerate (0 = unknown).

        Explicit override first; else the accelerator table; else derived
        from the topology's total chip count over the expected hosts."""
        if self.chips_per_host:
            return self.chips_per_host
        per_host = ACCELERATOR_CHIPS_PER_HOST.get(self.accelerator, 0)
        if per_host:
            return per_host
        total = chips_for_topology(self.topology)
        if total and self.expected_hosts:
            return max(1, total // self.expected_hosts)
        return 0

    def is_multi_host(self) -> bool:
        return self.expected_hosts > 1


def slice_info_for_node(node: Node, keys: UpgradeKeys) -> Optional[SliceInfo]:
    """Derive the slice a node belongs to from its labels, or None if the
    node carries no TPU slice identity (then it upgrades as a singleton,
    reference semantics)."""
    labels = node.labels
    accelerator = labels.get(GKE_TPU_ACCELERATOR_LABEL, "")
    topology = labels.get(GKE_TPU_TOPOLOGY_LABEL, "")
    # Slice identity: explicit slice-id label wins; else the GKE node pool
    # (a multi-host TPU node pool is exactly one slice).
    slice_id = labels.get(keys.slice_id_label) or labels.get(GKE_NODEPOOL_LABEL)
    if not slice_id or not (accelerator or topology):
        return None
    raw_cph = labels.get(keys.chips_per_host_label, "")
    chips_per_host = int(raw_cph) if raw_cph.isdigit() else 0
    return SliceInfo(
        slice_id=slice_id,
        accelerator=accelerator,
        topology=topology,
        expected_hosts=hosts_for_topology(topology, accelerator, chips_per_host),
        dcn_group=(
            labels.get(keys.dcn_group_label)
            or _jobset_dcn_group(labels)
        ),
        chips_per_host=chips_per_host,
    )


def discover_slices(
    nodes: list[Node], keys: UpgradeKeys
) -> tuple[dict[str, SliceInfo], dict[str, list[Node]]]:
    """Group nodes by slice.

    Returns (slice_id -> SliceInfo, slice_id -> member nodes).  Nodes with
    no TPU labels are not returned here — callers treat them as singleton
    groups.
    """
    infos: dict[str, SliceInfo] = {}
    members: dict[str, list[Node]] = {}
    for node in nodes:
        info = slice_info_for_node(node, keys)
        if info is None:
            continue
        infos.setdefault(info.slice_id, info)
        members.setdefault(info.slice_id, []).append(node)
    # Keep member order deterministic by worker id then name.
    def _worker_key(n: Node) -> tuple[int, str]:
        wid = n.labels.get(GKE_TPU_WORKER_ID_LABEL, "")
        return (int(wid) if wid.isdigit() else 1 << 30, n.name)

    for ns in members.values():
        ns.sort(key=_worker_key)
    return infos, members
