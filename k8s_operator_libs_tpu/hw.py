"""Public TPU chip specs, for MFU math and health-floor derivation.

Numbers are the published per-chip peaks (Google Cloud TPU system
architecture docs): dense bf16 TFLOPS and HBM bandwidth.  They are used
two ways:

- **MFU**: canary tokens/s → model FLOPs utilisation against the chip's
  peak, the honest throughput metric (scaling-book convention);
- **health floors**: a sustained probe reading far below spec on a chip
  that enumerates fine is the silent-degradation failure mode the HBM
  probe exists to catch; floors default to a conservative fraction of
  spec (or of a measured healthy baseline).

``device_kind`` strings come from ``jax.Device.device_kind`` (e.g.
``"TPU v5 lite"``, ``"TPU v4"``); matching is substring-based and
case-insensitive, unknown kinds (CPU test meshes) yield None so callers
skip spec-relative checks.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional


@dataclass(frozen=True)
class ChipSpec:
    name: str
    bf16_tflops: float
    hbm_gbps: float
    hbm_gib: float


# Substring (lowercased) -> spec.  Order matters: more specific first.
# All values are PER-CHIP (v2/v3 HBM capacities are the chip totals, 16/32
# GiB — not the per-core 8/16 some tables quote).  The bare "v5" needle
# last is a fallback: some libtpu versions report v5p as plain "TPU v5",
# which must not silently disable MFU math and HBM floors.
_CHIP_SPECS: list[tuple[str, ChipSpec]] = [
    ("v5 lite", ChipSpec("v5e", 197.0, 819.0, 16.0)),
    ("v5litepod", ChipSpec("v5e", 197.0, 819.0, 16.0)),
    ("v5-lite", ChipSpec("v5e", 197.0, 819.0, 16.0)),
    ("v5e", ChipSpec("v5e", 197.0, 819.0, 16.0)),
    ("v5p", ChipSpec("v5p", 459.0, 2765.0, 95.0)),
    ("v6 lite", ChipSpec("v6e", 918.0, 1640.0, 32.0)),
    ("v6e", ChipSpec("v6e", 918.0, 1640.0, 32.0)),
    ("v4", ChipSpec("v4", 275.0, 1228.0, 32.0)),
    ("v3", ChipSpec("v3", 123.0, 900.0, 32.0)),
    ("v2", ChipSpec("v2", 45.0, 700.0, 16.0)),
    ("v5", ChipSpec("v5p", 459.0, 2765.0, 95.0)),
]


def chip_spec(device_kind: str) -> Optional[ChipSpec]:
    """Spec for a ``jax.Device.device_kind`` string (e.g. ``"TPU v5 lite"``)
    or a GKE accelerator label (e.g. ``"tpu-v5-lite-podslice"``), or None
    if unknown."""
    kind = (device_kind or "").lower()
    if "tpu" not in kind and not kind.startswith("v"):
        return None
    for needle, spec in _CHIP_SPECS:
        if needle in kind:
            return spec
    return None


def mfu(achieved_tflops: float, device_kind: str) -> Optional[float]:
    """Model FLOPs utilisation in [0, 1], or None off-spec hardware."""
    spec = chip_spec(device_kind)
    if spec is None or spec.bf16_tflops <= 0:
        return None
    return achieved_tflops / spec.bf16_tflops


def default_hbm_floor_gbps(
    device_kind: str, fraction: float = 0.5
) -> float:
    """A defensible min-HBM-bandwidth floor: ``fraction`` of chip spec
    (0.0 when the chip is unknown — floor disabled)."""
    spec = chip_spec(device_kind)
    if spec is None:
        return 0.0
    return fraction * spec.hbm_gbps
