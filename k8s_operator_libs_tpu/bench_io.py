"""Bench artifact emission: one SMALL stdout JSON line + a side file.

The round driver captures only the tail of bench stdout (~4 KB
observed), so a final metric line that inlines bulky evidence truncates
its own head away and the headline number never lands (round 4:
4,148 bytes measured on a complete run -> ``parsed: null``).  The
contract is therefore split:

- **stdout**: exactly one JSON line, hard-capped at ``MAX_LINE_BYTES``,
  carrying ``metric/value/unit/vs_baseline`` plus a compact
  ``details`` summary and the path of the side file;
- **side file** (``BENCH_DETAILS.json``): the full evidence — per-state
  transition histories, probe metric dicts, per-roll traces — with no
  size pressure.

``compact_line`` enforces the cap structurally: if a summary ever grows
past the budget, expendable keys are dropped (headline keys never are)
so the driver can always parse the line.  The reference's analogue is
its CI artifact gate (`.github/workflows/ci.yaml:18-66` upstream): an
artifact that cannot be consumed by the pipeline is a failure of the
producer, not the pipeline.
"""

from __future__ import annotations

import json
import os
from typing import Any, Mapping, Optional

# Hard cap for the single stdout line.  The observed driver tail capture
# is ~4 KB; half that leaves headroom for driver-side framing.
MAX_LINE_BYTES = 2048

# Keys that must survive any size-pressure dropping: the driver's parse
# targets plus the honesty labels.
_PROTECTED = {"complete", "backend", "details_file", "error"}


def compact_line(
    metric: str,
    value: float,
    unit: str,
    vs_baseline: float,
    summary: Mapping[str, Any],
) -> str:
    """Serialize the one-line payload, guaranteed <= MAX_LINE_BYTES.

    Expendable summary keys are dropped last-first under size pressure;
    the headline fields and ``_PROTECTED`` keys always survive."""
    details = dict(summary)

    def render() -> str:
        return json.dumps(
            {
                "metric": metric,
                "value": value,
                "unit": unit,
                "vs_baseline": vs_baseline,
                "details": details,
            },
            separators=(",", ":"),
        )

    line = render()
    if len(line.encode("utf-8")) <= MAX_LINE_BYTES:
        return line
    for key in reversed(list(details)):
        if key in _PROTECTED:
            continue
        del details[key]
        line = render()
        if len(line.encode("utf-8")) <= MAX_LINE_BYTES:
            return line
    # Only protected keys remain; as a last resort shorten the metric
    # string, then the longest remaining string values (an oversized
    # protected 'error'/'backend' must not reintroduce the r4 bug the
    # cap exists to prevent) — the numbers are never touched.  Protected
    # values that are not strings (a list of tracebacks smuggled under
    # 'error') are flattened to truncated strings first so the shrink
    # loop can always make progress.
    metric = metric[:80]
    for key, val in list(details.items()):
        if not isinstance(val, (str, int, float, bool, type(None))):
            details[key] = json.dumps(val, default=str)[:200]
    line = render()
    while len(line.encode("utf-8")) > MAX_LINE_BYTES:
        key = max(
            (k for k in details if isinstance(details[k], str)),
            key=lambda k: len(details[k]),
            default=None,
        )
        if key is None or len(details[key]) <= 8:
            break
        details[key] = details[key][: max(8, len(details[key]) // 2)]
        line = render()
    if len(line.encode("utf-8")) > MAX_LINE_BYTES:
        # Unconditional floor: the driver must always get a parseable
        # line.  Drop the details payload entirely rather than emit an
        # over-budget line that truncates its own head away.
        details.clear()
        details["dropped"] = "details exceeded line budget"
        unit = unit[:32]
        line = render()
    return line


def emit(
    metric: str,
    value: float,
    unit: str,
    vs_baseline: float,
    summary: Mapping[str, Any],
    full_details: Optional[Mapping[str, Any]] = None,
    details_path: Optional[str] = None,
) -> str:
    """Write the full evidence to ``details_path`` (if given) and print
    the capped one-line summary to stdout.  Returns the printed line."""
    summary = dict(summary)
    if details_path is not None and full_details is not None:
        # The side file is optional evidence; the stdout line is the
        # mandatory artifact.  A full disk or read-only directory must
        # degrade to a line that SAYS the evidence is missing, never to
        # a traceback with no line at all.
        try:
            tmp = details_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(full_details, f, indent=1, sort_keys=True)
                f.write("\n")
            os.replace(tmp, details_path)
            summary["details_file"] = os.path.basename(details_path)
        except (OSError, TypeError, ValueError) as e:
            summary["details_file"] = f"<write failed: {e}>"[:120]
    line = compact_line(metric, value, unit, vs_baseline, summary)
    print(line, flush=True)
    return line
