"""Global budget arbitration: the level above the per-cluster ledger.

The engine's :class:`~k8s_operator_libs_tpu.upgrade.sharded.BudgetLedger`
arbitrates fleet ∧ pool inside one cluster.  A federated roll adds one
more level: the sum of every cluster's in-flight unavailability must
stay under the GLOBAL ``maxUnavailable`` no matter which cluster admits
next.  :class:`GlobalBudgetLedger` is that level — each member cluster's
``BudgetLedger`` points at it via ``parent``/``cluster_name`` and every
local admission becomes global ∧ cluster ∧ pool in a single
check-and-charge.

Fail-static contract: a partitioned cluster's engine never runs, so its
charges here are never released and never resynced away — the frozen
capacity stays debited against the global cap until the cluster heals
and re-baselines its own slice.  Releasing optimistically would let the
healthy clusters respend units that may still be down in the
unreachable region.

Locking: a cluster ledger consults this one while holding its own lock
(order: cluster → global).  This ledger never calls back into a cluster
ledger, so the order can never invert.
"""

from __future__ import annotations

import threading
from typing import Dict, Mapping, Tuple

from k8s_operator_libs_tpu.consts import get_logger
from k8s_operator_libs_tpu.upgrade.sharded import LedgerError

logger = get_logger(__name__)


class GlobalBudgetLedger:
    """Atomic global ∧ per-cluster check-and-charge for federated rolls.

    Charges are keyed ``(cluster, group_id)``.  Unlike the per-cluster
    ledger this one is STRICT by construction: a double release raises
    :class:`LedgerError` — the cluster ledger below filters the engine's
    idempotent "ensure free" no-ops, so an unmatched release reaching
    this level is always a real accounting bug."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.unit = "node"
        self.max_unavailable = 0  # 0 = unlimited (unconfigured)
        self.max_parallel = 0  # 0 = unlimited
        self.total_units = 0
        # cluster → (max_unavailable_units, max_parallel); absent = only
        # bounded by the global caps (the cluster's own ledger already
        # enforces its local policy caps).
        self._cluster_caps: Dict[str, Tuple[int, int]] = {}
        # (cluster, group_id) → cost.
        self._charges: Dict[Tuple[str, str], int] = {}
        # cluster → total units it contributes to the federation (for
        # percentage scaling and status).
        self._cluster_units: Dict[str, int] = {}
        # Lifetime counters.  ``violations`` counts non-forced grants
        # that left usage above the configured cap — the invariant the
        # chaos/bench pins assert stays ZERO; forced charges past the
        # caps are legitimate (an already-unavailable group is a fact,
        # not an admission request) and are tallied separately.
        self.denials = 0
        self.violations = 0
        self.forced_over_cap = 0
        self.peak_unavailable = 0

    # -- configuration -------------------------------------------------------

    def configure(
        self,
        total_units: int,
        max_unavailable: int,
        max_parallel: int = 0,
        unit: str = "node",
    ) -> None:
        with self._lock:
            self.total_units = total_units
            self.max_unavailable = max_unavailable
            self.max_parallel = max_parallel
            self.unit = unit

    def configure_clusters(
        self, caps: Mapping[str, Tuple[int, int]]
    ) -> None:
        """Install per-cluster ``(max_unavailable_units, max_parallel)``
        overrides.  0 max_parallel = unlimited."""
        with self._lock:
            self._cluster_caps = dict(caps)

    # -- claims --------------------------------------------------------------

    def _cluster_usage(self, cluster: str) -> Tuple[int, int]:
        """(units, parallel count) charged to ``cluster``.  Caller holds
        the lock."""
        used = 0
        count = 0
        for (c, _gid), cost in self._charges.items():
            if c == cluster:
                used += cost
                count += 1
        return used, count

    def _denied_locked(self, cluster: str, cost: int) -> bool:
        if (
            self.max_parallel > 0
            and len(self._charges) >= self.max_parallel
        ):
            return True
        used = sum(self._charges.values())
        if self.max_unavailable > 0 and used + cost > self.max_unavailable:
            return True
        caps = self._cluster_caps.get(cluster)
        if caps is not None:
            cap_units, cap_parallel = caps
            c_used, c_count = self._cluster_usage(cluster)
            if cap_parallel > 0 and c_count >= cap_parallel:
                return True
            if c_used + cost > cap_units:
                return True
        return False

    def can_claim(self, cluster: str, group_id: str, cost: int) -> bool:
        """Read-only probe (never charges)."""
        if cost < 0:
            raise LedgerError(
                f"negative charge for {cluster}/{group_id}: {cost}"
            )
        with self._lock:
            if (cluster, group_id) in self._charges:
                return True
            return not self._denied_locked(cluster, cost)

    def try_claim(
        self, cluster: str, group_id: str, cost: int, force: bool = False
    ) -> bool:
        """Atomically admit ``group_id`` of ``cluster`` at ``cost``
        units against the global ∧ cluster caps.  Idempotent per
        (cluster, group).  ``force`` charges past the caps but still
        records the charge so every other cluster's admission sees it."""
        if cost < 0:
            raise LedgerError(
                f"negative charge for {cluster}/{group_id}: {cost}"
            )
        key = (cluster, group_id)
        with self._lock:
            if key in self._charges:
                return True
            if not force and self._denied_locked(cluster, cost):
                self.denials += 1
                return False
            self._charges[key] = cost
            used = sum(self._charges.values())
            if used > self.peak_unavailable:
                self.peak_unavailable = used
            if self.max_unavailable > 0 and used > self.max_unavailable:
                if force:
                    self.forced_over_cap += 1
                else:
                    # Should be unreachable: _denied_locked gates every
                    # non-forced grant.  Counted (not raised) so the
                    # chaos/bench pins can assert it stayed zero.
                    self.violations += 1
        return True

    def release(self, cluster: str, group_id: str) -> None:
        with self._lock:
            had = self._charges.pop((cluster, group_id), None)
        if had is None:
            raise LedgerError(
                f"double release of {cluster}/{group_id}: no charge held"
            )

    def sync_cluster(
        self,
        cluster: str,
        charges: Mapping[str, int],
        total_units: int = -1,
        unit: str = "",
    ) -> None:
        """Replace ``cluster``'s slice of the charge table with the
        authoritative set its own ledger just re-derived from observed
        state.  Other clusters' charges (including a partitioned peer's
        fail-static reservations) are untouched."""
        with self._lock:
            for key in [k for k in self._charges if k[0] == cluster]:
                del self._charges[key]
            for gid, cost in charges.items():
                if cost < 0:
                    raise LedgerError(
                        f"negative charge for {cluster}/{gid}: {cost}"
                    )
                self._charges[(cluster, gid)] = cost
            if total_units >= 0:
                self._cluster_units[cluster] = total_units
            if unit:
                self.unit = unit
            used = sum(self._charges.values())
            if used > self.peak_unavailable:
                self.peak_unavailable = used

    # -- introspection -------------------------------------------------------

    def unavailable_used(self) -> int:
        with self._lock:
            return sum(self._charges.values())

    def parallel_used(self) -> int:
        with self._lock:
            return len(self._charges)

    def cluster_used(self, cluster: str) -> int:
        with self._lock:
            return self._cluster_usage(cluster)[0]

    def holds(self, cluster: str, group_id: str) -> bool:
        with self._lock:
            return (cluster, group_id) in self._charges

    def cluster_charges(self, cluster: str) -> Dict[str, int]:
        with self._lock:
            return {
                gid: cost
                for (c, gid), cost in self._charges.items()
                if c == cluster
            }

    def snapshot(self) -> dict:
        with self._lock:
            per_cluster: Dict[str, int] = {}
            for (c, _gid), cost in self._charges.items():
                per_cluster[c] = per_cluster.get(c, 0) + cost
            return {
                "unit": self.unit,
                "totalUnits": self.total_units,
                "maxUnavailable": self.max_unavailable,
                "maxParallel": self.max_parallel,
                "used": sum(self._charges.values()),
                "parallel": len(self._charges),
                "peakUnavailable": self.peak_unavailable,
                "perCluster": per_cluster,
                "clusterUnits": dict(self._cluster_units),
                "denials": self.denials,
                "violations": self.violations,
                "forcedOverCap": self.forced_over_cap,
            }
