"""The federation coordinator: a thin, restartable global brain.

One :class:`FederationCoordinator` drives N member clusters — each with
its own :class:`~k8s_operator_libs_tpu.upgrade.upgrade_state.
ClusterUpgradeStateManager`, write plane, and budget ledger — through
one global roll:

* **Regional canary first.**  Only the canary region's clusters get
  engine passes until the canary completes AND its telemetry baselines
  stay clean for the configured soak (:class:`~k8s_operator_libs_tpu.
  federation.canary.CanaryGate`).  A confirmed regression hard-stops
  promotion: the ``CanaryHeld`` condition (with the canary roll's trace
  id) is raised and a Warning event emitted.
* **Fail-static partitions.**  Cluster health comes from the registry's
  probe ladder; a Partitioned cluster is skipped ENTIRELY — no reads,
  no writes, its in-flight groups frozen at last-known state and its
  budget charges left reserved in the global ledger — while the healthy
  clusters' waves proceed under the global cap net of those
  reservations.  On heal the cluster resumes via the engine's own
  adoption pass (annotation-anchored, zero repeated writes).
* **Crash durability.**  Coordinator state (phase, soak-start epoch,
  hold reason/trace, adoption stamp) persists as annotations on a tiny
  federation custom object, written only on change; a restarted
  coordinator re-adopts mid-canary with the soak clock rebased via the
  same ``monotonic_from_epoch`` path the engine's progress clocks use.

Conditions follow the controller's CR-status shape (type / status /
reason / message / lastTransitionTime, with the timestamp preserved
while the status is unchanged).
"""

from __future__ import annotations

import time
from collections import Counter
from typing import Callable, Dict, List, Optional

from k8s_operator_libs_tpu.api.schema import POLICY_GROUP, POLICY_VERSION
from k8s_operator_libs_tpu.consts import get_logger
from k8s_operator_libs_tpu.federation.canary import (
    HELD,
    PROMOTE,
    CanaryGate,
)
from k8s_operator_libs_tpu.federation.ledger import GlobalBudgetLedger
from k8s_operator_libs_tpu.federation.plan import (
    FederatedPlan,
    plan_federated,
)
from k8s_operator_libs_tpu.federation.registry import (
    ClusterHealth,
    ClusterRegistry,
    MemberCluster,
)
from k8s_operator_libs_tpu.k8s.client import NotFoundError
from k8s_operator_libs_tpu.upgrade.consts import UpgradeState
from k8s_operator_libs_tpu.upgrade.durable import format_adoption_stamp
from k8s_operator_libs_tpu.upgrade.sharded import BudgetLedger

logger = get_logger(__name__)

# The federation roll object: one tiny custom resource anchoring the
# coordinator's durable state as annotations (the same pattern as the
# engine's per-node progress clocks — durable, CAS-guarded, cheap).
FEDERATION_PLURAL = "tpufederationrolls"

PHASE_KEY = f"{POLICY_GROUP}/fed-phase"
SOAK_KEY = f"{POLICY_GROUP}/fed-soak-start-epoch"
HELD_REASON_KEY = f"{POLICY_GROUP}/fed-held-reason"
HELD_TRACE_KEY = f"{POLICY_GROUP}/fed-held-trace"
ADOPTED_KEY = f"{POLICY_GROUP}/fed-adopted-by"

# Coordinator phases (durable via PHASE_KEY).
PHASE_CANARY = "canary"
PHASE_SOAKING = "soaking"
PHASE_HELD = "held"
PHASE_PROMOTED = "promoted"
PHASE_DONE = "done"


def ensure_federation_kind(client) -> None:
    """Enable the federation-roll kind on clients that gate unknown
    kinds (FakeCluster / in-process apiserver).  Idempotent; a no-op
    for clients without a registry."""
    register = getattr(client, "register_custom_resource", None)
    if register is not None:
        register(POLICY_GROUP, POLICY_VERSION, FEDERATION_PLURAL)


class FederationStateStore:
    """Annotation-anchored durable state on the federation roll object.

    ``save`` is only-on-change: an unchanged annotation set issues ZERO
    writes, which is what makes coordinator re-adoption write-free."""

    def __init__(self, client, namespace: str, name: str = "global-roll"):
        self.client = client
        self.namespace = namespace
        self.name = name
        self.writes = 0

    def load(self) -> Dict[str, str]:
        try:
            obj = self.client.get_custom_object(
                POLICY_GROUP,
                POLICY_VERSION,
                FEDERATION_PLURAL,
                self.namespace,
                self.name,
            )
        except NotFoundError:
            return {}
        return dict((obj.get("metadata") or {}).get("annotations") or {})

    def save(self, updates: Dict[str, Optional[str]]) -> int:
        """Merge ``updates`` into the object's annotations (None deletes
        a key).  Creates the object on first use.  Returns the number of
        API writes issued (0 when nothing changed)."""
        try:
            obj = self.client.get_custom_object(
                POLICY_GROUP,
                POLICY_VERSION,
                FEDERATION_PLURAL,
                self.namespace,
                self.name,
            )
        except NotFoundError:
            annotations = {
                k: v for k, v in updates.items() if v is not None
            }
            self.client.create_custom_object(
                POLICY_GROUP,
                POLICY_VERSION,
                FEDERATION_PLURAL,
                self.namespace,
                {
                    "apiVersion": f"{POLICY_GROUP}/{POLICY_VERSION}",
                    "kind": "TPUFederationRoll",
                    "metadata": {
                        "name": self.name,
                        "annotations": annotations,
                    },
                },
            )
            self.writes += 1
            return 1
        meta = obj.setdefault("metadata", {})
        annotations = dict(meta.get("annotations") or {})
        changed = False
        for key, value in updates.items():
            if value is None:
                if key in annotations:
                    del annotations[key]
                    changed = True
            elif annotations.get(key) != value:
                annotations[key] = value
                changed = True
        if not changed:
            return 0
        meta["annotations"] = annotations
        self.client.update_custom_object(
            POLICY_GROUP,
            POLICY_VERSION,
            FEDERATION_PLURAL,
            self.namespace,
            obj,
        )
        self.writes += 1
        return 1


def _iso(epoch: float) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(epoch))


def _parse_float_epoch(raw: Optional[str]) -> Optional[float]:
    """Like durable.parse_epoch but sub-second: the soak anchor keeps
    fractional seconds so short soaks survive restarts losslessly."""
    if not raw:
        return None
    try:
        return float(raw)
    except (TypeError, ValueError):
        return None


class FederationCoordinator:
    """Drives one global roll across the registry's member clusters."""

    def __init__(
        self,
        registry: ClusterRegistry,
        policy,
        namespace: str,
        driver_labels: Dict[str, str],
        store: FederationStateStore,
        identity: str = "federation-coordinator",
        term: int = 0,
        async_wait_s: float = 10.0,
        epoch_clock: Callable[[], float] = time.time,
        mono_clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.registry = registry
        self.policy = policy
        self.namespace = namespace
        self.driver_labels = dict(driver_labels)
        self.store = store
        self.identity = identity
        self.term = term
        self.async_wait_s = async_wait_s
        self.epoch_clock = epoch_clock

        fed = getattr(policy, "federation", None)
        canary = getattr(fed, "canary", None)
        regions = sorted(registry.regions())
        self.canary_region = (
            getattr(canary, "region", "") or (regions[0] if regions else "")
        )
        self.soak_s = float(getattr(canary, "soak_second", 0) or 0)
        self._global_max_unavailable = getattr(fed, "max_unavailable", None)
        self._global_max_parallel = int(
            getattr(fed, "max_parallel_upgrades", 0) or 0
        )

        self.global_ledger = GlobalBudgetLedger()
        self.gate = CanaryGate(
            self.soak_s, mono_clock=mono_clock, epoch_clock=epoch_clock
        )
        self.phase = PHASE_CANARY
        self.stats: Counter = Counter()
        self.canary_trace_ids: Dict[str, str] = {}
        self._done: Dict[str, bool] = {}
        self._last_state: Dict[str, object] = {}
        self._frozen: set = set()
        self._conditions: Dict[str, dict] = {}
        # Wire every member's engine into the budget hierarchy: local
        # admission becomes global ∧ cluster ∧ pool.
        for member in registry.members():
            if member.manager is None:
                continue
            ledger = BudgetLedger()
            ledger.parent = self.global_ledger
            ledger.cluster_name = member.name
            member.ledger = ledger
            member.manager.budget_ledger = ledger

    # -- durable state -------------------------------------------------------

    def adopt(self, now_epoch: Optional[float] = None) -> dict:
        """Re-adopt a (possibly mid-canary) global roll after a crash or
        failover: restore phase / soak clock / hold from the durable
        store, stamp the coordinator identity (only-on-change), and run
        the engine's own adoption pass on every reachable member.  A
        restart with nothing changed issues ZERO writes."""
        anno = self.store.load()
        self.phase = anno.get(PHASE_KEY) or PHASE_CANARY
        soak_epoch = _parse_float_epoch(anno.get(SOAK_KEY))
        if soak_epoch is not None:
            self.gate.adopt_soak(soak_epoch, now_epoch=now_epoch)
        if self.phase == PHASE_HELD and self.gate.held is None:
            self.gate.held = {
                "reason": anno.get(HELD_REASON_KEY, ""),
                "trace_id": anno.get(HELD_TRACE_KEY, ""),
                "epoch": self.epoch_clock(),
                "confirmations": [],
            }
        stamp = format_adoption_stamp(self.identity, self.term)
        store_writes = self.store.save({ADOPTED_KEY: stamp})
        members: Dict[str, dict] = {}
        for member in self.registry.members():
            if member.manager is None:
                continue
            if self.registry.health(member.name) is ClusterHealth.PARTITIONED:
                continue  # fail-static: re-adopted on heal instead
            try:
                members[member.name] = self._adopt_member(member)
            except Exception as exc:
                self.registry.observe_failure(member.name, str(exc))
                self.stats["member_adopt_failures"] += 1
        self.stats["adoptions"] += 1
        return {
            "phase": self.phase,
            "soakAdopted": soak_epoch is not None,
            "storeWrites": store_writes,
            "members": members,
        }

    def _adopt_member(self, member: MemberCluster) -> dict:
        mgr = member.manager
        state = mgr.build_state(
            self.namespace, self.driver_labels, self.policy
        )
        summary = mgr.adopt(
            state, identity=self.identity, term=self.term, policy=self.policy
        )
        self._last_state[member.name] = state
        if member.ledger is not None:
            member.ledger.sync_from_state(mgr, state, self.policy)
        return summary

    # -- the tick ------------------------------------------------------------

    def tick(self, now_epoch: Optional[float] = None) -> dict:
        """One federation pass: probe health, freeze/resume on
        transitions, run engine passes on the phase's active clusters,
        and advance the canary phase machine."""
        now = self.epoch_clock() if now_epoch is None else now_epoch
        self.stats["ticks"] += 1
        summary: dict = {
            "phase": self.phase,
            "clusters": {},
            "skippedPartitioned": [],
        }
        # 1. Health probes + freeze/resume transitions.
        for member in self.registry.members():
            health = self.registry.probe(member.name)
            if (
                health is ClusterHealth.PARTITIONED
                and member.name not in self._frozen
            ):
                self._freeze(member, now)
            elif (
                health is not ClusterHealth.PARTITIONED
                and member.name in self._frozen
            ):
                self._resume(member, now)
        healths = self.registry.healths()
        # 2. Engine passes on the phase's active clusters.  A
        # partitioned cluster is skipped ENTIRELY: no reads, no writes,
        # its charges stay reserved (fail-static).
        for member in self._active_members():
            if healths[member.name] is ClusterHealth.PARTITIONED:
                summary["skippedPartitioned"].append(member.name)
                self.stats["skipped_partitioned"] += 1
                continue
            if member.manager is None:
                continue
            try:
                done = self._pass(member)
                self.registry.observe_success(member.name)
            except Exception as exc:
                self.registry.observe_failure(member.name, str(exc))
                self.stats["pass_failures"] += 1
                if (
                    self.registry.health(member.name)
                    is ClusterHealth.PARTITIONED
                    and member.name not in self._frozen
                ):
                    self._freeze(member, now)
                done = False
            self._done[member.name] = done
        # 3. Canary phase machine.
        self._advance_phase(now)
        # 4. Conditions.
        self._refresh_conditions(now)
        summary["phase"] = self.phase
        summary["clusters"] = {
            m.name: {
                "region": m.region,
                "health": healths.get(
                    m.name, ClusterHealth.REACHABLE
                ).value,
                "done": bool(self._done.get(m.name)),
                "frozenGroups": len(m.frozen_groups),
            }
            for m in self.registry.members()
        }
        summary["globalBudget"] = self.global_ledger.snapshot()
        return summary

    def _active_members(self) -> List[MemberCluster]:
        members = self.registry.members()
        if self.phase in (PHASE_CANARY, PHASE_SOAKING, PHASE_HELD):
            # Pre-promotion: only the canary region rolls.  Soak (and
            # even a hold) keeps the canary's passes running — telemetry
            # needs the engine's probe batteries, and a held canary is
            # stopped from PROMOTING, not from converging.
            return [m for m in members if m.region == self.canary_region]
        return members

    def _pass(self, member: MemberCluster) -> bool:
        mgr = member.manager
        state = mgr.build_state(
            self.namespace, self.driver_labels, self.policy
        )
        self._last_state[member.name] = state
        if member.ledger is not None:
            member.ledger.sync_from_state(mgr, state, self.policy)
        self._configure_global()
        mgr.apply_state(state, self.policy)
        mgr.wait_for_async_work(self.async_wait_s)
        rec = getattr(mgr, "trace_recorder", None)
        if rec is not None:
            tid = rec.active_trace_id()
            if tid is None:
                last = rec.last_completed()
                tid = last.trace_id if last is not None else None
            if tid:
                self.canary_trace_ids[member.name] = tid
        groups = list(state.all_groups())
        return bool(groups) and all(
            g.effective_state(mgr.keys.state_label) is UpgradeState.DONE
            for g in groups
        )

    def _configure_global(self) -> None:
        """Re-derive the global caps from the members' current totals.
        A partitioned member's last-synced total (and charges) persist —
        the federation does not shrink its denominator because a region
        went dark."""
        total = 0
        unit = "node"
        for member in self.registry.members():
            if member.ledger is not None:
                total += member.ledger.total_units
                unit = member.ledger.unit
        cap = 0
        if self._global_max_unavailable is not None and total > 0:
            cap = self._global_max_unavailable.scaled_value(
                total, round_up=True
            )
        self.global_ledger.configure(
            total, cap, max_parallel=self._global_max_parallel, unit=unit
        )

    # -- fail-static freeze / heal-time resume -------------------------------

    def _freeze(self, member: MemberCluster, now: float) -> None:
        """Partition detected: freeze the cluster at last-known state.
        Its budget charges are NOT released — the frozen capacity stays
        debited against the global cap until the cluster heals."""
        charges = (
            dict(member.ledger.snapshot().get("charges", {}))
            if member.ledger is not None
            else {}
        )
        member.frozen_groups = charges
        self._frozen.add(member.name)
        self.stats["freezes"] += 1
        self._emit_event(
            "ClusterPartitioned",
            f"cluster {member.name} (region {member.region}) partitioned: "
            f"{len(charges)} in-flight group(s) frozen fail-static, "
            f"{sum(charges.values())} budget unit(s) stay reserved",
            type_="Warning",
        )
        logger.warning(
            "cluster %s partitioned: %d group(s) frozen",
            member.name,
            len(charges),
        )

    def _resume(self, member: MemberCluster, now: float) -> None:
        """Heal detected: resume via the engine's adoption pass — the
        durable per-node record (labels, rungs, clocks, stamps) is the
        source of truth, so nothing is repeated."""
        frozen = len(member.frozen_groups)
        member.frozen_groups = {}
        self._frozen.discard(member.name)
        if member.manager is not None:
            try:
                self._adopt_member(member)
            except Exception as exc:
                self.registry.observe_failure(member.name, str(exc))
                self._frozen.add(member.name)
                self.stats["resume_failures"] += 1
                return
        self.stats["resumes"] += 1
        self._emit_event(
            "ClusterHealed",
            f"cluster {member.name} (region {member.region}) healed: "
            f"re-adopted, {frozen} frozen group(s) resumed",
        )

    # -- canary phase machine ------------------------------------------------

    def _advance_phase(self, now: float) -> None:
        if self.phase == PHASE_CANARY:
            canary_members = [
                m
                for m in self.registry.members()
                if m.region == self.canary_region
            ]
            if canary_members and all(
                self._done.get(m.name) for m in canary_members
            ):
                if self.gate.begin_soak(now_epoch=now):
                    self.phase = PHASE_SOAKING
                    self.store.save(
                        {
                            PHASE_KEY: PHASE_SOAKING,
                            SOAK_KEY: repr(
                                float(self.gate.soak_started_epoch)
                            ),
                        }
                    )
                    self._emit_event(
                        "CanarySoakStarted",
                        f"canary region {self.canary_region} complete; "
                        f"soaking health baselines for "
                        f"{self.soak_s:.0f}s",
                    )
            return
        if self.phase == PHASE_SOAKING:
            for m in self.registry.members():
                if m.region != self.canary_region or m.manager is None:
                    continue
                self.gate.observe_plane(
                    getattr(m.manager, "telemetry_plane", None),
                    trace_id=self.canary_trace_ids.get(m.name, ""),
                )
            verdict = self.gate.evaluate()
            if verdict.phase == HELD:
                self.phase = PHASE_HELD
                self.store.save(
                    {
                        PHASE_KEY: PHASE_HELD,
                        HELD_REASON_KEY: verdict.reason,
                        HELD_TRACE_KEY: verdict.trace_id,
                    }
                )
                self._emit_event(
                    "CanaryHeld",
                    f"promotion held: {verdict.reason} "
                    f"(trace {verdict.trace_id or 'unknown'})",
                    type_="Warning",
                )
                self.stats["canary_holds"] += 1
            elif verdict.phase == PROMOTE:
                self.phase = PHASE_PROMOTED
                self.store.save(
                    {
                        PHASE_KEY: PHASE_PROMOTED,
                        HELD_REASON_KEY: None,
                        HELD_TRACE_KEY: None,
                    }
                )
                self._emit_event(
                    "CanaryPromoted",
                    f"canary soak clean for {self.soak_s:.0f}s; "
                    f"promoting to remaining regions",
                )
            return
        if self.phase == PHASE_PROMOTED:
            members = [
                m for m in self.registry.members() if m.manager is not None
            ]
            reachable_done = all(
                self._done.get(m.name)
                for m in members
                if m.name not in self._frozen
            )
            if members and reachable_done and not self._frozen:
                self.phase = PHASE_DONE
                self.store.save({PHASE_KEY: PHASE_DONE})
                self._emit_event(
                    "FederatedRollComplete",
                    "all clusters converged",
                )

    # -- conditions / events / status ----------------------------------------

    def _set_condition(
        self,
        type_: str,
        status: bool,
        reason: str,
        message: str,
        now: float,
    ) -> None:
        status_str = "True" if status else "False"
        prev = self._conditions.get(type_)
        last_transition = (
            prev["lastTransitionTime"]
            if prev is not None and prev["status"] == status_str
            else _iso(now)
        )
        self._conditions[type_] = {
            "type": type_,
            "status": status_str,
            "reason": reason,
            "message": message,
            "lastTransitionTime": last_transition,
        }

    def _refresh_conditions(self, now: float) -> None:
        partitioned = self.registry.partitioned()
        if partitioned:
            frozen = sum(
                len(self.registry.member(n).frozen_groups)
                for n in partitioned
            )
            self._set_condition(
                "Partitioned",
                True,
                "ClusterPartitioned",
                f"{len(partitioned)} cluster(s) partitioned "
                f"({', '.join(partitioned)}); {frozen} group(s) frozen "
                f"fail-static, budget reserved",
                now,
            )
        else:
            self._set_condition(
                "Partitioned",
                False,
                "AllReachable",
                "every member cluster reachable",
                now,
            )
        held = self.gate.held
        if held is not None:
            self._set_condition(
                "CanaryHeld",
                True,
                "TelemetryRegression",
                f"{held['reason']} (trace "
                f"{held.get('trace_id') or 'unknown'})",
                now,
            )
        else:
            self._set_condition(
                "CanaryHeld",
                False,
                "BaselinesClean",
                f"canary soak "
                f"{'running' if self.phase == PHASE_SOAKING else 'clean'}",
                now,
            )

    def conditions(self) -> List[dict]:
        return [self._conditions[t] for t in sorted(self._conditions)]

    def _emit_event(
        self, reason: str, message: str, type_: str = "Normal"
    ) -> None:
        try:
            self.store.client.create_event(
                self.namespace,
                {
                    "metadata": {
                        "generateName": f"fed-{reason.lower()}-"
                    },
                    "type": type_,
                    "reason": reason,
                    "message": message,
                    "involvedObject": {
                        "apiVersion": f"{POLICY_GROUP}/{POLICY_VERSION}",
                        "kind": "TPUFederationRoll",
                        "name": self.store.name,
                        "namespace": self.namespace,
                    },
                    "source": {"component": "federation-coordinator"},
                },
            )
        except Exception:
            # Events are observe-only; never fail a tick over one.
            self.stats["event_drops"] += 1

    def plan(self, now: Optional[float] = None) -> FederatedPlan:
        """READ-ONLY federated projection from the last built
        snapshots (no API traffic)."""
        healths = self.registry.healths()
        entries = []
        for member in self.registry.members():
            if member.manager is None:
                continue
            health = healths[member.name]
            state = (
                None
                if health is ClusterHealth.PARTITIONED
                else self._last_state.get(member.name)
            )
            entries.append((member, state, health))
        return plan_federated(
            entries,
            self.policy,
            canary_region=self.canary_region,
            soak_s=self.soak_s,
            now=now,
        )

    def status(self) -> dict:
        """CLI / CR-status surface."""
        healths = self.registry.healths()
        verdict = self.gate.evaluate()
        return {
            "phase": self.phase,
            "canary": {
                "region": self.canary_region,
                "phase": verdict.phase,
                "soakSeconds": self.soak_s,
                "soakRemainingSeconds": round(
                    verdict.soak_remaining_s, 1
                ),
                "reason": verdict.reason,
                "traceId": verdict.trace_id,
            },
            "clusters": {
                m.name: {
                    "region": m.region,
                    "health": healths[m.name].value,
                    "done": bool(self._done.get(m.name)),
                    "frozenGroups": len(m.frozen_groups),
                }
                for m in self.registry.members()
            },
            "globalBudget": self.global_ledger.snapshot(),
            "conditions": self.conditions(),
        }
