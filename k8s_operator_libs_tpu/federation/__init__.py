"""Partition-tolerant federated control plane.

Presents N member clusters to ONE global libtpu roll while keeping
every failure local (Podracer's fan-out shape: many independent
per-cluster actors under a thin, restartable global brain):

* :mod:`registry` — cluster membership + per-cluster health state
  machine (Reachable → Degraded → Partitioned) driven by the existing
  per-endpoint circuit breaker and lease freshness, with fail-static
  freeze bookkeeping.
* :mod:`ledger` — :class:`GlobalBudgetLedger`, the global ∧ cluster
  level above the engine's per-cluster ``BudgetLedger`` (global ∧
  cluster ∧ pool check-and-charge).
* :mod:`plan` — :class:`FederatedPlan`: the analytic planner run per
  cluster, composed region-by-region (canary region first).
* :mod:`canary` — telemetry-gated regional canary soak
  (:class:`CanaryGate`): promotion requires the health baselines to
  stay clean for a configurable soak.
* :mod:`coordinator` — :class:`FederationCoordinator`: the restartable
  global brain.  Crash-durable via the same annotation-anchored
  adoption path as the engine (``upgrade/durable.py``).

See docs/federation.md for the topology, the failure matrix, the
canary lifecycle and the fail-static rules.
"""

from k8s_operator_libs_tpu.federation.canary import (  # noqa: F401
    CanaryGate,
    CanaryVerdict,
)
from k8s_operator_libs_tpu.federation.coordinator import (  # noqa: F401
    FederationCoordinator,
    FederationStateStore,
    ensure_federation_kind,
)
from k8s_operator_libs_tpu.federation.ledger import (  # noqa: F401
    GlobalBudgetLedger,
)
from k8s_operator_libs_tpu.federation.plan import (  # noqa: F401
    ClusterRollPlan,
    FederatedPlan,
    plan_federated,
)
from k8s_operator_libs_tpu.federation.registry import (  # noqa: F401
    ClusterHealth,
    ClusterRegistry,
    MemberCluster,
)
