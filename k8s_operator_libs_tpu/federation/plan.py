"""Federated roll plan: the analytic planner, composed across clusters.

``plan_federated`` runs the existing READ-ONLY per-cluster planner
(:func:`~k8s_operator_libs_tpu.planning.planner.plan_roll`) for every
reachable member and composes the wave schedules region-by-region: the
canary region's clusters start at offset 0 (concurrently — they are
independent control planes), every later region starts after the
previous region's slowest cluster plus the canary soak.  Like the
per-cluster planner this issues ZERO writes: it is a projection of
what the coordinator would admit, renderable from the status CLI or
CI.

Fail-static composition rule: a Partitioned cluster contributes no
waves — its in-flight groups appear as ``frozen_groups`` (budget still
reserved in the global ledger) and its pending work as ``deferred``
until the cluster heals.  The remaining clusters' schedules are
composed as usual: the reroute is emergent — healthy clusters proceed
under the global cap net of the frozen reservations.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from k8s_operator_libs_tpu.federation.registry import ClusterHealth
from k8s_operator_libs_tpu.planning.planner import RollPlan, plan_roll


@dataclass
class ClusterRollPlan:
    """One member cluster's slice of the federated plan."""

    cluster: str
    region: str
    health: str
    # None while the cluster is partitioned (fail-static: no projection
    # is possible without a fresh snapshot, and none is needed — the
    # cluster is frozen).
    plan: Optional[RollPlan]
    start_offset_s: float = 0.0
    frozen_groups: Dict[str, int] = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "cluster": self.cluster,
            "region": self.region,
            "health": self.health,
            "startOffsetSeconds": round(self.start_offset_s, 1),
            "frozenGroups": dict(self.frozen_groups),
            "plan": self.plan.to_dict() if self.plan is not None else None,
        }


@dataclass
class FederatedPlan:
    """Region-composed projection of the global roll."""

    created_epoch: float
    canary_region: str
    regions: List[str]  # rollout order: canary first
    clusters: List[ClusterRollPlan]
    soak_s: float
    projected_duration_s: float
    total_nodes: int
    pending_groups: int

    def cluster_plan(self, name: str) -> Optional[ClusterRollPlan]:
        for cp in self.clusters:
            if cp.cluster == name:
                return cp
        return None

    def to_dict(self) -> dict:
        return {
            "createdEpoch": self.created_epoch,
            "canaryRegion": self.canary_region,
            "regions": list(self.regions),
            "soakSeconds": self.soak_s,
            "projectedDurationSeconds": round(self.projected_duration_s, 1),
            "totalNodes": self.total_nodes,
            "pendingGroups": self.pending_groups,
            "clusters": [cp.to_dict() for cp in self.clusters],
        }

    def render(self) -> str:
        lines = [
            f"federated roll plan: {len(self.clusters)} cluster(s) across "
            f"{len(self.regions)} region(s), canary={self.canary_region}, "
            f"soak={self.soak_s:.0f}s, projected "
            f"{self.projected_duration_s:.0f}s",
        ]
        for region in self.regions:
            tag = " (canary)" if region == self.canary_region else ""
            lines.append(f"  region {region}{tag}:")
            for cp in self.clusters:
                if cp.region != region:
                    continue
                if cp.plan is None:
                    lines.append(
                        f"    {cp.cluster}: {cp.health} — fail-static, "
                        f"{len(cp.frozen_groups)} group(s) frozen, "
                        f"budget reserved"
                    )
                    continue
                lines.append(
                    f"    {cp.cluster}: {cp.health}, "
                    f"{cp.plan.wave_count} wave(s), "
                    f"{cp.plan.pending_groups} pending group(s), "
                    f"start +{cp.start_offset_s:.0f}s, "
                    f"duration {cp.plan.projected_duration_s:.0f}s"
                )
        return "\n".join(lines)


def plan_federated(
    entries,
    policy,
    canary_region: str,
    soak_s: float = 0.0,
    now: Optional[float] = None,
    assumptions=None,
) -> FederatedPlan:
    """Compose per-cluster plans region-by-region.

    ``entries`` is an iterable of ``(member, state, health)`` where
    ``member`` carries ``name``/``region``/``manager``/``frozen_groups``
    and ``state`` is the cluster's built snapshot (None for a
    partitioned member — its planner never runs)."""
    if now is None:
        now = time.time()
    cluster_plans: List[ClusterRollPlan] = []
    regions_seen: List[str] = []
    for member, state, health in entries:
        if member.region not in regions_seen:
            regions_seen.append(member.region)
        if health is ClusterHealth.PARTITIONED or state is None:
            cluster_plans.append(
                ClusterRollPlan(
                    cluster=member.name,
                    region=member.region,
                    health=health.value,
                    plan=None,
                    frozen_groups=dict(member.frozen_groups),
                )
            )
            continue
        rp = plan_roll(
            member.manager, state, policy, now=now, assumptions=assumptions
        )
        cluster_plans.append(
            ClusterRollPlan(
                cluster=member.name,
                region=member.region,
                health=health.value,
                plan=rp,
            )
        )
    # Rollout order: canary region first, then the rest sorted.
    ordered = [r for r in [canary_region] if r in regions_seen]
    ordered += sorted(r for r in regions_seen if r != canary_region)
    offset = 0.0
    total_nodes = 0
    pending_groups = 0
    duration = 0.0
    for idx, region in enumerate(ordered):
        region_end = offset
        for cp in cluster_plans:
            if cp.region != region:
                continue
            if cp.plan is None:
                continue
            cp.start_offset_s = offset
            end = offset + cp.plan.projected_duration_s
            region_end = max(region_end, end)
            total_nodes += cp.plan.total_nodes
            pending_groups += cp.plan.pending_groups
        duration = max(duration, region_end)
        # The canary's soak gates promotion to every later region.
        offset = region_end + (soak_s if idx == 0 else 0.0)
    return FederatedPlan(
        created_epoch=now,
        canary_region=canary_region,
        regions=ordered,
        clusters=cluster_plans,
        soak_s=soak_s,
        projected_duration_s=duration,
        total_nodes=total_nodes,
        pending_groups=pending_groups,
    )
