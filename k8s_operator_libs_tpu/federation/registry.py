"""Cluster membership + per-cluster health state machine.

Health is a three-state ladder — ``Reachable → Degraded → Partitioned``
— driven by the two control-plane liveness signals the stack already
maintains:

* the per-endpoint :class:`~k8s_operator_libs_tpu.k8s.retry.
  CircuitBreaker` carried by the cluster's resilient client (an open
  endpoint means repeated transport failures already exhausted their
  retries), and
* lease freshness from ``k8s/leader.py`` semantics: the member
  cluster's controller Lease is read through the same client, and —
  exactly like a leader-election candidate — the registry never
  compares the holder's ``renewTime`` against its own wall clock; it
  records *when it observed* the (holder, renewTime) pair change and
  calls the lease stale only after ``lease_duration_s`` of its OWN
  clock without an observed renewal.

Escalation: every failed probe bumps a consecutive-failure streak
(``degraded_after`` failures → Degraded, ``partitioned_after`` →
Partitioned); a probe that fast-fails on an OPEN breaker escalates
straight to Partitioned — the breaker only opens after the retry tier
has already proven the endpoint down repeatedly.  Healing descends the
same ladder with hysteresis: a Partitioned cluster needs
``heal_probes`` consecutive clean probes to step down to Degraded, and
one more to be Reachable again — a flapping WAN link cannot whipsaw
the coordinator between freeze and resume.

Fail-static bookkeeping rides the member record: when the coordinator
freezes a partitioned cluster it snapshots the cluster's in-flight
budget charges into ``MemberCluster.frozen_groups`` so the global plan
and the status surface can show exactly which capacity stays reserved.
"""

from __future__ import annotations

import enum
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from k8s_operator_libs_tpu.consts import get_logger
from k8s_operator_libs_tpu.k8s.leader import (
    LEASE_GROUP,
    LEASE_PLURAL,
    LEASE_VERSION,
)
from k8s_operator_libs_tpu.k8s.retry import CircuitOpenError

logger = get_logger(__name__)


class ClusterHealth(enum.Enum):
    """Per-cluster control-plane health (NOT a node upgrade state: like
    preemption and window holds this is a *condition* — see the state
    diagram's doctrine notes)."""

    REACHABLE = "Reachable"
    DEGRADED = "Degraded"
    PARTITIONED = "Partitioned"


_LADDER = [
    ClusterHealth.REACHABLE,
    ClusterHealth.DEGRADED,
    ClusterHealth.PARTITIONED,
]


class MemberCluster:
    """One federated member: a name, a region, a (breaker-wrapped)
    client, and optionally the engine driving it."""

    def __init__(
        self,
        name: str,
        region: str,
        client,
        manager=None,
        lease_namespace: str = "",
        lease_name: str = "",
    ) -> None:
        self.name = name
        self.region = region
        self.client = client
        self.manager = manager
        # Per-cluster budget ledger (wired by the coordinator).
        self.ledger = None
        # "" = no lease to watch (single-replica member controllers).
        self.lease_namespace = lease_namespace
        self.lease_name = lease_name
        # Fail-static freeze: group_id → charged units at partition
        # time.  Non-empty only while the cluster is frozen.
        self.frozen_groups: Dict[str, int] = {}

    @property
    def breaker(self):
        return getattr(self.client, "breaker", None)


class ClusterRegistry:
    """Membership + health probing for every federated cluster."""

    def __init__(
        self,
        degraded_after: int = 1,
        partitioned_after: int = 3,
        heal_probes: int = 2,
        lease_duration_s: float = 30.0,
        epoch_clock: Callable[[], float] = time.time,
        mono_clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.degraded_after = max(1, int(degraded_after))
        self.partitioned_after = max(
            self.degraded_after, int(partitioned_after)
        )
        self.heal_probes = max(1, int(heal_probes))
        self.lease_duration_s = lease_duration_s
        self.epoch_clock = epoch_clock
        self.mono_clock = mono_clock
        self._lock = threading.Lock()
        self._members: Dict[str, MemberCluster] = {}
        self._health: Dict[str, ClusterHealth] = {}
        self._fail_streak: Dict[str, int] = {}
        self._heal_streak: Dict[str, int] = {}
        self._last_detail: Dict[str, str] = {}
        # name → ((holder, renewTime), observed_at_mono) — the
        # observer-clock lease freshness record.
        self._lease_obs: Dict[str, Tuple[Tuple[str, str], float]] = {}
        # (epoch, cluster, from, to, reason) — bounded history for the
        # status surface and the tests.
        self.transitions: List[Tuple[float, str, str, str, str]] = []
        self.stats: Dict[str, int] = {
            "probes": 0,
            "probe_failures": 0,
            "partitions": 0,
            "heals": 0,
        }

    # -- membership ----------------------------------------------------------

    def add(
        self,
        name: str,
        region: str,
        client,
        manager=None,
        lease_namespace: str = "",
        lease_name: str = "",
    ) -> MemberCluster:
        member = MemberCluster(
            name,
            region,
            client,
            manager=manager,
            lease_namespace=lease_namespace,
            lease_name=lease_name,
        )
        with self._lock:
            self._members[name] = member
            self._health[name] = ClusterHealth.REACHABLE
            self._fail_streak[name] = 0
            self._heal_streak[name] = 0
        return member

    def member(self, name: str) -> MemberCluster:
        return self._members[name]

    def members(self) -> List[MemberCluster]:
        with self._lock:
            return list(self._members.values())

    def regions(self) -> Dict[str, List[str]]:
        """region → sorted member names."""
        out: Dict[str, List[str]] = {}
        with self._lock:
            for m in self._members.values():
                out.setdefault(m.region, []).append(m.name)
        return {r: sorted(names) for r, names in out.items()}

    # -- health --------------------------------------------------------------

    def health(self, name: str) -> ClusterHealth:
        with self._lock:
            return self._health[name]

    def healths(self) -> Dict[str, ClusterHealth]:
        with self._lock:
            return dict(self._health)

    def detail(self, name: str) -> str:
        with self._lock:
            return self._last_detail.get(name, "")

    def partitioned(self) -> List[str]:
        with self._lock:
            return sorted(
                n
                for n, h in self._health.items()
                if h is ClusterHealth.PARTITIONED
            )

    def reachable(self) -> List[str]:
        with self._lock:
            return sorted(
                n
                for n, h in self._health.items()
                if h is not ClusterHealth.PARTITIONED
            )

    def _lease_fresh(self, member: MemberCluster) -> Optional[bool]:
        """True/False lease freshness on the observer's own clock, or
        None when the member has no lease configured or the read itself
        failed (the transport failure is already the probe verdict)."""
        if not member.lease_name:
            return None
        try:
            lease = member.client.get_custom_object(
                LEASE_GROUP,
                LEASE_VERSION,
                LEASE_PLURAL,
                member.lease_namespace,
                member.lease_name,
            )
        except Exception:
            return None
        spec = lease.get("spec") or {}
        pair = (
            str(spec.get("holderIdentity") or ""),
            str(spec.get("renewTime") or ""),
        )
        now = self.mono_clock()
        prev = self._lease_obs.get(member.name)
        if prev is None or prev[0] != pair:
            self._lease_obs[member.name] = (pair, now)
            return True
        duration = float(
            spec.get("leaseDurationSeconds") or self.lease_duration_s
        )
        return (now - prev[1]) <= duration

    def probe(self, name: str, detail: str = "") -> ClusterHealth:
        """One active health probe: a cheap quorum read through the
        member's (breaker-wrapped) client, plus lease freshness.  The
        read doubles as the breaker's half-open probe after an outage
        ends, so healing needs no out-of-band reset."""
        member = self._members[name]
        self.stats["probes"] += 1
        ok = True
        hard = False
        try:
            member.client.list_page("Node", limit=1)
        except CircuitOpenError as exc:
            ok = False
            hard = True  # breaker already proved the endpoint down
            detail = detail or str(exc)
        except Exception as exc:
            ok = False
            detail = detail or str(exc)
        breaker = member.breaker
        if ok and breaker is not None:
            open_eps = breaker.open_endpoints()
            # The probe endpoint answered but others are still open:
            # count the probe clean (half-open probes on the remaining
            # endpoints close them organically as traffic resumes).
            if open_eps and not detail:
                detail = f"{len(open_eps)} endpoint(s) still open"
        if ok:
            fresh = self._lease_fresh(member)
            if fresh is False:
                ok = False
                detail = detail or (
                    f"lease {member.lease_namespace}/{member.lease_name} "
                    f"stale on observer clock"
                )
        return self._step(name, ok, hard, detail)

    def observe_failure(self, name: str, detail: str = "") -> ClusterHealth:
        """Engine-pass failure feedback (e.g. apply_state raised through
        the resilient client).  A CircuitOpen detail escalates hard."""
        hard = "circuit open" in detail.lower()
        return self._step(name, False, hard, detail)

    def observe_success(self, name: str) -> ClusterHealth:
        return self._step(name, True, False, "")

    def _step(
        self, name: str, ok: bool, hard: bool, detail: str
    ) -> ClusterHealth:
        transition: Optional[Tuple[str, str, str]] = None
        with self._lock:
            cur = self._health[name]
            if ok:
                self._fail_streak[name] = 0
                self._heal_streak[name] += 1
                new = cur
                if cur is ClusterHealth.PARTITIONED:
                    if self._heal_streak[name] >= self.heal_probes:
                        new = ClusterHealth.DEGRADED
                        self._heal_streak[name] = 0
                elif cur is ClusterHealth.DEGRADED:
                    new = ClusterHealth.REACHABLE
                reason = "clean probe"
            else:
                self.stats["probe_failures"] += 1
                self._heal_streak[name] = 0
                streak = self._fail_streak[name] + 1
                if hard:
                    streak = max(streak, self.partitioned_after)
                self._fail_streak[name] = streak
                if streak >= self.partitioned_after:
                    new = ClusterHealth.PARTITIONED
                elif streak >= self.degraded_after:
                    # Never step DOWN on a failure.
                    new = (
                        ClusterHealth.DEGRADED
                        if cur is not ClusterHealth.PARTITIONED
                        else cur
                    )
                else:
                    new = cur
                reason = detail or "probe failed"
            self._last_detail[name] = detail if not ok else ""
            if new is not cur:
                self._health[name] = new
                transition = (cur.value, new.value, reason)
                if new is ClusterHealth.PARTITIONED:
                    self.stats["partitions"] += 1
                if (
                    cur is ClusterHealth.PARTITIONED
                    and new is not ClusterHealth.PARTITIONED
                ):
                    self.stats["heals"] += 1
        if transition is not None:
            self.transitions.append(
                (self.epoch_clock(), name) + transition
            )
            del self.transitions[:-256]
            logger.info(
                "cluster %s health %s -> %s (%s)",
                name,
                transition[0],
                transition[1],
                transition[2],
            )
        with self._lock:
            return self._health[name]
