"""Telemetry-gated regional canary soak.

The first region rolls alone; promotion to the remaining regions
requires the fleet-health baselines (``obs/telemetry.py`` /
``obs/baseline.py``) to stay CLEAN for a configurable soak window.  A
straggler confirmed by the telemetry plane during the soak — the same
``confirm_batteries``-deep longitudinal verdict the engine's health
gate uses — hard-stops promotion: the gate latches ``held`` with the
regression's node/stat/z and the roll's trace id, and only an explicit
operator ``clear_hold`` (or a fresh roll) releases it.

Crash durability: the soak start is persisted as an epoch by the
coordinator's durable store and rebased onto the process monotonic
clock on adoption via :func:`~k8s_operator_libs_tpu.upgrade.durable.
monotonic_from_epoch` — the same annotation-anchored rebase every
engine progress clock uses — so a restarted coordinator resumes the
soak AT its elapsed point instead of restarting it (a crash can only
lengthen a soak, never shorten it).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional

from k8s_operator_libs_tpu.consts import get_logger
from k8s_operator_libs_tpu.upgrade.durable import monotonic_from_epoch

logger = get_logger(__name__)

# Gate phases.
PENDING = "pending"  # canary region still rolling
SOAKING = "soaking"  # canary done, baselines under observation
HELD = "held"  # regression confirmed: promotion hard-stopped
PROMOTE = "promote"  # soak elapsed clean


@dataclass
class CanaryVerdict:
    phase: str
    reason: str = ""
    trace_id: str = ""
    soak_remaining_s: float = 0.0
    confirmations: List[dict] = field(default_factory=list)


class CanaryGate:
    """Soak clock + telemetry verdict latch for the canary region."""

    def __init__(
        self,
        soak_s: float,
        mono_clock: Callable[[], float] = time.monotonic,
        epoch_clock: Callable[[], float] = time.time,
    ) -> None:
        self.soak_s = max(0.0, float(soak_s))
        self.mono_clock = mono_clock
        self.epoch_clock = epoch_clock
        # Monotonic anchor of the soak start (None = not started) and
        # its durable wall-clock twin (what the store persists).
        self._soak_anchor: Optional[float] = None
        self.soak_started_epoch: Optional[float] = None
        # Latched hold: {"reason", "trace_id", "epoch", "confirmations"}.
        self.held: Optional[dict] = None
        self.holds_total = 0

    # -- soak clock ----------------------------------------------------------

    def begin_soak(self, now_epoch: Optional[float] = None) -> bool:
        """Start the soak (idempotent).  Returns True on the first call
        — the coordinator persists the epoch exactly then."""
        if self._soak_anchor is not None:
            return False
        self._soak_anchor = self.mono_clock()
        self.soak_started_epoch = (
            self.epoch_clock() if now_epoch is None else now_epoch
        )
        return True

    def adopt_soak(
        self, started_epoch: float, now_epoch: Optional[float] = None
    ) -> None:
        """Resume a persisted soak: rebase the wall-clock anchor onto
        this process's monotonic clock (elapsed time survives the
        restart; wall-clock regressions clamp to zero elapsed)."""
        self.soak_started_epoch = started_epoch
        # Pass now_epoch explicitly: monotonic_from_epoch's default
        # truncates to whole seconds, which a sub-second soak anchor
        # cannot afford.
        if now_epoch is None:
            now_epoch = self.epoch_clock()
        self._soak_anchor = monotonic_from_epoch(
            started_epoch, now_epoch=now_epoch
        )

    @property
    def soaking(self) -> bool:
        return self._soak_anchor is not None

    # -- verdicts ------------------------------------------------------------

    def observe_plane(self, plane, trace_id: str = "") -> List[dict]:
        """Fold one telemetry-plane reading into the gate.  Any NEW
        straggler confirmation while the gate is armed latches a hold.
        Returns the fresh confirmations (for event emission)."""
        if plane is None:
            return []
        try:
            plane.recompute()
            fresh = plane.new_confirmations()
        except Exception:
            # The plane is fail-open everywhere else; a broken reading
            # must not silently PROMOTE either — it simply yields no
            # verdict this pass.
            logger.debug("canary telemetry read failed", exc_info=True)
            return []
        if fresh and self.held is None:
            worst = fresh[0]
            self.hold(
                reason=(
                    f"telemetry regression: node {worst.get('node')} "
                    f"{worst.get('worstStat')} z={worst.get('z')} "
                    f"(score {worst.get('score')}, "
                    f"streak {worst.get('streak')})"
                ),
                trace_id=trace_id,
                confirmations=fresh,
            )
        return fresh

    def hold(
        self,
        reason: str,
        trace_id: str = "",
        confirmations: Optional[List[dict]] = None,
    ) -> None:
        if self.held is not None:
            return
        self.held = {
            "reason": reason,
            "trace_id": trace_id,
            "epoch": self.epoch_clock(),
            "confirmations": list(confirmations or []),
        }
        self.holds_total += 1
        logger.warning("canary held: %s (trace %s)", reason, trace_id)

    def clear_hold(self) -> None:
        self.held = None

    def evaluate(self) -> CanaryVerdict:
        if self.held is not None:
            return CanaryVerdict(
                phase=HELD,
                reason=self.held["reason"],
                trace_id=self.held.get("trace_id", ""),
                confirmations=list(self.held.get("confirmations", [])),
            )
        if self._soak_anchor is None:
            return CanaryVerdict(phase=PENDING)
        remaining = self.soak_s - (self.mono_clock() - self._soak_anchor)
        if remaining > 0:
            return CanaryVerdict(phase=SOAKING, soak_remaining_s=remaining)
        return CanaryVerdict(phase=PROMOTE)
