"""Read-only upgrade status summary: the operator's mid-roll view.

    python -m k8s_operator_libs_tpu.status \
        --namespace kube-system --selector app=libtpu-driver [--json]

Snapshots the cluster exactly the way the engine does (BuildState — no
writes) and prints per-slice state, host counts, availability, the
driver's current ControllerRevision, policy-CR conditions when present,
and recent Warning events.  This is the human/scripting face of the
same facts the controller acts on; the reference leaves this to kubectl
one-liners over its labels (docs/automatic-ofed-upgrade.md
troubleshooting section).
"""

from __future__ import annotations

import argparse
import json as _json
from typing import Optional

from k8s_operator_libs_tpu.k8s.interface import KubeClient
from k8s_operator_libs_tpu.metrics import PREFIX
from k8s_operator_libs_tpu.upgrade.consts import TRUE_STRING, UpgradeState
from k8s_operator_libs_tpu.upgrade.node_state_provider import node_ready
from k8s_operator_libs_tpu.upgrade.upgrade_state import (
    BuildStateError,
    ClusterUpgradeStateManager,
)
from k8s_operator_libs_tpu.upgrade.util import UpgradeKeys


# Controller /metrics series → status keys for the shard-health section.
SHARDED_METRIC_KEYS = {
    "reconcile_shards": "shards",
    "reconcile_shard_busy": "busyShards",
    "reconcile_dirty_pools": "lastTickPools",
    "dirty_queue_depth": "queueDepth",
    "dirty_queue_in_flight": "queueInFlight",
    "dirty_queue_oldest_wait_seconds": "queueOldestWaitSeconds",
    "dirty_tick_duration_seconds": "lastTickSeconds",
    "dirty_events_routed_total": "eventsRouted",
    "dirty_events_coalesced_total": "eventsCoalesced",
    "dirty_pools_reconciled_total": "poolsReconciled",
    "dirty_shard_errors_total": "shardErrors",
    "dirty_shard_fenced_total": "shardFenced",
    "full_resyncs_total": "fullResyncs",
    "budget_unavailable_used": "budgetUsed",
    "budget_unavailable_cap": "budgetCap",
    "budget_parallel_used": "budgetParallel",
    "matview_hits_total": "viewHits",
    "matview_fallback_rebuilds_total": "viewFallbacks",
    "matview_diff_mismatches_total": "viewDiffMismatches",
    "matview_pools": "viewPools",
    "matview_rows": "viewRows",
    "matview_interned_strings": "viewInternedStrings",
    "matview_apply_latency_us": "viewApplyLatencyUs",
}


# Controller /metrics series → status keys for the elastic-coordination
# section (unlabeled series only; elastic_negotiations_total{outcome=...}
# and elastic_resizes_total{direction=...} are parsed label-aware below).
ELASTIC_METRIC_KEYS = {
    "elastic_excluded_slices": "excludedSlices",
    "elastic_resize_seconds": "lastResizeSeconds",
}


# Controller /metrics series → status keys for the probe-battery section
# (unlabeled series only; probe_battery_seconds{phase=...} and
# validation_wall_seconds{slice=...} are parsed label-aware below).
BATTERY_METRIC_KEYS = {
    "probe_battery_cache_hits_total": "cacheHits",
    "probe_battery_cache_misses_total": "cacheMisses",
    "probe_battery_fallbacks_total": "fallbacks",
    "probe_battery_cached_programs": "cachedPrograms",
}


# Controller /metrics series → status keys for the write-plane section
# (unlabeled series only; writeplan_writes_total{flow=...},
# writeplan_pending{kind=...}, flow_tokens{flow=...} and
# flow_throttled{flow=...} are parsed label-aware below).
WRITEPLANE_METRIC_KEYS = {
    "writes_suppressed_total": "suppressed",
    "writes_coalesced_total": "coalescedKeys",
    "writeplan_flushes_total": "flushes",
    "writeplan_fenced_drops_total": "fencedDrops",
    "writeplan_conflict_replays_total": "conflictReplays",
    "events_published_total": "eventsPublished",
    "events_aggregated_total": "eventsAggregated",
    "flow_throttle_waits_total": "throttleWaits",
    "flow_deferred_total": "deferred",
    "api_writes_per_tick": "apiWritesPerTick",
}


# Controller /metrics series → status keys for the plan section
# (unlabeled series only; fleet_roll_infeasible{reason=...} and
# fleet_window_invalid{pool=...} are parsed label-aware below).
PLAN_METRIC_KEYS = {
    "plan_waves": "waves",
    "plan_groups": "plannedGroups",
    "plan_completed_groups": "completedGroups",
    "plan_projected_completion_timestamp_seconds": "projectedCompletionEpoch",
    "plan_drift_seconds": "driftSeconds",
    "plan_replans_total": "replans",
    "budget_saturation": "budgetSaturation",
    "budget_idle_ticks_total": "budgetIdleTicks",
    "admission_packed_total": "packedAdmissions",
}

# Admission keys are published even with no active roll, so (like
# "replans") they must not by themselves make plan_health report a
# section.
_PLAN_ALWAYS_ON_KEYS = {
    "replans",
    "budgetSaturation",
    "budgetIdleTicks",
    "packedAdmissions",
    "admissionMode",
}


def _metrics_text(metrics_url: str, fetch=None) -> str:
    """Fetch the exposition text; ``fetch`` is injectable for tests."""
    if fetch is None:
        from urllib.request import urlopen

        with urlopen(metrics_url, timeout=5) as resp:
            return resp.read().decode()
    return fetch(metrics_url)


def sharded_health(metrics_url: str, fetch=None) -> Optional[dict]:
    """Shard health from the controller's /metrics exposition.

    The sharded reconciler lives inside the controller process; this
    read-only tool cannot see its queue directly, so it reads the same
    numbers the controller already exports.  Returns None when the
    family is absent (controller running the classic full-pass loop),
    an ``{"error": ...}`` dict when the endpoint is unreachable.
    ``fetch`` is injectable for tests."""
    try:
        text = _metrics_text(metrics_url, fetch)
    except Exception as e:  # noqa: BLE001 — status must render regardless
        return {"error": f"metrics unreachable: {e}"}
    out: dict = {}
    for line in text.splitlines():
        if line.startswith("#") or " " not in line:
            continue
        name, _, value = line.rpartition(" ")
        name = name.split("{")[0]
        if not name.startswith(PREFIX + "_"):
            continue
        key = SHARDED_METRIC_KEYS.get(name[len(PREFIX) + 1 :])
        if key is None:
            continue
        try:
            out[key] = float(value)
        except ValueError:
            continue
    return out or None


def battery_health(metrics_url: str, fetch=None) -> Optional[dict]:
    """Fused probe-battery + validation-gate health from /metrics.

    Returns None when the battery family is absent (controller never
    probed in-process — e.g. agents run the battery instead), an
    ``{"error": ...}`` dict when the endpoint is unreachable."""
    try:
        text = _metrics_text(metrics_url, fetch)
    except Exception as e:  # noqa: BLE001 — status must render regardless
        return {"error": f"metrics unreachable: {e}"}
    out: dict = {}
    walls: dict[str, float] = {}
    for line in text.splitlines():
        if line.startswith("#") or " " not in line:
            continue
        name, _, value = line.rpartition(" ")
        labels = ""
        if "{" in name:
            name, _, labels = name.partition("{")
        if not name.startswith(PREFIX + "_"):
            continue
        short = name[len(PREFIX) + 1 :]
        try:
            val = float(value)
        except ValueError:
            continue
        if short == "probe_battery_seconds":
            if 'phase="compile"' in labels:
                out["compileSeconds"] = val
            elif 'phase="execute"' in labels:
                out["executeSeconds"] = val
        elif short == "validation_wall_seconds":
            gid = labels.split('slice="', 1)
            if len(gid) == 2:
                walls[gid[1].split('"', 1)[0]] = val
        else:
            key = BATTERY_METRIC_KEYS.get(short)
            if key is not None:
                out[key] = val
    if walls:
        out["validationWallSeconds"] = walls
    return out or None


def elastic_health(metrics_url: str, fetch=None) -> Optional[dict]:
    """Elastic-roll coordination health from the controller's /metrics.

    Returns None when the elastic family is absent (coordination never
    engaged — disabled in policy, or no registered workloads), an
    ``{"error": ...}`` dict when the endpoint is unreachable."""
    try:
        text = _metrics_text(metrics_url, fetch)
    except Exception as e:  # noqa: BLE001 — status must render regardless
        return {"error": f"metrics unreachable: {e}"}
    out: dict = {}
    negotiations: dict[str, float] = {}
    resizes: dict[str, float] = {}
    for line in text.splitlines():
        if line.startswith("#") or " " not in line:
            continue
        name, _, value = line.rpartition(" ")
        labels = ""
        if "{" in name:
            name, _, labels = name.partition("{")
        if not name.startswith(PREFIX + "_"):
            continue
        short = name[len(PREFIX) + 1 :]
        try:
            val = float(value)
        except ValueError:
            continue
        if short == "elastic_negotiations_total":
            outcome = labels.split('outcome="', 1)
            if len(outcome) == 2:
                negotiations[outcome[1].split('"', 1)[0]] = val
        elif short == "elastic_resizes_total":
            direction = labels.split('direction="', 1)
            if len(direction) == 2:
                resizes[direction[1].split('"', 1)[0]] = val
        else:
            key = ELASTIC_METRIC_KEYS.get(short)
            if key is not None:
                out[key] = val
    if negotiations:
        out["negotiations"] = negotiations
    if resizes:
        out["resizes"] = resizes
    return out or None


def artifact_health(metrics_url: str, fetch=None) -> Optional[dict]:
    """Multi-artifact stack progress from the controller's /metrics.

    Returns None when the artifact family is absent (single-artifact
    policy — the classic path publishes no per-artifact series), an
    ``{"error": ...}`` dict when the endpoint is unreachable."""
    try:
        text = _metrics_text(metrics_url, fetch)
    except Exception as e:  # noqa: BLE001 — status must render regardless
        return {"error": f"metrics unreachable: {e}"}
    out: dict = {}
    artifacts: dict[str, dict] = {}

    def _row(labels: str) -> Optional[dict]:
        name = labels.split('artifact="', 1)
        if len(name) != 2:
            return None
        return artifacts.setdefault(name[1].split('"', 1)[0], {})

    for line in text.splitlines():
        if line.startswith("#") or " " not in line:
            continue
        name, _, value = line.rpartition(" ")
        labels = ""
        if "{" in name:
            name, _, labels = name.partition("{")
        if not name.startswith(PREFIX + "_"):
            continue
        short = name[len(PREFIX) + 1 :]
        try:
            val = float(value)
        except ValueError:
            continue
        if short == "artifact_synced_nodes":
            row = _row(labels)
            if row is not None:
                row["synced"] = int(val)
        elif short == "artifact_nodes":
            row = _row(labels)
            if row is not None:
                row["nodes"] = int(val)
        elif short == "artifact_skew_holds_total":
            row = _row(labels)
            if row is not None:
                row["skewHolds"] = int(val)
        elif short == "artifact_gate_holds_total":
            row = _row(labels)
            if row is not None:
                row["gateHolds"] = int(val)
        elif short == "artifact_rollbacks_total":
            out["rollbacks"] = int(val)
        elif short == "artifact_shared_window_savings_total":
            out["sharedWindowSavings"] = int(val)
    if artifacts:
        out["artifacts"] = artifacts
    return out if artifacts else None


def write_plane_health(metrics_url: str, fetch=None) -> Optional[dict]:
    """Transactional write-plane health from the controller's /metrics.

    Shows per-flow writes and throttle state, pending queue depths, and
    the hygiene counters (suppressed / coalesced / aggregated).  Returns
    None when the write-plane family is absent (controller predates the
    write plane), an ``{"error": ...}`` dict when the endpoint is
    unreachable."""
    try:
        text = _metrics_text(metrics_url, fetch)
    except Exception as e:  # noqa: BLE001 — status must render regardless
        return {"error": f"metrics unreachable: {e}"}
    out: dict = {}
    writes: dict[str, float] = {}
    pending: dict[str, float] = {}
    tokens: dict[str, float] = {}
    throttled: dict[str, float] = {}
    for line in text.splitlines():
        if line.startswith("#") or " " not in line:
            continue
        name, _, value = line.rpartition(" ")
        labels = ""
        if "{" in name:
            name, _, labels = name.partition("{")
        if not name.startswith(PREFIX + "_"):
            continue
        short = name[len(PREFIX) + 1 :]
        try:
            val = float(value)
        except ValueError:
            continue
        if short == "writeplan_writes_total":
            flow = labels.split('flow="', 1)
            if len(flow) == 2:
                writes[flow[1].split('"', 1)[0]] = val
        elif short == "writeplan_pending":
            kind = labels.split('kind="', 1)
            if len(kind) == 2:
                pending[kind[1].split('"', 1)[0]] = val
        elif short == "flow_tokens":
            flow = labels.split('flow="', 1)
            if len(flow) == 2:
                tokens[flow[1].split('"', 1)[0]] = val
        elif short == "flow_throttled":
            flow = labels.split('flow="', 1)
            if len(flow) == 2:
                throttled[flow[1].split('"', 1)[0]] = val
        else:
            key = WRITEPLANE_METRIC_KEYS.get(short)
            if key is not None:
                out[key] = val
    if writes:
        out["writes"] = writes
    if pending:
        out["pending"] = pending
    if tokens:
        out["flowTokens"] = tokens
    if throttled:
        out["flowThrottled"] = throttled
    # api_writes_per_tick alone predates the write plane — only report a
    # section when a write-plane-specific series was actually present.
    plane_only = set(out) - {"apiWritesPerTick"}
    return out if plane_only else None


def plan_health(metrics_url: str, fetch=None) -> Optional[dict]:
    """Predictive-planning health from the controller's /metrics: the
    anchored plan's projected waves, drift-adjusted ETA, and any
    structural infeasibility reasons the drift watchdog detected.

    Returns None when the plan family is absent (no active roll — the
    watchdog clears its gauges when the roll finishes), an
    ``{"error": ...}`` dict when the endpoint is unreachable."""
    try:
        text = _metrics_text(metrics_url, fetch)
    except Exception as e:  # noqa: BLE001 — status must render regardless
        return {"error": f"metrics unreachable: {e}"}
    out: dict = {}
    infeasible: list[str] = []
    invalid_windows: list[str] = []
    for line in text.splitlines():
        if line.startswith("#") or " " not in line:
            continue
        name, _, value = line.rpartition(" ")
        labels = ""
        if "{" in name:
            name, _, labels = name.partition("{")
        if not name.startswith(PREFIX + "_"):
            continue
        short = name[len(PREFIX) + 1 :]
        try:
            val = float(value)
        except ValueError:
            continue
        if short == "fleet_roll_infeasible":
            reason = labels.split('reason="', 1)
            if len(reason) == 2 and val:
                infeasible.append(reason[1].split('"', 1)[0])
        elif short == "admission_mode":
            mode = labels.split('mode="', 1)
            if len(mode) == 2 and val:
                out["admissionMode"] = mode[1].split('"', 1)[0]
        elif short == "fleet_window_invalid":
            pool = labels.split('pool="', 1)
            if len(pool) == 2 and val:
                invalid_windows.append(pool[1].split('"', 1)[0])
        else:
            key = PLAN_METRIC_KEYS.get(short)
            if key is not None:
                out[key] = val
    if infeasible:
        out["infeasible"] = sorted(infeasible)
    if invalid_windows:
        out["invalidWindows"] = sorted(invalid_windows)
    # plan_replans_total and the admission keys are published even with
    # no active roll — require a wave/ETA series before reporting a
    # section.
    return out if set(out) - _PLAN_ALWAYS_ON_KEYS else None


def telemetry_health(metrics_url: str, fetch=None) -> Optional[dict]:
    """Fleet health from the controller's /metrics: per-node health
    scores folded to a distribution, confirmed stragglers per
    (generation, pool) cohort, and the telemetry plane's own counters.

    Returns None when the family is absent (telemetry disabled or no
    batteries observed yet), an ``{"error": ...}`` dict when the
    endpoint is unreachable."""
    try:
        text = _metrics_text(metrics_url, fetch)
    except Exception as e:  # noqa: BLE001 — status must render regardless
        return {"error": f"metrics unreachable: {e}"}
    scores: dict[str, float] = {}
    stragglers: list[dict] = []
    out: dict = {}
    for line in text.splitlines():
        if line.startswith("#") or " " not in line:
            continue
        name, _, value = line.rpartition(" ")
        labels = ""
        if "{" in name:
            name, _, labels = name.partition("{")
        if not name.startswith(PREFIX + "_"):
            continue
        short = name[len(PREFIX) + 1 :]
        try:
            val = float(value)
        except ValueError:
            continue
        if short == "node_health_score":
            node = labels.split('node="', 1)
            if len(node) == 2:
                scores[node[1].split('"', 1)[0]] = val
        elif short == "fleet_stragglers" and val:
            gen = labels.split('generation="', 1)
            pool = labels.split('pool="', 1)
            stragglers.append(
                {
                    "generation": (
                        gen[1].split('"', 1)[0] if len(gen) == 2 else ""
                    ),
                    "pool": (
                        pool[1].split('"', 1)[0] if len(pool) == 2 else ""
                    ),
                    "count": int(val),
                }
            )
        elif short == "telemetry_samples_total":
            out["samples"] = int(val)
        elif short == "telemetry_drops_total":
            out["drops"] = int(val)
    if scores:
        out["scoredNodes"] = len(scores)
        out["meanScore"] = round(sum(scores.values()) / len(scores), 1)
        worst = min(scores, key=scores.get)
        out["worstNode"] = worst
        out["worstScore"] = scores[worst]
    if stragglers:
        out["stragglers"] = sorted(
            stragglers, key=lambda s: (s["generation"], s["pool"])
        )
    return out if (scores or stragglers or out.get("samples")) else None


def federation_health(metrics_url: str, fetch=None) -> Optional[dict]:
    """Federated control plane from the controller's /metrics: the
    per-cluster health ladder, fail-static freeze depth, the canary
    gate, and the global budget counters.

    Returns None when the family is absent (federation disabled), an
    ``{"error": ...}`` dict when the endpoint is unreachable."""
    try:
        text = _metrics_text(metrics_url, fetch)
    except Exception as e:  # noqa: BLE001 — status must render regardless
        return {"error": f"metrics unreachable: {e}"}
    rung = {0: "Reachable", 1: "Degraded", 2: "Partitioned"}

    def _label(labels: str, key: str) -> str:
        part = labels.split(f'{key}="', 1)
        return part[1].split('"', 1)[0] if len(part) == 2 else ""

    clusters: dict[str, dict] = {}
    out: dict = {}
    for line in text.splitlines():
        if line.startswith("#") or " " not in line:
            continue
        name, _, value = line.rpartition(" ")
        labels = ""
        if "{" in name:
            name, _, labels = name.partition("{")
        if not name.startswith(PREFIX + "_federation_"):
            continue
        short = name[len(PREFIX) + 12 :]
        try:
            val = float(value)
        except ValueError:
            continue
        if short == "cluster_health":
            row = clusters.setdefault(_label(labels, "cluster"), {})
            row["region"] = _label(labels, "region")
            row["health"] = rung.get(int(val), "Partitioned")
        elif short == "cluster_done":
            clusters.setdefault(_label(labels, "cluster"), {})["done"] = (
                bool(val)
            )
        elif short == "frozen_groups" and val:
            clusters.setdefault(_label(labels, "cluster"), {})[
                "frozenGroups"
            ] = int(val)
        elif short == "phase" and val:
            out["phase"] = _label(labels, "phase")
        elif short == "canary_held":
            out["canaryHeld"] = bool(val)
        elif short == "soak_remaining_seconds" and val:
            out["soakRemainingSeconds"] = val
        elif short == "budget_unavailable_used":
            out["budgetUsed"] = int(val)
        elif short == "budget_unavailable_cap":
            out["budgetCap"] = int(val)
        elif short == "budget_parallel_used":
            out["budgetParallel"] = int(val)
        elif short == "budget_violations_total":
            out["budgetViolations"] = int(val)
        elif short == "partitions_total":
            out["partitions"] = int(val)
        elif short == "heals_total":
            out["heals"] = int(val)
    if clusters:
        out["clusters"] = {
            name: clusters[name] for name in sorted(clusters)
        }
    return out if (clusters or "phase" in out) else None


def gather(
    client: KubeClient,
    namespace: str,
    driver_labels: dict[str, str],
    keys: Optional[UpgradeKeys] = None,
    policy_ref: Optional[tuple[str, str]] = None,
    max_events: int = 10,
    lease_name: str = "tpu-upgrade-controller",
    lease_namespace: Optional[str] = None,
    metrics_url: Optional[str] = None,
    metrics_fetch=None,
) -> dict:
    """Collect the status snapshot as a JSON-shaped dict (no writes)."""
    keys = keys or UpgradeKeys()
    # Fetch + parse the policy FIRST: grouping can depend on it
    # (slice_atomic, topology overrides), and the controller passes its
    # policy into build_state — showing a different grouping here would
    # misrepresent what the engine acts on.
    policy = None
    policy_section: Optional[dict] = None
    if policy_ref is not None:
        from k8s_operator_libs_tpu.api import TPUUpgradePolicySpec
        from k8s_operator_libs_tpu.api.schema import (
            POLICY_GROUP,
            POLICY_PLURAL,
            POLICY_VERSION,
        )
        from k8s_operator_libs_tpu.k8s.client import NotFoundError

        try:
            cr = client.get_custom_object(
                POLICY_GROUP,
                POLICY_VERSION,
                POLICY_PLURAL,
                policy_ref[0],
                policy_ref[1],
            )
            cr_status = cr.get("status") or {}
            policy_section = {
                "spec": cr.get("spec") or {},
                "conditions": cr_status.get("conditions", []),
                # Lifetime counters the controller publishes (crash-safe:
                # re-seeded from annotations on leader adoption).
                "evictionEscalations": cr_status.get("evictionEscalations")
                or {},
                "rollbackAttempts": cr_status.get("rollbackAttempts") or {},
            }
            # Durable planning surface (written by the drift watchdog
            # each full pass; survives a controller restart).
            cr_plan = {
                key: cr_status[key]
                for key in (
                    "projectedCompletion",
                    "planDriftSeconds",
                    "planWaves",
                    "planCompletedGroups",
                    "planReplans",
                    "planInfeasible",
                    "admissionMode",
                    "budgetSaturation",
                    "planTraceId",
                )
                if key in cr_status
            }
            if cr_plan:
                policy_section["plan"] = cr_plan
            # Completed-roll makespan attribution (obs/critical.py),
            # durable on the CR so the CLI renders it after the fact.
            if cr_status.get("makespanBreakdown"):
                policy_section["makespanBreakdown"] = cr_status[
                    "makespanBreakdown"
                ]
            # Fleet health telemetry (obs/telemetry.py): cohort
            # baselines + confirmed stragglers as the controller last
            # published them.
            if cr_status.get("healthSummary"):
                policy_section["healthSummary"] = cr_status[
                    "healthSummary"
                ]
            if cr_status.get("stragglers"):
                policy_section["stragglers"] = cr_status["stragglers"]
            try:
                policy = TPUUpgradePolicySpec.from_dict(cr.get("spec") or {})
            except (ValueError, TypeError):
                policy = None
        except NotFoundError:
            policy_section = {"error": "policy CR not found"}
    mgr = ClusterUpgradeStateManager(client, keys=keys)
    try:
        state = mgr.build_state(namespace, driver_labels, policy)
    except BuildStateError as e:
        return {"error": f"snapshot incoherent: {e} (mid-rollout; retry)"}
    from k8s_operator_libs_tpu.upgrade.durable import parse_int

    rung_key = keys.eviction_rung_annotation
    attempts_key = keys.rollback_attempts_annotation
    cycles_key = keys.quarantine_cycle_count_annotation
    # Nodes currently mid-escalation, per persisted ladder rung — read
    # from the durable annotations, so this is correct even while no
    # controller is running (or right after a leader handoff).
    escalations_in_flight: dict[str, int] = {}
    groups = []
    for group in sorted(state.all_groups(), key=lambda g: g.id):
        effective = group.effective_state(keys.state_label).value or "idle"
        member_states = {
            m.node.name: m.node.labels.get(keys.state_label, "")
            for m in group.members
        }
        unavailable = sum(
            1
            for m in group.members
            if m.node.spec.unschedulable or not node_ready(m.node)
        )
        for m in group.members:
            rung = m.node.annotations.get(rung_key, "")
            if rung:
                escalations_in_flight[rung] = (
                    escalations_in_flight.get(rung, 0) + 1
                )
        groups.append(
            {
                "group": group.id,
                "state": effective,
                "hosts": group.size(),
                "unavailable": unavailable,
                "rollbackAttempts": max(
                    (
                        parse_int(m.node.annotations.get(attempts_key))
                        for m in group.members
                    ),
                    default=0,
                ),
                "quarantineCycles": max(
                    (
                        parse_int(m.node.annotations.get(cycles_key))
                        for m in group.members
                    ),
                    default=0,
                ),
                "quarantined": effective == UpgradeState.QUARANTINED.value,
                "elasticExcluded": any(
                    m.node.annotations.get(keys.elastic_excluded_annotation)
                    == TRUE_STRING
                    for m in group.members
                ),
                "accelerator": (
                    group.slice_info.accelerator if group.slice_info else ""
                ),
                "topology": (
                    group.slice_info.topology if group.slice_info else ""
                ),
                "dcn_group": (
                    group.slice_info.dcn_group
                    if group.slice_info and group.slice_info.dcn_group
                    else ""
                ),
                "members": member_states,
            }
        )
    # Heterogeneous-fleet view: hosts per TPU generation, currently
    # preempted hosts, and pools holding for a maintenance window —
    # read from the durable annotations the engine stamps, so the
    # section is correct even with no controller running.
    from k8s_operator_libs_tpu.fleet.profiles import generation_of
    from k8s_operator_libs_tpu.upgrade.consts import (
        NODE_PREEMPTION_ANNOTATION,
    )

    generations: dict[str, dict] = {}
    window_holds: dict[str, int] = {}
    window_key = keys.window_wait_annotation
    for group in state.all_groups():
        accel = group.slice_info.accelerator if group.slice_info else ""
        gen = generation_of(accel) or "unknown"
        row = generations.setdefault(
            gen, {"nodes": 0, "groups": 0, "preempted": 0}
        )
        row["nodes"] += group.size()
        row["groups"] += 1
        row["preempted"] += sum(
            1
            for m in group.members
            if NODE_PREEMPTION_ANNOTATION in m.node.annotations
        )
        for m in group.members:
            pool = m.node.annotations.get(window_key, "")
            if pool:
                window_holds[pool] = window_holds.get(pool, 0) + 1
                break
    out = {
        "totalManagedNodes": mgr.get_total_managed_nodes(state),
        "totalManagedGroups": mgr.get_total_managed_groups(state),
        "upgradesInProgress": mgr.get_upgrades_in_progress(state),
        "upgradesDone": mgr.get_upgrades_done(state),
        "upgradesFailed": mgr.get_upgrades_failed(state),
        "upgradesPending": mgr.get_upgrades_pending(state),
        "slicesQuarantined": len(
            state.groups_in(UpgradeState.QUARANTINED)
        ),
        "evictionEscalationsInFlight": escalations_in_flight,
        "groups": groups,
    }
    if generations:
        fleet_section: dict = {"generations": generations}
        if window_holds:
            fleet_section["windowHolds"] = window_holds
        out["fleet"] = fleet_section
    if policy_section is not None:
        out["policy"] = policy_section
    # Control-plane health: when the client carries a circuit breaker
    # (RestClient / ResilientClient), surface open endpoints + retry
    # counters — the operator-facing view of degraded mode.
    breaker = getattr(client, "breaker", None)
    if breaker is not None and hasattr(breaker, "open_endpoints"):
        retry_stats = getattr(client, "retry_stats", None) or {}
        out["apiHealth"] = {
            "openCircuits": dict(breaker.open_endpoints()),
            "retries": int(retry_stats.get("retries", 0)),
            "breakerFastFails": int(
                retry_stats.get("breaker_fast_fail", 0)
            ),
        }
    # Who is driving: the election Lease names the active controller
    # replica (empty/absent = single-replica mode or between terms).
    try:
        from k8s_operator_libs_tpu.k8s.client import NotFoundError
        from k8s_operator_libs_tpu.k8s.leader import (
            LEASE_GROUP,
            LEASE_PLURAL,
            LEASE_VERSION,
        )

        lease = client.get_custom_object(
            LEASE_GROUP, LEASE_VERSION, LEASE_PLURAL,
            lease_namespace or namespace,
            lease_name,
        )
        spec = lease.get("spec") or {}
        out["leader"] = {
            "holder": spec.get("holderIdentity") or "",
            "renewTime": spec.get("renewTime") or "",
        }
    except NotFoundError:
        pass
    except Exception:  # noqa: BLE001 — read-only nicety, never fail status
        pass
    if metrics_url:
        sharded = sharded_health(metrics_url, fetch=metrics_fetch)
        if sharded is not None:
            out["shardedReconcile"] = sharded
        battery = battery_health(metrics_url, fetch=metrics_fetch)
        if battery is not None:
            out["probeBattery"] = battery
        elastic = elastic_health(metrics_url, fetch=metrics_fetch)
        if elastic is not None:
            out["elasticCoordination"] = elastic
        artifact = artifact_health(metrics_url, fetch=metrics_fetch)
        if artifact is not None:
            out["artifactStack"] = artifact
        plane = write_plane_health(metrics_url, fetch=metrics_fetch)
        if plane is not None:
            out["writePlane"] = plane
        plan = plan_health(metrics_url, fetch=metrics_fetch)
        if plan is not None:
            out["plan"] = plan
        health = telemetry_health(metrics_url, fetch=metrics_fetch)
        if health is not None:
            out["fleetHealth"] = health
        federation = federation_health(metrics_url, fetch=metrics_fetch)
        if federation is not None:
            out["federation"] = federation
    if hasattr(client, "list_events"):
        warnings = [
            e
            for e in client.list_events(namespace=namespace)
            if e.get("type") == "Warning"
        ]
        # Wire order is not time order on a real apiserver: sort by the
        # event timestamps (ISO strings sort correctly) before slicing.
        warnings.sort(
            key=lambda e: e.get("lastTimestamp")
            or e.get("firstTimestamp")
            or ""
        )
        out["recentWarnings"] = [
            {
                "object": (e.get("involvedObject") or {}).get("name", ""),
                "reason": e.get("reason", ""),
                "message": e.get("message", ""),
            }
            for e in warnings[-max_events:]
        ]
    return out


def render(status: dict) -> str:
    """Human-readable table of the gathered snapshot."""
    if "error" in status:
        return f"status unavailable: {status['error']}"
    lines = [
        f"nodes: {status['totalManagedNodes']} in {status['totalManagedGroups']} "
        f"group(s) | in-progress {status['upgradesInProgress']} "
        f"pending {status['upgradesPending']} done {status['upgradesDone']} "
        f"failed {status['upgradesFailed']} "
        f"quarantined {status.get('slicesQuarantined', 0)}",
        "",
        f"{'GROUP':32s} {'STATE':24s} {'HOSTS':>5s} {'UNAVAIL':>7s} "
        f"{'TOPOLOGY':10s} {'ELASTIC':8s} DCN",
    ]
    for g in status["groups"]:
        elastic_flag = "excluded" if g.get("elasticExcluded") else ""
        lines.append(
            f"{g['group'][:32]:32s} {g['state']:24s} {g['hosts']:>5d} "
            f"{g['unavailable']:>7d} {g['topology']:10s} "
            f"{elastic_flag:8s} {g['dcn_group']}"
        )
    esc = status.get("evictionEscalationsInFlight") or {}
    if esc:
        lines.append("")
        lines.append(
            "eviction ladders in flight (nodes at rung): "
            + ", ".join(f"{r}={n}" for r, n in sorted(esc.items()))
        )
    rollbacks = {
        g["group"]: g["rollbackAttempts"]
        for g in status["groups"]
        if g.get("rollbackAttempts")
    }
    if rollbacks:
        lines.append(
            "rollback attempts: "
            + ", ".join(f"{gid}={n}" for gid, n in sorted(rollbacks.items()))
        )
    cycles = {
        g["group"]: g["quarantineCycles"]
        for g in status["groups"]
        if g.get("quarantineCycles")
    }
    if cycles:
        lines.append(
            "quarantine cycles: "
            + ", ".join(f"{gid}={n}" for gid, n in sorted(cycles.items()))
        )
    fleet = status.get("fleet")
    if fleet is not None:
        lines.append("")
        lines.append("fleet by generation:")
        for gen, row in sorted((fleet.get("generations") or {}).items()):
            extra = (
                f", {int(row.get('preempted', 0))} preempted"
                if row.get("preempted")
                else ""
            )
            lines.append(
                f"  {gen:10s} {int(row['nodes']):>4d} host(s) in "
                f"{int(row['groups'])} group(s){extra}"
            )
        holds = fleet.get("windowHolds") or {}
        if holds:
            lines.append(
                "maintenance-window holds: "
                + ", ".join(
                    f"{pool}={int(n)} group(s)"
                    for pool, n in sorted(holds.items())
                )
            )
    leader = status.get("leader")
    if leader is not None:
        lines.append("")
        lines.append(
            f"leader: {leader['holder'] or '(none — between terms)'} "
            f"(renewed {leader['renewTime']})"
        )
    policy = status.get("policy")
    if policy is not None:
        lines.append("")
        if "error" in policy:
            lines.append(f"policy: {policy['error']}")
        else:
            for c in policy.get("conditions", []):
                lines.append(
                    f"condition {c.get('type', ''):12s} "
                    f"{c.get('status', ''):6s} {c.get('reason', '')}: "
                    f"{c.get('message', '')}"
                )
            lifetime = policy.get("evictionEscalations") or {}
            if lifetime:
                lines.append(
                    "escalations (lifetime): "
                    + ", ".join(
                        f"{r}={int(n)}" for r, n in sorted(lifetime.items())
                    )
                )
            rb = policy.get("rollbackAttempts") or {}
            if rb:
                lines.append(
                    "rollback attempts (lifetime): "
                    + ", ".join(
                        f"{gid}={int(n)}" for gid, n in sorted(rb.items())
                    )
                )
    sharded = status.get("shardedReconcile")
    if sharded is not None:
        lines.append("")
        if "error" in sharded:
            lines.append(f"sharded reconcile: {sharded['error']}")
        else:
            lines.append(
                f"sharded reconcile: {int(sharded.get('busyShards', 0))}/"
                f"{int(sharded.get('shards', 0))} shards busy | queue "
                f"{int(sharded.get('queueDepth', 0))} queued "
                f"{int(sharded.get('queueInFlight', 0))} in-flight "
                f"(oldest {sharded.get('queueOldestWaitSeconds', 0.0):.1f}s)"
                f" | budget {int(sharded.get('budgetUsed', 0))}/"
                f"{int(sharded.get('budgetCap', 0))}"
            )
            lines.append(
                f"  lifetime: "
                f"{int(sharded.get('poolsReconciled', 0))} pool passes, "
                f"{int(sharded.get('fullResyncs', 0))} full resyncs, "
                f"{int(sharded.get('eventsRouted', 0))} events routed "
                f"({int(sharded.get('eventsCoalesced', 0))} coalesced), "
                f"errors {int(sharded.get('shardErrors', 0))}, "
                f"fenced {int(sharded.get('shardFenced', 0))}"
            )
            if "viewPools" in sharded:
                lines.append(
                    f"  materialized view: "
                    f"{int(sharded.get('viewPools', 0))} pools "
                    f"{int(sharded.get('viewRows', 0))} rows | "
                    f"hits {int(sharded.get('viewHits', 0))} "
                    f"fallbacks {int(sharded.get('viewFallbacks', 0))} | "
                    f"diff mismatches "
                    f"{int(sharded.get('viewDiffMismatches', 0))} | "
                    f"interned {int(sharded.get('viewInternedStrings', 0))}"
                    f", apply "
                    f"{sharded.get('viewApplyLatencyUs', 0.0):.1f}us"
                )
    battery = status.get("probeBattery")
    if battery is not None:
        lines.append("")
        if "error" in battery:
            lines.append(f"probe battery: {battery['error']}")
        else:
            lines.append(
                f"probe battery: compile "
                f"{battery.get('compileSeconds', 0.0):.3f}s execute "
                f"{battery.get('executeSeconds', 0.0):.3f}s | cache "
                f"{int(battery.get('cacheHits', 0))} hit(s) "
                f"{int(battery.get('cacheMisses', 0))} miss(es) "
                f"({int(battery.get('cachedPrograms', 0))} cached), "
                f"fallbacks {int(battery.get('fallbacks', 0))}"
            )
            walls = battery.get("validationWallSeconds") or {}
            if walls:
                lines.append(
                    "  validation wall: "
                    + ", ".join(
                        f"{gid}={s:.1f}s" for gid, s in sorted(walls.items())
                    )
                )
    elastic = status.get("elasticCoordination")
    if elastic is not None:
        lines.append("")
        if "error" in elastic:
            lines.append(f"elastic coordination: {elastic['error']}")
        else:
            neg = elastic.get("negotiations") or {}
            res = elastic.get("resizes") or {}
            lines.append(
                f"elastic coordination: "
                f"{int(elastic.get('excludedSlices', 0))} slice(s) excluded"
                f" | negotiations accept {int(neg.get('accept', 0))} "
                f"decline {int(neg.get('decline', 0))} "
                f"timeout {int(neg.get('timeout', 0))}"
                f" | resizes down {int(res.get('down', 0))} "
                f"up {int(res.get('up', 0))} "
                f"(last {elastic.get('lastResizeSeconds', 0.0):.1f}s)"
            )
    artifact = status.get("artifactStack")
    if artifact is not None:
        lines.append("")
        if "error" in artifact:
            lines.append(f"artifact stack: {artifact['error']}")
        else:
            lines.append(
                f"artifact stack: "
                f"{int(artifact.get('sharedWindowSavings', 0))} shared-"
                f"window saving(s), "
                f"{int(artifact.get('rollbacks', 0))} rollback(s)"
            )
            for name, row in sorted(
                (artifact.get("artifacts") or {}).items()
            ):
                bits = [
                    f"  {name}: {int(row.get('synced', 0))}/"
                    f"{int(row.get('nodes', 0))} node(s) synced"
                ]
                if row.get("skewHolds"):
                    bits.append(f"{int(row['skewHolds'])} skew hold(s)")
                if row.get("gateHolds"):
                    bits.append(f"{int(row['gateHolds'])} gate hold(s)")
                lines.append(" | ".join(bits))
    plane = status.get("writePlane")
    if plane is not None:
        lines.append("")
        if "error" in plane:
            lines.append(f"write plane: {plane['error']}")
        else:
            writes = plane.get("writes") or {}
            pending = plane.get("pending") or {}
            tokens = plane.get("flowTokens") or {}
            throttled = plane.get("flowThrottled") or {}
            flow_bits = []
            for flow in ("mutating", "status"):
                state = "THROTTLED" if throttled.get(flow) else "ok"
                flow_bits.append(
                    f"{flow} {int(writes.get(flow, 0))} write(s) "
                    f"({tokens.get(flow, 0.0):.0f} tokens, {state})"
                )
            lines.append("write plane: " + " | ".join(flow_bits))
            lines.append(
                f"  queued: "
                + ", ".join(
                    f"{kind}={int(n)}" for kind, n in sorted(pending.items())
                )
                + f" | last tick {int(plane.get('apiWritesPerTick', 0))} "
                f"api write(s)"
            )
            lines.append(
                f"  hygiene: {int(plane.get('suppressed', 0))} suppressed, "
                f"{int(plane.get('coalescedKeys', 0))} coalesced key(s), "
                f"{int(plane.get('eventsAggregated', 0))} event(s) "
                f"aggregated into {int(plane.get('eventsPublished', 0))} "
                f"published"
            )
            lines.append(
                f"  safety: {int(plane.get('fencedDrops', 0))} fenced "
                f"drop(s), {int(plane.get('conflictReplays', 0))} conflict "
                f"replay(s), {int(plane.get('deferred', 0))} deferred, "
                f"{int(plane.get('throttleWaits', 0))} throttle wait(s)"
            )
    plan = status.get("plan")
    # The durable CR-status copy backs the section when the live metrics
    # endpoint was not consulted (or had no active roll).
    if plan is None:
        cr_plan = (status.get("policy") or {}).get("plan")
        if cr_plan:
            plan = {
                "waves": cr_plan.get("planWaves", 0),
                "completedGroups": cr_plan.get("planCompletedGroups", 0),
                "driftSeconds": cr_plan.get("planDriftSeconds", 0),
                "replans": cr_plan.get("planReplans", 0),
                "projectedCompletion": cr_plan.get(
                    "projectedCompletion", ""
                ),
                "infeasible": cr_plan.get("planInfeasible") or [],
            }
            if "admissionMode" in cr_plan:
                plan["admissionMode"] = cr_plan["admissionMode"]
            if "budgetSaturation" in cr_plan:
                plan["budgetSaturation"] = cr_plan["budgetSaturation"]
    if plan is not None:
        lines.append("")
        if "error" in plan:
            lines.append(f"plan: {plan['error']}")
        else:
            eta = plan.get("projectedCompletion", "")
            if not eta and plan.get("projectedCompletionEpoch"):
                import time as _time

                eta = _time.strftime(
                    "%Y-%m-%dT%H:%M:%SZ",
                    _time.gmtime(plan["projectedCompletionEpoch"]),
                )
            drift = float(plan.get("driftSeconds", 0))
            lines.append(
                f"plan: {int(plan.get('completedGroups', 0))}/"
                f"{int(plan.get('plannedGroups', plan.get('waves', 0)))} "
                f"group(s) done over {int(plan.get('waves', 0))} wave(s)"
                f" | drift {drift:+.0f}s"
                f" | replans {int(plan.get('replans', 0))}"
                + (f" | ETA {eta}" if eta else "")
            )
            mode = plan.get("admissionMode")
            if mode:
                admission = f"  admission: {mode}"
                if "budgetSaturation" in plan:
                    admission += (
                        " | budget "
                        f"{float(plan['budgetSaturation']) * 100:.0f}%"
                        " saturated"
                    )
                if "budgetIdleTicks" in plan:
                    admission += (
                        f" | idle ticks {int(plan['budgetIdleTicks'])}"
                    )
                if "packedAdmissions" in plan:
                    admission += (
                        f" | packed {int(plan['packedAdmissions'])}"
                    )
                lines.append(admission)
            for reason in plan.get("infeasible") or []:
                lines.append(f"  INFEASIBLE: {reason}")
            invalid = plan.get("invalidWindows") or []
            if invalid:
                lines.append(
                    "  invalid maintenance-window cron (failing open): "
                    + ", ".join(invalid)
                )
            trace_id = plan.get("planTraceId") or (
                (status.get("policy") or {}).get("plan") or {}
            ).get("planTraceId")
            if trace_id:
                lines.append(f"  trace: {trace_id}")
    health = status.get("fleetHealth")
    cr_health = (status.get("policy") or {}).get("healthSummary")
    cr_stragglers = (status.get("policy") or {}).get("stragglers")
    # The durable CR-status copy backs the section when the live
    # metrics endpoint was not consulted.
    if health is None and (cr_health or cr_stragglers):
        health = {}
        if cr_health:
            health["scoredNodes"] = cr_health.get("scoredNodes", 0)
            health["meanScore"] = cr_health.get("meanScore", 0.0)
            health["cohorts"] = cr_health.get("cohorts") or []
        if cr_stragglers:
            health["confirmed"] = cr_stragglers
    if health is not None:
        lines.append("")
        if "error" in health:
            lines.append(f"fleet health: {health['error']}")
        else:
            head = (
                f"fleet health: {int(health.get('scoredNodes', 0))} "
                f"node(s) scored, mean {health.get('meanScore', 0.0):.1f}"
            )
            if health.get("worstNode"):
                head += (
                    f" (worst {health['worstNode']} at "
                    f"{health.get('worstScore', 0.0):.1f})"
                )
            if "samples" in health:
                head += (
                    f" | {int(health['samples'])} sample(s), "
                    f"{int(health.get('drops', 0))} drop(s)"
                )
            lines.append(head)
            # Per-generation cohort baselines (CR path only: the
            # metric surface carries medians per check, not cohorts).
            for cohort in health.get("cohorts") or []:
                stats = ", ".join(
                    f"{stat} {b.get('median', 0.0):g}±{b.get('mad', 0.0):g}"
                    for stat, b in sorted(
                        (cohort.get("baseline") or {}).items()
                    )
                )
                lines.append(
                    f"  {cohort.get('generation', '') or '?'}/"
                    f"{cohort.get('pool', '') or 'default'}: "
                    f"{int(cohort.get('nodes', 0))} node(s)"
                    + (f" | {stats}" if stats else "")
                )
            for s in health.get("stragglers") or []:
                lines.append(
                    f"  STRAGGLERS {s.get('generation', '') or '?'}/"
                    f"{s.get('pool', '') or 'default'}: "
                    f"{int(s.get('count', 0))}"
                )
            for v in health.get("confirmed") or []:
                lines.append(
                    f"  STRAGGLER {v.get('node', '')}: score "
                    f"{v.get('score', 0.0)}, z {v.get('z', 0.0)} on "
                    f"{v.get('worstStat', '')} over "
                    f"{int(v.get('streak', 0))} consecutive batteries"
                )
    federation = status.get("federation")
    if federation is not None:
        lines.append("")
        if "error" in federation:
            lines.append(f"federation: {federation['error']}")
        else:
            head = f"federation: phase {federation.get('phase', '?')}"
            if "budgetCap" in federation:
                head += (
                    f" | global budget "
                    f"{int(federation.get('budgetUsed', 0))}/"
                    f"{int(federation['budgetCap'])} unavailable, "
                    f"{int(federation.get('budgetParallel', 0))} "
                    "parallel"
                )
            if federation.get("budgetViolations"):
                head += (
                    f" | {int(federation['budgetViolations'])} "
                    "VIOLATION(S)"
                )
            if federation.get("partitions"):
                head += (
                    f" | {int(federation['partitions'])} partition(s), "
                    f"{int(federation.get('heals', 0))} heal(s)"
                )
            lines.append(head)
            for name, row in (federation.get("clusters") or {}).items():
                detail = row.get("health", "?")
                if row.get("done"):
                    detail += ", done"
                if row.get("frozenGroups"):
                    detail += (
                        f", {int(row['frozenGroups'])} frozen group(s)"
                    )
                lines.append(
                    f"  {name} ({row.get('region', '?')}): {detail}"
                )
            if federation.get("canaryHeld"):
                lines.append("  canary: HELD — promotion stopped")
            elif federation.get("soakRemainingSeconds"):
                lines.append(
                    f"  canary: soaking, "
                    f"{federation['soakRemainingSeconds']:.0f}s remaining"
                )
    breakdown = (status.get("policy") or {}).get("makespanBreakdown")
    if breakdown:
        from k8s_operator_libs_tpu.obs.critical import render_breakdown

        lines.append("")
        lines.append("last roll (critical-path attribution):")
        for row in render_breakdown(breakdown).splitlines():
            lines.append(f"  {row}")
    api_health = status.get("apiHealth")
    if api_health is not None and api_health.get("openCircuits"):
        lines.append("")
        lines.append("api health: DEGRADED (circuit open)")
        for ep, err in sorted(api_health["openCircuits"].items()):
            lines.append(f"  {ep}: {err}")
    warnings = status.get("recentWarnings") or []
    if warnings:
        lines.append("")
        lines.append("recent warnings:")
        for w in warnings:
            lines.append(
                f"  {w['object']}: {w['reason']}: {w['message']}"
            )
    return "\n".join(lines)


def main(argv: Optional[list[str]] = None) -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--namespace", default="kube-system")
    parser.add_argument("--selector", default="app=libtpu-driver")
    parser.add_argument("--driver-name", default="libtpu")
    parser.add_argument("--policy-cr", default="", metavar="NAMESPACE/NAME")
    # Same flags (and defaults) as the controller, so a deployment that
    # customizes its election Lease still gets a leader section here.
    parser.add_argument("--lease-name", default="tpu-upgrade-controller")
    parser.add_argument(
        "--lease-namespace",
        default="",
        help="defaults to --namespace (the controller's behavior)",
    )
    parser.add_argument(
        "--metrics-url",
        default="",
        help="controller /metrics endpoint (e.g. http://HOST:9090/metrics);"
        " adds the sharded-reconcile, write-plane and plan health sections",
    )
    parser.add_argument("--json", action="store_true", dest="as_json")
    args = parser.parse_args(argv)
    from k8s_operator_libs_tpu.controller import _parse_labels
    from k8s_operator_libs_tpu.k8s import get_default_client

    policy_ref = None
    if args.policy_cr:
        ns, sep, name = args.policy_cr.partition("/")
        if not sep or not ns or not name:
            parser.error("--policy-cr must look like NAMESPACE/NAME")
        policy_ref = (ns, name)
    status = gather(
        get_default_client(),
        args.namespace,
        _parse_labels(args.selector),
        keys=UpgradeKeys(driver_name=args.driver_name),
        policy_ref=policy_ref,
        lease_name=args.lease_name,
        lease_namespace=args.lease_namespace or None,
        metrics_url=args.metrics_url or None,
    )
    print(_json.dumps(status, indent=2) if args.as_json else render(status))


if __name__ == "__main__":
    main()
