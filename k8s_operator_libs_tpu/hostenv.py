"""Host-environment sanitization for outage-proof backend selection.

This rig (and GKE nodes mid-libtpu-upgrade generally) can have a
registered accelerator plugin whose backend init HANGS rather than
raising — observed with the remote-relay plugin during the 2026-07-30
outage: any device call, including ``jax.devices("cpu")`` under
``JAX_PLATFORMS=cpu``, wedged every process that had the plugin
registered.  Anything that must keep working through such an outage
(the test suite, ``__graft_entry__.dryrun_multichip``, ``bench.py``'s
cpu fallback) runs its device work in an environment with the plugin
unloadable.  The knowledge of HOW to build that environment lives here,
once — three hand-rolled copies drifted in round 4's first draft.

Two halves:

- :func:`sanitized_cpu_env` — env dict for a CHILD process: plugin site
  dir stripped from PYTHONPATH, its sitecustomize gate var dropped, cpu
  platform pinned, optional virtual-device count.
- :func:`pin_current_process_to_cpu` — best-effort in-process version
  for an interpreter whose sitecustomize already registered the plugin
  at startup (registration precedes any conftest/module code, so env
  mutation alone is too late): deregister the factory and re-pin the
  already-captured jax config.
"""

from __future__ import annotations

import os
from typing import Optional

# Names whose presence marks the remote-accelerator plugin: the PYTHONPATH
# site-dir basename substring, and the sitecustomize env var that gates
# its registration.
PLUGIN_PATH_MARKER = "axon"
PLUGIN_GATE_ENV_VAR = "PALLAS_AXON_POOL_IPS"
PLUGIN_BACKEND_NAME = "axon"


def _is_plugin_path(entry: str) -> bool:
    return PLUGIN_PATH_MARKER in os.path.basename(
        os.path.normpath(entry or "")
    )


def sanitized_cpu_env(
    base_env: Optional[dict] = None,
    *,
    host_device_count: Optional[int] = None,
    prepend_pythonpath: Optional[str] = None,
) -> dict:
    """A copy of ``base_env`` (default ``os.environ``) in which a child
    interpreter cannot load the remote-accelerator plugin and resolves
    the cpu platform.

    ``host_device_count``: set ``--xla_force_host_platform_device_count``
    (replacing any existing value) for an n-device virtual mesh.
    ``prepend_pythonpath``: path the child needs importable (e.g. the
    repo root for ``import __graft_entry__``)."""
    env = dict(os.environ if base_env is None else base_env)
    env.pop(PLUGIN_GATE_ENV_VAR, None)
    entries = [
        p
        for p in env.get("PYTHONPATH", "").split(os.pathsep)
        if p and not _is_plugin_path(p)
    ]
    if prepend_pythonpath:
        entries.insert(0, prepend_pythonpath)
    if entries:
        env["PYTHONPATH"] = os.pathsep.join(entries)
    else:
        env.pop("PYTHONPATH", None)
    env["JAX_PLATFORMS"] = "cpu"
    if host_device_count is not None:
        flags = [
            f
            for f in env.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f
        ]
        flags.append(
            f"--xla_force_host_platform_device_count={host_device_count}"
        )
        env["XLA_FLAGS"] = " ".join(flags)
    return env


def pin_current_process_to_cpu(
    default_host_device_count: Optional[int] = None,
) -> bool:
    """Best-effort: make THIS interpreter's jax resolve the cpu backend
    even though the plugin was registered at startup.

    Returns True when the deregistration hack matched jax internals
    (callers keep a subprocess-probe guard for the day it doesn't).
    Also sanitizes ``os.environ`` so child processes inherit a safe
    environment.  Call before the first device call.

    ``default_host_device_count``: ensure a virtual-device count is set
    WITHOUT replacing one already present (an operator running with a
    custom count keeps it)."""
    if default_host_device_count is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count"
                f"={default_host_device_count}"
            ).strip()
    clean = sanitized_cpu_env(dict(os.environ))
    # Only adopt the sanitization keys; leave everything else untouched.
    for key in ("PYTHONPATH", "XLA_FLAGS"):
        if key in clean:
            os.environ[key] = clean[key]
        else:
            os.environ.pop(key, None)
    os.environ.pop(PLUGIN_GATE_ENV_VAR, None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        import jax
        from jax._src import xla_bridge

        xla_bridge._backend_factories.pop(PLUGIN_BACKEND_NAME, None)
        jax.config.update("jax_platforms", "cpu")
        return True
    except Exception:  # noqa: BLE001 — internals moved; caller's guard
        return False
