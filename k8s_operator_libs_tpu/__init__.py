"""tpu-operator-libs: TPU-native Kubernetes operator library.

A brand-new framework with the capabilities of the reference
`k8s-operator-libs` (NVIDIA's GPU/NIC driver-upgrade library, see
/root/reference — SURVEY.md for the structural analysis), redesigned for
Google TPU node pools as a first-class device class:

- the cluster-wide, label-driven, idempotent upgrade state machine
  (reference: pkg/upgrade/upgrade_state.go:102-120) becomes **ICI-slice
  aware** — the schedulable upgrade unit is a whole multi-host TPU slice
  that must move atomically so the torus is never split;
- the validation layer (reference: pkg/upgrade/validation_manager.go)
  becomes a JAX/XLA health backend probing device enumeration, MXU
  matmuls, HBM bandwidth and ICI all-reduce reachability;
- the NVIDIA driver-container assumption is replaced by a libtpu
  device-plugin reconciler.
"""

__version__ = "0.1.0"
