"""Deterministic, side-effect-free analytic roll planner.

Given one built cluster snapshot (``ClusterUpgradeState``) and one
``TPUUpgradePolicySpec``, :func:`plan_roll` emits a :class:`RollPlan`:
ordered upgrade waves respecting every admission rule the live engine
enforces — hierarchical fleet ∧ pool budgets, DCN anti-affinity,
oldest-generation-first ordering, maintenance-window open intervals,
and elastic offer timeouts — with per-wave projected durations derived
from measured per-phase clocks and a projected completion time.

The planner issues ZERO API write verbs (the dry-run path asserts this
through the write plane) and shares its admission predicates with the
live engine's helpers (`_pool_for_group`, `_unavailability_unit`, slot
math constants, `group_sort_key`), so a plan and the engine disagree
only where reality diverges from the snapshot — which is exactly what
the drift watchdog (:mod:`drift`) measures, and what the digital twin
(:mod:`twin`) validates ahead of time.

Wave semantics: a wave is one admission BATCH — the set of groups the
engine would admit together under the caps.  With uniform per-group
phase durations the engine's rolling admission degenerates to exactly
these batches (validated by the twin and the seeded fuzz cross-check);
with heterogeneous durations the waves are a conservative projection.
"""

from __future__ import annotations

import time as _time
from dataclasses import dataclass, field
from typing import Optional

from k8s_operator_libs_tpu.artifacts.dag import artifact_dag_of
from k8s_operator_libs_tpu.consts import get_logger
from k8s_operator_libs_tpu.fleet.scheduler import (
    group_sort_key,
    packed_group_sort_key,
)
from k8s_operator_libs_tpu.fleet.windows import (
    NEXT_OPEN_HORIZON_S,
    next_open,
    window_open,
)
from k8s_operator_libs_tpu.upgrade.consts import (
    IN_PROGRESS_STATES,
    TRUE_STRING,
    UpgradeState,
)

logger = get_logger(__name__)

# Default per-phase clocks (seconds), production-shaped: the fused probe
# battery's warm time is the validation clock (BENCH records < 1 s warm,
# docs/fused-probe-battery.md); cordon/uncordon are single label writes;
# drain covers the eviction ladder's polite rung; pod restart is the
# kubelet pull+start path.  Tests and the bench stage override these to
# the twin's measured clocks.
DEFAULT_CORDON_S = 1.0
DEFAULT_WAIT_FOR_JOBS_S = 0.0
DEFAULT_POD_DELETION_S = 2.0
DEFAULT_DRAIN_S = 30.0
DEFAULT_POD_RESTART_S = 20.0
DEFAULT_VALIDATION_S = 1.0
DEFAULT_UNCORDON_S = 1.0
DEFAULT_NEGOTIATE_S = 2.0
DEFAULT_REJOIN_S = 2.0

# Hard cap on simulated waves: a plan needing more than one wave per
# pending group (plus window jumps) indicates a modeling bug, not a
# bigger fleet.
_MAX_EXTRA_WAVES = 64


@dataclass
class PhaseClocks:
    """Measured per-phase durations the projection is built from."""

    cordon_s: float = DEFAULT_CORDON_S
    wait_for_jobs_s: float = DEFAULT_WAIT_FOR_JOBS_S
    pod_deletion_s: float = DEFAULT_POD_DELETION_S
    drain_s: float = DEFAULT_DRAIN_S
    pod_restart_s: float = DEFAULT_POD_RESTART_S
    validation_s: float = DEFAULT_VALIDATION_S
    uncordon_s: float = DEFAULT_UNCORDON_S
    negotiate_s: float = DEFAULT_NEGOTIATE_S
    rejoin_s: float = DEFAULT_REJOIN_S

    def to_dict(self) -> dict:
        return {
            "cordonSeconds": self.cordon_s,
            "waitForJobsSeconds": self.wait_for_jobs_s,
            "podDeletionSeconds": self.pod_deletion_s,
            "drainSeconds": self.drain_s,
            "podRestartSeconds": self.pod_restart_s,
            "validationSeconds": self.validation_s,
            "uncordonSeconds": self.uncordon_s,
            "negotiateSeconds": self.negotiate_s,
            "rejoinSeconds": self.rejoin_s,
        }


@dataclass
class PlanAssumptions:
    """What-if knobs shared by the planner and the twin.

    ``elastic_answer`` models the workload's response to exclusion
    offers (Tenplex negotiation makes roll duration workload-dependent):
    ``"accept"`` adds the negotiate+rejoin resize clocks, ``"decline"``
    adds one negotiate round before the classic drain path, and
    ``"timeout"`` charges the policy's full ``offerTimeoutSeconds``.
    """

    elastic_answer: str = "accept"  # accept | decline | timeout
    clocks: PhaseClocks = field(default_factory=PhaseClocks)
    # Measured per-pool clocks (pool name -> PhaseClocks, "" for the
    # pool-less bucket) overriding ``clocks`` for that pool's groups —
    # the drift watchdog feeds the EWMA tracker's estimates in here so
    # re-plans tighten as the roll progresses.
    pool_clocks: dict = field(default_factory=dict)
    # Wave-ordering override: "" inherits planning.admissionMode from
    # the policy; "greedy"/"packed" force one packer for what-ifs.
    admission_mode: str = ""
    # Group ids assumed preempted for the projection (what-if knob; the
    # live preemption annotation is honored regardless).
    preempted_groups: frozenset = frozenset()
    horizon_s: float = NEXT_OPEN_HORIZON_S


@dataclass
class PlannedGroup:
    """One group's place in the plan."""

    group_id: str
    pool: Optional[str]
    wave: int
    cost: int
    nodes: list[str]
    accelerator: str
    duration_s: float
    start_offset_s: float
    in_flight: bool = False

    def to_dict(self) -> dict:
        return {
            "group": self.group_id,
            "pool": self.pool,
            "wave": self.wave,
            "cost": self.cost,
            "nodes": list(self.nodes),
            "accelerator": self.accelerator,
            "durationSeconds": round(self.duration_s, 3),
            "startOffsetSeconds": round(self.start_offset_s, 3),
            "inFlight": self.in_flight,
        }


@dataclass
class PlanWave:
    """One admission batch."""

    index: int
    start_offset_s: float
    duration_s: float
    group_ids: list[str]
    pools: list[str]

    def to_dict(self) -> dict:
        return {
            "index": self.index,
            "startOffsetSeconds": round(self.start_offset_s, 3),
            "durationSeconds": round(self.duration_s, 3),
            "groups": list(self.group_ids),
            "pools": list(self.pools),
        }


@dataclass
class RollPlan:
    """The analytic projection of one roll from one snapshot."""

    created_epoch: float
    waves: list[PlanWave] = field(default_factory=list)
    groups: list[PlannedGroup] = field(default_factory=list)
    # node name -> wave index (the fuzz cross-check's unit of agreement)
    node_wave: dict[str, int] = field(default_factory=dict)
    # group id -> reason it is excluded from the projection
    held: dict[str, str] = field(default_factory=dict)
    # Plan-infeasibility reasons (window starvation, budget deadlock);
    # non-empty means the roll as planned never finishes.
    infeasible: list[str] = field(default_factory=list)
    total_nodes: int = 0
    pending_groups: int = 0
    projected_duration_s: float = 0.0
    projected_completion_epoch: float = 0.0
    unit: str = "slice"
    # Wave-ordering the projection was packed under (greedy | packed).
    admission_mode: str = "greedy"
    # Lazy group->wave index: packed admission asks wave_of once per
    # pending group per pass, which must stay O(1) amortized.
    _wave_index: Optional[dict] = field(
        default=None, repr=False, compare=False
    )

    @property
    def wave_count(self) -> int:
        return len(self.waves)

    def wave_of(self, group_id: str) -> Optional[int]:
        if self._wave_index is None or len(self._wave_index) != len(
            self.groups
        ):
            self._wave_index = {g.group_id: g.wave for g in self.groups}
        return self._wave_index.get(group_id)

    def to_dict(self) -> dict:
        return {
            "createdEpoch": int(self.created_epoch),
            "unit": self.unit,
            "admissionMode": self.admission_mode,
            "totalNodes": self.total_nodes,
            "pendingGroups": self.pending_groups,
            "waveCount": len(self.waves),
            "waves": [w.to_dict() for w in self.waves],
            "groups": [g.to_dict() for g in self.groups],
            "held": dict(self.held),
            "infeasible": list(self.infeasible),
            "projectedDurationSeconds": round(self.projected_duration_s, 3),
            "projectedCompletion": int(self.projected_completion_epoch),
        }

    def render(self) -> str:
        """Human-readable plan (the --dry-run output)."""
        lines = [
            f"RollPlan: {self.pending_groups} pending group(s) over "
            f"{len(self.waves)} wave(s), unit={self.unit}, "
            f"{self.total_nodes} managed nodes",
        ]
        for wave in self.waves:
            lines.append(
                f"  wave {wave.index}: t+{wave.start_offset_s:.0f}s "
                f"for {wave.duration_s:.0f}s — "
                f"{len(wave.group_ids)} group(s): "
                + ", ".join(wave.group_ids)
            )
        for gid, reason in sorted(self.held.items()):
            lines.append(f"  held {gid}: {reason}")
        if self.infeasible:
            for reason in self.infeasible:
                lines.append(f"  INFEASIBLE: {reason}")
        else:
            lines.append(
                f"  projected duration {self.projected_duration_s:.0f}s, "
                "completion "
                + _time.strftime(
                    "%Y-%m-%dT%H:%M:%SZ",
                    _time.gmtime(self.projected_completion_epoch),
                )
            )
        return "\n".join(lines)


def _group_duration_s(
    group,
    policy,
    assumptions: PlanAssumptions,
    elastic_candidate: bool,
    pool_name: Optional[str] = None,
) -> float:
    """Projected wall-clock for one group's pass through the disruptive
    states, from the assumption clocks + the policy's enabled phases.
    Pools with measured EWMA clocks use those; the rest fall back to
    the assumption-level (static or twin-measured) clocks."""
    clocks = assumptions.pool_clocks.get(pool_name or "") or assumptions.clocks
    total = clocks.cordon_s + clocks.uncordon_s + clocks.pod_restart_s
    # Multi-artifact stacks step through their serialized levels inside
    # the ONE shared window: each extra level costs another pod-restart
    # clock, while cordon/drain/validation/uncordon stay amortized —
    # skew-pinned edges therefore serialize WITHIN a wave, they never
    # add waves.
    try:
        dag = artifact_dag_of(policy)
    except Exception:
        dag = None
    if dag is not None:
        total += (dag.serialized_steps() - 1) * clocks.pod_restart_s
    total += clocks.validation_s
    if policy.wait_for_completion is not None:
        total += clocks.wait_for_jobs_s
    drain_enabled = (
        policy.drain_spec is not None and policy.drain_spec.enable
    )
    if drain_enabled:
        total += clocks.drain_s
    else:
        total += clocks.pod_deletion_s
    if elastic_candidate:
        answer = assumptions.elastic_answer
        if answer == "accept":
            total += clocks.negotiate_s + clocks.rejoin_s
        elif answer == "decline":
            total += clocks.negotiate_s
        else:  # timeout: the offer ages out at the policy clock
            elastic = getattr(policy, "elastic", None)
            total += float(
                getattr(elastic, "offer_timeout_second", 0) or 0
            )
    return total


def _pool_caps(manager, state, policy, unit: str) -> dict:
    """name -> (max_unavailable_units, max_parallel) per policy pool,
    derived exactly like BudgetLedger.sync_from_state: the percentage
    scales against the pool's own unit population."""
    pools = manager._policy_pools(policy)
    if not pools:
        return {}
    pool_units: dict[str, int] = {}
    for group in state.all_groups():
        name = manager._pool_for_group(group, policy)
        if name is None:
            continue
        cost = 1 if unit == "slice" else group.size()
        pool_units[name] = pool_units.get(name, 0) + cost
    caps = {}
    for pool in pools:
        units_in_pool = pool_units.get(pool.name, 0)
        if pool.max_unavailable is not None:
            cap = pool.max_unavailable.scaled_value(
                units_in_pool, round_up=True
            )
        else:
            cap = units_in_pool
        caps[pool.name] = (cap, pool.max_parallel_upgrades or 0)
    return caps


def _group_requires_upgrade(manager, group, ds_hash_cache: dict) -> bool:
    """Would process_done_or_unknown_groups flag this group?  Same
    predicate as the engine's, with the per-DaemonSet revision-hash
    lookup cached so a 4096-node plan does not re-list
    ControllerRevisions per node."""
    for member in group.members:
        if manager._is_upgrade_requested(member.node):
            return True
        if member.is_orphaned_pod():
            continue
        ds = member.driver_daemon_set
        key = (ds.namespace, ds.name)
        ds_hash = ds_hash_cache.get(key)
        if ds_hash is None:
            try:
                ds_hash = (
                    manager.pod_manager
                    .get_daemonset_controller_revision_hash(ds)
                )
            except ValueError:
                continue
            ds_hash_cache[key] = ds_hash
        try:
            pod_hash = manager.pod_manager.get_pod_controller_revision_hash(
                member.driver_pod
            )
        except (ValueError, AttributeError):
            continue
        if pod_hash != ds_hash:
            return True
    return False


def _elastic_candidate(manager, policy, group) -> bool:
    elastic = getattr(policy, "elastic", None)
    if elastic is None or not elastic.enable:
        return False
    key = manager.keys.elastic_workload_annotation
    excluded_key = manager.keys.elastic_excluded_annotation
    return any(
        key in m.node.annotations
        and m.node.annotations.get(excluded_key) != TRUE_STRING
        for m in group.members
    )


def find_infeasibilities(
    manager,
    state,
    policy,
    now: Optional[float] = None,
    horizon_s: float = NEXT_OPEN_HORIZON_S,
) -> list[str]:
    """Cheap structural feasibility scan (no wave simulation): reasons
    this roll can provably never finish.  Used by the fleet-level stuck
    signal (upgrade/stuck.py) and the drift watchdog every pass, so it
    must stay O(groups)."""
    now = _time.time() if now is None else now
    reasons: list[str] = []
    unit = manager._unavailability_unit(policy)
    total_units = manager._total_units(state, unit)
    fleet_cap = total_units
    if policy.max_unavailable is not None:
        fleet_cap = policy.max_unavailable.scaled_value(
            total_units, round_up=True
        )
    caps = _pool_caps(manager, state, policy, unit)
    pools = {p.name: p for p in manager._policy_pools(policy)}

    # Pending cost per pool (UPGRADE_REQUIRED groups only: the cheap
    # scan runs on live snapshots where outdatedness is already
    # reflected in the state labels).
    pending: dict[Optional[str], list] = {}
    for group in state.groups_in(UpgradeState.UPGRADE_REQUIRED):
        pending.setdefault(
            manager._pool_for_group(group, policy), []
        ).append(group)

    for pool_name, groups in sorted(
        pending.items(), key=lambda kv: kv[0] or ""
    ):
        min_cost = min(
            1 if unit == "slice" else g.size() for g in groups
        )
        if min_cost > fleet_cap:
            reasons.append(
                f"budget-deadlock: fleet maxUnavailable admits "
                f"{fleet_cap} {unit}(s) but the smallest pending group "
                f"costs {min_cost}"
            )
        if pool_name is None:
            continue
        pool_cap = caps.get(pool_name, (None, 0))[0]
        if pool_cap is not None and min_cost > pool_cap:
            reasons.append(
                f"budget-deadlock: pool {pool_name} maxUnavailable "
                f"admits {pool_cap} {unit}(s) but its smallest pending "
                f"group costs {min_cost}"
            )
        pool = pools.get(pool_name)
        window = pool.maintenance_window if pool is not None else None
        if window is not None and window.cron:
            try:
                opens = next_open(window.cron, now, horizon_s)
            except ValueError:
                opens = now  # unparseable cron fails open at runtime
            if opens is None:
                reasons.append(
                    f"window-starvation: pool {pool_name} maintenance "
                    f"window {window.cron!r} never opens"
                )
    # Window-held groups were DROPPED from the live snapshot by
    # process_maintenance_windows (the hold is budget-free and
    # condition-only), so starvation for them must be read from the
    # manager's hold record: a pool whose window never opens again is
    # infeasible even with zero visible pending groups.
    held_info = getattr(manager, "window_held_info", None) or {}
    for pool_name, entries in sorted(held_info.items()):
        if pool_name is None or any(
            r.startswith(f"window-starvation: pool {pool_name} ")
            for r in reasons
        ):
            continue
        pool = pools.get(pool_name)
        window = pool.maintenance_window if pool is not None else None
        if window is None or not window.cron:
            continue
        try:
            opens = next_open(window.cron, now, horizon_s)
        except ValueError:
            continue  # unparseable cron fails open at runtime
        if opens is None:
            reasons.append(
                f"window-starvation: pool {pool_name} maintenance "
                f"window {window.cron!r} never opens "
                f"({len(entries)} group(s) held)"
            )
    # Elastic-decline storm: every negotiation so far was refused or
    # timed out, and slices keep re-entering negotiation — the roll is
    # burning offer timeouts without making exclusion progress.
    negotiations = getattr(manager, "elastic_negotiations", None)
    if negotiations and pending:
        refused = negotiations.get("decline", 0) + negotiations.get(
            "timeout", 0
        )
        if refused >= 5 and negotiations.get("accept", 0) == 0:
            reasons.append(
                f"elastic-decline-storm: {refused} exclusion offers "
                "declined or timed out with zero accepts; every slice "
                "is taking the full drain path"
            )
    return reasons


def plan_roll(
    manager,
    state,
    policy,
    now: Optional[float] = None,
    assumptions: Optional[PlanAssumptions] = None,
) -> RollPlan:
    """Emit the analytic :class:`RollPlan` for this snapshot + policy.

    Pure projection: reads the snapshot through the manager's helper
    predicates, never mutates it, and never stages a write."""
    now = _time.time() if now is None else now
    assumptions = assumptions or PlanAssumptions()
    plan = RollPlan(created_epoch=now)

    unit = manager._unavailability_unit(policy)
    plan.unit = unit
    total_units = manager._total_units(state, unit)
    plan.total_nodes = manager.get_total_managed_nodes(state)
    fleet_cap = total_units
    if policy.max_unavailable is not None:
        fleet_cap = policy.max_unavailable.scaled_value(
            total_units, round_up=True
        )
    fleet_parallel = policy.max_parallel_upgrades or 0
    caps = _pool_caps(manager, state, policy, unit)
    pools = {p.name: p for p in manager._policy_pools(policy)}
    window_key = manager.keys.window_wait_annotation
    skip_key = manager.keys.skip_label

    def _cost(group) -> int:
        return 1 if unit == "slice" else group.size()

    def _pool_window_cron(pool_name: Optional[str]) -> Optional[str]:
        pool = pools.get(pool_name) if pool_name else None
        window = pool.maintenance_window if pool is not None else None
        return window.cron if window is not None and window.cron else None

    def _window_open_at(pool_name: Optional[str], epoch: float) -> bool:
        cron = _pool_window_cron(pool_name)
        if cron is None:
            return True
        try:
            return window_open(cron, epoch)
        except ValueError:
            return True  # runtime fail-open, mirrored from the engine

    # -- classify every group -------------------------------------------
    ds_hash_cache: dict = {}
    pending: list = []  # (group, pool, cost, elastic, duration)
    in_flight: list = []
    for group in state.all_groups():
        eff = group.effective_state(manager.keys.state_label)
        pool_name = manager._pool_for_group(group, policy)
        if any(
            m.node.labels.get(skip_key) == TRUE_STRING
            for m in group.members
        ):
            plan.held[group.id] = "skip label set"
            continue
        if (
            group.id in assumptions.preempted_groups
            or manager._group_preempted(group)
        ):
            plan.held[group.id] = "preempted (holding budget-free)"
            continue
        if eff in (UpgradeState.FAILED, UpgradeState.QUARANTINED):
            plan.held[group.id] = f"in terminal/parked state {eff.value}"
            continue
        if (
            group.slice_info is not None
            and group.size() < group.slice_info.expected_hosts
        ):
            plan.held[group.id] = (
                f"incomplete slice ({group.size()}/"
                f"{group.slice_info.expected_hosts} hosts present)"
            )
            continue
        elastic = _elastic_candidate(manager, policy, group)
        duration = _group_duration_s(
            group, policy, assumptions, elastic, pool_name
        )
        if eff in IN_PROGRESS_STATES:
            in_flight.append(
                (group, pool_name, _cost(group), elastic, duration)
            )
        elif eff == UpgradeState.UPGRADE_REQUIRED:
            pending.append(
                (group, pool_name, _cost(group), elastic, duration)
            )
        elif eff in (UpgradeState.DONE, UpgradeState.UNKNOWN):
            if _group_requires_upgrade(manager, group, ds_hash_cache):
                pending.append(
                    (group, pool_name, _cost(group), elastic, duration)
                )

    admission_mode = assumptions.admission_mode or getattr(
        getattr(policy, "planning", None), "admission_mode", ""
    )
    plan.admission_mode = admission_mode or "greedy"
    if admission_mode == "packed":
        # First-fit-decreasing within each generation class: the wave
        # loop below is already first-fit (denied groups stay pending
        # while later ones fill the residual budget), so the decreasing
        # cost order is all packing adds — no gate is relaxed.
        pending.sort(
            key=lambda item: packed_group_sort_key(item[0], item[2])
        )
    else:
        pending.sort(key=lambda item: group_sort_key(item[0]))
    plan.pending_groups = len(pending) + len(in_flight)

    # -- simulate admission waves ---------------------------------------
    t = 0.0
    wave_index = 0
    max_waves = len(pending) + len(in_flight) + _MAX_EXTRA_WAVES
    while (pending or in_flight) and wave_index < max_waves:
        admitted: list = []
        used_budget = 0
        used_parallel = 0
        pool_used: dict[str, tuple[int, int]] = {}
        busy_dcn: set = set()

        # In-flight groups occupy the first wave unconditionally: their
        # unavailability is a fact, not an admission request (mirrors
        # the ledger's force re-charge semantics).
        for item in in_flight:
            group, pool_name, cost, _elastic, _duration = item
            admitted.append(item + (True,))
            if any(
                window_key in m.node.annotations for m in group.members
            ):
                continue  # window-held holds no budget
            used_budget += cost
            used_parallel += 1
            if pool_name is not None:
                pu, pp = pool_used.get(pool_name, (0, 0))
                pool_used[pool_name] = (pu + cost, pp + 1)
            dcn = (
                group.slice_info.dcn_group
                if group.slice_info is not None
                else None
            )
            if dcn:
                busy_dcn.add(dcn)
        in_flight = []

        still_pending: list = []
        for item in pending:
            group, pool_name, cost, elastic, _duration = item
            if not _window_open_at(pool_name, now + t):
                still_pending.append(item)
                continue
            if fleet_parallel and used_parallel + 1 > fleet_parallel:
                still_pending.append(item)
                continue
            if used_budget + cost > fleet_cap:
                still_pending.append(item)
                continue
            dcn = (
                group.slice_info.dcn_group
                if group.slice_info is not None
                else None
            )
            dcn_gate = getattr(policy, "dcn_anti_affinity", False)
            if dcn_gate and dcn and dcn in busy_dcn:
                still_pending.append(item)
                continue
            if pool_name is not None and pool_name in caps:
                pool_cap, pool_parallel = caps[pool_name]
                pu, pp = pool_used.get(pool_name, (0, 0))
                if pu + cost > pool_cap:
                    still_pending.append(item)
                    continue
                if pool_parallel and pp + 1 > pool_parallel:
                    still_pending.append(item)
                    continue
                pool_used[pool_name] = (pu + cost, pp + 1)
            admitted.append(item + (False,))
            used_budget += cost
            used_parallel += 1
            if dcn:
                busy_dcn.add(dcn)
        pending = still_pending

        if not admitted:
            # Nothing admitted this round.  Groups whose window IS open
            # are budget-deadlocked (the wave started with zero usage,
            # so if the caps deny them now they deny them forever);
            # groups behind a closed window wait for its next opening —
            # jump the virtual clock there, or report starvation when it
            # never comes.
            still: list = []
            for item in pending:
                group, pool_name, cost, _el, _dur = item
                if _window_open_at(pool_name, now + t):
                    where = (
                        f"pool {pool_name}" if pool_name else "fleet"
                    )
                    plan.infeasible.append(
                        f"budget-deadlock: {where} budget can never "
                        f"admit group {group.id} (cost {cost} {unit}(s))"
                    )
                    plan.held[group.id] = "budget-deadlocked"
                else:
                    still.append(item)
            pending = still
            if not pending:
                break
            jump_to: Optional[float] = None
            for item in pending:
                cron = _pool_window_cron(item[1])
                if cron is None:
                    continue
                try:
                    opens = next_open(
                        cron, now + t, assumptions.horizon_s
                    )
                except ValueError:
                    opens = now + t  # fail-open
                if opens is not None and (
                    jump_to is None or opens < jump_to
                ):
                    jump_to = opens
            if jump_to is not None and jump_to > now + t:
                t = jump_to - now
                continue
            if jump_to is None:
                for item in pending:
                    group, pool_name = item[0], item[1]
                    cron = _pool_window_cron(pool_name)
                    plan.infeasible.append(
                        f"window-starvation: pool {pool_name} "
                        f"maintenance window {cron!r} never opens for "
                        f"group {group.id}"
                    )
                    plan.held[group.id] = "window-starved"
            break

        start = t
        duration = max(item[4] for item in admitted)
        wave_groups = []
        wave_pools = []
        for group, pool_name, cost, _el, dur, was_in_flight in admitted:
            accelerator = (
                group.slice_info.accelerator
                if group.slice_info is not None
                else ""
            )
            plan.groups.append(
                PlannedGroup(
                    group_id=group.id,
                    pool=pool_name,
                    wave=wave_index,
                    cost=cost,
                    nodes=[n.name for n in group.nodes],
                    accelerator=accelerator,
                    duration_s=dur,
                    start_offset_s=start,
                    in_flight=was_in_flight,
                )
            )
            for node in group.nodes:
                plan.node_wave[node.name] = wave_index
            wave_groups.append(group.id)
            if pool_name and pool_name not in wave_pools:
                wave_pools.append(pool_name)
        plan.waves.append(
            PlanWave(
                index=wave_index,
                start_offset_s=start,
                duration_s=duration,
                group_ids=wave_groups,
                pools=wave_pools,
            )
        )
        t += duration
        wave_index += 1

    plan.projected_duration_s = t
    plan.projected_completion_epoch = now + t
    # Merge the cheap structural reasons so a plan that IS simulable but
    # rides a decline storm still reports it.
    for reason in find_infeasibilities(
        manager, state, policy, now=now, horizon_s=assumptions.horizon_s
    ):
        if reason not in plan.infeasible:
            plan.infeasible.append(reason)
    return plan
