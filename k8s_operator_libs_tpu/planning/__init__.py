"""Predictive rollout planning.

Three cooperating read-only parts:

- :mod:`planner` — deterministic analytic planner: one snapshot + one
  policy in, an ordered-wave :class:`~planner.RollPlan` with projected
  durations and a completion time out.  Zero API write verbs.
- :mod:`twin` — digital twin: clones the snapshot into a fresh
  ``FakeCluster`` and runs the REAL engine against it on an accelerated
  fake clock, validating the analytic plan against actual engine
  behavior (with what-if knobs: inject preemptions, decline elastic
  offers, close a window).
- :mod:`drift` — live drift watchdog: anchors an active roll to its
  admitted plan, republishes the ETA every tick, and triggers a bounded
  re-plan when reality diverges beyond a threshold.
- :mod:`clocks` — per-pool EWMA phase clocks measured from observed
  transitions, feeding the watchdog's re-plans (and serialized through
  CR status so estimates survive controller failover).

The one write-adjacent consumer is plan-GUIDED admission
(``planning.admissionMode: packed``): the engine's admission pass reads
the watchdog's fresh plan to order chargeable groups
(first-fit-decreasing within each generation class) — planning itself
still never writes.

See docs/rollout-planning.md.
"""

from k8s_operator_libs_tpu.planning.planner import (  # noqa: F401
    PhaseClocks,
    PlanAssumptions,
    PlannedGroup,
    PlanWave,
    RollPlan,
    find_infeasibilities,
    plan_roll,
)
from k8s_operator_libs_tpu.planning.twin import (  # noqa: F401
    TwinResult,
    run_twin,
)
from k8s_operator_libs_tpu.planning.drift import (  # noqa: F401
    DriftReport,
    DriftWatchdog,
)
from k8s_operator_libs_tpu.planning.clocks import (  # noqa: F401
    PhaseClockTracker,
)
