"""Per-pool EWMA phase clocks measured from observed transitions.

The planner ships static default :class:`~.planner.PhaseClocks`
(planner.py ``DEFAULT_*``) — production-shaped, but blind to the fleet
actually being rolled.  :class:`PhaseClockTracker` closes that gap: the
node-state provider reports every group-level transition through
``transition_observer`` (one callback per
``change_nodes_upgrade_state`` batch, fired BEFORE the new labels are
staged, so the old state is still readable), the tracker charges the
elapsed wall time to the phase the group is leaving, and folds it into
an exponentially weighted moving average keyed by ``(pool, phase)``.

The drift watchdog feeds ``pool_clocks()`` into every anchor/re-plan
via ``PlanAssumptions.pool_clocks``, so projections tighten as the roll
progresses; pools with no samples yet fall back to the assumption-level
clocks.  Aggregates ride the policy CR status (``phaseClocks``) through
the write plane and are re-seeded on controller adoption, so a restart
or failover does not reset the estimate to the static defaults.
"""

from __future__ import annotations

import threading
import time
from dataclasses import replace
from typing import Iterable, Optional

from k8s_operator_libs_tpu.planning.planner import PhaseClocks
from k8s_operator_libs_tpu.upgrade.consts import UpgradeState

# The phase a group is *in* while its nodes carry this state label —
# the duration charged when the group transitions onward.
PHASE_OF_STATE = {
    UpgradeState.CORDON_REQUIRED.value: "cordon_s",
    UpgradeState.WAIT_FOR_JOBS_REQUIRED.value: "wait_for_jobs_s",
    UpgradeState.POD_DELETION_REQUIRED.value: "pod_deletion_s",
    UpgradeState.DRAIN_REQUIRED.value: "drain_s",
    UpgradeState.POD_RESTART_REQUIRED.value: "pod_restart_s",
    UpgradeState.VALIDATION_REQUIRED.value: "validation_s",
    UpgradeState.UNCORDON_REQUIRED.value: "uncordon_s",
    UpgradeState.NEGOTIATE_REQUIRED.value: "negotiate_s",
    UpgradeState.REJOIN_RESIZE_REQUIRED.value: "rejoin_s",
}

_PHASE_TO_CAMEL = {
    "cordon_s": "cordonSeconds",
    "wait_for_jobs_s": "waitForJobsSeconds",
    "pod_deletion_s": "podDeletionSeconds",
    "drain_s": "drainSeconds",
    "pod_restart_s": "podRestartSeconds",
    "validation_s": "validationSeconds",
    "uncordon_s": "uncordonSeconds",
    "negotiate_s": "negotiateSeconds",
    "rejoin_s": "rejoinSeconds",
}
_CAMEL_TO_PHASE = {v: k for k, v in _PHASE_TO_CAMEL.items()}

# Serialized name for the pool-less bucket ("" internally): CR status
# keys read better than an empty string.
_DEFAULT_POOL_KEY = "default"

DEFAULT_EWMA_ALPHA = 0.3


class PhaseClockTracker:
    """EWMA of measured per-(pool, phase) durations.

    Thread-safe: transitions are reported from both the reconcile
    thread and fenced worker threads.
    """

    def __init__(self, alpha: float = DEFAULT_EWMA_ALPHA) -> None:
        self.alpha = alpha
        self._lock = threading.Lock()
        self._ewma: dict[tuple[str, str], float] = {}
        self._samples: dict[tuple[str, str], int] = {}
        # group key -> (state value occupied, entry timestamp)
        self._entered: dict[str, tuple[str, float]] = {}
        # node name -> pool name ("" = pool-less); refreshed each full
        # pass by the controller from the policy's pool selectors.
        self._node_pool: dict[str, str] = {}
        # Confirmed health stragglers (fed by the telemetry plane each
        # pass): the status block annotates which pools' measured
        # clocks — and therefore the planner's ETA — are inflated by a
        # slow node rather than by the phase itself.
        self._straggler_nodes: set[str] = set()

    # -- wiring --------------------------------------------------------

    def seed_pools(self, node_pool: dict[str, str]) -> None:
        """Refresh the node→pool attribution map (full pass scope)."""
        with self._lock:
            self._node_pool.update(node_pool)

    def set_straggler_nodes(self, names: Iterable[str]) -> None:
        """Replace the confirmed-straggler set (telemetry plane feed,
        once per pass; a cleared verdict drops the annotation)."""
        with self._lock:
            self._straggler_nodes = {str(n) for n in names}

    # -- observation ---------------------------------------------------

    def observe_group_transition(
        self, nodes: Iterable, new_state, now: Optional[float] = None
    ) -> None:
        """One group-level transition (called before labels change).

        ``nodes`` is the group's member list; the group key is the
        lexicographically-first node name (stable for a slice).  The
        phase being LEFT is charged ``now - entry``; the phase being
        ENTERED starts its clock.
        """
        names = sorted(
            n.name for n in nodes if getattr(n, "name", None) is not None
        )
        if not names:
            return
        key = names[0]
        ts = time.monotonic() if now is None else now
        new_value = getattr(new_state, "value", new_state)
        with self._lock:
            # Idempotent re-issue of the current state (crash replay,
            # re-driven pass): keep the original entry clock running.
            cur = self._entered.get(key)
            if cur is not None and cur[0] == new_value:
                return
            # First sight of a group has no entry timestamp, so there is
            # no duration to charge — only the new phase's clock opens.
            prev = self._entered.pop(key, None)
            if prev is not None:
                prev_value, entered_at = prev
                phase = PHASE_OF_STATE.get(prev_value)
                if phase is not None and ts >= entered_at:
                    self._record_locked(key, phase, ts - entered_at)
            if new_value in PHASE_OF_STATE:
                self._entered[key] = (new_value, ts)

    def _record_locked(self, node: str, phase: str, duration: float) -> None:
        pool = self._node_pool.get(node, "")
        k = (pool, phase)
        cur = self._ewma.get(k)
        if cur is None:
            self._ewma[k] = duration
        else:
            self._ewma[k] = self.alpha * duration + (1 - self.alpha) * cur
        self._samples[k] = self._samples.get(k, 0) + 1

    # -- consumption ---------------------------------------------------

    def clocks_for(
        self, pool: str, base: Optional[PhaseClocks] = None
    ) -> PhaseClocks:
        """Measured clocks for ``pool`` over ``base`` defaults."""
        base = base if base is not None else PhaseClocks()
        with self._lock:
            overrides = {
                phase: val
                for (p, phase), val in self._ewma.items()
                if p == pool
            }
        return replace(base, **overrides) if overrides else base

    def pool_clocks(
        self, base: Optional[PhaseClocks] = None
    ) -> dict[str, PhaseClocks]:
        """All pools with at least one measured phase."""
        with self._lock:
            pools = {p for (p, _phase) in self._ewma}
        return {p: self.clocks_for(p, base) for p in sorted(pools)}

    def sample_count(self) -> int:
        with self._lock:
            return sum(self._samples.values())

    # -- durability (CR status via the write plane) --------------------

    def to_status(self) -> dict:
        """``{pool: {camelPhase: seconds}}`` for the CR status block.

        Pools containing a confirmed health straggler additionally carry
        ``stragglersInflatingEta`` (the slow nodes by name), so an
        operator reading a pool's inflated measured clocks can tell
        "this pool's ETA is inflated by node X" apart from "this phase
        is slow fleet-wide".  ``load_status`` ignores the key on
        adoption — verdicts re-derive from the telemetry rings, never
        from the status echo."""
        with self._lock:
            out: dict[str, dict] = {}
            for (pool, phase), val in sorted(self._ewma.items()):
                name = pool or _DEFAULT_POOL_KEY
                out.setdefault(name, {})[_PHASE_TO_CAMEL[phase]] = round(
                    val, 3
                )
            for node in sorted(self._straggler_nodes):
                name = self._node_pool.get(node, "") or _DEFAULT_POOL_KEY
                out.setdefault(name, {}).setdefault(
                    "stragglersInflatingEta", []
                ).append(node)
            return out

    def load_status(self, data: Optional[dict]) -> None:
        """Re-seed the EWMA from a CR status block (adoption path).

        Loaded values never overwrite a live sample — adoption happens
        before any transition is observed, and a later stale re-load
        must not clobber fresher measurements.
        """
        if not isinstance(data, dict):
            return
        with self._lock:
            for pool_name, phases in data.items():
                if not isinstance(phases, dict):
                    continue
                pool = "" if pool_name == _DEFAULT_POOL_KEY else str(pool_name)
                for camel, val in phases.items():
                    phase = _CAMEL_TO_PHASE.get(camel)
                    if phase is None:
                        continue
                    try:
                        seconds = float(val)
                    except (TypeError, ValueError):
                        continue
                    k = (pool, phase)
                    if k not in self._ewma:
                        self._ewma[k] = seconds
                        self._samples[k] = self._samples.get(k, 0) + 1
