"""Digital-twin validation of an analytic plan.

Clones a cluster's driver-managed objects into a fresh ``FakeCluster``
and runs the REAL upgrade engine (`upgrade_state.py`, optionally through
`upgrade/sharded.py`) against the clone on an accelerated fake clock —
so the projection in a :class:`~planner.RollPlan` is validated against
actual engine behavior (admission order, budget arbitration, window
gating, elastic timeouts), not against a second model of it.

What-if knobs ride through :class:`~planner.PlanAssumptions` plus twin
options: inject preemptions (stamp the platform preemption annotation),
decline-all elastic offers (no responder answers, so every offer ages
out at ``offerTimeoutSeconds`` under the accelerated clock — the
decline-equivalent fallback to the classic drain path), or close a
window (pass a policy whose pool cron is out-of-window).

The twin observes WAVES the same way the fuzz cross-check defines them:
a wave is the set of groups first admitted (state-label set leaves the
settled lattice) in the same reconcile tick.
"""

from __future__ import annotations

import copy
import time
from dataclasses import dataclass, field
from typing import Optional

from k8s_operator_libs_tpu.artifacts.dag import artifact_dag_of
from k8s_operator_libs_tpu.consts import get_logger
from k8s_operator_libs_tpu.k8s import (
    ContainerStatus,
    FakeCluster,
    ObjectMeta,
    Pod,
    PodPhase,
)
from k8s_operator_libs_tpu.k8s.objects import PodSpec, PodStatus
from k8s_operator_libs_tpu.upgrade.consts import (
    NODE_PREEMPTION_ANNOTATION,
    UpgradeState,
)

logger = get_logger(__name__)

# Label values that do NOT mean "this group is being worked on".
_SETTLED = {
    "",
    UpgradeState.UPGRADE_REQUIRED.value,
    UpgradeState.DONE.value,
}


class AcceleratedClock:
    """Additive offset over the process clocks, installed module-wide.

    The engine's durable clocks read ``time.time()`` and its dwell
    tracking reads ``time.monotonic()``; patching both lets the twin
    skip hours of offer timeouts / window closures in milliseconds.
    ``time.sleep`` is left real so worker polling still yields.  Always
    uninstall in a ``finally`` — the patch is process-global.
    """

    def __init__(self) -> None:
        self.offset = 0.0
        self._real_time = time.time
        self._real_monotonic = time.monotonic
        self._installed = False

    def now(self) -> float:
        return self._real_time() + self.offset

    def install(self) -> None:
        if self._installed:
            return
        time.time = self.now
        time.monotonic = lambda: self._real_monotonic() + self.offset
        self._installed = True

    def uninstall(self) -> None:
        if not self._installed:
            return
        time.time = self._real_time
        time.monotonic = self._real_monotonic
        self._installed = False

    def advance(self, seconds: float) -> None:
        self.offset += seconds


@dataclass
class TwinResult:
    """What the real engine actually did to the cloned fleet."""

    waves: list[list[str]] = field(default_factory=list)
    node_wave: dict[str, int] = field(default_factory=dict)
    converged: bool = False
    ticks: int = 0
    virtual_duration_s: float = 0.0
    unfinished: list[str] = field(default_factory=list)
    held: list[str] = field(default_factory=list)
    elastic_negotiations: dict[str, int] = field(default_factory=dict)
    write_verbs: int = 0
    # Engine admission telemetry: the ordering mode actually used over
    # the roll (packed requires a fresh anchored plan) and the
    # cumulative manager.admission_stats counters.
    admission_mode: str = "greedy"
    admission: dict = field(default_factory=dict)

    @property
    def wave_count(self) -> int:
        return len(self.waves)

    def wave_of(self, group_id: str) -> Optional[int]:
        for i, wave in enumerate(self.waves):
            if group_id in wave:
                return i
        return None


def clone_cluster(
    source_client,
    namespace: str,
    driver_labels: dict[str, str],
    artifact_selectors: Optional[dict[str, dict[str, str]]] = None,
) -> FakeCluster:
    """Deep-copy every driver-managed object the engine reads — nodes,
    driver DaemonSets + their ControllerRevisions, driver pods, and
    (multi-artifact stacks) every artifact selector's DaemonSets and
    pods — into a fresh FakeCluster.  Read-only against the source."""
    twin = FakeCluster()
    for node in source_client.list_nodes():
        twin.create_node(copy.deepcopy(node))
    seen_ds: set = set()
    seen_pods: set = set()
    selector_sets = [driver_labels] + list(
        (artifact_selectors or {}).values()
    )
    for sel in selector_sets:
        for ds in source_client.list_daemon_sets(namespace, sel):
            key = (ds.namespace, ds.name)
            if key in seen_ds:
                continue
            seen_ds.add(key)
            twin.create_daemon_set(copy.deepcopy(ds))
    for rev in source_client.list_controller_revisions(namespace):
        twin.create_controller_revision(copy.deepcopy(rev))
    for sel in selector_sets:
        for pod in source_client.list_pods(
            namespace=namespace, match_labels=sel
        ):
            key = (pod.namespace, pod.name)
            if key in seen_pods:
                continue
            seen_pods.add(key)
            twin.create_pod(copy.deepcopy(pod))
    return twin


def _install_kubelet(twin: FakeCluster, manager) -> None:
    """Emulate the DaemonSet controller + kubelet on the twin: a deleted
    driver pod is recreated Ready from the owning DaemonSet's NEWEST
    revision (same contract as the test fixtures' recreate hook)."""

    def hook(pod: Pod) -> None:
        owners = pod.metadata.owner_references
        if not owners:
            return
        try:
            ds = twin.get_daemon_set(pod.namespace, owners[0].name)
        except Exception:
            return
        if owners[0].uid != ds.metadata.uid:
            return
        try:
            ds_hash = (
                manager.pod_manager
                .get_daemonset_controller_revision_hash(ds)
            )
        except ValueError:
            return
        labels = dict(ds.spec.selector.match_labels)
        labels["controller-revision-hash"] = ds_hash
        twin.create_pod(
            Pod(
                metadata=ObjectMeta(
                    name=pod.name,
                    namespace=pod.namespace,
                    labels=labels,
                    owner_references=list(owners),
                ),
                spec=PodSpec(node_name=pod.spec.node_name),
                status=PodStatus(
                    phase=PodPhase.RUNNING,
                    container_statuses=[ContainerStatus(ready=True)],
                ),
            )
        )

    twin.on_pod_deleted(hook)


def _group_states(
    twin: FakeCluster, keys, membership: dict[str, list[str]]
) -> dict[str, set]:
    """group id -> set of member state-label values, quorum-read."""
    out: dict[str, set] = {}
    for gid, nodes in membership.items():
        out[gid] = {
            twin.get_node(n, cached=False).labels.get(
                keys.state_label, ""
            )
            for n in nodes
        }
    return out


def run_twin(
    source_client,
    namespace: str,
    driver_labels: dict[str, str],
    policy,
    keys=None,
    preempt_groups: Optional[set] = None,
    sharded: bool = False,
    shards: int = 4,
    max_ticks: int = 400,
    stall_advance_s: float = 60.0,
    max_virtual_s: float = 14 * 86400.0,
) -> TwinResult:
    """Clone the fleet and roll it with the real engine until every
    rollable group is DONE (or the tick/virtual-time budget runs out).

    ``preempt_groups``: group ids whose nodes get the platform
    preemption annotation stamped on the clone — the engine must hold
    them budget-free and the roll must complete around them.
    ``sharded=True`` drives the roll through ``ShardedReconciler``'s
    full-resync path instead of direct apply_state, so ledger
    arbitration is exercised exactly as in a --sharded controller.
    """
    from k8s_operator_libs_tpu.upgrade import (
        ClusterUpgradeStateManager,
        UpgradeKeys,
    )

    keys = keys or UpgradeKeys()
    # Multi-artifact policies: the twin must hold every artifact's
    # DaemonSet + pods, or the engine would see them vacuously synced
    # and skip the serialized steps the plan is meant to validate.
    try:
        dag = artifact_dag_of(policy)
    except Exception:
        dag = None
    artifact_selectors = None
    if dag is not None:
        primary = dag.primary()
        artifact_selectors = {
            name: dict(dag.artifact(name).match_labels)
            for name in dag.topo_order()
            if name != primary
        }
    twin = clone_cluster(
        source_client,
        namespace,
        driver_labels,
        artifact_selectors=artifact_selectors,
    )
    policy = copy.deepcopy(policy)

    clock = AcceleratedClock()
    result = TwinResult()
    clock.install()
    try:
        mgr = ClusterUpgradeStateManager(
            twin, keys=keys, poll_interval_s=0.005, poll_timeout_s=2.0
        )
        _install_kubelet(twin, mgr)
        # Plan-guided admission needs the same wiring the controller
        # has: a drift watchdog anchored before each apply so packed
        # mode orders admission off a fresh plan.  Greedy twins skip it
        # (the engine would ignore the plan and per-tick observe costs
        # a plan_roll + find_infeasibilities).
        watchdog = None
        planning_spec = getattr(policy, "planning", None)
        if (
            planning_spec is not None
            and getattr(planning_spec, "admission_mode", "greedy")
            == "packed"
        ):
            from k8s_operator_libs_tpu.planning.drift import DriftWatchdog

            watchdog = DriftWatchdog(keys)
            watchdog.configure(planning_spec)
            mgr.drift_watchdog = watchdog

        sharded_reconciler = None
        if sharded:
            from k8s_operator_libs_tpu.upgrade.sharded import (
                ShardedReconciler,
            )

            sharded_reconciler = ShardedReconciler(
                mgr, namespace, driver_labels, shards=shards
            )

        # Membership + what-if preemptions from the initial snapshot.
        state = mgr.build_state(namespace, driver_labels, policy)
        membership = {
            g.id: [n.name for n in g.nodes] for g in state.all_groups()
        }
        for gid in preempt_groups or ():
            for node_name in membership.get(gid, []):
                twin.patch_node_annotations(
                    node_name, {NODE_PREEMPTION_ANNOTATION: "true"}
                )
                result.held.append(gid)

        admitted_at: dict[str, int] = {}
        last_states = _group_states(twin, keys, membership)
        writes_before = _write_verbs(twin)
        t0 = clock.now()
        tick = 0
        while tick < max_ticks and clock.now() - t0 <= max_virtual_s:
            tick += 1
            state = mgr.build_state(namespace, driver_labels, policy)
            if watchdog is not None:
                # Mirror reconcile_once: anchor/refresh the plan from
                # this snapshot BEFORE acting on it.
                watchdog.observe(mgr, state, policy, now=clock.now())
            if sharded_reconciler is not None:
                started = sharded_reconciler.observe_full_state(
                    state, policy, started=clock.now()
                )
                mgr.apply_state(state, policy)
                sharded_reconciler.complete_full_resync(started)
                sharded_reconciler.wait_idle(30.0)
            else:
                mgr.apply_state(state, policy)
            mgr.wait_for_async_work(30.0)

            states = _group_states(twin, keys, membership)
            for gid, values in states.items():
                if gid in admitted_at:
                    continue
                was, now_settled = last_states.get(gid, set()), values
                left_settled = bool(now_settled - _SETTLED)
                completed_in_one = (
                    was
                    and was != {UpgradeState.DONE.value}
                    and now_settled == {UpgradeState.DONE.value}
                )
                if left_settled or completed_in_one:
                    admitted_at[gid] = tick
            progressed = states != last_states
            last_states = states

            pending = [
                gid
                for gid, values in states.items()
                if gid not in (preempt_groups or set())
                and values != {UpgradeState.DONE.value}
            ]
            if not pending:
                break
            if not progressed:
                clock.advance(stall_advance_s)
        result.ticks = tick
        result.virtual_duration_s = clock.now() - t0
        result.write_verbs = _write_verbs(twin) - writes_before
        result.elastic_negotiations = dict(mgr.elastic_negotiations)
        # The final tick sees an inactive roll (plan dropped), so the
        # live mode flag has already fallen back — report packed if any
        # admission during the roll actually used the packed ordering.
        result.admission = dict(mgr.admission_stats)
        result.admission_mode = (
            "packed" if result.admission.get("packed_admitted") else "greedy"
        )

        # Assemble waves from admission ticks.
        by_tick: dict[int, list[str]] = {}
        for gid, at in admitted_at.items():
            by_tick.setdefault(at, []).append(gid)
        for at in sorted(by_tick):
            wave = sorted(by_tick[at])
            index = len(result.waves)
            result.waves.append(wave)
            for gid in wave:
                for node_name in membership.get(gid, []):
                    result.node_wave[node_name] = index
        final = _group_states(twin, keys, membership)
        result.unfinished = sorted(
            gid
            for gid, values in final.items()
            if gid not in (preempt_groups or set())
            and values != {UpgradeState.DONE.value}
        )
        result.converged = not result.unfinished
        if sharded_reconciler is not None:
            sharded_reconciler.shutdown()
        return result
    finally:
        clock.uninstall()


def _write_verbs(cluster: FakeCluster) -> int:
    prefixes = ("patch", "create", "delete", "evict", "update", "post", "put")
    return sum(
        count
        for verb, count in cluster.stats.items()
        if verb.lower().startswith(prefixes)
    )
