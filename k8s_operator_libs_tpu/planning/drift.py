"""Live drift watchdog: is the active roll on its admitted plan?

On the first pass that sees an active roll the watchdog anchors a
:class:`~planner.RollPlan` from that snapshot.  Every subsequent pass it
compares actual completions against the plan's projected finish times:

    drift_seconds = elapsed − planned finish of the NEXT group due

positive drift means the roll is behind its projection (the next
planned completion is overdue), negative means ahead.  The ETA is
republished continuously (``projectedCompletion`` + ``planDriftSeconds``
in CR status, metrics, and the status CLI), and when drift exceeds the
policy threshold the watchdog re-plans from the live snapshot — bounded
by ``maxReplans`` so a pathological fleet cannot turn planning into the
hot path.

Infeasibility (window starvation, budget deadlock, elastic-decline
storms — see :func:`planner.find_infeasibilities`) is surfaced every
pass: a roll that will provably never finish is reported as
plan-infeasible, not silence.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field, replace
from typing import Optional

from k8s_operator_libs_tpu.consts import get_logger
from k8s_operator_libs_tpu.planning.planner import (
    PlanAssumptions,
    RollPlan,
    find_infeasibilities,
    plan_roll,
)
from k8s_operator_libs_tpu.upgrade.consts import (
    IN_PROGRESS_STATES,
    UpgradeState,
)

logger = get_logger(__name__)

DEFAULT_DRIFT_THRESHOLD_S = 300.0
DEFAULT_REPLAN_INTERVAL_S = 60.0
DEFAULT_MAX_REPLANS = 5
# A plan older than this no longer guides admission (packed mode falls
# back to greedy until the next anchor/re-plan refreshes it).  Generous
# vs the re-plan cadence: any healthy watchdog re-anchors well inside
# it; only a stalled watchdog leaves a plan to age out.
DEFAULT_PLAN_STALENESS_S = 600.0


@dataclass
class DriftReport:
    """One pass's verdict, consumed by metrics + CR status."""

    active: bool = False
    drift_seconds: float = 0.0
    projected_completion_epoch: float = 0.0
    wave_count: int = 0
    completed_groups: int = 0
    planned_groups: int = 0
    infeasible: list[str] = field(default_factory=list)
    replans: int = 0
    replanned: bool = False
    plan: Optional[RollPlan] = None


class DriftWatchdog:
    """Anchors the active roll to its plan and measures divergence."""

    def __init__(
        self,
        keys,
        threshold_s: float = DEFAULT_DRIFT_THRESHOLD_S,
        replan_interval_s: float = DEFAULT_REPLAN_INTERVAL_S,
        max_replans: int = DEFAULT_MAX_REPLANS,
        assumptions: Optional[PlanAssumptions] = None,
    ) -> None:
        self.keys = keys
        self.threshold_s = threshold_s
        self.replan_interval_s = replan_interval_s
        self.max_replans = max_replans
        self.assumptions = assumptions
        self.plan: Optional[RollPlan] = None
        self.replans = 0
        self._last_replan_epoch = 0.0
        self._last_observe_epoch = 0.0
        self.last_report: Optional[DriftReport] = None
        # Freshness bound for fresh_plan() (plan-guided admission).
        self.plan_staleness_s = DEFAULT_PLAN_STALENESS_S
        # Optional PhaseClockTracker (planning/clocks.py): when set,
        # every anchor/re-plan folds its per-pool EWMA estimates into
        # the assumptions so projections tighten as the roll runs.
        self.clock_tracker = None
        # Scoped-pass activity fed by ShardedReconciler.progress_observer
        # (dirty ticks between full resyncs): evidence the engine is
        # working the plan even when no full pass has run yet.
        self.scoped_ticks = 0
        self.scoped_pools_walked = 0

    def note_tick(self, tick_report) -> None:
        """ShardedReconciler.progress_observer target: record scoped
        dirty-tick activity between full resyncs."""
        self.scoped_ticks += 1
        self.scoped_pools_walked += getattr(
            tick_report, "pools_walked", 0
        )

    def configure(self, planning_spec) -> None:
        """Adopt the CR's planning knobs (None leaves defaults)."""
        if planning_spec is None:
            return
        self.threshold_s = float(planning_spec.drift_threshold_second)
        self.replan_interval_s = float(
            planning_spec.replan_interval_second
        )
        self.max_replans = int(planning_spec.max_replans)
        # A fresh plan must outlive at least one threshold+re-plan
        # cycle, but never shrink below the default admission window.
        self.plan_staleness_s = max(
            DEFAULT_PLAN_STALENESS_S,
            self.threshold_s + self.replan_interval_s,
        )

    def reset(self) -> None:
        """Drop the anchor (roll finished, or policy changed)."""
        self.plan = None
        self.replans = 0
        self._last_replan_epoch = 0.0
        self._last_observe_epoch = 0.0

    def fresh_plan(self, now: Optional[float] = None) -> Optional[RollPlan]:
        """The anchored plan IF the watchdog is still maintaining it.

        Freshness is measured from the last active ``observe`` pass,
        not plan creation — a healthy long roll keeps its anchor fresh
        every full pass, while a wedged controller lets it age out.
        Returns None when stale: packed admission and targeted wakeups
        must fall back to greedy/blanket behavior rather than chase a
        projection nobody is validating."""
        if self.plan is None:
            return None
        now = time.time() if now is None else now
        if now - self._last_observe_epoch > self.plan_staleness_s:
            return None
        return self.plan

    def _plan_assumptions(self) -> Optional[PlanAssumptions]:
        """Assumptions for an anchor/re-plan, with the clock tracker's
        measured per-pool EWMA folded in when any samples exist."""
        base = self.assumptions
        tracker = self.clock_tracker
        if tracker is None:
            return base
        try:
            pool_clocks = tracker.pool_clocks(
                base.clocks if base is not None else None
            )
        except Exception:  # never let telemetry break planning
            logger.exception("drift watchdog: clock tracker failed")
            return base
        if not pool_clocks:
            return base
        if base is None:
            return PlanAssumptions(pool_clocks=pool_clocks)
        merged = dict(pool_clocks)
        merged.update(base.pool_clocks)  # explicit what-ifs win
        return replace(base, pool_clocks=merged)

    def _roll_active(self, state, manager=None) -> bool:
        if state.groups_in(UpgradeState.UPGRADE_REQUIRED):
            return True
        if any(state.groups_in(s) for s in IN_PROGRESS_STATES):
            return True
        # Window-held groups are dropped from the post-pass snapshot but
        # the roll is still live — and possibly window-starved, which is
        # exactly when the watchdog must keep watching.
        return bool(getattr(manager, "window_held_groups", 0))

    def observe(
        self, manager, state, policy, now: Optional[float] = None
    ) -> DriftReport:
        """Run after a FULL reconcile pass (scoped passes see one pool
        and cannot measure fleet progress)."""
        now = time.time() if now is None else now
        report = DriftReport()
        if not self._roll_active(state, manager):
            if self.plan is not None:
                logger.info(
                    "drift watchdog: roll complete; dropping plan anchor"
                )
            self.reset()
            self.last_report = report
            return report
        report.active = True
        self._last_observe_epoch = now

        if self.plan is None:
            self.plan = plan_roll(
                manager, state, policy, now=now,
                assumptions=self._plan_assumptions(),
            )
            self._last_replan_epoch = now
            logger.info(
                "drift watchdog: anchored plan (%d waves, %ds projected)",
                self.plan.wave_count,
                int(self.plan.projected_duration_s),
            )
        plan = self.plan

        # Completion ledger: which planned groups reached DONE.
        done_ids = {
            g.id for g in state.groups_in(UpgradeState.DONE)
        }
        planned = sorted(
            plan.groups,
            key=lambda g: (g.start_offset_s + g.duration_s, g.group_id),
        )
        completed = sum(1 for g in planned if g.group_id in done_ids)
        report.completed_groups = completed
        report.planned_groups = len(planned)
        report.wave_count = plan.wave_count

        elapsed = now - plan.created_epoch
        if completed >= len(planned):
            drift = elapsed - plan.projected_duration_s
        else:
            next_due = planned[completed]
            drift = elapsed - (
                next_due.start_offset_s + next_due.duration_s
            )
        report.drift_seconds = drift
        report.projected_completion_epoch = (
            plan.projected_completion_epoch + max(0.0, drift)
        )

        # Infeasibility: structural reasons from the live snapshot plus
        # anything the anchored plan already knew.
        reasons = find_infeasibilities(manager, state, policy, now=now)
        for reason in plan.infeasible:
            if reason not in reasons:
                reasons.append(reason)
        report.infeasible = reasons

        if (
            drift > self.threshold_s
            and self.replans < self.max_replans
            and now - self._last_replan_epoch >= self.replan_interval_s
        ):
            self.plan = plan_roll(
                manager, state, policy, now=now,
                assumptions=self._plan_assumptions(),
            )
            self.replans += 1
            self._last_replan_epoch = now
            report.replanned = True
            report.projected_completion_epoch = (
                self.plan.projected_completion_epoch
            )
            logger.warning(
                "drift watchdog: drift %.0fs over threshold %.0fs; "
                "re-planned (%d/%d): %d waves, new ETA +%ds",
                drift,
                self.threshold_s,
                self.replans,
                self.max_replans,
                self.plan.wave_count,
                int(self.plan.projected_duration_s),
            )
        report.replans = self.replans
        report.plan = self.plan
        self.last_report = report
        return report
