"""The upgrade controller: reconcile loop + CLI.

The reference is a library whose consumers (GPU/Network Operator) own the
reconcile loop (SURVEY.md §1 "consumer operators — outside this repo").
For TPU node pools the consumer is in-repo: this module wires the driver
DaemonSet reconciler, the slice-aware state manager, the health gate and
metrics into one loop, runnable as::

    python -m k8s_operator_libs_tpu.controller \
        --namespace kube-system --selector app=libtpu-driver \
        --policy-file policy.yaml --interval 30 --metrics-port 8081

The policy YAML is the same camelCase shape a CRD would embed
(api.v1alpha1 round-trips it), e.g.::

    autoUpgrade: true
    maxParallelUpgrades: 1
    maxUnavailable: 25%
    drain: {enable: true, timeoutSeconds: 300}
    sliceAtomic: true
    healthGate: {enable: true, timeoutSeconds: 600}
"""

from __future__ import annotations

import argparse
import signal
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Optional

from k8s_operator_libs_tpu.api.v1alpha1 import (
    DriverUpgradePolicySpec,
    TPUUpgradePolicySpec,
)
from k8s_operator_libs_tpu.consts import get_logger
from k8s_operator_libs_tpu.driver.daemonset import (
    AgentDaemonSetSpec,
    DriverDaemonSetSpec,
    DriverSetReconciler,
)
from k8s_operator_libs_tpu.health import NodeReportProber
from k8s_operator_libs_tpu.k8s.interface import KubeClient
from k8s_operator_libs_tpu.k8s.retry import CircuitOpenError
from k8s_operator_libs_tpu.upgrade.consts import UpgradeState
from k8s_operator_libs_tpu.metrics import (
    MetricsRegistry,
    MetricsServer,
    SliceUpgradeTimer,
    UpgradeMetrics,
)
from k8s_operator_libs_tpu.upgrade.upgrade_state import (
    BuildStateError,
    ClusterUpgradeStateManager,
)
from k8s_operator_libs_tpu.upgrade.util import (
    EVENT_TYPE_WARNING,
    EventRecorder,
    UpgradeKeys,
    log_event,
)

logger = get_logger(__name__)


@dataclass
class ControllerConfig:
    namespace: str = "kube-system"
    driver_labels: dict[str, str] = field(
        default_factory=lambda: {"app": "libtpu-driver"}
    )
    driver_name: str = "libtpu"
    interval_s: float = 30.0
    policy: Optional[DriverUpgradePolicySpec] = None
    # When set, the controller also owns the driver DaemonSet.
    daemonset_spec: Optional[DriverDaemonSetSpec] = None
    # When set, the controller also owns the health-agent DaemonSet and
    # keeps its DRIVER_REVISION env pinned to the driver's current
    # ControllerRevision (agents restart and re-report on every driver
    # template change).
    agent_spec: Optional[AgentDaemonSetSpec] = None
    metrics_port: Optional[int] = None
    # Health-gate HBM floor as a fraction of the slice accelerator's
    # published spec bandwidth (hw.chip_spec).  0 disables the floor —
    # only for environments whose probe hosts are not the accelerator the
    # slice labels claim (CPU test rigs).
    hbm_floor_fraction: float = 0.5
    # Resolve HBM/ICI health-gate floors from the fleet GenerationProfile
    # registry (fleet.profiles) when no explicit floor is configured, so a
    # mixed v4/v5e/v6e fleet gates each pool at its own generation's spec.
    # Off by default: the fraction-based floor above stays the reference
    # wiring, and CPU test rigs carry accelerator labels whose published
    # ICI spec their fake reports can't meet.
    generation_floors: bool = False
    # (namespace, name) of a TPUUpgradePolicy CR to read the policy from
    # each pass instead of a static ``policy`` — the consumer-operator
    # pattern (reference SURVEY §1: "policy flows in from the consumer's
    # CRD").  The controller also writes upgrade counters back to the
    # CR's status subresource.
    policy_ref: Optional[tuple[str, str]] = None
    # Event-driven reconcile (controller-runtime informer semantics): a
    # watch on nodes/pods/daemonsets (+ the policy CR when policy_ref is
    # set) triggers a pass immediately instead of waiting out interval_s,
    # which becomes the periodic-resync fallback.  Mid-roll this makes
    # progress latency event-bound, not interval-bound.
    watch: bool = False
    # Coalesce bursts of watch events into one pass.
    watch_debounce_s: float = 0.1
    # Sharded dirty-set reconcile (requires watch): informer deltas feed
    # a per-pool dirty queue; event-driven passes rebuild and reconcile
    # ONLY the touched pools on parallel worker shards, with budget
    # arbitration through a shared maxUnavailable ledger.  interval_s
    # becomes the full-resync safety net.  Tick cost is O(changed)
    # instead of O(fleet) — see docs/automatic-libtpu-upgrade.md.
    sharded: bool = False
    # Worker shards (parallel per-pool reconciles; each pool is still
    # serialized onto at most one shard at a time).
    reconcile_shards: int = 4
    # Scope the informer's Pod list+watch to the driver namespace+labels
    # (field-selector analogue) so non-driver pod volume cannot bloat
    # the store; out-of-scope pod queries (the drain path's per-node
    # all-namespace listing) pass through to the live API.
    informer_pod_scope: bool = True
    # Publish recorded transition/failure events to the cluster as
    # core/v1 Events (reference parity: every transition is an Event,
    # visible in `kubectl describe node`).
    publish_events: bool = True
    # HA: run leader election over a coordination.k8s.io/v1 Lease and
    # reconcile only while holding it — required whenever 2+ controller
    # replicas run (the consumer-operator pattern: controller-runtime
    # managers do the same before starting their reconcilers).
    leader_elect: bool = False
    lease_name: str = "tpu-upgrade-controller"
    # Defaults to ``namespace`` when None.
    lease_namespace: Optional[str] = None
    # Candidate identity; auto hostname_uuid when empty.
    identity: str = ""
    # Flight-recorder spool directory for black-box snapshots (obs/
    # flightrec.py).  None = <tmpdir>/tpu-upgrade-blackbox; "" disables
    # the on-disk spool (ring + triggers still run in memory).
    trace_spool_dir: Optional[str] = None
    # Address the metrics/healthz server binds.  Loopback by default:
    # exposing the scrape endpoint beyond the pod is a deployment
    # decision ("0.0.0.0"), not a side effect of enabling metrics.
    metrics_bind_addr: str = "127.0.0.1"


class UpgradeController:
    """Owns one driver's upgrade lifecycle end to end."""

    def __init__(self, client: KubeClient, config: ControllerConfig) -> None:
        self.client = client
        self.config = config
        self.keys = UpgradeKeys(driver_name=config.driver_name)
        self.events = EventRecorder()
        # Informer-backed cached reconcile (watch mode only): the watch
        # pump doubles as the informer's event feed, and the manager
        # reads through a CachedKubeClient so steady-state passes serve
        # nodes/pods/daemonsets/revisions from the cache instead of
        # re-listing.  Polling mode keeps the raw client: with no event
        # stream the cache would always be stale and every read would
        # fall through anyway.  self.client stays raw — leases, the
        # watch pump's own lists and the quorum fences must not be
        # cache-served.
        self.informer = None
        manager_client = client
        if config.watch:
            from k8s_operator_libs_tpu.k8s.informer import (
                CachedKubeClient,
                Informer,
            )

            self.informer = Informer(
                client,
                pod_namespace=(
                    config.namespace if config.informer_pod_scope else ""
                ),
                pod_match_labels=(
                    config.driver_labels
                    if config.informer_pod_scope
                    else None
                ),
            )
            manager_client = CachedKubeClient(client, informer=self.informer)
        self.manager = ClusterUpgradeStateManager(
            manager_client, keys=self.keys, event_recorder=self.events
        )
        # Sharded dirty-set reconcile rides on the watch pump's event
        # stream; without a watch there are no deltas to route.
        self._sharded = None
        if config.sharded and config.watch:
            from k8s_operator_libs_tpu.upgrade.sharded import (
                ShardedReconciler,
            )

            self._sharded = ShardedReconciler(
                self.manager,
                config.namespace,
                config.driver_labels,
                shards=config.reconcile_shards,
                # Same liveness fence as the manager's async workers —
                # reads self.elector at call time (set below).
                fence=lambda: (
                    self.elector is None or self.elector.is_leader()
                ),
                # Budget-release wakeups originate on shard threads, so
                # they must set the loop's wake event themselves (watch
                # events get theirs from the pump).
                wake=lambda: (
                    self._wake.set() if self._wake is not None else None
                ),
            )
        # TPU health gate: per-host probe-agent reports aggregated per
        # slice, pinned to the current driver revision.  The HBM floor is
        # derived per slice from the accelerator's published spec
        # (hw.chip_spec), so the silent-degradation mode the bandwidth
        # probe measures actually gates in the default wiring.
        self.manager.with_validation_enabled(
            NodeReportProber(
                self.keys,
                revision_resolver=(
                    self.manager.pod_manager
                    .get_daemonset_controller_revision_hash
                ),
                hbm_floor_fraction=config.hbm_floor_fraction,
                generation_floors=config.generation_floors,
            )
        )
        self.ds_reconciler = (
            DriverSetReconciler(client, config.daemonset_spec)
            if config.daemonset_spec is not None
            else None
        )
        self.agent_reconciler = (
            DriverSetReconciler(client, config.agent_spec)
            if config.agent_spec is not None
            else None
        )
        self.registry = MetricsRegistry()
        self.metrics = UpgradeMetrics(self.registry)
        self.slice_timer = SliceUpgradeTimer(self.registry)
        # Stuck-state dwell gauge flows into the same registry.
        self.manager.stuck_detector.registry = self.registry
        # Predictive rollout planning: the drift watchdog anchors the
        # active roll to its analytic RollPlan after every full pass and
        # republishes the ETA (CR status + metrics).  Planning is
        # read-only — it never issues a write verb.
        from k8s_operator_libs_tpu.planning.clocks import PhaseClockTracker
        from k8s_operator_libs_tpu.planning.drift import DriftWatchdog

        self.watchdog = DriftWatchdog(self.keys)
        # Per-pool EWMA phase clocks: every group-level transition the
        # provider stages is also reported here (read-only observer), and
        # the watchdog folds the measured clocks into each anchor/re-plan
        # so projections tighten as the roll progresses.
        self.clock_tracker = PhaseClockTracker()
        self.watchdog.clock_tracker = self.clock_tracker
        # Multicast registration: the trace recorder subscribed itself in
        # the manager's constructor, and the clock tracker joins it here
        # — each observer is exception-isolated by the provider.
        self.manager.provider.add_transition_observer(
            self.clock_tracker.observe_group_transition
        )
        # Black box: ring of recent facts + throttled redacted snapshots
        # on failure triggers (stuck, infeasible, quarantine, circuit-
        # open, crash-adoption).  Spool defaults under the system tmpdir;
        # trace_spool_dir="" keeps it memory-only.
        from k8s_operator_libs_tpu.obs.flightrec import FlightRecorder

        spool_dir = config.trace_spool_dir
        if spool_dir is None:
            import os
            import tempfile

            spool_dir = os.path.join(
                tempfile.gettempdir(), "tpu-upgrade-blackbox"
            )
        self.flight_recorder = FlightRecorder(spool_dir=spool_dir or None)
        self.manager.set_flight_recorder(self.flight_recorder)
        self.flight_recorder.snapshot_providers["informer"] = (
            self._informer_snapshot
        )
        # One makespan breakdown publication per completed roll trace.
        self._published_breakdown_trace: Optional[str] = None
        self._last_breakdown: Optional[dict] = None
        # Plan-guided admission (planning.admissionMode: packed): the
        # engine's admission pass consults the watchdog's fresh plan to
        # ORDER chargeable groups — no budget/window/DCN gate is relaxed.
        self.manager.drift_watchdog = self.watchdog
        if self._sharded is not None:
            # Scoped dirty ticks between full resyncs feed the watchdog
            # as progress evidence (read-only observer).
            self._sharded.progress_observer = self.watchdog.note_tick
            # Budget-release wakeups target the planned-next wave first
            # (blanket wake when no fresh plan).
            self._sharded.plan_provider = self.watchdog.fresh_plan
        self.elector = None
        if config.leader_elect:
            from k8s_operator_libs_tpu.k8s.leader import (
                LeaderElector,
                ensure_lease_kind,
            )

            # No-op on real clusters (coordination.k8s.io is built in);
            # required on the FakeCluster/KubeApiServer tiers, where an
            # unregistered kind would fail every election round.
            ensure_lease_kind(client)
            self.elector = LeaderElector(
                client,
                identity=config.identity or None,
                namespace=config.lease_namespace or config.namespace,
                name=config.lease_name,
            )
            # Crash-safety fence: every async worker (drain, eviction,
            # rollback) consults this before mutating, so a deposed
            # leader's in-flight workers abandon instead of racing the
            # successor.  Reads ``self.elector`` at call time — tests and
            # embedders may swap the elector after construction.
            self.manager.fence = (
                lambda: self.elector is None or self.elector.is_leader()
            )
            # Term fence on top: workers quorum-read the persisted
            # adoption stamp at entry/barriers and abandon if a HIGHER
            # term has stamped their nodes — closes the renew-deadline
            # window without waiting out any clock.  Built on the raw
            # client: the whole point is a quorum read.
            from k8s_operator_libs_tpu.upgrade.durable import make_term_fence

            self.manager.term_fence = make_term_fence(
                client,
                self.keys,
                lambda: self.elector.term if self.elector is not None else 0,
            )
        self._stop = False
        # Re-adoption: the first reconcile pass of every leadership epoch
        # (and of a non-HA process lifetime) rebuilds in-memory progress
        # — escalation ladders, rollback attempts, probe backoffs — from
        # the durable annotation record instead of from zero.
        self._needs_adoption = True
        self._adoptions = 0
        # Policy-CR bookkeeping: the CR fetched this pass (reused for the
        # status write) and whether "missing" was already logged.
        self._policy_cr: Optional[dict] = None
        self._policy_cr_missing = False
        # Set while run_forever is in watch mode so stop() can interrupt
        # a long resync wait.
        self._wake: Optional[threading.Event] = None
        # Election bookkeeping (leader_elect mode).
        self._last_election_at: Optional[float] = None
        self._was_leader = False
        # Set in run_forever when watch + leader-elect are both on: the
        # watch pump streams only while this Event is set (leading).
        self._pump_gate: Optional[threading.Event] = None

    def reconcile_once(self) -> bool:
        """One full pass; returns False when the snapshot was incoherent
        (requeue and retry, reference reconcile-error semantics) or when
        the client's circuit breaker fast-failed the pass (degraded mode:
        the condition/metrics surface it, the loop keeps ticking, and the
        breaker's half-open probes heal the path)."""
        t0 = time.monotonic()
        try:
            if self.config.policy_ref is not None:
                self._refresh_policy_from_cr()
            if not self._still_leading():
                return False
            if self.ds_reconciler is not None:
                self.ds_reconciler.reconcile()
            if self.agent_reconciler is not None:
                self.config.agent_spec.driver_revision = (
                    self._current_driver_revision()
                )
                self.agent_reconciler.reconcile()
            # Stamp BEFORE the build: deltas that land while the (slow,
            # fleet-sized) snapshot is being assembled are not in it, so
            # the sharded layer must not treat them as covered by this
            # resync.  Only marks older than this instant may be cleared.
            resync_t0 = time.monotonic()
            try:
                state = self.manager.build_state(
                    self.config.namespace,
                    self.config.driver_labels,
                    self.config.policy,
                )
            except BuildStateError as e:
                logger.warning("build_state: %s (requeueing)", e)
                return False
            # Re-check right before the mutating phase: a pass that
            # outlived the renew deadline (apiserver latency, huge
            # snapshot) must not cordon/drain concurrently with a
            # successor that has already taken over.  is_leader() goes
            # False at the renew deadline, BEFORE anyone else's observed
            # term expires.
            if not self._still_leading():
                return False
            if self._needs_adoption:
                identity = (
                    self.elector.identity
                    if self.elector is not None
                    else (self.config.identity or "standalone")
                )
                term = self.elector.term if self.elector is not None else 0
                self.manager.adopt(
                    state,
                    identity=identity,
                    term=term,
                    policy=self.config.policy,
                )
                # Measured phase clocks ride the CR status: re-seed the
                # EWMA on adoption so a restart or leader handoff does
                # not reset estimates to the static defaults.  Loaded
                # values never overwrite live samples.
                if self._policy_cr is not None:
                    self.clock_tracker.load_status(
                        (self._policy_cr.get("status") or {}).get(
                            "phaseClocks"
                        )
                    )
                self._needs_adoption = False
                self._adoptions += 1
                self.registry.set(
                    "controller_adoptions_total", float(self._adoptions)
                )
                self.registry.set("controller_leader_term", float(term))
            resync_started = None
            if self._sharded is not None:
                # Anchor the sharded layer to ground truth: re-seed the
                # node→pool registry and re-baseline the budget ledger
                # from this full snapshot BEFORE acting on it.
                resync_started = self._sharded.observe_full_state(
                    state, self.config.policy, started=resync_t0
                )
            # Drift watchdog: full passes only (a scoped pass sees one
            # pool and cannot measure fleet progress).  Read-only —
            # plan_roll and find_infeasibilities never touch the API.
            # Runs BEFORE apply_state so the plan anchored from this
            # snapshot guides this pass's admission ordering (packed
            # mode); every observe input is bucket-fixed at build_state
            # time, so the verdict is identical either side of apply.
            if self.config.policy is not None:
                self.watchdog.configure(
                    getattr(self.config.policy, "planning", None)
                )
                # Refresh node→pool attribution for the phase-clock
                # tracker (full pass = whole-fleet scope), so measured
                # durations are charged to the right pool's EWMA.
                node_pools = {
                    m.node.name: (
                        self.manager._pool_for_group(
                            g, self.config.policy
                        )
                        or ""
                    )
                    for g in state.all_groups()
                    for m in g.members
                }
                self.clock_tracker.seed_pools(node_pools)
                # Same attribution feeds the span tree: group spans hang
                # under the right pool span.
                rec = getattr(self.manager, "trace_recorder", None)
                if rec is not None:
                    rec.seed_pools(node_pools)
                # ... and the telemetry plane, so health baselines fold
                # per (generation, pool) cohort instead of fleet-wide.
                plane = getattr(self.manager, "telemetry_plane", None)
                if plane is not None:
                    plane.seed_pools(node_pools)
                drift_report = self.watchdog.observe(
                    self.manager, state, self.config.policy
                )
            else:
                drift_report = None
            self.manager.apply_state(state, self.config.policy)
            if resync_started is not None:
                # Deltas queued before this pass began are covered by it.
                self._sharded.complete_full_resync(resync_started)
                self.metrics.observe_sharded(self._sharded)
        except CircuitOpenError as e:
            self._handle_circuit_open(e)
            return False
        self.metrics.observe_plan(drift_report)
        self.metrics.observe_trace(self.manager, self._trace_breakdown())
        self._observe_telemetry()
        if self.config.policy_ref is not None:
            self._update_cr_status(state)
        duration = time.monotonic() - t0
        self.metrics.observe(self.manager, state, duration)
        self.slice_timer.observe_state(state)
        self._flush_events(state)
        return True

    def reconcile_dirty(self) -> bool:
        """One event-driven dirty pass (sharded mode): reconcile ONLY
        the pools touched by watch deltas, on parallel worker shards —
        an idle tick takes 0 pools and builds 0 state.  Falls back to a
        full pass when the sharded layer is not yet seeded by a full
        resync or a new leadership epoch still needs re-adoption."""
        if (
            self._sharded is None
            or self._needs_adoption
            or not self._sharded.ready()
        ):
            return self.reconcile_once()
        try:
            if self.config.policy_ref is not None:
                self._refresh_policy_from_cr()
            if not self._still_leading():
                return False
            report = self._sharded.tick(self.config.policy)
        except CircuitOpenError as e:
            self._handle_circuit_open(e)
            return False
        self.metrics.observe_sharded(self._sharded, report)
        self._flush_events()
        return report.errors == 0 and report.fenced == 0

    def dry_run(self):
        """Build one read-only snapshot, return the analytic RollPlan,
        and PROVE the pass wrote nothing: every write verb the client
        observed and everything the transactional write plane issued
        must be zero (the ISSUE's planning-is-read-only contract)."""
        from k8s_operator_libs_tpu.planning.planner import plan_roll

        if self.config.policy_ref is not None:
            self._refresh_policy_from_cr()
        before = self._write_verb_count()
        state = self.manager.build_state(
            self.config.namespace,
            self.config.driver_labels,
            self.config.policy,
        )
        plan = plan_roll(self.manager, state, self.config.policy)
        writes = self._write_verb_count() - before
        if writes:
            raise RuntimeError(
                f"dry-run issued {writes} API write verb(s); planning "
                "must be read-only"
            )
        return plan

    def score_policy(self, candidate_path: str) -> str:
        """What-if scoring: run the digital twin under the CURRENT policy
        and under the candidate policy file, and report the makespan
        delta.  Same zero-write contract as --dry-run — both twins roll a
        cloned fleet; the live cluster sees only reads."""
        from k8s_operator_libs_tpu.planning.twin import run_twin

        if self.config.policy_ref is not None:
            self._refresh_policy_from_cr()
        candidate = load_policy(candidate_path)
        before = self._write_verb_count()
        results = {}
        for label, policy in (
            ("current", self.config.policy),
            ("candidate", candidate),
        ):
            results[label] = run_twin(
                self.client,
                self.config.namespace,
                self.config.driver_labels,
                policy,
                keys=self.keys,
            )
        writes = self._write_verb_count() - before
        if writes:
            raise RuntimeError(
                f"what-if scoring issued {writes} API write verb(s) "
                "against the live cluster; scoring must be read-only"
            )
        cur, cand = results["current"], results["candidate"]
        delta = cand.virtual_duration_s - cur.virtual_duration_s
        lines = [
            f"what-if: {candidate_path}",
            (
                f"  current:   makespan {cur.virtual_duration_s:10.1f}s"
                f"  waves {cur.wave_count:3d}"
                f"  converged {cur.converged}"
            ),
            (
                f"  candidate: makespan {cand.virtual_duration_s:10.1f}s"
                f"  waves {cand.wave_count:3d}"
                f"  converged {cand.converged}"
            ),
            (
                f"  delta:     {delta:+10.1f}s"
                + (
                    "  (candidate faster)"
                    if delta < 0
                    else ("  (candidate slower)" if delta > 0 else "")
                )
            ),
        ]
        if cur.held or cand.held:
            lines.append(
                f"  held groups: current {sorted(cur.held)} "
                f"candidate {sorted(cand.held)}"
            )
        return "\n".join(lines)

    def _write_verb_count(self) -> float:
        """Write verbs observed so far: client per-verb stats (fake and
        REST clients both expose ``stats``) plus everything the write
        plane has flushed."""
        total = 0.0
        stats = getattr(
            getattr(self.manager, "client", None), "stats", None
        )
        if stats is not None and hasattr(stats, "items"):
            total += sum(
                v
                for k, v in stats.items()
                if str(k)
                .lower()
                .startswith(
                    (
                        "patch",
                        "create",
                        "delete",
                        "evict",
                        "update",
                        "post",
                        "put",
                    )
                )
            )
        plan = self.write_plan
        if plan is not None and hasattr(plan, "counters"):
            c = plan.counters()
            total += c.get("writes_mutating", 0) + c.get(
                "writes_status", 0
            )
        return total

    def _open_circuit_count(self) -> int:
        breaker = getattr(self.client, "breaker", None)
        if breaker is None or not hasattr(breaker, "open_endpoints"):
            return 0
        return len(breaker.open_endpoints())

    @property
    def write_plan(self):
        """The manager's transactional write plane (None with injected
        fake managers): CR status and Events route through it so status
        churn rides the status flow — never the mutating one — and a
        deposed leader's queued writes drop at flush."""
        return getattr(self.manager, "write_plan", None)

    def _informer_snapshot(self):
        """Informer cache health for black-box snapshots (None when the
        controller runs without a watch)."""
        informer = self.informer
        if informer is None:
            return None
        age = informer.age_s()
        return {
            "age_seconds": age if age != float("inf") else None,
            "stats": dict(getattr(informer, "stats", {}) or {}),
        }

    def _trace_breakdown(self) -> Optional[dict]:
        """Critical-path makespan attribution for the most recently
        COMPLETED roll trace, computed once per trace id (the analysis
        walks the whole span tree) and cached for the CR status, the
        metrics surface and the status CLI."""
        rec = getattr(self.manager, "trace_recorder", None)
        if rec is None:
            return self._last_breakdown
        completed = rec.last_completed()
        if completed is None:
            return self._last_breakdown
        if completed.trace_id == self._published_breakdown_trace:
            return self._last_breakdown
        from k8s_operator_libs_tpu.obs.critical import (
            analyze,
            expected_from_tracker,
            makespan_breakdown,
            phase_drift,
        )

        try:
            attribution = analyze(completed)
            expected = expected_from_tracker(self.clock_tracker)
            drift = phase_drift(attribution, expected)
            breakdown = makespan_breakdown(attribution, drift)
        except Exception:  # noqa: BLE001 — attribution is advisory
            logger.exception(
                "makespan attribution failed for trace %s",
                completed.trace_id,
            )
            self._published_breakdown_trace = completed.trace_id
            return self._last_breakdown
        self._published_breakdown_trace = completed.trace_id
        self._last_breakdown = breakdown
        logger.info(
            "roll %s complete: makespan %.1fs across %d group(s)",
            completed.trace_id,
            breakdown.get("makespanSeconds", 0.0),
            breakdown.get("groups", 0),
        )
        return breakdown

    def _observe_telemetry(self) -> None:
        """Fold this pass's probe telemetry into fleet baselines and
        publish the verdicts: metric families, straggler-aware phase
        clocks (the planner's ETA annotation), one NodeHealthDegraded
        Warning per FRESH confirmation (stamped with the active trace
        id), and a flight-recorder snapshot while the slow batteries
        are still in the evidence ring.  Observe-only: nothing here
        changes any node's upgrade state."""
        plane = getattr(self.manager, "telemetry_plane", None)
        if plane is None:
            return
        plane.recompute()
        self.metrics.observe_telemetry(self.manager)
        straggler_nodes = [
            s["node"]
            for s in plane.to_status().get("stragglers") or []
        ]
        self.clock_tracker.set_straggler_nodes(straggler_nodes)
        fresh = plane.new_confirmations()
        if not fresh:
            return
        suffix_fn = getattr(self.manager, "_trace_event_suffix", None)
        trace_suffix = suffix_fn() if suffix_fn is not None else ""
        for verdict in fresh:
            log_event(
                self.events,
                verdict["node"],
                EVENT_TYPE_WARNING,
                "NodeHealthDegraded",
                "Node confirmed as fleet straggler: worst stat "
                f"{verdict['worstStat']} at z={verdict['z']} vs its "
                f"({verdict['generation']}, {verdict['pool']}) cohort "
                f"baseline over {verdict['streak']} consecutive "
                f"batteries (health score {verdict['score']}); "
                "observe-only unless healthGate.quarantineStragglers"
                f"{trace_suffix}",
            )
        self.flight_recorder.trigger(
            "straggler",
            nodes=",".join(v["node"] for v in fresh),
            detail=f"{len(fresh)} fresh straggler confirmation(s)",
        )

    def _handle_circuit_open(self, exc: CircuitOpenError) -> None:
        """Degrade gracefully instead of crashing or wedging: log once
        per pass, publish the gauge, and best-effort surface a Degraded
        condition on the policy CR (an outage scoped to some endpoints
        still lets the status write land; a total one is swallowed and
        retried next pass)."""
        logger.warning(
            "reconcile degraded: %s (ticking on; half-open probes will "
            "close the circuit once the apiserver recovers)",
            exc,
        )
        self.metrics.registry.set(
            "api_circuit_open_endpoints",
            float(max(1, self._open_circuit_count())),
        )
        self.flight_recorder.trigger(
            "circuit_open",
            detail=str(exc),
            open_endpoints=self._open_circuit_count(),
        )
        self._flush_events()
        if self.config.policy_ref is None or self._policy_cr is None:
            return
        from k8s_operator_libs_tpu.api.schema import (
            POLICY_GROUP,
            POLICY_PLURAL,
            POLICY_VERSION,
        )

        ns, name = self.config.policy_ref
        cr = self._policy_cr
        prev_status = dict(cr.get("status") or {})
        status = dict(prev_status)
        status["apiCircuitOpenEndpoints"] = max(
            1, self._open_circuit_count()
        )
        status["conditions"] = self._conditions(
            status, prev_status.get("conditions") or []
        )
        if status == prev_status:
            return
        cr["status"] = status
        try:
            plan = self.write_plan
            if plan is not None:
                plan.stage_cr_status(
                    POLICY_GROUP, POLICY_VERSION, POLICY_PLURAL, ns, cr
                )
                plan.flush_status()
            else:
                self.client.update_custom_object_status(
                    POLICY_GROUP, POLICY_VERSION, POLICY_PLURAL, ns, cr
                )
        except Exception as e:  # noqa: BLE001 — best-effort while degraded
            logger.debug("degraded status publication failed: %s", e)

    def _flush_events(self, state=None) -> None:
        """Drain recorded events to the log AND, when enabled, to the
        cluster as core/v1 Events (reference util.go:141-153 via
        client-go's EventRecorder — `kubectl describe node` shows them).
        Identical events within one pass aggregate into a count.
        Publication failures never fail the pass."""
        drained = self.events.drain()
        counts: dict[tuple[str, str, str, str], int] = {}
        for ev in drained:
            logger.info(
                "event %s %s %s: %s",
                ev.event_type,
                ev.object_name,
                ev.reason,
                ev.message,
            )
            key = (ev.object_name, ev.event_type, ev.reason, ev.message)
            counts[key] = counts.get(key, 0) + 1
        if not self.config.publish_events:
            return
        # involvedObject needs the node UID for `kubectl describe node`
        # to find the event (client-go's Search filters on it).
        node_uids: dict[str, str] = {}
        if state is not None:
            for group in state.all_groups():
                for n in group.nodes:
                    node_uids[n.name] = n.metadata.uid
        now = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        plan = self.write_plan
        for (obj, etype, reason, message), count in counts.items():
            involved: dict = {"name": obj, "apiVersion": "v1"}
            if obj in node_uids:
                involved["kind"] = "Node"
                involved["uid"] = node_uids[obj]
            else:
                involved["kind"] = "Pod"  # restart-failure events name pods
            event = {
                "apiVersion": "v1",
                "kind": "Event",
                # A real apiserver requires a client-supplied
                # name (client-go EventRecorder does the same
                # object.timestamp scheme).
                "metadata": {"name": f"{obj}.{uuid.uuid4().hex[:12]}"},
                "involvedObject": involved,
                "type": etype,
                "reason": reason,
                "message": message,
                "count": count,
                "firstTimestamp": now,
                "lastTimestamp": now,
                "source": {"component": "tpu-upgrade-controller"},
            }
            if plan is not None:
                # Kubelet-style aggregation: identical events within the
                # window collapse into one count-carrying publication on
                # the status flow.
                plan.stage_event(self.config.namespace, event, count)
                continue
            try:
                self.client.create_event(self.config.namespace, event)
            except Exception as e:  # noqa: BLE001 — telemetry best-effort
                logger.debug("event publication failed: %s", e)
        if plan is not None:
            plan.flush_events()

    def _refresh_policy_from_cr(self) -> None:
        """Re-read the TPUUpgradePolicy CR: a policy edit takes effect on
        the next pass, like a consumer operator re-reading its CRD spec
        every reconcile.  A missing CR disables upgrades (policy None =
        no-op gate, reference upgrade_state.go:372); a malformed one
        keeps the last good policy (admission should have rejected it)."""
        from k8s_operator_libs_tpu.api.schema import (
            POLICY_GROUP,
            POLICY_PLURAL,
            POLICY_VERSION,
        )
        from k8s_operator_libs_tpu.k8s.client import NotFoundError

        ns, name = self.config.policy_ref
        try:
            cr = self.client.get_custom_object(
                POLICY_GROUP, POLICY_VERSION, POLICY_PLURAL, ns, name
            )
        except NotFoundError:
            # Log on every transition into "missing" AND on the very
            # first pass: a typoed --policy-cr must not be a silent
            # permanent no-op.
            if not self._policy_cr_missing:
                logger.warning(
                    "policy CR %s/%s not found: upgrades paused "
                    "(create the TPUUpgradePolicy or fix --policy-cr)",
                    ns,
                    name,
                )
            self._policy_cr_missing = True
            self._policy_cr = None
            self.config.policy = None
            return
        self._policy_cr_missing = False
        self._policy_cr = cr
        try:
            policy = TPUUpgradePolicySpec.from_dict(cr.get("spec") or {})
            policy.validate()
            self.config.policy = policy
        except (ValueError, TypeError) as e:
            logger.warning(
                "policy CR %s/%s invalid (%s): keeping previous policy",
                ns,
                name,
                e,
            )

    def _update_cr_status(self, state) -> None:
        """Publish the method-counters (reference upgrade_state.go:
        1038-1120 exposes them for consumers to export) to the CR's
        status subresource, so `kubectl get tpuupgradepolicy -o yaml`
        shows progress.  Uses the CR fetched by _refresh_policy_from_cr
        this pass; lost-update conflicts are skipped — the next pass
        rewrites."""
        from k8s_operator_libs_tpu.api.schema import (
            POLICY_GROUP,
            POLICY_PLURAL,
            POLICY_VERSION,
        )
        from k8s_operator_libs_tpu.k8s.client import (
            ConflictError,
            NotFoundError,
        )

        ns, name = self.config.policy_ref
        cr = self._policy_cr
        if cr is None:
            return
        m = self.manager
        try:
            status = {
                "totalManagedNodes": m.get_total_managed_nodes(state),
                "totalManagedGroups": m.get_total_managed_groups(state),
                "upgradesInProgress": m.get_upgrades_in_progress(state),
                "upgradesDone": m.get_upgrades_done(state),
                "upgradesFailed": m.get_upgrades_failed(state),
                "upgradesPending": m.get_upgrades_pending(state),
                "currentUnavailableNodes": m.get_current_unavailable_nodes(
                    state
                ),
                "quarantinedSlices": len(
                    state.groups_in(UpgradeState.QUARANTINED)
                ),
                "apiCircuitOpenEndpoints": self._open_circuit_count(),
                # Escalation/rollback telemetry (crash-safe: seeded from
                # the durable annotation record on adoption, so these
                # survive controller restarts and leader handoffs).
                "evictionEscalations": {
                    rung: count
                    for rung, count in sorted(
                        m.escalation_stats.snapshot().items()
                    )
                    if count
                },
                "rollbackAttempts": dict(
                    sorted(
                        getattr(
                            m.validation_manager, "rollback_attempts", {}
                        ).items()
                    )
                ),
                "quarantineCycleDemotions": m.quarantine_cycle_demotions,
            }
            # Predictive-planning surface (drift watchdog; durable so the
            # status CLI can render the plan section from the CR alone).
            report = self.watchdog.last_report
            if report is not None and report.active:
                status["projectedCompletion"] = time.strftime(
                    "%Y-%m-%dT%H:%M:%SZ",
                    time.gmtime(report.projected_completion_epoch),
                )
                status["planDriftSeconds"] = int(report.drift_seconds)
                status["planWaves"] = report.wave_count
                status["planCompletedGroups"] = report.completed_groups
                status["planReplans"] = report.replans
                if report.infeasible:
                    status["planInfeasible"] = list(report.infeasible)
            # Roll-tracing surface: the active trace id joins the plan
            # block (Events carry the same id, so operators can pivot
            # Events ↔ trace ↔ plan), and a completed roll publishes its
            # critical-path makespan attribution.
            rec = getattr(m, "trace_recorder", None)
            active_trace = (
                rec.active_trace_id() if rec is not None else None
            )
            if active_trace:
                status["planTraceId"] = active_trace
            breakdown = self._trace_breakdown()
            if breakdown:
                status["makespanBreakdown"] = breakdown
            # Measured per-pool phase clocks (EWMA): durable through the
            # write plane so a successor controller adopts them instead
            # of restarting from the static defaults.
            phase_clocks = self.clock_tracker.to_status()
            if phase_clocks:
                status["phaseClocks"] = phase_clocks
            # Fleet health telemetry: per-cohort baselines + any
            # confirmed stragglers (observe-only; quarantine routing is
            # the policy's healthGate.quarantineStragglers opt-in).
            plane = getattr(m, "telemetry_plane", None)
            if plane is not None:
                health = plane.to_status()
                if health.get("healthSummary"):
                    status["healthSummary"] = health["healthSummary"]
                if health.get("stragglers"):
                    status["stragglers"] = health["stragglers"]
            astats = self.manager.admission_stats
            if astats.get("last_budget_cap"):
                status["admissionMode"] = self.manager.admission_mode
                status["budgetSaturation"] = round(
                    astats.get("last_budget_used", 0)
                    / astats["last_budget_cap"],
                    3,
                )
            status["conditions"] = self._conditions(
                status, (cr.get("status") or {}).get("conditions") or []
            )
            if cr.get("status") == status:
                return  # no churn: don't bump resourceVersion every pass
            cr["status"] = status
            plan = self.write_plan
            if plan is not None:
                # Status flow: a dry bucket defers to the next pass
                # (which re-stages the freshest counters); a 409 replays
                # once onto a fresh read inside the plan.
                plan.stage_cr_status(
                    POLICY_GROUP, POLICY_VERSION, POLICY_PLURAL, ns, cr
                )
                plan.flush_status()
            else:
                self.client.update_custom_object_status(
                    POLICY_GROUP, POLICY_VERSION, POLICY_PLURAL, ns, cr
                )
        except (NotFoundError, ConflictError) as e:
            logger.debug("status update skipped: %s", e)

    @staticmethod
    def _conditions(status: dict, previous: list[dict]) -> list[dict]:
        """Standard operator status.conditions derived from the counters,
        with lastTransitionTime preserved while a condition's status is
        unchanged (k8s meta.v1 Condition semantics).

        Degraded is True on failed slices OR an open API circuit (the
        controller cannot currently drive the cluster); counters are read
        with defaults so a degraded pass can rebuild conditions from a
        partial previous status."""
        in_progress = status.get("upgradesInProgress", 0)
        pending = status.get("upgradesPending", 0)
        failed = status.get("upgradesFailed", 0)
        quarantined = status.get("quarantinedSlices", 0)
        open_circuits = status.get("apiCircuitOpenEndpoints", 0)
        in_flight = in_progress + pending
        if failed:
            degraded_reason = "SlicesFailed"
            degraded_msg = f"{failed} node(s) in upgrade-failed"
            if quarantined:
                degraded_msg += f"; {quarantined} slice(s) quarantined"
            if open_circuits:
                degraded_msg += (
                    f"; {open_circuits} API endpoint(s) circuit-open"
                )
        elif quarantined:
            degraded_reason = "SliceQuarantined"
            degraded_msg = (
                f"{quarantined} slice(s) quarantined after mid-roll "
                "hardware loss; each resumes once its hosts stay Ready "
                "past the dwell window"
            )
            if open_circuits:
                degraded_msg += (
                    f"; {open_circuits} API endpoint(s) circuit-open"
                )
        elif open_circuits:
            degraded_reason = "ApiCircuitOpen"
            degraded_msg = (
                f"{open_circuits} API endpoint(s) circuit-open after "
                "sustained apiserver failures; reconcile is degraded "
                "until the circuit closes"
            )
        else:
            degraded_reason = "AllHealthy"
            degraded_msg = f"{failed} node(s) in upgrade-failed"
        want = [
            (
                "Progressing",
                in_flight > 0,
                "UpgradesInFlight" if in_flight else "NoPendingUpgrades",
                f"{in_progress} in progress, "
                f"{pending} pending",
            ),
            (
                "Degraded",
                failed > 0 or quarantined > 0 or open_circuits > 0,
                degraded_reason,
                degraded_msg,
            ),
            (
                "Complete",
                in_flight == 0 and failed == 0 and quarantined == 0,
                (
                    "AllDone"
                    if in_flight == 0 and failed == 0 and quarantined == 0
                    else "Failures"
                    if failed
                    else "SlicesQuarantined"
                    if quarantined
                    else "InProgress"
                ),
                f"{status.get('upgradesDone', 0)}/"
                f"{status.get('totalManagedNodes', 0)} "
                "nodes at the current driver",
            ),
        ]
        prev_by_type = {c.get("type"): c for c in previous}
        now = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        out = []
        for ctype, truthy, reason, message in want:
            cond_status = "True" if truthy else "False"
            prev = prev_by_type.get(ctype)
            last_transition = (
                prev["lastTransitionTime"]
                if prev is not None
                and prev.get("status") == cond_status
                and prev.get("lastTransitionTime")
                else now
            )
            out.append(
                {
                    "type": ctype,
                    "status": cond_status,
                    "reason": reason,
                    "message": message,
                    "lastTransitionTime": last_transition,
                }
            )
        return out

    def _current_driver_revision(self) -> str:
        """Current ControllerRevision hash of the (first) driver
        DaemonSet matching the selector, or "" when the DaemonSet is
        absent OR has no recorded revision yet (a just-created DS: the
        DS controller hasn't written its first ControllerRevision)."""
        daemon_sets = self.client.list_daemon_sets(
            namespace=self.config.namespace,
            match_labels=self.config.driver_labels,
        )
        if not daemon_sets:
            return ""
        try:
            return self.manager.pod_manager.get_daemonset_controller_revision_hash(
                daemon_sets[0]
            )
        except ValueError:
            return ""

    def stop(self, *_args) -> None:
        self._stop = True
        if self._sharded is not None:
            self._sharded.shutdown()
        if self._wake is not None:
            self._wake.set()  # interrupt a watch-mode resync wait

    def _still_leading(self) -> bool:
        """Mid-pass leadership guard; True when not in leader-elect mode.

        Runs a (retry-period-throttled) election round rather than only
        reading the deadline: a pass that takes longer than the renew
        deadline RENEWS here and proceeds — without this, every slow
        pass would abort at the guard, renew at the top of the loop, and
        abort again, livelocking a large cluster."""
        if self.elector is None or self._election_round():
            return True
        logger.warning(
            "leadership lost mid-pass (identity=%s); aborting reconcile",
            self.elector.identity,
        )
        return False

    def _election_round(self) -> bool:
        """Renew/acquire at the elector's retry cadence; between renewals
        trust ``is_leader()`` (itself bounded by the renew deadline, so a
        partitioned holder stands down before its term expires for
        anyone else).  Called at the top of every pass AND from inside
        the inter-pass waits — a 30 s reconcile interval must not starve
        a 10 s renew deadline."""
        e = self.elector
        now = time.monotonic()
        if (
            self._last_election_at is None
            or now - self._last_election_at >= e.retry_period_s
            # A HOLDER whose deadline decayed mid-wait renews at once
            # (the slow-pass guard).  A standby must NOT bypass the
            # throttle — `not is_leader()` is always true for it, and
            # _wait's 0.2 s chunks would turn the stated retry cadence
            # into ~5 Lease GETs per second per replica.
            or (self._was_leader and not e.is_leader())
        ):
            self._last_election_at = now
            leading = e.acquire_or_renew()
        else:
            leading = self._was_leader
        self.registry.set(
            "tpu_upgrade_controller_is_leader",
            1.0 if leading else 0.0,
            identity=e.identity,
        )
        if leading != self._was_leader:
            logger.info(
                "%s leadership (lease=%s identity=%s term=%d)",
                "gained" if leading else "lost",
                self.config.lease_name,
                e.identity,
                e.term,
            )
            if leading:
                # New leadership epoch: the next pass re-adopts in-flight
                # state from the durable record before acting on it.
                self._needs_adoption = True
        self._was_leader = leading
        if self._pump_gate is not None:
            if leading:
                self._pump_gate.set()
            else:
                self._pump_gate.clear()
        return leading

    def _wait(
        self,
        duration: float,
        wake: Optional[threading.Event] = None,
    ) -> bool:
        """Sleep up to ``duration``, chunked so ``stop()`` interrupts
        promptly, the leader keeps renewing its lease (a reconcile
        interval must never starve the renew deadline), and a leadership
        change in EITHER direction ends the wait early (the caller's
        loop re-evaluates).  Returns True iff ``wake`` fired (a watch
        event)."""
        deadline = time.monotonic() + duration
        e = self.elector
        was = self._was_leader
        while not self._stop:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return False
            if wake is not None:
                chunk = (
                    remaining
                    if e is None
                    else min(remaining, e.retry_period_s)
                )
                if wake.wait(chunk):
                    return True
            else:
                time.sleep(min(remaining, 0.2))
            if e is not None:
                self._election_round()
                if self._was_leader != was:
                    return False
        return False

    def _watch_kinds(self) -> list[str]:
        # ControllerRevision rides along because the steady-state pass
        # resolves the driver DS's revision hash every tick — without
        # caching it, that one lookup would keep a per-tick LIST alive.
        kinds = ["Node", "Pod", "DaemonSet", "ControllerRevision"]
        if self.config.policy_ref is not None:
            from k8s_operator_libs_tpu.api.schema import (
                POLICY_GROUP,
                POLICY_PLURAL,
                POLICY_VERSION,
            )

            ns, _ = self.config.policy_ref
            kinds.append(
                f"{POLICY_GROUP}/{POLICY_VERSION}/{ns}/{POLICY_PLURAL}"
            )
        return kinds

    def _watch_pump(self, wake: threading.Event) -> None:
        """Background thread: any watch event sets the wake flag; the
        stream is re-established on errors (apiserver restarts).

        Informer reconnect semantics (the client-go list-then-watch
        loop): each connect first takes a BASELINE — the cluster
        resourceVersion from a cheap one-item list — and watches from
        it; every event raises its own KIND's floor, and a reconnect
        resumes from the MINIMUM floor across kinds.  The per-kind
        minimum matters: on the wire tier each kind is an independent
        stream feeding one queue, so the highest rv seen globally may be
        ahead of an event still buffered in a slower stream — resuming
        from the max would skip it permanently, while resuming from the
        min replays at worst a few already-seen events (wakes are
        idempotent).  A 410 Gone (resume point compacted away) drops the
        baseline and forces an immediate wake — the pass it triggers
        re-snapshots the world, which is this controller's re-list.

        Under leader election the pump holds streams only while this
        replica leads (controller-runtime starts informers after winning
        the election): a standby discards every event anyway, and on a
        large pool the Pod watch is a heavy stream the apiserver should
        not carry twice."""
        from k8s_operator_libs_tpu.k8s.client import ExpiredError

        resume_rv: Optional[int] = None
        floors: dict[str, int] = {}
        while not self._stop:
            gate = self._pump_gate
            if gate is not None and not gate.is_set():
                gate.wait(0.5)
                continue
            kinds = self._watch_kinds()
            try:
                if resume_rv is None:
                    # Baseline: the cluster RV "now" (shared across
                    # kinds — one etcd-style sequence), so the watch
                    # below misses nothing after this instant.  With an
                    # informer this is its LIST phase: sync() takes the
                    # same one-item baseline first, then snapshots every
                    # tracked kind, so the cache is coherent as of the
                    # rv the watch resumes from.
                    if self.informer is not None:
                        resume_rv = self.informer.sync()
                    else:
                        resume_rv = int(
                            self.client.list_page("Node", limit=1)[
                                "resourceVersion"
                            ]
                        )
                floors = {
                    (k.split("/")[-1] if "/" in k else k): resume_rv
                    for k in kinds
                }
                for ev in self.client.watch_events(
                    kinds, since_rv=resume_rv, bookmarks=True
                ):
                    if self._stop:
                        return
                    if self.informer is not None:
                        # Every yield feeds the cache: deltas apply,
                        # BOOKMARKs and None heartbeats refresh the
                        # staleness clock (a quiet-but-connected stream
                        # keeps cached reads valid).
                        self.informer.handle_event(ev)
                    if self._sharded is not None:
                        # ... and the dirty-set router: the delta marks
                        # exactly the pools it touches, which is what the
                        # next event-driven pass reconciles.
                        self._sharded.handle_event(ev)
                    if gate is not None and not gate.is_set():
                        # Lost leadership: drop the streams; keep the
                        # floors so regaining replays the standby gap.
                        # The informer is NOT invalidated — its age just
                        # grows unfed, so cached reads degrade to
                        # passthrough on their own.
                        resume_rv = min(floors.values())
                        break
                    if ev is not None:
                        if ev.rv and ev.kind in floors:
                            floors[ev.kind] = max(floors[ev.kind], ev.rv)
                        # BOOKMARKs advance resume points on quiet kinds
                        # (no reconcile-worthy change happened).
                        if ev.type != "BOOKMARK":
                            wake.set()
            except ExpiredError as e:
                logger.warning(
                    "watch resume point expired (%s); re-listing via an "
                    "immediate reconcile pass",
                    e,
                )
                resume_rv = None
                # Drop the floors too: they hold the rv that just
                # expired, and the generic reconnect handler below would
                # otherwise resurrect it after a transient baseline-list
                # failure, forcing a guaranteed second 410/re-list cycle.
                floors = {}
                if self.informer is not None:
                    # The cache may have missed compacted deltas: mark it
                    # unsynced so reads pass through until the relist
                    # (the next sync() above) rebuilds it.
                    self.informer.invalidate()
                wake.set()
            except Exception as e:  # noqa: BLE001 — reconnect, don't die
                logger.warning("watch stream broke (%s); reconnecting", e)
                if floors:
                    resume_rv = min(floors.values())
                if self.informer is not None:
                    self.informer.stats["watch_reconnects"] += 1
                time.sleep(1.0)

    def run_forever(self) -> None:
        server = None
        if self.config.metrics_port is not None:
            server = MetricsServer(
                self.registry,
                self.config.metrics_port,
                bind_addr=self.config.metrics_bind_addr,
            )
            server.start()
        wake: Optional[threading.Event] = None
        if self.config.watch:
            wake = threading.Event()
            self._wake = wake
            if self.elector is not None:
                self._pump_gate = threading.Event()
            threading.Thread(
                target=self._watch_pump, args=(wake,), daemon=True
            ).start()
        logger.info(
            "upgrade controller started: ns=%s selector=%s interval=%.0fs "
            "watch=%s",
            self.config.namespace,
            self.config.driver_labels,
            self.config.interval_s,
            self.config.watch,
        )
        # Sharded mode: event-driven wakes run DIRTY passes (only the
        # touched pools); the periodic FULL resync — the safety net that
        # catches missed deltas, re-baselines the budget ledger, and
        # runs stuck detection — is paced by wall clock, NOT by the wait
        # expiring quietly.  The wait below restarts after every pass,
        # so under sustained watch traffic (routine on a 10k-node fleet)
        # it would never expire and the full pass would starve; instead
        # a full pass is forced whenever one hasn't SUCCEEDED within
        # interval_s, regardless of wake activity.
        woken = False
        last_full = float("-inf")
        try:
            while not self._stop:
                if self.elector is not None and not self._election_round():
                    # Standby: never reconcile without the lease; retry
                    # at the election cadence (the wait ends early on
                    # gaining leadership).
                    self._wait(self.elector.retry_period_s)
                    continue
                if wake is not None:
                    # Clear BEFORE reconciling: an event that lands
                    # mid-pass must trigger another pass, not be lost.
                    wake.clear()
                try:
                    full_due = (
                        time.monotonic() - last_full
                        >= self.config.interval_s
                    )
                    if self._sharded is not None and woken and not full_due:
                        self.reconcile_dirty()
                    elif self.reconcile_once():
                        last_full = time.monotonic()
                except Exception:  # noqa: BLE001 — loop must survive
                    logger.exception("reconcile pass failed")
                # Event-driven: wake on the first change; otherwise the
                # interval is the (resync) cadence.  Losing leadership
                # ends the wait and the top of the loop goes standby.
                woken = self._wait(self.config.interval_s, wake)
                if woken and self.config.watch_debounce_s > 0:
                    time.sleep(self.config.watch_debounce_s)
        finally:
            if self.elector is not None:
                # Clean shutdown hands the lease over immediately instead
                # of making the successor wait out the term.
                self.elector.release()
            if server is not None:
                server.stop()


def _parse_labels(raw: str) -> dict[str, str]:
    out: dict[str, str] = {}
    for part in raw.split(","):
        if not part:
            continue
        k, _, v = part.partition("=")
        out[k.strip()] = v.strip()
    return out


def load_policy(path: Optional[str]) -> DriverUpgradePolicySpec:
    if not path:
        return TPUUpgradePolicySpec(auto_upgrade=True)
    import yaml

    from k8s_operator_libs_tpu.api.schema import spec_schema, validate_object

    with open(path) as f:
        data = yaml.safe_load(f) or {}
    # Reject malformed policy with apiserver-style messages — the same
    # schema the generated CRD advertises (config/crd/), so a file that
    # loads here would also be admitted as a TPUUpgradePolicy CR.
    errors = validate_object(data, spec_schema(TPUUpgradePolicySpec))
    if errors:
        raise ValueError(
            f"invalid policy {path}: " + "; ".join(errors)
        )
    policy = TPUUpgradePolicySpec.from_dict(data)
    policy.validate()
    return policy


def main(argv: Optional[list[str]] = None) -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--namespace", default="kube-system")
    parser.add_argument(
        "--selector",
        default="app=libtpu-driver",
        help="driver pod label selector, k=v[,k2=v2]",
    )
    parser.add_argument("--driver-name", default="libtpu")
    parser.add_argument("--interval", type=float, default=30.0)
    parser.add_argument("--policy-file", default="")
    parser.add_argument("--metrics-port", type=int, default=None)
    parser.add_argument(
        "--metrics-bind-addr",
        default="127.0.0.1",
        help="address the /metrics + /healthz server binds "
        "(loopback by default; use 0.0.0.0 to expose beyond the pod)",
    )
    parser.add_argument(
        "--manage-daemonset",
        action="store_true",
        help="also reconcile the libtpu device-plugin DaemonSet",
    )
    parser.add_argument(
        "--manage-agent",
        action="store_true",
        help="also reconcile the health-probe-agent DaemonSet "
        "(DRIVER_REVISION follows the driver's ControllerRevision)",
    )
    parser.add_argument("--driver-image", default="")
    parser.add_argument("--driver-version", default="latest")
    parser.add_argument("--probe-interval", type=float, default=30.0)
    parser.add_argument(
        "--deep-probe",
        action="store_true",
        help="agents also run the ring-attention ICI soak",
    )
    parser.add_argument(
        "--policy-cr",
        default="",
        metavar="NAMESPACE/NAME",
        help="read the policy from a TPUUpgradePolicy CR each pass "
        "(requires config/crd/ installed) instead of --policy-file; "
        "upgrade counters are written back to the CR status",
    )
    parser.add_argument(
        "--watch",
        action="store_true",
        help="event-driven reconcile: watch nodes/pods/daemonsets (and "
        "the policy CR) and reconcile on change; --interval becomes the "
        "periodic-resync fallback",
    )
    parser.add_argument(
        "--sharded",
        action="store_true",
        help="sharded dirty-set reconcile (requires --watch): informer "
        "deltas feed a per-pool dirty queue; event-driven passes "
        "reconcile only the touched pools on parallel worker shards; "
        "--interval becomes the full-resync safety net",
    )
    parser.add_argument(
        "--shards",
        type=int,
        default=4,
        help="worker shards for --sharded (each pool is serialized onto "
        "at most one shard at a time)",
    )
    parser.add_argument(
        "--dry-run",
        action="store_true",
        help="build one read-only snapshot, print the analytic RollPlan "
        "(waves, per-wave durations, projected completion, holds, "
        "infeasibility) and exit without issuing a single API write verb",
    )
    parser.add_argument(
        "--score-policy",
        default="",
        metavar="FILE",
        help="what-if scoring: run the digital twin under the current "
        "policy and under FILE, print the makespan delta, and exit — "
        "zero API write verbs against the live cluster (same contract "
        "as --dry-run)",
    )
    parser.add_argument(
        "--leader-elect",
        action="store_true",
        help="run leader election over a coordination.k8s.io Lease and "
        "reconcile only while holding it (required with 2+ replicas)",
    )
    parser.add_argument(
        "--lease-name",
        default="tpu-upgrade-controller",
        help="Lease object name for --leader-elect",
    )
    parser.add_argument(
        "--lease-namespace",
        default="",
        help="Lease namespace (defaults to --namespace)",
    )
    args = parser.parse_args(argv)
    if args.policy_cr and args.policy_file:
        parser.error("--policy-cr and --policy-file are mutually exclusive")
    if args.sharded and not args.watch:
        parser.error("--sharded requires --watch (deltas feed the dirty set)")
    policy_ref = None
    if args.policy_cr:
        ns, sep, name = args.policy_cr.partition("/")
        if not sep or not ns or not name:
            parser.error("--policy-cr must look like NAMESPACE/NAME")
        policy_ref = (ns, name)

    from k8s_operator_libs_tpu.k8s import get_default_client

    ds_spec = None
    if args.manage_daemonset:
        ds_spec = DriverDaemonSetSpec(
            namespace=args.namespace,
            driver_name=args.driver_name,
            version=args.driver_version,
            **({"image": args.driver_image} if args.driver_image else {}),
        )
    agent_spec = None
    if args.manage_agent:
        agent_spec = AgentDaemonSetSpec(
            namespace=args.namespace,
            driver_name=args.driver_name,
            version=args.driver_version,
            probe_interval_s=args.probe_interval,
            deep=args.deep_probe,
            **({"image": args.driver_image} if args.driver_image else {}),
        )
    controller = UpgradeController(
        get_default_client(),
        ControllerConfig(
            namespace=args.namespace,
            driver_labels=_parse_labels(args.selector),
            driver_name=args.driver_name,
            interval_s=args.interval,
            policy=(
                None if policy_ref else load_policy(args.policy_file)
            ),
            daemonset_spec=ds_spec,
            agent_spec=agent_spec,
            metrics_port=args.metrics_port,
            metrics_bind_addr=args.metrics_bind_addr,
            policy_ref=policy_ref,
            watch=args.watch,
            sharded=args.sharded,
            reconcile_shards=args.shards,
            leader_elect=args.leader_elect,
            lease_name=args.lease_name,
            lease_namespace=args.lease_namespace or None,
        ),
    )
    if args.dry_run:
        print(controller.dry_run().render())
        return
    if args.score_policy:
        print(controller.score_policy(args.score_policy))
        return
    signal.signal(signal.SIGTERM, controller.stop)
    signal.signal(signal.SIGINT, controller.stop)
    controller.run_forever()


if __name__ == "__main__":
    main()
