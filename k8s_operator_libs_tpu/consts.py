"""Shared logging-verbosity constants.

Capability parity with the reference's ``pkg/consts/consts.go:24-29`` (logr
verbosity levels Error=-2 … Debug=1, zap-calibrated).  Python's stdlib
``logging`` uses the inverse convention (higher = more severe), so we map the
four levels onto stdlib levels and keep the reference's names so call sites
read the same.
"""

import logging

# Reference: pkg/consts/consts.go:24-29 (LogLevelError=-2 … LogLevelDebug=1).
# Mapped onto Python stdlib logging levels.
LOG_LEVEL_ERROR = logging.ERROR
LOG_LEVEL_WARNING = logging.WARNING
LOG_LEVEL_INFO = logging.INFO
LOG_LEVEL_DEBUG = logging.DEBUG


def get_logger(name: str = "tpu_operator_libs") -> logging.Logger:
    """Return the library logger (consumers configure handlers/levels)."""
    return logging.getLogger(name)
