"""Observability: counters as methods (reference parity) + Prometheus text.

The reference deliberately exposes upgrade counters as *methods* on the
state manager, leaving export to consumers (SURVEY.md §5, reference
upgrade_state.go:1038-1120 — no prometheus dependency anywhere).  We keep
that contract and additionally ship the thin exporter consumers always
end up writing: a snapshot-based registry rendering Prometheus text
exposition format, served by a stdlib HTTP thread.  Gauges are slice-
granular as well as node-granular, plus the north-star timing metrics
(reconcile duration, per-slice upgrade wall-clock, probe latency).
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from k8s_operator_libs_tpu.consts import get_logger
from k8s_operator_libs_tpu.upgrade.consts import UpgradeState

logger = get_logger(__name__)

PREFIX = "tpu_operator"


class MetricsRegistry:
    """Thread-safe gauge/counter store rendering Prometheus text format."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # name -> help text
        self._help: dict[str, str] = {}
        # name -> {label-tuple: value}
        self._values: dict[str, dict[tuple, float]] = defaultdict(dict)
        # name -> label key names
        self._label_keys: dict[str, tuple[str, ...]] = {}
        # name -> "counter" | "gauge" (drives the # TYPE line)
        self._kinds: dict[str, str] = {}
        # every describe() call in order — lets the registry self-lint
        # test catch a family registered twice
        self.described: list[str] = []

    def describe(
        self,
        name: str,
        help_text: str,
        *label_keys: str,
        kind: Optional[str] = None,
    ) -> None:
        """Register a family.  ``kind`` defaults by naming convention:
        families ending ``_total`` are counters, everything else a
        gauge — the registry self-lint pins that the convention and any
        explicit override agree."""
        with self._lock:
            self.described.append(name)
            self._help[name] = help_text
            self._label_keys[name] = tuple(label_keys)
            self._kinds[name] = kind or (
                "counter" if name.endswith("_total") else "gauge"
            )

    def kind(self, name: str) -> str:
        with self._lock:
            return self._kinds.get(name, "gauge")

    def _keys_for(self, name: str, labels: dict[str, str]) -> tuple:
        """Label keys for a metric; an undescribed metric adopts the keys
        of its first write (and keeps them), so render() never emits the
        same series with and without labels."""
        keys = self._label_keys.get(name)
        if keys is None:
            keys = tuple(sorted(labels))
            self._label_keys[name] = keys
        return keys

    def set(self, name: str, value: float, **labels: str) -> None:
        with self._lock:
            keys = self._keys_for(name, labels)
            self._values[name][tuple(labels.get(k, "") for k in keys)] = value

    def inc(self, name: str, delta: float = 1.0, **labels: str) -> None:
        with self._lock:
            keys = self._keys_for(name, labels)
            key = tuple(labels.get(k, "") for k in keys)
            self._values[name][key] = self._values[name].get(key, 0.0) + delta

    def clear(self, name: str) -> None:
        """Drop all series of a gauge (before re-publishing a snapshot, so
        removed slices/states don't linger)."""
        with self._lock:
            self._values[name] = {}

    def remove(self, name: str, **labels: str) -> None:
        """Drop one series of a gauge (e.g. a slice that is no longer
        stuck): the series disappears from render() instead of lingering
        at its last value."""
        with self._lock:
            keys = self._label_keys.get(name)
            if keys is None:
                return
            self._values[name].pop(
                tuple(labels.get(k, "") for k in keys), None
            )

    def render(self) -> str:
        with self._lock:
            lines: list[str] = []
            for name in sorted(self._values):
                full = f"{PREFIX}_{name}"
                if name in self._help:
                    lines.append(f"# HELP {full} {self._help[name]}")
                    kind = self._kinds.get(name, "gauge")
                    lines.append(f"# TYPE {full} {kind}")
                keys = self._label_keys.get(name, ())
                for label_vals, value in sorted(self._values[name].items()):
                    if keys:
                        rendered = ",".join(
                            f'{k}="{v}"' for k, v in zip(keys, label_vals)
                        )
                        lines.append(f"{full}{{{rendered}}} {value:g}")
                    else:
                        lines.append(f"{full} {value:g}")
            return "\n".join(lines) + "\n"


class UpgradeMetrics:
    """Publishes a state-manager snapshot into a registry each reconcile."""

    def __init__(self, registry: Optional[MetricsRegistry] = None) -> None:
        self.registry = registry or MetricsRegistry()
        r = self.registry
        r.describe(
            "nodes_by_state", "Managed nodes per upgrade state", "state"
        )
        r.describe(
            "slices_by_state", "Upgrade groups per effective state", "state"
        )
        r.describe("nodes_total", "Total managed nodes")
        r.describe("slices_total", "Total upgrade groups")
        r.describe("upgrades_in_progress", "Nodes in any in-progress state")
        r.describe("upgrades_done", "Nodes in upgrade-done")
        r.describe("upgrades_failed", "Nodes in upgrade-failed")
        r.describe("upgrades_pending", "Nodes in upgrade-required")
        r.describe(
            "reconcile_duration_seconds", "Last BuildState+ApplyState pass"
        )
        r.describe(
            "reconcile_total", "Reconcile passes since controller start"
        )
        r.describe(
            "slice_upgrade_seconds",
            "Wall-clock of each slice's last completed upgrade",
            "slice",
        )
        r.describe(
            "slice_stuck_seconds",
            "Dwell time of groups stuck in one in-progress state beyond "
            "the policy threshold (0 = not stuck)",
            "slice",
            "state",
        )
        r.describe(
            "slices_quarantined",
            "Groups currently parked in quarantined (mid-roll hardware "
            "loss; holds no unavailability budget)",
        )
        r.describe(
            "slice_quarantines_total",
            "Slice quarantine transitions since controller start",
        )
        r.describe(
            "slice_rejoins_total",
            "Slice rejoin-after-quarantine transitions since controller "
            "start",
        )
        r.describe(
            "eviction_escalations_total",
            "Eviction-ladder rung entries since controller start "
            "(re-seeded from persisted rung annotations on adoption)",
            "rung",
        )
        r.describe(
            "quarantine_cycle_demotions_total",
            "Slices demoted quarantined -> upgrade-failed after flapping "
            "across the configured number of dwell windows",
        )
        r.describe(
            "controller_adoptions_total",
            "Re-adoption passes run (one per leadership epoch / process "
            "start)",
        )
        r.describe(
            "controller_leader_term",
            "leaseTransitions number of the current leadership epoch",
        )
        r.describe(
            "api_circuit_open_endpoints",
            "API endpoints whose circuit breaker is currently open "
            "(>0 = reconcile degraded)",
        )
        r.describe(
            "api_request_retries_total",
            "Transient API failures retried by the client",
        )
        r.describe(
            "api_breaker_fast_fails_total",
            "API calls fast-failed because the endpoint circuit was open",
        )
        # Informer-backed cached reconcile surface (absent when the
        # manager reads through a raw client, i.e. polling mode).
        r.describe(
            "api_requests_per_tick",
            "API round trips issued during the last reconcile pass "
            "(all verbs; ~0 at steady state with a warm cache)",
        )
        r.describe(
            "api_writes_per_tick",
            "Mutating API round trips (patch/create/delete/evict/update) "
            "issued during the last reconcile pass — the write-path "
            "hygiene number the coalesced node patches drive down",
        )
        # Transactional write-plane surface (k8s/writeplan.py): absent
        # when the manager is an injected fake without a plan.
        r.describe(
            "writes_suppressed_total",
            "Writes skipped because the value already matched the cached "
            "object (no-op suppression, stage- and flush-time)",
        )
        r.describe(
            "writes_coalesced_total",
            "Extra key-groups folded into combined per-node metadata "
            "patches (round trips avoided by coalescing)",
        )
        r.describe(
            "writeplan_writes_total",
            "API writes issued by the write plane, by flow",
            "flow",
        )
        r.describe(
            "writeplan_flushes_total",
            "Write-plan flush batches executed",
        )
        r.describe(
            "writeplan_fenced_drops_total",
            "Queued write intents dropped whole at flush because the "
            "liveness or term fence said this process was deposed",
        )
        r.describe(
            "writeplan_conflict_replays_total",
            "409-conflicted patches replayed through quorum re-read + "
            "re-fence + re-dedupe (node and CR-status flows)",
        )
        r.describe(
            "writeplan_pending",
            "Write intents staged but not yet flushed, by kind",
            "kind",
        )
        r.describe(
            "events_published_total",
            "Cluster Events actually created by the write plane",
        )
        r.describe(
            "events_aggregated_total",
            "Event occurrences absorbed into count-carrying aggregates "
            "instead of separate Event objects (kubelet-style)",
        )
        r.describe(
            "flow_tokens",
            "Token-bucket level per APF flow",
            "flow",
        )
        r.describe(
            "flow_throttled",
            "1 when the flow's bucket is penalized below its base rate "
            "(429/Retry-After feedback), else 0",
            "flow",
        )
        r.describe(
            "flow_throttle_waits_total",
            "Times a mutating write waited on its bucket",
        )
        r.describe(
            "flow_deferred_total",
            "Status/event writes deferred to the next tick by a dry "
            "status bucket",
        )
        r.describe(
            "informer_cache_hits_total",
            "Hot-path reads served from the informer store",
        )
        r.describe(
            "informer_cache_misses_total",
            "Hot-path reads that fell through to the API (cold, stale, "
            "or absent object)",
        )
        r.describe(
            "informer_snapshot_age_seconds",
            "Seconds since the informer feed last heard from the "
            "apiserver (-1 = never synced)",
        )
        r.describe(
            "informer_lists_total",
            "Baseline LIST syncs the informer has performed",
        )
        r.describe(
            "informer_watch_reconnects_total",
            "Watch stream reconnects (resumed from the per-kind floor)",
        )
        r.describe(
            "informer_relists_total",
            "410-Gone invalidations that forced a full re-list",
        )
        # Sharded dirty-set reconcile surface (absent when the
        # controller runs the classic full-pass loop).
        r.describe(
            "reconcile_dirty_pools",
            "Pools walked by the last dirty tick (0 at steady state — "
            "the tick-cost-is-O(changed) evidence)",
        )
        r.describe(
            "reconcile_shard_busy",
            "Reconcile shards currently executing a pool pass",
        )
        r.describe(
            "reconcile_shards", "Configured reconcile shard count"
        )
        r.describe(
            "dirty_queue_depth", "Pools currently marked dirty (queued)"
        )
        r.describe(
            "dirty_queue_in_flight",
            "Pools claimed by a shard and not yet released",
        )
        r.describe(
            "dirty_queue_oldest_wait_seconds",
            "Age of the oldest still-queued dirty mark",
        )
        r.describe(
            "dirty_tick_duration_seconds",
            "Wall-clock of the last dirty tick (batch submit + wait)",
        )
        r.describe(
            "dirty_tick_max_queue_wait_seconds",
            "Longest time a pool in the last batch sat queued before a "
            "shard picked it up (queue latency)",
        )
        r.describe(
            "dirty_events_routed_total",
            "Watch deltas routed into the dirty set",
        )
        r.describe(
            "dirty_events_coalesced_total",
            "Routed deltas folded into an already-dirty pool entry",
        )
        r.describe(
            "dirty_pools_reconciled_total",
            "Pool-scoped reconcile passes completed by shards",
        )
        r.describe(
            "dirty_shard_errors_total",
            "Shard passes that crashed (pool requeued)",
        )
        r.describe(
            "dirty_shard_fenced_total",
            "Shard passes abandoned by the leadership fence",
        )
        r.describe(
            "dirty_pod_events_unrouted_total",
            "Pod deltas on nodes absent from the pool registry (covered "
            "by the node's own event or the next full resync)",
        )
        r.describe(
            "full_resyncs_total",
            "Periodic full-resync passes (safety net; re-seeds the pool "
            "registry and re-baselines the budget ledger)",
        )
        r.describe(
            "budget_unavailable_used",
            "Unavailability units currently charged in the shared "
            "maxUnavailable ledger (claims + external faults)",
        )
        r.describe(
            "budget_unavailable_cap",
            "Effective maxUnavailable cap the ledger enforces",
        )
        r.describe(
            "budget_parallel_used",
            "Groups currently holding an in-progress budget claim",
        )
        # Materialized-view surface (upgrade/matview.py): the O(delta)
        # incremental read path.  The view is an optimization, never an
        # authority — hits vs fallbacks show how often ticks avoided a
        # full scoped build, diff mismatches count every disagreement
        # the resync audit found (each one also triggered a fail-open
        # reseed).
        r.describe(
            "matview_hits_total",
            "Pool reconciles served from the materialized view "
            "(O(changed-objects) build, no informer re-scan)",
        )
        r.describe(
            "matview_fallback_rebuilds_total",
            "Pool reconciles that fell back to a full scoped "
            "build_state (view unseeded / stale / invalidated)",
        )
        r.describe(
            "matview_diff_mismatches_total",
            "View-vs-build_state disagreements found by the full-resync "
            "audit (each batch triggers a fail-open reseed)",
        )
        r.describe(
            "matview_pools",
            "Pools currently materialized in the view",
        )
        r.describe(
            "matview_rows",
            "Node rows currently materialized in the view",
        )
        r.describe(
            "matview_interned_strings",
            "Distinct strings in the view's intern pool (state labels, "
            "pool keys)",
        )
        r.describe(
            "matview_apply_latency_us",
            "Mean per-delta view apply latency in microseconds "
            "(runs under the informer lock; must stay O(1))",
        )
        # Multi-artifact stack surface (artifacts/ + the engine's
        # POD_RESTART_REQUIRED stepping; absent on single-artifact
        # policies, where the DAG of size 1 IS the classic path).
        r.describe(
            "artifact_synced_nodes",
            "Nodes whose pod for this artifact is at the target "
            "revision (vacuously synced nodes included)",
            "artifact",
        )
        r.describe(
            "artifact_nodes",
            "Nodes in groups currently stepping through this artifact",
            "artifact",
        )
        r.describe(
            "artifact_skew_holds_total",
            "Pod restarts withheld because a pinned-order edge put the "
            "artifact at a later level than the group's cursor",
            "artifact",
        )
        r.describe(
            "artifact_gate_holds_total",
            "Times an artifact's network-path gate held the stack at "
            "its edge (probe failed or errored; fail-closed)",
            "artifact",
        )
        r.describe(
            "artifact_rollbacks_total",
            "Multi-artifact rollbacks unwound in reverse topological "
            "order after a crash-looping artifact pod",
        )
        r.describe(
            "artifact_shared_window_savings_total",
            "Node cordon/drain windows avoided by rolling the whole "
            "stack inside one window (nodes x extra artifacts)",
        )
        # Fused probe-battery surface (health.fused; absent when the
        # controller never probed in-process, e.g. NodeReportProber-only
        # deployments where the agents run the battery instead).
        r.describe(
            "probe_battery_seconds",
            "Last fused-battery phase duration (compile is 0 on a "
            "topology-keyed cache hit)",
            "phase",
        )
        r.describe(
            "probe_battery_cache_hits_total",
            "Fused-battery dispatches served by the topology-keyed "
            "compile cache",
        )
        r.describe(
            "probe_battery_cache_misses_total",
            "Fused-battery compiles (first sight of a topology key)",
        )
        r.describe(
            "probe_battery_fallbacks_total",
            "Fused-battery failures that fell back to the unfused probes",
        )
        r.describe(
            "probe_battery_cached_programs",
            "Distinct topology keys currently held in the compile cache",
        )
        # Elastic roll coordination surface (absent on injected fakes
        # and on controllers with `elastic` disabled in policy).
        r.describe(
            "elastic_negotiations_total",
            "Exclusion-offer negotiations settled since controller start",
            "outcome",
        )
        r.describe(
            "elastic_resizes_total",
            "Workload mesh resizes completed (down = slice excluded, "
            "up = slice rejoined)",
            "direction",
        )
        r.describe(
            "elastic_resize_seconds",
            "Offer-to-resize-complete wall-clock of the last workload "
            "mesh resize (annotation epochs, 1s resolution)",
        )
        r.describe(
            "elastic_excluded_slices",
            "Slices currently excluded from their workload's mesh "
            "(rolling without budget charge)",
        )
        r.describe(
            "validation_wall_seconds",
            "Wall-clock of each slice's last passed validation gate "
            "(stamp -> healthy verdict, including async probe queueing)",
            "slice",
        )
        # Heterogeneous-fleet surface.
        r.describe(
            "preemptions_total",
            "Preempted in-flight slices observed, per generation "
            "(fast-path handling: no quarantine, budget released)",
            "generation",
        )
        r.describe(
            "fleet_nodes",
            "Managed nodes per device generation",
            "generation",
        )
        r.describe(
            "fleet_pool_window_open",
            "1 when the pool's maintenance window is open (or it has "
            "none), 0 while its groups hold in window-wait",
            "pool",
        )
        r.describe(
            "fleet_window_held_groups",
            "Groups currently holding in the budget-free window-wait "
            "condition",
        )
        r.describe(
            "fleet_window_invalid",
            "1 while the pool's maintenanceWindow cron fails to parse at "
            "runtime (the engine fails OPEN; see WindowCronInvalid events)",
            "pool",
        )
        # Predictive rollout-planning surface (planning/; absent until a
        # roll is active and the drift watchdog has anchored a plan).
        r.describe(
            "fleet_roll_infeasible",
            "1 per structural reason the active roll can provably never "
            "finish (window-starvation, budget-deadlock, "
            "elastic-decline-storm)",
            "reason",
        )
        r.describe(
            "plan_waves",
            "Upgrade waves in the anchored roll plan",
        )
        r.describe(
            "plan_groups",
            "Groups covered by the anchored roll plan",
        )
        r.describe(
            "plan_completed_groups",
            "Planned groups that have reached upgrade-done",
        )
        r.describe(
            "plan_projected_completion_timestamp_seconds",
            "Projected roll completion (unix epoch), drift-adjusted",
        )
        r.describe(
            "plan_drift_seconds",
            "Lateness of the next planned completion (positive = behind "
            "plan, negative = ahead)",
        )
        r.describe(
            "plan_infeasible",
            "Count of structural plan-infeasibility reasons currently "
            "detected (0 = the roll can finish)",
        )
        r.describe(
            "plan_replans_total",
            "Bounded re-plans triggered by drift over threshold",
        )
        # Plan-guided admission surface (planning.admissionMode).
        r.describe(
            "admission_mode",
            "1 for the admission ordering the engine used on its last "
            "pass: packed (plan-guided first-fit-decreasing) or greedy "
            "(generation/id order; also the fallback when no fresh plan "
            "is anchored)",
            "mode",
        )
        r.describe(
            "budget_saturation",
            "Fraction of the unavailability budget in use after the last "
            "admission pass (used / cap)",
        )
        r.describe(
            "budget_idle_ticks_total",
            "Admission passes that ended with idle budget despite an "
            "admissible group having been denied earlier in the same "
            "pass — structurally 0; any increase is an admission bug",
        )
        r.describe(
            "admission_packed_total",
            "Groups admitted under packed (plan-guided) ordering",
        )
        r.describe(
            "budget_wakeups_targeted_total",
            "Budget-release wakeups routed to the planned-next wave's "
            "pools only (vs blanket-waking every denied waiter)",
        )
        r.describe(
            "budget_wakeups_deferred_total",
            "Denied waiters re-queued (not woken) by a targeted "
            "budget-release wakeup; they re-enter on the next release "
            "or full resync",
        )
        # Fleet health telemetry surface (obs/telemetry; absent on
        # injected fake managers without the plane wired).
        r.describe(
            "node_health_score",
            "Per-node health score (100 = at fleet baseline; 12.5 points "
            "lost per robust-z of the worst below-baseline stat)",
            "node",
        )
        r.describe(
            "fleet_stragglers",
            "Nodes holding a confirmed straggler verdict (sustained "
            "below-baseline probe telemetry), per cohort",
            "generation",
            "pool",
        )
        r.describe(
            "probe_measured",
            "Fleet median of each measured probe statistic's latest "
            "per-node sample",
            "check",
            "stat",
        )
        r.describe(
            "telemetry_samples_total",
            "Probe-battery telemetry samples ingested into per-node "
            "histories",
        )
        r.describe(
            "telemetry_drops_total",
            "Telemetry-plane fail-open exceptions swallowed (capture, "
            "persistence, or adoption path)",
        )
        r.describe(
            "federation_cluster_health",
            "Member-cluster control-plane health ladder rung "
            "(0=Reachable, 1=Degraded, 2=Partitioned)",
            "cluster",
            "region",
        )
        r.describe(
            "federation_cluster_done",
            "1 when every group in the member cluster reached "
            "upgrade-done this federated roll",
            "cluster",
        )
        r.describe(
            "federation_frozen_groups",
            "Budget charges held fail-static for a partitioned member "
            "cluster (released only on heal-time re-adoption)",
            "cluster",
        )
        r.describe(
            "federation_probes_total",
            "Cross-cluster reachability probes issued by the registry",
        )
        r.describe(
            "federation_probe_failures_total",
            "Reachability probes that failed (hard or breaker-open)",
        )
        r.describe(
            "federation_partitions_total",
            "Member clusters stepped onto the Partitioned rung",
        )
        r.describe(
            "federation_heals_total",
            "Member clusters stepped back off the Partitioned rung",
        )
        r.describe(
            "federation_phase",
            "Federated roll phase (1 on the current phase's series)",
            "phase",
        )
        r.describe(
            "federation_canary_held",
            "1 while the canary gate holds promotion on a confirmed "
            "telemetry regression",
        )
        r.describe(
            "federation_canary_holds_total",
            "Canary promotion holds latched over the coordinator's "
            "lifetime",
        )
        r.describe(
            "federation_soak_remaining_seconds",
            "Seconds of clean canary soak still required before "
            "promotion",
        )
        r.describe(
            "federation_budget_unavailable_used",
            "Units currently charged against the global unavailability "
            "budget across all member clusters",
        )
        r.describe(
            "federation_budget_unavailable_cap",
            "Global unavailability budget cap in units",
        )
        r.describe(
            "federation_budget_parallel_used",
            "Groups concurrently in flight against the global parallel "
            "cap",
        )
        r.describe(
            "federation_budget_denials_total",
            "Admission attempts denied by the global budget hierarchy",
        )
        r.describe(
            "federation_budget_violations_total",
            "Non-forced grants observed above the global cap (must stay "
            "0)",
        )
        r.describe(
            "federation_store_writes_total",
            "Writes the durable federation state store issued (phase "
            "edges only, never per tick)",
        )
        # api_requests_per_tick baseline: total verb count at the end of
        # the previous observe() call.
        self._last_api_total: Optional[float] = None
        # api_writes_per_tick baseline, write verbs only.
        self._last_api_writes: Optional[float] = None

    def observe(self, manager, state, duration_s: float) -> None:
        r = self.registry
        r.clear("nodes_by_state")
        r.clear("slices_by_state")
        for st in UpgradeState:
            label = st.value or "unknown"
            r.set(
                "nodes_by_state", len(state.nodes_in(st)), state=label
            )
            r.set(
                "slices_by_state", len(state.groups_in(st)), state=label
            )
        r.set("nodes_total", manager.get_total_managed_nodes(state))
        r.set("slices_total", manager.get_total_managed_groups(state))
        r.set("upgrades_in_progress", manager.get_upgrades_in_progress(state))
        r.set("upgrades_done", manager.get_upgrades_done(state))
        r.set("upgrades_failed", manager.get_upgrades_failed(state))
        r.set("upgrades_pending", manager.get_upgrades_pending(state))
        r.set("reconcile_duration_seconds", duration_s)
        r.inc("reconcile_total")
        # Data-plane fault-tolerance surface (absent on injected fakes).
        r.set(
            "slices_quarantined",
            len(state.groups_in(UpgradeState.QUARANTINED)),
        )
        r.set(
            "slice_quarantines_total",
            getattr(manager, "quarantines_total", 0),
        )
        r.set("slice_rejoins_total", getattr(manager, "rejoins_total", 0))
        r.set(
            "quarantine_cycle_demotions_total",
            getattr(manager, "quarantine_cycle_demotions", 0),
        )
        # Multi-artifact stack surface (absent on injected fakes and a
        # no-op for single-artifact policies, whose progress dict stays
        # empty).  Per-artifact gauges republish as a snapshot so a
        # finished stack's series don't linger.
        progress = getattr(manager, "artifact_progress", None)
        if progress is not None:
            r.clear("artifact_synced_nodes")
            r.clear("artifact_nodes")
            for name, (synced, total) in sorted(progress.items()):
                r.set("artifact_synced_nodes", synced, artifact=name)
                r.set("artifact_nodes", total, artifact=name)
            for name, count in sorted(
                getattr(manager, "artifact_skew_holds", {}).items()
            ):
                r.set("artifact_skew_holds_total", count, artifact=name)
            for name, count in sorted(
                getattr(manager, "artifact_gate_holds", {}).items()
            ):
                r.set("artifact_gate_holds_total", count, artifact=name)
            r.set(
                "artifact_rollbacks_total",
                getattr(manager, "artifact_rollbacks_total", 0),
            )
            r.set(
                "artifact_shared_window_savings_total",
                getattr(manager, "artifact_window_savings", 0),
            )
        negotiations = getattr(manager, "elastic_negotiations", None)
        if negotiations is not None:
            for outcome, count in sorted(negotiations.items()):
                r.set("elastic_negotiations_total", count, outcome=outcome)
        resizes = getattr(manager, "elastic_resizes", None)
        if resizes is not None:
            for direction, count in sorted(resizes.items()):
                r.set("elastic_resizes_total", count, direction=direction)
            r.set(
                "elastic_resize_seconds",
                getattr(manager, "elastic_resize_seconds", 0.0),
            )
        excluded_check = getattr(manager, "_group_elastic_excluded", None)
        if excluded_check is not None:
            excluded = {
                group.id
                for groups in state.groups.values()
                for group in groups
                if excluded_check(group)
            }
            r.set("elastic_excluded_slices", len(excluded))
        esc_stats = getattr(manager, "escalation_stats", None)
        if esc_stats is not None and hasattr(esc_stats, "snapshot"):
            for rung, count in sorted(esc_stats.snapshot().items()):
                r.set("eviction_escalations_total", count, rung=rung)
        # Plan-guided admission surface (absent on injected fakes).
        astats = getattr(manager, "admission_stats", None)
        if astats is not None:
            cap = astats.get("last_budget_cap", 0)
            if cap:
                r.set(
                    "budget_saturation",
                    astats.get("last_budget_used", 0) / cap,
                )
            r.set(
                "budget_idle_ticks_total",
                astats.get("budget_idle_ticks", 0),
            )
            r.set(
                "admission_packed_total", astats.get("packed_admitted", 0)
            )
            mode = getattr(manager, "admission_mode", "greedy")
            r.clear("admission_mode")
            r.set("admission_mode", 1.0, mode=mode)
        # Client resilience surface (present on RestClient and
        # ResilientClient; absent on a bare FakeCluster).
        client = getattr(manager, "client", None)
        breaker = getattr(client, "breaker", None)
        if breaker is not None and hasattr(breaker, "open_endpoints"):
            r.set(
                "api_circuit_open_endpoints", len(breaker.open_endpoints())
            )
        retry_stats = getattr(client, "retry_stats", None)
        if retry_stats is not None:
            r.set(
                "api_request_retries_total", retry_stats.get("retries", 0)
            )
            r.set(
                "api_breaker_fast_fails_total",
                retry_stats.get("breaker_fast_fail", 0),
            )
        # Cached-reconcile surface.  ``client.stats`` counts actual API
        # round trips per verb (a CachedKubeClient delegates the attr to
        # its inner client), so the delta across observe() calls is the
        # API cost of the tick that just ran — the number the informer
        # exists to drive to ~0 at steady state.
        api_stats = getattr(client, "stats", None)
        if api_stats is not None and hasattr(api_stats, "values"):
            total = float(sum(api_stats.values()))
            if self._last_api_total is not None:
                r.set(
                    "api_requests_per_tick", total - self._last_api_total
                )
            self._last_api_total = total
            # Write verbs only.  Stats keys are "patch_node" style on the
            # fake cluster and "PATCH nodes" style on the REST client, so
            # a case-insensitive prefix match covers both.
            writes = float(
                sum(
                    v
                    for k, v in api_stats.items()
                    if str(k)
                    .lower()
                    .startswith(
                        (
                            "patch",
                            "create",
                            "delete",
                            "evict",
                            "update",
                            "post",
                            "put",
                        )
                    )
                )
            )
            if self._last_api_writes is not None:
                r.set("api_writes_per_tick", writes - self._last_api_writes)
            self._last_api_writes = writes
        # Transactional write-plane surface (k8s/writeplan.py).
        plan = getattr(manager, "write_plan", None)
        if plan is not None and hasattr(plan, "counters"):
            c = plan.counters()
            r.set("writes_suppressed_total", c.get("suppressed", 0))
            r.set("writes_coalesced_total", c.get("coalesced_keys", 0))
            r.set("writeplan_flushes_total", c.get("flushes", 0))
            r.set(
                "writeplan_writes_total",
                c.get("writes_mutating", 0),
                flow="mutating",
            )
            r.set(
                "writeplan_writes_total",
                c.get("writes_status", 0),
                flow="status",
            )
            r.set(
                "writeplan_fenced_drops_total",
                c.get("fenced_drops", 0)
                + c.get("fenced_drops_status", 0)
                + c.get("fenced_drops_events", 0),
            )
            r.set(
                "writeplan_conflict_replays_total",
                c.get("conflict_replays", 0)
                + c.get("status_conflict_replays", 0),
            )
            r.set("events_published_total", c.get("events_published", 0))
            r.set("events_aggregated_total", c.get("events_aggregated", 0))
            r.set(
                "flow_throttle_waits_total",
                c.get("throttle_waits_mutating", 0),
            )
            r.set("flow_deferred_total", c.get("deferred_status", 0))
            for kind, depth in sorted(plan.pending_depth().items()):
                r.set("writeplan_pending", depth, kind=kind)
            for flow, fs in sorted(plan.flows.state().items()):
                r.set("flow_tokens", fs.get("tokens", 0.0), flow=flow)
                r.set("flow_throttled", fs.get("throttled", 0.0), flow=flow)
        # Heterogeneous-fleet surface.
        preemptions = getattr(manager, "preemptions", None)
        if preemptions is not None:
            for gen, count in sorted(preemptions.items()):
                r.set("preemptions_total", count, generation=gen or "unknown")
        try:
            from k8s_operator_libs_tpu.fleet.profiles import generation_of
        except Exception:  # noqa: BLE001 — keep metrics best-effort
            generation_of = None
        if generation_of is not None:
            gen_nodes: dict = {}
            for groups in state.groups.values():
                for group in groups:
                    accel = getattr(
                        getattr(group, "slice_info", None), "accelerator", ""
                    )
                    gen = generation_of(accel or "") or "unknown"
                    gen_nodes[gen] = gen_nodes.get(gen, 0) + group.size()
            r.clear("fleet_nodes")
            for gen, count in sorted(gen_nodes.items()):
                r.set("fleet_nodes", count, generation=gen)
        window_open = getattr(manager, "pool_window_open", None)
        if window_open is not None:
            r.clear("fleet_pool_window_open")
            for pool, is_open in sorted(window_open.items()):
                r.set(
                    "fleet_pool_window_open",
                    1 if is_open else 0,
                    pool=pool,
                )
        r.set(
            "fleet_window_held_groups",
            getattr(manager, "window_held_groups", 0),
        )
        cron_invalid = getattr(manager, "window_cron_invalid", None)
        if cron_invalid is not None:
            r.clear("fleet_window_invalid")
            for pool in sorted(cron_invalid):
                r.set("fleet_window_invalid", 1, pool=pool)
        # Fused-battery surface: import lazily so a controller built
        # without jax (pure NodeReportProber aggregation) still exports
        # everything else.
        try:
            from k8s_operator_libs_tpu.health.fused import battery_stats
        except Exception:  # noqa: BLE001 — jax/libtpu absent is fine
            battery_stats = None
        if battery_stats is not None:
            bstats = battery_stats()
            if bstats.get("compile_cache_hits") or bstats.get(
                "compile_cache_misses"
            ):
                r.set(
                    "probe_battery_seconds",
                    bstats.get("last_compile_ms", 0.0) / 1000.0,
                    phase="compile",
                )
                r.set(
                    "probe_battery_seconds",
                    bstats.get("last_execute_ms", 0.0) / 1000.0,
                    phase="execute",
                )
                r.set(
                    "probe_battery_cache_hits_total",
                    bstats.get("compile_cache_hits", 0),
                )
                r.set(
                    "probe_battery_cache_misses_total",
                    bstats.get("compile_cache_misses", 0),
                )
                r.set(
                    "probe_battery_fallbacks_total",
                    bstats.get("fallbacks", 0),
                )
                r.set(
                    "probe_battery_cached_programs",
                    bstats.get("cached_programs", 0),
                )
        vm = getattr(manager, "validation_manager", None)
        for gid, wall in getattr(vm, "validation_wall_s", {}).items():
            r.set("validation_wall_seconds", wall, slice=gid)
        informer = getattr(client, "informer", None)
        if informer is not None and hasattr(informer, "stats"):
            istats = informer.stats
            r.set("informer_cache_hits_total", istats.get("cache_hits", 0))
            r.set(
                "informer_cache_misses_total",
                istats.get("cache_misses", 0),
            )
            r.set("informer_lists_total", istats.get("lists", 0))
            r.set(
                "informer_watch_reconnects_total",
                istats.get("watch_reconnects", 0),
            )
            r.set("informer_relists_total", istats.get("relists_410", 0))
            age = informer.age_s()
            r.set(
                "informer_snapshot_age_seconds",
                age if age != float("inf") else -1.0,
            )

    def observe_plan(self, report) -> None:
        """Publish the drift watchdog's verdict (a planning.DriftReport).

        An inactive report clears the whole surface so a finished roll's
        ETA does not linger as a stale promise.
        """
        r = self.registry
        if report is None or not report.active:
            for name in (
                "plan_waves",
                "plan_groups",
                "plan_completed_groups",
                "plan_projected_completion_timestamp_seconds",
                "plan_drift_seconds",
                "plan_infeasible",
            ):
                r.clear(name)
            return
        r.set("plan_waves", report.wave_count)
        r.set("plan_groups", report.planned_groups)
        r.set("plan_completed_groups", report.completed_groups)
        r.set(
            "plan_projected_completion_timestamp_seconds",
            report.projected_completion_epoch,
        )
        r.set("plan_drift_seconds", report.drift_seconds)
        r.set("plan_infeasible", len(report.infeasible))
        r.set("plan_replans_total", report.replans)

    def observe_trace(self, manager, breakdown=None) -> None:
        """Publish the roll-tracing surface (obs/): recorder health
        (open spans, fail-open drops), flight-recorder activity (dumps
        per trigger reason, spool footprint), and — once a roll
        completes — its critical-path makespan buckets.  Everything here
        is getattr-guarded: injected fake managers without the obs
        wiring publish nothing."""
        r = self.registry
        rec = getattr(manager, "trace_recorder", None)
        if rec is not None:
            r.set("trace_spans_open", rec.open_span_count())
            r.set("trace_drops_total", rec.drops)
            r.set("trace_active", 1.0 if rec.active else 0.0)
        fr = getattr(manager, "flight_recorder", None)
        if fr is not None:
            for reason, count in sorted(fr.dumps_total.items()):
                r.set("flightrec_dumps_total", count, reason=reason)
            r.set("flightrec_throttled_total", fr.throttled_total)
            r.set("flightrec_note_drops_total", fr.note_drops)
            r.set("flightrec_spool_bytes", fr.spool_bytes())
        if breakdown:
            r.set(
                "roll_makespan_seconds",
                breakdown.get("makespanSeconds", 0.0),
            )
            for bucket, seconds in sorted(
                (breakdown.get("buckets") or {}).items()
            ):
                r.set(
                    "roll_makespan_bucket_seconds", seconds, bucket=bucket
                )

    def observe_telemetry(self, manager) -> None:
        """Publish the fleet-health telemetry surface (obs/telemetry):
        per-node health scores, confirmed stragglers per cohort, and the
        fleet median of each measured probe stat.  Gauges are cleared
        first so departed nodes and cohorts don't linger.  getattr-
        guarded: injected fake managers without the plane publish
        nothing."""
        plane = getattr(manager, "telemetry_plane", None)
        if plane is None:
            return
        r = self.registry
        view = plane.metrics_view()
        r.clear("node_health_score")
        for node, score in sorted(view["scores"].items()):
            r.set("node_health_score", score, node=node)
        r.clear("fleet_stragglers")
        for (generation, pool), count in sorted(
            view["stragglers"].items()
        ):
            r.set(
                "fleet_stragglers", count, generation=generation, pool=pool
            )
        r.clear("probe_measured")
        for (check, stat), value in sorted(view["measured"].items()):
            r.set("probe_measured", value, check=check, stat=stat)
        r.set("telemetry_samples_total", view["samples_total"])
        r.set("telemetry_drops_total", view["drops"])

    def observe_federation(self, coordinator) -> None:
        """Publish the federated control-plane surface (federation/):
        the per-cluster health ladder, fail-static freeze depth, the
        canary gate, and the global budget hierarchy's counters.
        Cleared-then-set for every labelled family so removed clusters
        and stale phases don't linger.  getattr-guarded like the other
        observe_* hooks: a bare manager publishes nothing."""
        registry = getattr(coordinator, "registry", None)
        if registry is None:
            return
        r = self.registry
        rung = {"Reachable": 0.0, "Degraded": 1.0, "Partitioned": 2.0}
        healths = registry.healths()
        done = getattr(coordinator, "_done", {})
        r.clear("federation_cluster_health")
        r.clear("federation_cluster_done")
        r.clear("federation_frozen_groups")
        for member in registry.members():
            health = healths[member.name].value
            r.set(
                "federation_cluster_health",
                rung.get(health, 2.0),
                cluster=member.name,
                region=member.region,
            )
            r.set(
                "federation_cluster_done",
                1.0 if done.get(member.name) else 0.0,
                cluster=member.name,
            )
            r.set(
                "federation_frozen_groups",
                len(member.frozen_groups),
                cluster=member.name,
            )
        stats = registry.stats
        r.set("federation_probes_total", stats.get("probes", 0))
        r.set(
            "federation_probe_failures_total",
            stats.get("probe_failures", 0),
        )
        r.set("federation_partitions_total", stats.get("partitions", 0))
        r.set("federation_heals_total", stats.get("heals", 0))
        r.clear("federation_phase")
        r.set("federation_phase", 1.0, phase=coordinator.phase)
        gate = getattr(coordinator, "gate", None)
        if gate is not None:
            verdict = gate.evaluate()
            r.set(
                "federation_canary_held",
                1.0 if gate.held is not None else 0.0,
            )
            r.set("federation_canary_holds_total", gate.holds_total)
            r.set(
                "federation_soak_remaining_seconds",
                round(verdict.soak_remaining_s, 3),
            )
        ledger = getattr(coordinator, "global_ledger", None)
        if ledger is not None:
            r.set(
                "federation_budget_unavailable_used",
                ledger.unavailable_used(),
            )
            r.set(
                "federation_budget_unavailable_cap",
                ledger.max_unavailable,
            )
            r.set(
                "federation_budget_parallel_used", ledger.parallel_used()
            )
            r.set("federation_budget_denials_total", ledger.denials)
            r.set(
                "federation_budget_violations_total", ledger.violations
            )
        store = getattr(coordinator, "store", None)
        if store is not None:
            r.set(
                "federation_store_writes_total",
                getattr(store, "writes", 0),
            )

    def observe_sharded(self, sharded, report=None) -> None:
        """Publish the sharded-reconcile surface.  Called with a
        TickReport after each dirty tick, and without one after a full
        resync (queue/ledger gauges still refresh there)."""
        r = self.registry
        r.set("reconcile_shards", sharded.shards)
        r.set("reconcile_shard_busy", sharded.busy_shards())
        r.set("dirty_queue_depth", sharded.queue.depth())
        r.set("dirty_queue_in_flight", sharded.queue.in_flight())
        r.set(
            "dirty_queue_oldest_wait_seconds",
            sharded.queue.oldest_wait_s(),
        )
        qstats = sharded.queue.stats
        r.set(
            "dirty_events_routed_total", qstats.get("events_routed", 0)
        )
        r.set(
            "dirty_events_coalesced_total",
            qstats.get("events_coalesced", 0),
        )
        r.set(
            "dirty_pod_events_unrouted_total",
            sharded.router.stats.get("pod_events_unrouted", 0),
        )
        sstats = sharded.stats
        r.set(
            "dirty_pools_reconciled_total",
            sstats.get("pools_reconciled", 0),
        )
        r.set("dirty_shard_errors_total", sstats.get("shard_errors", 0))
        r.set("dirty_shard_fenced_total", sstats.get("fenced", 0))
        r.set("full_resyncs_total", sstats.get("full_resyncs", 0))
        r.set(
            "budget_wakeups_targeted_total",
            sstats.get("budget_wakeups_targeted", 0),
        )
        r.set(
            "budget_wakeups_deferred_total",
            sstats.get("budget_wakeups_deferred", 0),
        )
        ledger = sharded.ledger
        r.set("budget_unavailable_used", ledger.unavailable_used())
        r.set("budget_unavailable_cap", ledger.max_unavailable)
        r.set("budget_parallel_used", ledger.parallel_used())
        view = getattr(sharded, "matview", None)
        if view is not None:
            r.set("matview_hits_total", sstats.get("matview_hits", 0))
            r.set(
                "matview_fallback_rebuilds_total",
                sstats.get("matview_fallbacks", 0),
            )
            r.set(
                "matview_diff_mismatches_total",
                view.stats.get("diff_mismatches", 0),
            )
            vstats = view.snapshot_stats()
            r.set("matview_pools", vstats["pools"])
            r.set("matview_rows", vstats["rows"])
            r.set(
                "matview_interned_strings", vstats["interned_strings"]
            )
            r.set(
                "matview_apply_latency_us",
                round(vstats["apply_avg_us"], 3),
            )
        if report is not None:
            r.set("reconcile_dirty_pools", report.pools_walked)
            r.set("dirty_tick_duration_seconds", report.duration_s)
            r.set(
                "dirty_tick_max_queue_wait_seconds",
                report.max_queue_wait_s,
            )


class SliceUpgradeTimer:
    """Tracks per-slice upgrade wall-clock: starts when a slice leaves
    done/unknown, stops when it returns to done — the north-star number."""

    # Snapshots a group must be absent from before its in-flight entry is
    # pruned: a mid-upgrade group can transiently vanish from ONE snapshot
    # (its driver pod recreated and briefly unscheduled), and pruning on
    # first miss would restart the clock and under-report the outage.
    PRUNE_AFTER_MISSES = 3

    def __init__(self, registry: MetricsRegistry) -> None:
        self.registry = registry
        self._started: dict[str, float] = {}
        self._misses: dict[str, int] = {}

    def observe_state(self, state) -> None:
        # Groups arrive pre-bucketed by effective state in state.groups.
        now = time.monotonic()
        seen: set[str] = set()
        for label, groups in state.groups.items():
            # upgrade-failed counts as in-flight: dwell time in failed IS
            # wall-clock the slice was disrupted, and a failed-then-
            # recovered upgrade should report its full outage.
            in_flight = label not in ("", UpgradeState.DONE.value)
            for group in groups:
                seen.add(group.id)
                if in_flight and group.id not in self._started:
                    self._started[group.id] = now
                elif not in_flight and group.id in self._started:
                    elapsed = now - self._started.pop(group.id)
                    self.registry.set(
                        "slice_upgrade_seconds", elapsed, slice=group.id
                    )
        # Prune groups that stay vanished from the snapshot (deleted node
        # pool, relabeled slice): a long-lived controller must not leak
        # entries, and a re-created slice id must not inherit a stale
        # start time.  Absence must persist PRUNE_AFTER_MISSES snapshots —
        # a transiently-invisible mid-upgrade group keeps its clock.
        for gid in set(self._started) - seen:
            self._misses[gid] = self._misses.get(gid, 0) + 1
            if self._misses[gid] >= self.PRUNE_AFTER_MISSES:
                del self._started[gid]
                del self._misses[gid]
        for gid in list(self._misses):
            if gid in seen or gid not in self._started:
                self._misses.pop(gid, None)


class MetricsServer:
    """Serve the registry at /metrics (plus a /healthz liveness probe)
    on a stdlib HTTP thread.  Binds loopback by default — exposing the
    scrape endpoint beyond the pod is an explicit deployment decision
    (``--metrics-bind-addr 0.0.0.0``), not a side effect."""

    def __init__(
        self,
        registry: MetricsRegistry,
        port: int = 0,
        bind_addr: str = "127.0.0.1",
    ) -> None:
        registry_ref = registry

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802
                path = self.path.rstrip("/")
                if path == "/healthz":
                    body = b"ok\n"
                    content_type = "text/plain"
                elif path in ("", "/metrics"):
                    body = registry_ref.render().encode()
                    content_type = "text/plain; version=0.0.4"
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):
                pass

        self._server = ThreadingHTTPServer((bind_addr, port), Handler)
        self.bind_addr = bind_addr
        self.port = self._server.server_port
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )

    def start(self) -> None:
        self._thread.start()
        logger.info(
            "metrics listening on %s:%d/metrics (liveness at /healthz)",
            self.bind_addr,
            self.port,
        )

    def stop(self) -> None:
        self._server.shutdown()
