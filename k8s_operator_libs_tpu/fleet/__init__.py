"""Heterogeneous fleet subsystem: per-generation hardware profiles,
generation-aware roll ordering, and maintenance-window math.

Real TPU fleets run several device generations concurrently (v2 through
Trillium), each with its own peak TFLOPs, HBM bandwidth, ICI fabric,
power envelope, and failure characteristics.  This package is the layer
above ``hw.ChipSpec`` that makes the rest of the operator aware of that:

- :mod:`.profiles` — the :class:`~.profiles.GenerationProfile` registry
  (chips-per-host, expected ICI bandwidth, per-generation probe floors,
  power weight, preemptible capability);
- :mod:`.scheduler` — deterministic oldest-generation-first,
  efficiency-weighted ordering for groups and dirty pools;
- :mod:`.windows` — cron-style UTC maintenance-window membership used by
  the per-pool ``maintenanceWindow`` policy field.
"""

from k8s_operator_libs_tpu.fleet.profiles import (  # noqa: F401
    GenerationProfile,
    generation_of,
    generation_profile,
    known_generations,
    register_generation,
)
from k8s_operator_libs_tpu.fleet.scheduler import (  # noqa: F401
    generation_order_key,
    group_sort_key,
    order_groups,
    pool_sort_key,
)
from k8s_operator_libs_tpu.fleet.windows import (  # noqa: F401
    validate_window,
    window_open,
)
