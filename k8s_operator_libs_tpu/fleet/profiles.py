"""Per-generation hardware profiles: the registry above ``hw.ChipSpec``.

``hw.ChipSpec`` answers "what is one chip's published peak"; a
:class:`GenerationProfile` answers the fleet-level questions the operator
asks about a *generation*: how many chips share a host, what the ICI
fabric should sustain, where the health-probe floors sit, how much power
the generation burns per unit of work (the retirement-ordering weight),
and whether the capacity class is preemptible.

Probe floors live here — not as global constants — so a v5e pool is not
judged against v5p bandwidth and vice versa.  The fused probe battery
already isolates compile caches per ``device_kind`` (health.fused
``BatteryKey``); this registry gives the same key a place to resolve
thresholds from.

Resolution accepts anything ``hw.chip_spec`` accepts: a
``jax.Device.device_kind`` string (``"TPU v5 lite"``) or a GKE
accelerator label (``"tpu-v5-lite-podslice"``).  Unknown kinds resolve
to None and callers skip generation-relative behavior, same contract as
``chip_spec``.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Optional

from k8s_operator_libs_tpu.hw import ChipSpec, chip_spec

# Default floor fractions, applied to the chip's published peak when a
# profile does not pin explicit values: sustained readings below half of
# spec on hardware that enumerates fine are exactly the
# silent-degradation mode the probes exist to catch (hw.py rationale),
# and ICI floors are more conservative because collective bus bandwidth
# degrades with real topology/congestion long before the links are sick.
HBM_FLOOR_FRACTION = 0.5
MXU_FLOOR_FRACTION = 0.5
ICI_FLOOR_FRACTION = 0.25


@dataclass(frozen=True)
class GenerationProfile:
    """One TPU generation's fleet-level operating envelope.

    ``order`` is the generation's age rank (lower = older hardware) and
    drives oldest-first canary ordering; ``watts_per_chip`` is the
    approximate board power used as the efficiency weight (watt-hungry
    generations' downtime is retired first among equals).  Both are
    scheduling inputs, not billing figures.
    """

    name: str
    chip: ChipSpec
    # Hosts of the standard podslice machine shape.
    chips_per_host: int
    # Aggregate per-chip ICI bandwidth the fabric should sustain, GB/s
    # (published interconnect figures, one-way aggregate per chip).
    ici_gbps: float
    # Approximate board power per chip, watts (efficiency weight).
    watts_per_chip: float
    # Generation age rank for canary ordering (lower = older).
    order: int
    # Whether this generation is commonly run as preemptible/spot
    # capacity; advisory metadata surfaced in status — the preemption
    # *signal* on a node is always authoritative regardless.
    preemptible: bool = False
    # Per-generation probe thresholds.  0.0 = derive from the chip spec
    # with the default fractions at resolve time.
    mxu_tflops_floor: float = 0.0
    hbm_gbps_floor: float = 0.0
    ici_busbw_floor_gbps: float = 0.0
    # Ceiling on a small all-reduce's latency, milliseconds; generous
    # defaults — the probe exists to catch order-of-magnitude stalls
    # (a wedged ICI retransmit path), not to benchmark the fabric.
    allreduce_latency_ceiling_ms: float = field(default=2000.0)

    def hbm_floor(self, fraction: float = 0.0) -> float:
        """Effective HBM bandwidth floor, GB/s.  An explicit ``fraction``
        (the policy-configured knob) wins; else the profile's pinned
        floor; else the default fraction of chip spec."""
        if fraction:
            return fraction * self.chip.hbm_gbps
        if self.hbm_gbps_floor:
            return self.hbm_gbps_floor
        return HBM_FLOOR_FRACTION * self.chip.hbm_gbps

    def mxu_floor(self) -> float:
        """MXU matmul throughput floor, TFLOPs."""
        if self.mxu_tflops_floor:
            return self.mxu_tflops_floor
        return MXU_FLOOR_FRACTION * self.chip.bf16_tflops

    def ici_floor(self) -> float:
        """ICI all-reduce bus-bandwidth floor, GB/s."""
        if self.ici_busbw_floor_gbps:
            return self.ici_busbw_floor_gbps
        return ICI_FLOOR_FRACTION * self.ici_gbps


# Canonical generation name (ChipSpec.name) -> profile.  ICI figures are
# the published aggregate interconnect bandwidths (v4 2400 Gbps/chip,
# v5e 1600, v5p 4800, v6e 3584 — converted to GB/s); power figures are
# approximate public board numbers, used only as relative weights.
_BUILTIN_PROFILES: tuple[GenerationProfile, ...] = (
    GenerationProfile(
        name="v2", chip=chip_spec("tpu v2"), chips_per_host=4,
        ici_gbps=62.0, watts_per_chip=280.0, order=2,
    ),
    GenerationProfile(
        name="v3", chip=chip_spec("tpu v3"), chips_per_host=4,
        ici_gbps=112.0, watts_per_chip=220.0, order=3,
    ),
    GenerationProfile(
        name="v4", chip=chip_spec("tpu v4"), chips_per_host=4,
        ici_gbps=300.0, watts_per_chip=192.0, order=4,
    ),
    GenerationProfile(
        name="v5e", chip=chip_spec("tpu v5e"), chips_per_host=4,
        ici_gbps=200.0, watts_per_chip=130.0, order=5,
        preemptible=True,
    ),
    GenerationProfile(
        name="v5p", chip=chip_spec("tpu v5p"), chips_per_host=4,
        ici_gbps=600.0, watts_per_chip=350.0, order=6,
    ),
    GenerationProfile(
        name="v6e", chip=chip_spec("tpu v6e"), chips_per_host=4,
        ici_gbps=448.0, watts_per_chip=170.0, order=7,
        preemptible=True,
    ),
)

_LOCK = threading.Lock()
_PROFILES: dict[str, GenerationProfile] = {
    p.name: p for p in _BUILTIN_PROFILES
}


def register_generation(profile: GenerationProfile) -> None:
    """Add (or replace) a generation profile — the extensibility hook for
    generations this table predates.  The profile's ``chip.name`` should
    match ``profile.name`` so ``chip_spec`` resolution finds it."""
    with _LOCK:
        _PROFILES[profile.name] = profile


def known_generations() -> list[GenerationProfile]:
    """All registered profiles, oldest generation first."""
    with _LOCK:
        return sorted(_PROFILES.values(), key=lambda p: (p.order, p.name))


def generation_profile(device_kind: str) -> Optional[GenerationProfile]:
    """Profile for a device-kind string or GKE accelerator label, or None
    when the generation is unknown (CPU test meshes)."""
    spec = chip_spec(device_kind)
    if spec is None:
        return None
    with _LOCK:
        return _PROFILES.get(spec.name)


def generation_of(device_kind: str) -> str:
    """Canonical generation name ("v5e"), or "" when unknown."""
    profile = generation_profile(device_kind)
    return profile.name if profile is not None else ""
