"""Cron-style UTC maintenance-window membership.

A pool's ``maintenanceWindow.cron`` is a standard 5-field cron
expression (minute hour day-of-month month day-of-week, UTC) read as a
*membership test*: the window is OPEN at instant T iff every field of
T's UTC breakdown matches the expression.  ``"* 2-5 * * 6,0"`` therefore
means "02:00–05:59 UTC on weekends" — the natural way to write a
maintenance window without a separate duration field, and crash-safe for
free because openness is a pure function of the clock (no state to
persist across controller incarnations).

Standard cron quirks are honored: ``*``, lists, ranges, steps
(``*/15``), day-of-week 0 and 7 both meaning Sunday, and the dom/dow OR
rule (when *both* are restricted, matching either opens the window).
"""

from __future__ import annotations

import calendar
import time

# Scanning horizon for next_open: a full leap cycle covers every
# reachable (month, dom, dow) combination, so a window that has not
# opened within it never opens (e.g. "0 0 31 2 *" — Feb 31).
NEXT_OPEN_HORIZON_S = 4 * 366 * 86400.0

# Field index -> (low, high) inclusive bounds, standard cron order.
_BOUNDS = ((0, 59), (0, 23), (1, 31), (1, 12), (0, 7))
_FIELD_NAMES = ("minute", "hour", "day-of-month", "month", "day-of-week")


def _parse_field(text: str, lo: int, hi: int, name: str) -> frozenset[int]:
    """Expand one cron field into the set of matching values."""
    values: set[int] = set()
    for part in text.split(","):
        part = part.strip()
        if not part:
            raise ValueError(f"empty element in cron {name} field {text!r}")
        step = 1
        if "/" in part:
            part, step_text = part.split("/", 1)
            if not step_text.isdigit() or int(step_text) < 1:
                raise ValueError(
                    f"invalid step {step_text!r} in cron {name} field"
                )
            step = int(step_text)
        if part == "*":
            start, end = lo, hi
        elif "-" in part:
            a, b = part.split("-", 1)
            if not (a.isdigit() and b.isdigit()):
                raise ValueError(
                    f"invalid range {part!r} in cron {name} field"
                )
            start, end = int(a), int(b)
        elif part.isdigit():
            start = end = int(part)
        else:
            raise ValueError(f"invalid value {part!r} in cron {name} field")
        if start > end or start < lo or end > hi:
            raise ValueError(
                f"cron {name} value {part!r} out of range [{lo}, {hi}]"
            )
        values.update(range(start, end + 1, step))
    return frozenset(values)


def _parse(cron: str) -> tuple[frozenset[int], ...]:
    fields = cron.split()
    if len(fields) != 5:
        raise ValueError(
            f"cron expression must have 5 fields, got {len(fields)}: {cron!r}"
        )
    return tuple(
        _parse_field(text, lo, hi, name)
        for text, (lo, hi), name in zip(fields, _BOUNDS, _FIELD_NAMES)
    )


def validate_window(cron: str) -> None:
    """Raise ValueError when ``cron`` is not a valid 5-field expression."""
    _parse(cron)


def window_open(cron: str, now: float | None = None) -> bool:
    """True when the UTC instant ``now`` (epoch seconds; default current
    time) falls inside the window described by ``cron``."""
    minute_f, hour_f, dom_f, month_f, dow_f = _parse(cron)
    t = time.gmtime(time.time() if now is None else now)
    if t.tm_min not in minute_f or t.tm_hour not in hour_f:
        return False
    if t.tm_mon not in month_f:
        return False
    # Cron dow: 0 and 7 are both Sunday; struct_time wday: Monday=0.
    dow = (t.tm_wday + 1) % 7
    dom_ok = t.tm_mday in dom_f
    dow_ok = dow in dow_f or (dow == 0 and 7 in dow_f)
    dom_restricted = dom_f != frozenset(range(1, 32))
    dow_restricted = dow_f != frozenset(range(0, 8))
    if dom_restricted and dow_restricted:
        # Standard cron OR rule when both are restricted.
        return dom_ok or dow_ok
    return dom_ok and dow_ok


def _day_matches(
    t: time.struct_time,
    dom_f: frozenset[int],
    month_f: frozenset[int],
    dow_f: frozenset[int],
) -> bool:
    """The date part of the membership test (same dom/dow OR rule as
    :func:`window_open`), independent of the time of day."""
    if t.tm_mon not in month_f:
        return False
    dow = (t.tm_wday + 1) % 7
    dom_ok = t.tm_mday in dom_f
    dow_ok = dow in dow_f or (dow == 0 and 7 in dow_f)
    dom_restricted = dom_f != frozenset(range(1, 32))
    dow_restricted = dow_f != frozenset(range(0, 8))
    if dom_restricted and dow_restricted:
        return dom_ok or dow_ok
    return dom_ok and dow_ok


def next_open(
    cron: str,
    now: float | None = None,
    horizon_s: float = NEXT_OPEN_HORIZON_S,
) -> float | None:
    """Earliest UTC epoch second ≥ ``now`` at which the window is open,
    or None when it never opens within ``horizon_s`` (a provably
    unreachable window — e.g. ``"0 0 31 2 *"``).

    Deterministic and pure (clock in, epoch out), so the planner can
    project wave start times and admission can reject never-opening
    windows without waiting on wall-clock."""
    minute_f, hour_f, dom_f, month_f, dow_f = _parse(cron)
    now = time.time() if now is None else now
    if window_open(cron, now):
        return now
    hours = sorted(hour_f)
    minutes = sorted(minute_f)
    # Scan day by day from the current UTC midnight: cheap (≤ ~1464
    # struct_time conversions over the full horizon) and immune to the
    # varying month/DST-free UTC day lengths.
    t0 = time.gmtime(now)
    day_start = calendar.timegm(
        (t0.tm_year, t0.tm_mon, t0.tm_mday, 0, 0, 0, 0, 0, 0)
    )
    deadline = now + horizon_s
    day = float(day_start)
    while day <= deadline:
        if _day_matches(time.gmtime(day), dom_f, month_f, dow_f):
            for hour in hours:
                for minute in minutes:
                    candidate = day + hour * 3600 + minute * 60
                    if candidate >= now:
                        return candidate if candidate <= deadline else None
        day += 86400.0
    return None
