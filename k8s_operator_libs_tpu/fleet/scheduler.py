"""Generation-aware roll ordering.

Mixed-generation fleets want the oldest generation upgraded FIRST: it is
the cheapest canary (least valuable capacity, most battle-tested driver
path) and the first to surface a bad driver before it reaches the
flagship pools.  Among generations of equal age rank, the watt-hungrier
one goes first — its downtime is the most expensive to leave pending.

Everything here is a pure function of node labels (accelerator string →
profile), so the ordering is deterministic across controller
incarnations and trivially term-fence-safe: a deposed leader and its
successor compute the same sort key from the same observed state, and
the key never encodes wall-clock or identity.

Two consumers:

- the unsharded engine sorts ``upgrade-required`` groups with
  :func:`group_sort_key` before admission, so budget slots drain
  oldest-generation-first;
- the sharded reconciler passes :func:`pool_sort_key` (closed over its
  router's pool→accelerator memory) as the dirty-queue sort key, so
  dirty pools of older generations are reconciled first when the queue
  is deeper than one tick's batch.
"""

from __future__ import annotations

from typing import Callable, Iterable, Optional

from k8s_operator_libs_tpu.fleet.profiles import generation_profile

# Unknown generations (CPU meshes, unmapped accelerators) sort AFTER
# every known one: an unknown canary proves nothing.
_UNKNOWN_ORDER = 1 << 16


def generation_order_key(device_kind: str) -> tuple[int, float, str]:
    """Deterministic sort key for one generation: (age rank, -watts,
    name).  Lower sorts first: oldest generation, then watt-hungriest."""
    profile = generation_profile(device_kind)
    if profile is None:
        return (_UNKNOWN_ORDER, 0.0, device_kind or "")
    return (profile.order, -profile.watts_per_chip, profile.name)


def group_sort_key(group) -> tuple:
    """Sort key for an UpgradeGroup: generation key, then group id for a
    total deterministic order within a generation."""
    accelerator = ""
    if group.slice_info is not None:
        accelerator = group.slice_info.accelerator or ""
    return generation_order_key(accelerator) + (group.id,)


def order_groups(groups: Iterable) -> list:
    """Groups ordered oldest-generation-first (stable, deterministic)."""
    return sorted(groups, key=group_sort_key)


def packed_group_sort_key(group, cost: int) -> tuple:
    """First-fit-decreasing admission key (planning.admissionMode:
    packed), shared by the analytic packer and the live admission pass.

    The generation key stays primary — oldest-generation-first is
    inviolable, so a younger generation is only ever *tried* after
    every older group was tried (and admitted or found unchargeable).
    Within a generation, larger groups go first so smaller ones fill
    the residual budget instead of stranding it; id breaks ties for a
    total deterministic order."""
    accelerator = ""
    if group.slice_info is not None:
        accelerator = group.slice_info.accelerator or ""
    return generation_order_key(accelerator) + (-cost, group.id)


def pool_sort_key(
    accelerator_of: Callable[[str], Optional[str]],
) -> Callable[[str], tuple]:
    """Build the dirty-pool sort key for the sharded reconciler.

    ``accelerator_of`` maps a pool key to the accelerator string the
    router last observed for it (None when the pool has no recorded
    generation — such pools sort last, after every known generation)."""

    def key(pool_key: str) -> tuple:
        return generation_order_key(accelerator_of(pool_key) or "") + (
            pool_key,
        )

    return key
