"""Deployment manifests for the controller and probe agents.

The reference is a library and ships no manifests — its consumers (GPU /
Network Operator) own deployment.  Here the consumer operator is in-repo
(controller.py), so the install surface is too: ServiceAccounts, RBAC
scoped to exactly the verbs the engine issues on the wire (pinned by
tests/test_manifests.py, which records a full rolling upgrade through
RestClient and asserts every observed verb is granted — an ungranted new
verb fails the suite, an unused grant is flagged), and the controller
Deployment.  Rendered to config/manifests/ by ``tools/gen_manifests.py``
(drift-checked in CI via ``make generate-check``).
"""

from __future__ import annotations

from typing import Any, Optional

from k8s_operator_libs_tpu.api.schema import POLICY_GROUP, POLICY_PLURAL

CONTROLLER_NAME = "tpu-upgrade-controller"
# Shared by the driver pods (safe-load init container sets/polls its node
# annotation) and the health-agent pods (publish report annotations):
# both only ever get/patch their own Node.  DriverDaemonSetSpec defaults
# its pods onto this ServiceAccount.
NODE_REPORTER_NAME = "tpu-node-reporter"
DEFAULT_IMAGE = "tpu-operator-libs:latest"

# The controller's API surface.  Every (group, resource, verb) the engine
# can issue; see RestClient methods and _stat_key kinds.
CONTROLLER_RBAC_RULES: list[dict[str, Any]] = [
    # BuildState reads + cordon/uncordon + state-label/annotation writes.
    {"apiGroups": [""], "resources": ["nodes"], "verbs": ["get", "list", "patch"]},
    # Pod snapshots, wait-for-jobs checks, driver-pod restarts.
    {"apiGroups": [""], "resources": ["pods"], "verbs": ["get", "list", "delete"]},
    # Drain + workload eviction go through the Eviction subresource.
    {"apiGroups": [""], "resources": ["pods/eviction"], "verbs": ["create"]},
    # Transition/failure events (kubectl describe node shows them).
    {"apiGroups": [""], "resources": ["events"], "verbs": ["create"]},
    # Driver/agent DaemonSet reconciliation.
    {
        "apiGroups": ["apps"],
        "resources": ["daemonsets"],
        "verbs": ["get", "list", "create", "update"],
    },
    # The outdated-pod detector reads ControllerRevisions.
    {
        "apiGroups": ["apps"],
        "resources": ["controllerrevisions"],
        "verbs": ["get", "list"],
    },
    # Policy-as-CR mode: read the spec, publish counters to status.
    {
        "apiGroups": [POLICY_GROUP],
        "resources": [POLICY_PLURAL],
        "verbs": ["get", "list"],
    },
    {
        "apiGroups": [POLICY_GROUP],
        "resources": [f"{POLICY_PLURAL}/status"],
        "verbs": ["update"],
    },
]

# Namespaced (Role, not ClusterRole): leader election touches exactly one
# Lease in the install namespace — a cluster-wide lease grant would let a
# compromised controller pod rewrite kube-node-lease heartbeats or hijack
# other components' elections.
CONTROLLER_NAMESPACED_RULES: list[dict[str, Any]] = [
    {
        "apiGroups": ["coordination.k8s.io"],
        "resources": ["leases"],
        "verbs": ["get", "create", "update"],
    },
]

# Driver safe-load init containers and per-host agents only read their
# own Node and patch annotations on it.
NODE_REPORTER_RBAC_RULES: list[dict[str, Any]] = [
    {"apiGroups": [""], "resources": ["nodes"], "verbs": ["get", "patch"]},
]


def _service_account(name: str, namespace: str) -> dict:
    return {
        "apiVersion": "v1",
        "kind": "ServiceAccount",
        "metadata": {"name": name, "namespace": namespace},
    }


def _cluster_role(name: str, rules: list[dict]) -> dict:
    return {
        "apiVersion": "rbac.authorization.k8s.io/v1",
        "kind": "ClusterRole",
        "metadata": {"name": name},
        "rules": rules,
    }


def _role(name: str, namespace: str, rules: list[dict]) -> dict:
    return {
        "apiVersion": "rbac.authorization.k8s.io/v1",
        "kind": "Role",
        "metadata": {"name": name, "namespace": namespace},
        "rules": rules,
    }


def _role_binding(name: str, sa: str, namespace: str) -> dict:
    return {
        "apiVersion": "rbac.authorization.k8s.io/v1",
        "kind": "RoleBinding",
        "metadata": {"name": name, "namespace": namespace},
        "roleRef": {
            "apiGroup": "rbac.authorization.k8s.io",
            "kind": "Role",
            "name": name,
        },
        "subjects": [
            {
                "kind": "ServiceAccount",
                "name": sa,
                "namespace": namespace,
            }
        ],
    }


def _cluster_role_binding(name: str, sa: str, namespace: str) -> dict:
    return {
        "apiVersion": "rbac.authorization.k8s.io/v1",
        "kind": "ClusterRoleBinding",
        "metadata": {"name": name},
        "roleRef": {
            "apiGroup": "rbac.authorization.k8s.io",
            "kind": "ClusterRole",
            "name": name,
        },
        "subjects": [
            {
                "kind": "ServiceAccount",
                "name": sa,
                "namespace": namespace,
            }
        ],
    }


def controller_deployment(
    namespace: str,
    image: str,
    policy_cr: Optional[str] = None,
) -> dict:
    """Two-replica controller Deployment under leader election.

    All state lives in cluster labels and passes are idempotent, so even
    concurrent controllers only race benignly (chaos tier) — but the
    Lease keeps exactly one replica reconciling while the standby buys
    fast failover (clean shutdown releases the lease; a crash hands over
    after the term lapses)."""
    args = [
        "--namespace",
        namespace,
        "--manage-daemonset",
        "--manage-agent",
        "--metrics-port",
        "8081",
        "--leader-elect",
    ]
    if policy_cr:
        args += ["--policy-cr", policy_cr]
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {
            "name": CONTROLLER_NAME,
            "namespace": namespace,
            "labels": {"app": CONTROLLER_NAME},
        },
        "spec": {
            "replicas": 2,
            "selector": {"matchLabels": {"app": CONTROLLER_NAME}},
            "template": {
                "metadata": {"labels": {"app": CONTROLLER_NAME}},
                "spec": {
                    "serviceAccountName": CONTROLLER_NAME,
                    "containers": [
                        {
                            "name": "controller",
                            "image": image,
                            "command": [
                                "python",
                                "-m",
                                "k8s_operator_libs_tpu.controller",
                            ],
                            "args": args,
                            "ports": [
                                {"name": "metrics", "containerPort": 8081}
                            ],
                            "resources": {
                                "requests": {
                                    "cpu": "100m",
                                    "memory": "256Mi",
                                },
                                "limits": {"memory": "1Gi"},
                            },
                        }
                    ],
                },
            },
        },
    }


def controller_manifests(
    namespace: str = "kube-system",
    image: str = DEFAULT_IMAGE,
    policy_cr: Optional[str] = None,
) -> list[dict]:
    """Everything `kubectl apply` needs besides the CRD (config/crd/)."""
    return [
        _service_account(CONTROLLER_NAME, namespace),
        _cluster_role(CONTROLLER_NAME, CONTROLLER_RBAC_RULES),
        _cluster_role_binding(CONTROLLER_NAME, CONTROLLER_NAME, namespace),
        _role(CONTROLLER_NAME, namespace, CONTROLLER_NAMESPACED_RULES),
        _role_binding(CONTROLLER_NAME, CONTROLLER_NAME, namespace),
        _service_account(NODE_REPORTER_NAME, namespace),
        _cluster_role(NODE_REPORTER_NAME, NODE_REPORTER_RBAC_RULES),
        _cluster_role_binding(
            NODE_REPORTER_NAME, NODE_REPORTER_NAME, namespace
        ),
        controller_deployment(namespace, image, policy_cr),
    ]


# -- verb-coverage helpers (used by tests and gen tooling) -------------------

# RestClient._stat_key kind -> (apiGroup, resource).
_KIND_TO_RESOURCE = {
    "nodes": ("", "nodes"),
    "pods": ("", "pods"),
    "eviction": ("", "pods/eviction"),
    "events": ("", "events"),
    "daemonsets": ("apps", "daemonsets"),
    "controllerrevisions": ("apps", "controllerrevisions"),
    POLICY_PLURAL: (POLICY_GROUP, POLICY_PLURAL),
    f"{POLICY_PLURAL}/status": (POLICY_GROUP, f"{POLICY_PLURAL}/status"),
    "leases": ("coordination.k8s.io", "leases"),
}

_METHOD_TO_VERBS = {
    # A GET is a get or a list; RBAC needs whichever was used — we map to
    # both alternatives and accept either grant.
    "GET": ("get", "list"),
    "PATCH": ("patch",),
    "DELETE": ("delete",),
    "POST": ("create",),
    "PUT": ("update",),
}


def required_grants(stat_keys) -> set[tuple[str, str, tuple[str, ...]]]:
    """Map RestClient.stats keys ("GET nodes") to (group, resource,
    acceptable-verbs) requirements."""
    out = set()
    for key in stat_keys:
        method, _, kind = key.partition(" ")
        resource = _KIND_TO_RESOURCE.get(kind)
        verbs = _METHOD_TO_VERBS.get(method)
        if resource is None or verbs is None:
            raise ValueError(f"unmapped stat key {key!r}")
        out.add((resource[0], resource[1], verbs))
    return out


def rule_grants(rules: list[dict]) -> set[tuple[str, str, str]]:
    return {
        (group, resource, verb)
        for rule in rules
        for group in rule["apiGroups"]
        for resource in rule["resources"]
        for verb in rule["verbs"]
    }


def uncovered(stat_keys, rules: list[dict]) -> list[str]:
    """Requirements from observed traffic that no rule grants."""
    granted = rule_grants(rules)
    missing = []
    for group, resource, verbs in sorted(required_grants(stat_keys)):
        if not any((group, resource, v) in granted for v in verbs):
            missing.append(f"{group or 'core'}/{resource}: needs one of {verbs}")
    return missing
