"""Ring attention: context parallelism over the ICI torus.

Long-context sequence parallelism for the canary (and for consumers'
JAX workloads): the sequence dimension is sharded across the mesh's
``sp`` axis, and K/V blocks rotate around the ring via ``ppermute``
while each device accumulates its queries' attention with online
(flash-style) softmax — attention over a sequence n× longer than any
single device could hold, with compute overlapping the neighbor-to-
neighbor ICI transfers (the pallas-guide "ring collectives" pattern,
expressed at the XLA level: static ``fori_loop``, one ``ppermute`` per
step, no data-dependent shapes).

This doubles as the framework's ICI *soak* test: unlike one psum, a ring
pass per step keeps every directed link under sustained load for
``n_devices`` rounds — the traffic shape of real long-context training —
so the health backend exposes it as the optional deep probe
(``ici_ring_attention``) behind the quick all-reduce gate.

Numerics: online-softmax accumulation in f32; QK^T and PV matmuls in
bf16 with f32 accumulation (MXU contract).  Verified exactly against
single-device full attention in tests.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# shard_map's import path moved across the jax versions this library
# runs against; resolve the newest spelling first (same shim as
# health.probes — duplicated to keep workloads free of health imports).
try:
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover — older jax
    from jax.experimental.shard_map import shard_map

NEG_INF = -1e30


def _pvary(x, axis_name):
    """Mark a value device-varying over ``axis_name`` (API moved from
    lax.pvary to lax.pcast(..., to='varying') in newer jax)."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, (axis_name,), to="varying")
    if hasattr(jax.lax, "pvary"):
        return jax.lax.pvary(x, axis_name)
    return x  # older jax: shard_map values are implicitly varying


def _block_attention(q, k, v, mask):
    """One (q-block × kv-block) attention contribution.

    q: [B, Sq, H, D], k/v: [B, Sk, H, D], mask: [Sq, Sk] bool.
    Returns (numerator [B, Sq, H, D], row_max [B, Sq, H],
    row_sum [B, Sq, H]) for online-softmax merging."""
    scores = jnp.einsum(
        "bqhd,bkhd->bqhk",
        q.astype(jnp.bfloat16),
        k.astype(jnp.bfloat16),
        preferred_element_type=jnp.float32,
    ) * (q.shape[-1] ** -0.5)
    scores = jnp.where(mask[None, :, None, :], scores, NEG_INF)
    m = jnp.max(scores, axis=-1)  # [B, Sq, H]
    # Rows with no visible keys: exp(NEG_INF - NEG_INF) would be 1; pin
    # the max to 0 so those rows contribute exp(NEG_INF) = 0.
    m = jnp.where(m <= NEG_INF / 2, 0.0, m)
    p = jnp.exp(scores - m[..., None])  # [B, Sq, H, Sk]
    num = jnp.einsum(
        "bqhk,bkhd->bqhd",
        p.astype(jnp.bfloat16),
        v.astype(jnp.bfloat16),
        preferred_element_type=jnp.float32,
    )
    return num, m, jnp.sum(p, axis=-1)


def _merge(acc_num, acc_m, acc_den, num, m, den):
    """Merge a new block into the online-softmax accumulator."""
    new_m = jnp.maximum(acc_m, m)
    a = jnp.exp(acc_m - new_m)
    b = jnp.exp(m - new_m)
    return (
        acc_num * a[..., None] + num * b[..., None],
        new_m,
        acc_den * a + den * b,
    )


def ring_attention_sharded(q, k, v, axis_name: str, causal: bool = True):
    """Attention over the full (ring-distributed) sequence.

    Runs INSIDE shard_map: q/k/v are the local sequence shards
    [B, S_local, H, D]; the full sequence is ``n * S_local`` in ring
    order (shard i holds positions [i*S_local, (i+1)*S_local)).  K/V
    rotate ``n`` times via ppermute; queries never move."""
    n = jax.lax.psum(1, axis_name)
    rank = jax.lax.axis_index(axis_name)
    B, S, H, D = q.shape
    perm = [(i, (i + 1) % n) for i in range(n)]

    pos_q = jnp.arange(S)
    pos_k = jnp.arange(S)

    def mask_for(kv_rank):
        if not causal:
            return jnp.ones((S, S), jnp.bool_)
        # Global positions: q at rank*S + i, kv block at kv_rank*S + j.
        gq = rank * S + pos_q[:, None]
        gk = kv_rank * S + pos_k[None, :]
        return gq >= gk

    # pvary: the accumulators become device-varying inside the loop (the
    # mask depends on axis_index), so the carry must start varying too or
    # shard_map's varying-axes check rejects the fori_loop.
    acc_num = _pvary(jnp.zeros((B, S, H, D), jnp.float32), axis_name)
    acc_m = _pvary(jnp.full((B, S, H), NEG_INF, jnp.float32), axis_name)
    acc_den = _pvary(jnp.zeros((B, S, H), jnp.float32), axis_name)

    def step(i, carry):
        acc_num, acc_m, acc_den, cur_k, cur_v = carry
        # After i rotations each device holds the block that started at
        # rank - i (mod n).
        kv_rank = (rank - i) % n
        num, m, den = _block_attention(q, cur_k, cur_v, mask_for(kv_rank))
        acc_num, acc_m, acc_den = _merge(
            acc_num, acc_m, acc_den, num, m, den
        )
        # Rotate K/V to the next rank (skip the final, unused rotation
        # would be an optimization; keeping it static-shape uniform).
        cur_k = jax.lax.ppermute(cur_k, axis_name, perm)
        cur_v = jax.lax.ppermute(cur_v, axis_name, perm)
        return acc_num, acc_m, acc_den, cur_k, cur_v

    acc_num, acc_m, acc_den, _, _ = jax.lax.fori_loop(
        0, n, step, (acc_num, acc_m, acc_den, k, v)
    )
    den = jnp.where(acc_den == 0.0, 1.0, acc_den)
    return (acc_num / den[..., None]).astype(q.dtype)


def full_attention_reference(q, k, v, causal: bool = True):
    """Single-device full attention with the same bf16/f32 contract —
    the numerical ground truth ring attention must match."""
    S = q.shape[1]
    mask = (
        jnp.tril(jnp.ones((S, S), jnp.bool_))
        if causal
        else jnp.ones((S, S), jnp.bool_)
    )
    num, m, den = _block_attention(q, k, v, mask)
    den = jnp.where(den == 0.0, 1.0, den)
    return (num / den[..., None]).astype(q.dtype)


def make_ring_attention(
    mesh: Mesh, axis_name: str = "sp", causal: bool = True
):
    """Jitted ring attention over ``mesh``'s ``axis_name``: takes GLOBAL
    [B, S, H, D] arrays sequence-sharded over the axis and returns the
    sequence-sharded attention output."""
    spec = P(None, axis_name, None, None)

    fn = jax.jit(
        shard_map(
            partial(ring_attention_sharded, axis_name=axis_name,
                    causal=causal),
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
        )
    )

    def shard(x):
        return jax.device_put(x, NamedSharding(mesh, spec))

    return fn, shard


def ring_attention_soak(
    devices: Optional[Sequence[jax.Device]] = None,
    seq_per_device: int = 128,
    batch: int = 1,
    heads: int = 4,
    head_dim: int = 64,
    rounds: int = 1,
) -> dict:
    """Run ring attention as an ICI soak: returns
    {ok, latency_ms, moved_bytes, link_gbps} after verifying numerics
    against the single-device reference on round 0.

    Used by the health backend's deep probe; also a standalone
    long-context smoke for BASELINE configs 4-5."""
    import time

    devs = list(devices) if devices is not None else list(jax.devices())
    n = len(devs)
    if n < 2:
        return {"ok": True, "latency_ms": 0.0, "moved_bytes": 0,
                "link_gbps": 0.0, "detail": "single device; no ring"}
    mesh = Mesh(np.asarray(devs), ("sp",))
    fn, _ = make_ring_attention(mesh, "sp")
    S = seq_per_device * n
    rng = np.random.default_rng(0)
    shape = (batch, S, heads, head_dim)
    sharding = NamedSharding(mesh, P(None, "sp", None, None))
    host = [
        rng.standard_normal(shape).astype(np.float32) for _ in range(3)
    ]
    # make_array_from_callback assembles the global array from whatever
    # shards THIS process addresses — works single- and multi-host.
    q, k, v = (
        jax.make_array_from_callback(shape, sharding, lambda idx, a=arr: a[idx])
        for arr in host
    )

    out = jax.block_until_ready(fn(q, k, v))
    # Exact verification against the O(S²) single-device reference only
    # where it is feasible: one process (global arrays addressable) and a
    # bounded sequence (the reference materializes S×S scores).  On a
    # real multi-host slice we verify what each host CAN see: its local
    # output shards are finite and bounded by the softmax convexity
    # property |out| <= max|v| (checked against the local v bound — a
    # loose but device-cheap invariant).
    # Single-PROCESS meshes (judged from the probed devices, not the
    # default backend — under jax.distributed another registered backend
    # may still report one process) can verify exactly against the
    # O(S²) reference, which needs the global arrays addressable.
    multi_process = len({d.process_index for d in devs}) > 1
    if not multi_process and S <= 4096:
        ref = jax.block_until_ready(
            jax.jit(full_attention_reference)(
                jax.device_put(np.asarray(q), devs[0]),
                jax.device_put(np.asarray(k), devs[0]),
                jax.device_put(np.asarray(v), devs[0]),
            )
        )
        err = float(np.max(np.abs(np.asarray(out) - np.asarray(ref))))
        ok = bool(err < 5e-2)  # bf16 score/merge tolerance
    else:
        # (A local |out| <= max|v| convexity bound would need the GLOBAL
        # v max; keep the multi-host check to finiteness, which already
        # catches the NaN/garbage failure modes a broken link produces.)
        locals_ = [np.asarray(s.data) for s in out.addressable_shards]
        ok = bool(locals_) and all(np.isfinite(x).all() for x in locals_)
        err = float("nan")

    t0 = time.perf_counter()
    for _ in range(rounds):
        out = fn(q, k, v)
    jax.block_until_ready(out)
    latency_ms = (time.perf_counter() - t0) / rounds * 1e3
    # Per round, each link carries (n-1) K and V shard transfers.
    shard_bytes = batch * seq_per_device * heads * head_dim * 4
    moved = 2 * (n - 1) * shard_bytes
    link_gbps = moved / (latency_ms * 1e-3) / 1e9
    return {
        "ok": ok,
        "max_err": err,
        "latency_ms": latency_ms,
        "moved_bytes": moved,
        "link_gbps": link_gbps,
        "devices": n,
        "global_seq": S,
    }


class ElasticRingSoak:
    """Ring attention that re-forms its ring around excluded slices.

    The context-parallel counterpart to ``ElasticCanaryRunner``: devices
    are partitioned into ``n_slices`` contiguous blocks, and excluding a
    slice rebuilds the ``sp`` ring over the survivors (per-device
    sequence constant, so the global context shrinks with the ring —
    checkpoint-free, nothing to migrate: attention is stateless).  Each
    exclusion set's jitted program is cached on first use, so a resize
    during a roll costs one ring re-formation, not a recompile per
    round.  ``run_round`` verifies the shrunk ring's numerics against
    the single-device reference every time — a reshaped ring that
    silently corrupts attention must fail loudly, not train on garbage.

    ``exclude_slice``/``rejoin_slice`` are idempotent, matching the
    coordinator's crash-replay contract.
    """

    def __init__(
        self,
        devices: Optional[Sequence[jax.Device]] = None,
        n_slices: int = 2,
        seq_per_device: int = 64,
        batch: int = 1,
        heads: int = 2,
        head_dim: int = 32,
        seed: int = 0,
    ) -> None:
        devs = list(devices) if devices is not None else list(jax.devices())
        if n_slices <= 1 or len(devs) % n_slices != 0:
            raise ValueError(
                f"{len(devs)} devices do not partition into {n_slices} "
                "ring slices"
            )
        per = len(devs) // n_slices
        self.slice_devices = [
            devs[i * per : (i + 1) * per] for i in range(n_slices)
        ]
        self.n_slices = n_slices
        self.seq_per_device = seq_per_device
        self.batch = batch
        self.heads = heads
        self.head_dim = head_dim
        self.excluded: set[int] = set()
        self._rings: dict[frozenset, tuple] = {}
        self._rng = np.random.default_rng(seed)

    def _ring_for(self, excl: frozenset) -> tuple:
        if excl not in self._rings:
            if len(excl) >= self.n_slices:
                raise ValueError("cannot exclude every ring slice")
            devs = [
                d
                for i in range(self.n_slices)
                if i not in excl
                for d in self.slice_devices[i]
            ]
            if len(devs) < 2:
                raise ValueError("ring needs at least two devices")
            mesh = Mesh(np.asarray(devs), ("sp",))
            fn, shard = make_ring_attention(mesh, "sp")
            self._rings[excl] = (fn, shard, len(devs))
        return self._rings[excl]

    def exclude_slice(self, index: int) -> None:
        if not 0 <= index < self.n_slices:
            raise ValueError(f"slice index {index} out of range")
        self.excluded.add(index)
        self._ring_for(frozenset(self.excluded))

    def rejoin_slice(self, index: int) -> None:
        self.excluded.discard(index)
        self._ring_for(frozenset(self.excluded))

    def run_round(self) -> dict:
        """One attention pass on the current ring, verified exactly
        against the single-device full-attention reference."""
        fn, shard, n = self._ring_for(frozenset(self.excluded))
        S = self.seq_per_device * n
        shape = (self.batch, S, self.heads, self.head_dim)
        q, k, v = (
            shard(jnp.asarray(self._rng.standard_normal(shape), jnp.float32))
            for _ in range(3)
        )
        out = jax.block_until_ready(fn(q, k, v))
        ref = jax.block_until_ready(
            jax.jit(full_attention_reference)(q, k, v)
        )
        err = float(np.max(np.abs(np.asarray(out) - np.asarray(ref))))
        return {
            "ok": bool(err < 5e-2),
            "max_err": err,
            "devices": n,
            "global_seq": S,
        }
