"""Reference JAX workloads.

The reference manages the driver that NCCL/InfiniBand *workloads* depend
on, but contains no workload code (SURVEY.md §2.3).  For the TPU north
star the workload is first-class: BASELINE configs 3-5 measure *JAX
workload downtime* during a rolling libtpu upgrade, so the framework
ships a canary — a small sharded transformer LM train step (the MaxText
stand-in) plus a runner that timestamps steps and reports interruption
gaps.  The canary is also the flagship compute surface for the harness
entry points (``__graft_entry__.py``).
"""

from k8s_operator_libs_tpu.workloads.canary import (
    CanaryConfig,
    CanaryRunner,
    init_params,
    make_mesh,
    make_sharded_train_step,
    make_train_step,
)

__all__ = [
    "CanaryConfig",
    "CanaryRunner",
    "init_params",
    "make_mesh",
    "make_sharded_train_step",
    "make_train_step",
]
