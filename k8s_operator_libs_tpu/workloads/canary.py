"""Canary workload: a sharded transformer LM train step.

A compact decoder-only transformer (the MaxText/Llama stand-in from
BASELINE configs 4-5) written TPU-first:

- **MXU**: all matmuls run in bf16 with f32 accumulation
  (``preferred_element_type``), static shapes throughout;
- **compiler-friendly control flow**: the layer stack is a single
  ``lax.scan`` over stacked layer parameters — one trace, XLA unrolls
  onto the MXU pipeline;
- **SPMD**: parameters and data carry ``NamedSharding`` over a
  ``("dp", "tp")`` mesh — batch over ``dp``, attention heads and MLP
  hidden over ``tp`` (Megatron-style column→row sharding, so each layer
  needs exactly one all-reduce per projection pair, which XLA inserts
  from the shardings; no hand-written collectives);
- **downtime measurement**: :class:`CanaryRunner` timestamps every step
  so an upgrade's workload interruption is measured, not estimated — the
  north-star metric (<2 min interruption on v5p-64).

The model is deliberately small-configurable: the same code path
compiles at toy size on the 8-device CPU test mesh and at benchmark size
on real slices.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, replace
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from k8s_operator_libs_tpu.consts import get_logger

logger = get_logger(__name__)


@dataclass(frozen=True)
class CanaryConfig:
    vocab: int = 256
    d_model: int = 128
    n_heads: int = 4
    n_layers: int = 2
    d_ff: int = 512
    seq_len: int = 128
    batch: int = 8
    learning_rate: float = 1e-3
    # Rematerialize each scanned layer in the backward pass.  Without it
    # the scan saves every layer's attention temps (L·B·H·S·S and
    # L·B·H·S·d buffers) and a production-sized canary blows HBM;
    # with it only the per-layer carry survives the forward pass —
    # the standard FLOPs-for-memory trade on TPU.
    remat: bool = True

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


def init_params(rng: jax.Array, cfg: CanaryConfig) -> dict:
    """Parameter pytree; per-layer tensors are STACKED on a leading
    layer axis so the forward pass is one ``lax.scan``."""
    k_embed, k_layers, k_out = jax.random.split(rng, 3)
    scale = cfg.d_model**-0.5
    L = cfg.n_layers

    def norm(key, *shape):
        return jax.random.normal(key, shape, jnp.float32) * scale

    ks = jax.random.split(k_layers, 6)
    return {
        "embed": norm(k_embed, cfg.vocab, cfg.d_model),
        "layers": {
            "qkv": norm(ks[0], L, cfg.d_model, 3 * cfg.d_model),
            "proj": norm(ks[1], L, cfg.d_model, cfg.d_model),
            "mlp_in": norm(ks[2], L, cfg.d_model, cfg.d_ff),
            "mlp_out": norm(ks[3], L, cfg.d_ff, cfg.d_model),
            "ln1": jnp.ones((L, cfg.d_model), jnp.float32),
            "ln2": jnp.ones((L, cfg.d_model), jnp.float32),
        },
        "ln_f": jnp.ones((cfg.d_model,), jnp.float32),
        "out": norm(k_out, cfg.d_model, cfg.vocab),
    }


def param_specs(cfg: CanaryConfig) -> dict:
    """Megatron-style tensor-parallel PartitionSpecs (leading axis of the
    stacked layer tensors is never sharded).

    qkv / mlp_in are column-parallel (output dim over ``tp``); proj /
    mlp_out are row-parallel (input dim over ``tp``): activations stay
    sharded head-wise through attention and hidden-wise through the MLP,
    and XLA inserts exactly one all-reduce after each row-parallel matmul."""
    return {
        "embed": P(None, "tp"),
        "layers": {
            "qkv": P(None, None, "tp"),
            "proj": P(None, "tp", None),
            "mlp_in": P(None, None, "tp"),
            "mlp_out": P(None, "tp", None),
            "ln1": P(None, None),
            "ln2": P(None, None),
        },
        "ln_f": P(None),
        "out": P(None, "tp"),
    }


def _rms_norm(x: jax.Array, gain: jax.Array) -> jax.Array:
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    return x * jax.lax.rsqrt(var + 1e-6) * gain


def _matmul(a: jax.Array, b: jax.Array) -> jax.Array:
    """bf16 operands, f32 accumulation: the MXU contract."""
    return jnp.matmul(
        a.astype(jnp.bfloat16),
        b.astype(jnp.bfloat16),
        preferred_element_type=jnp.float32,
    )


def forward(params: dict, tokens: jax.Array, cfg: CanaryConfig) -> jax.Array:
    """Logits [B, S, V].  Layer stack via lax.scan (static depth, one
    trace); causal mask is a static constant."""
    B, S = tokens.shape
    h = params["embed"][tokens]  # [B, S, D] gather
    causal = jnp.tril(jnp.ones((S, S), jnp.bool_))

    def layer(h, lp):
        x = _rms_norm(h, lp["ln1"])
        qkv = _matmul(x, lp["qkv"])  # [B, S, 3D]
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):
            return t.reshape(B, S, cfg.n_heads, cfg.head_dim).transpose(
                0, 2, 1, 3
            )

        q, k, v = heads(q), heads(k), heads(v)
        scores = jnp.einsum(
            "bhqd,bhkd->bhqk",
            q.astype(jnp.bfloat16),
            k.astype(jnp.bfloat16),
            preferred_element_type=jnp.float32,
        ) * (cfg.head_dim**-0.5)
        scores = jnp.where(causal[None, None], scores, -1e30)
        attn = jax.nn.softmax(scores, axis=-1)
        ctx = jnp.einsum(
            "bhqk,bhkd->bhqd",
            attn.astype(jnp.bfloat16),
            v.astype(jnp.bfloat16),
            preferred_element_type=jnp.float32,
        )
        ctx = ctx.transpose(0, 2, 1, 3).reshape(B, S, cfg.d_model)
        h = h + _matmul(ctx, lp["proj"])
        x = _rms_norm(h, lp["ln2"])
        h = h + _matmul(jax.nn.gelu(_matmul(x, lp["mlp_in"])), lp["mlp_out"])
        return h, None

    body = jax.checkpoint(layer) if cfg.remat else layer
    h, _ = jax.lax.scan(body, h, params["layers"])
    h = _rms_norm(h, params["ln_f"])
    return _matmul(h, params["out"])  # [B, S, V]


def loss_fn(params: dict, batch: jax.Array, cfg: CanaryConfig) -> jax.Array:
    """Next-token cross entropy (batch carries S+1 tokens)."""
    tokens, targets = batch[:, :-1], batch[:, 1:]
    logits = forward(params, tokens, cfg)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)
    return -jnp.mean(ll)


def make_train_step(cfg: CanaryConfig, optimizer=None):
    """(params, opt_state, batch) -> (params, opt_state, loss), jittable."""
    opt = optimizer or optax.adam(cfg.learning_rate)

    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch, cfg)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    return step, opt


def make_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    tp: int = 0,
) -> Mesh:
    """A ``("dp", "tp")`` mesh over the given devices.  ``tp=0`` picks the
    largest power-of-two ≤ min(4, n/2) that divides n, so both axes are
    nontrivial from 4 devices up (heads are few; wide tp rarely helps a
    canary)."""
    devs = list(devices) if devices is not None else list(jax.devices())
    n = len(devs)
    if tp <= 0:
        tp = 1
        while tp * 2 <= min(n // 2, 4) and n % (tp * 2) == 0:
            tp *= 2
    if n % tp:
        raise ValueError(f"{n} devices not divisible by tp={tp}")
    return Mesh(np.asarray(devs).reshape(n // tp, tp), ("dp", "tp"))


def make_sharded_train_step(
    mesh: Mesh, cfg: CanaryConfig, optimizer=None
):
    """Jit the train step over the mesh with explicit NamedShardings.

    Returns (jitted_step, shard_params, shard_batch): callers place
    params/opt-state/batches with the shard_* helpers and then every step
    is pure SPMD — XLA inserts the tp all-reduces and dp grad psums from
    the sharding annotations (scaling-book recipe: pick a mesh, annotate,
    let XLA place collectives)."""
    step, opt = make_train_step(cfg, optimizer)
    pspecs = param_specs(cfg)
    param_sh = jax.tree.map(
        lambda spec: NamedSharding(mesh, spec),
        pspecs,
        is_leaf=lambda x: isinstance(x, P),
    )
    batch_sh = NamedSharding(mesh, P("dp", None))

    def shard_params(params):
        return jax.device_put(params, param_sh)

    def shard_batch(batch):
        return jax.device_put(batch, batch_sh)

    def shard_opt_state(params, opt_state):
        # Optimizer moments mirror the param shardings; scalar counts
        # replicate.  jax.jit would infer this, but placing explicitly
        # avoids a resharding step at first call.  Moments live in the
        # optimizer state as params-shaped subtrees, so match each state
        # leaf to the param whose tree path is a SUFFIX of the state
        # leaf's path (shape matching would pick wrong when two params
        # share a shape).
        def path_keys(path) -> tuple[str, ...]:
            return tuple(
                str(getattr(p, "key", getattr(p, "idx", p))) for p in path
            )

        params_flat = jax.tree_util.tree_flatten_with_path(params)[0]
        sh_flat = jax.tree_util.tree_flatten_with_path(param_sh)[0]
        by_path = {
            path_keys(ppath): (pleaf.shape, sh)
            for (ppath, pleaf), (_, sh) in zip(params_flat, sh_flat)
        }

        def place(path, leaf):
            keys = path_keys(path)
            for plen in range(len(keys), 0, -1):
                entry = by_path.get(keys[-plen:])
                if entry is not None and entry[0] == leaf.shape:
                    return jax.device_put(leaf, entry[1])
            return jax.device_put(leaf, NamedSharding(mesh, P()))

        return jax.tree_util.tree_map_with_path(place, opt_state)

    jitted = jax.jit(step, donate_argnums=(0, 1))
    return jitted, opt, shard_params, shard_batch, shard_opt_state


class CanaryRunner:
    """Run train steps and timestamp them; the gap analysis IS the
    workload-downtime metric (north star: <2 min interruption)."""

    def __init__(self, cfg: CanaryConfig, mesh: Optional[Mesh] = None,
                 seed: int = 0) -> None:
        self.cfg = cfg
        self.mesh = mesh
        rng = jax.random.PRNGKey(seed)
        params = init_params(rng, cfg)
        if mesh is not None:
            (
                self._step,
                opt,
                shard_params,
                shard_batch,
                shard_opt_state,
            ) = make_sharded_train_step(mesh, cfg)
            self.params = shard_params(params)
            self.opt_state = shard_opt_state(
                self.params, opt.init(jax.tree.map(np.asarray, params))
            )
            self._shard_batch = shard_batch
        else:
            step, opt = make_train_step(cfg)
            self._step = jax.jit(step, donate_argnums=(0, 1))
            self.params = params
            self.opt_state = opt.init(params)
            self._shard_batch = lambda b: b
        self.step_times: list[float] = []
        self.losses: list[float] = []
        self.window_start = time.monotonic()
        self._batch_rng = np.random.default_rng(seed)

    def _make_batch(self) -> jax.Array:
        batch = self._batch_rng.integers(
            0, self.cfg.vocab, (self.cfg.batch, self.cfg.seq_len + 1),
            dtype=np.int32,
        )
        return self._shard_batch(jnp.asarray(batch))

    def run_step(self) -> float:
        batch = self._make_batch()
        self.params, self.opt_state, loss = self._step(
            self.params, self.opt_state, batch
        )
        loss = float(loss)
        self.step_times.append(time.monotonic())
        self.losses.append(loss)
        return loss

    def reset_timing(self) -> None:
        """Start a fresh measurement window (call after warmup steps so
        compile time doesn't count as an interruption)."""
        self.step_times = []
        self.losses = []
        self.window_start = time.monotonic()

    def max_gap_seconds(self, until: Optional[float] = None) -> float:
        """Longest interruption between consecutive completed steps.

        ``until`` (a ``time.monotonic()`` timestamp) closes the window: if
        the workload is still disrupted when measurement ends, the OPEN
        interval since the last completed step counts as a gap — otherwise
        a canary that stalled terminally would report near-zero downtime
        (the round-1/2 fiction this parameter exists to kill).  With no
        completed steps at all, the whole window is the gap."""
        times = np.asarray(self.step_times)
        if times.size == 0:
            return float(max(0.0, until - self.window_start)) if until else 0.0
        gaps = np.diff(times) if times.size > 1 else np.asarray([0.0])
        closed = float(gaps.max()) if gaps.size else 0.0
        if until is not None:
            return max(closed, float(until - times[-1]))
        return closed

    # -- throughput / MFU ---------------------------------------------------

    def param_count(self) -> int:
        return int(
            sum(int(np.prod(p.shape)) for p in jax.tree.leaves(self.params))
        )

    def flops_per_step(self) -> float:
        """Training FLOPs per step: the standard 6·N·tokens matmul term
        plus the 12·L·B·S²·D attention term (fwd+bwd, PaLM-appendix
        convention — the MFU denominator every report uses)."""
        cfg = self.cfg
        tokens = cfg.batch * cfg.seq_len
        matmul = 6.0 * self.param_count() * tokens
        attention = 12.0 * cfg.n_layers * cfg.batch * cfg.seq_len**2 * cfg.d_model
        return matmul + attention

    def perf_summary(self) -> dict:
        """tokens/s, achieved TFLOPS and MFU from the recorded steps.

        Uses the *median* inter-step time so upgrade pauses (the gaps the
        downtime metric measures) don't depress the throughput figure."""
        if len(self.step_times) < 2:
            return {"steps": len(self.step_times)}
        dt = float(np.median(np.diff(np.asarray(self.step_times))))
        if dt <= 0:
            return {"steps": len(self.step_times)}
        out = {
            "steps": len(self.step_times),
            "median_step_s": dt,
            "params": self.param_count(),
        }
        out.update(self._throughput_from_step_time(dt))
        return out

    def _throughput_from_step_time(self, dt: float) -> dict:
        """tokens/s, achieved TFLOPS, device kind and (when the chip spec
        is known) MFU for one per-step time — shared by the wall and
        device-sustained summaries so the two figures can never diverge
        in accounting."""
        from k8s_operator_libs_tpu.hw import mfu as _mfu

        cfg = self.cfg
        if self.mesh is not None:
            devices = list(self.mesh.devices.flat)
        else:
            devices = [jax.devices()[0]]
        achieved_tflops = self.flops_per_step() / dt / 1e12
        out = {
            "tokens_per_s": cfg.batch * cfg.seq_len / dt,
            "achieved_tflops": achieved_tflops,
            "device": devices[0].device_kind,
        }
        # Per-device utilisation: the step's FLOPs spread over the mesh.
        mfu_frac = _mfu(
            achieved_tflops / max(1, len(devices)), devices[0].device_kind
        )
        if mfu_frac is not None:
            out["mfu"] = mfu_frac
        return out

    def sustained_perf_summary(self) -> dict:
        """Device-sustained step throughput via the slope estimator.

        ``perf_summary`` measures *wall* step time — one host round trip
        per step, so on a tunneled backend the figure is RTT-bound and
        says little about the hardware.  Here steps are enqueued
        back-to-back (each depends on the previous through the donated
        params/opt-state) and the k-vs-4k slope cancels the fixed
        dispatch/readback cost, yielding the per-step DEVICE time — the
        MFU a production on-host trainer would see.  Mutates
        params/opt-state (more training steps) but records no step
        timestamps, so the downtime metric is untouched."""
        # Reuse the health battery's estimator: same noise rejection,
        # same escalation, same inconclusive-over-fiction contract.
        from k8s_operator_libs_tpu.health.probes import (
            InconclusiveTiming,
            _timed_sustained,
        )

        batch = self._make_batch()

        def one(b):
            self.params, self.opt_state, loss = self._step(
                self.params, self.opt_state, b
            )
            return loss

        try:
            # deterministic under multi-process SPMD: the sharded step
            # contains dp/tp collectives, and every process must enqueue
            # identical step counts (timing-derived run lengths would
            # desync them and deadlock the slice — probes.py's contract).
            lat_ms, _out, iters = _timed_sustained(
                one, (batch,), deterministic=jax.process_count() > 1
            )
        except InconclusiveTiming as e:
            return {"timing_inconclusive": 1.0, "iters": float(e.applied)}
        dt = lat_ms / 1e3
        if dt <= 0:
            return {"timing_inconclusive": 1.0, "iters": float(iters)}
        out = {"device_step_s": dt, "iters": float(iters)}
        out.update(self._throughput_from_step_time(dt))
        return out


# -- elastic mesh reshaping ---------------------------------------------------


@dataclass
class _ElasticBundle:
    """One precompiled SPMD program for one exclusion set: the mesh over
    the surviving devices plus the sharded step and placement helpers."""

    mesh: Mesh
    cfg: CanaryConfig
    jitted: object
    opt: object
    shard_params: object
    shard_batch: object
    shard_opt_state: object


class ElasticCanaryRunner(CanaryRunner):
    """Canary that reshapes its mesh around a slice under maintenance.

    The zero-downtime half of the elastic-roll protocol: instead of
    draining when a slice upgrades, the workload drops that slice's
    devices from its mesh and keeps training.  A resize is
    checkpoint-free —

    1. snapshot params + opt-state host-side (``np.asarray`` per leaf);
    2. switch to the bundle compiled for the new exclusion set (a mesh
       over the surviving devices with its own sharded train step);
    3. ``device_put`` the snapshot through the new bundle's placement
       helpers and resume.

    Per-exclusion bundles are compiled up front (``precompile=True``) so
    the resize itself is only the host round-trip — at canary scale that
    is below one step time, which is what lets ``max_gap_seconds``
    report 0.00 s across an upgrade.

    Two modes, picked from the device/slice arithmetic:

    - **physical** (device count divides ``n_slices`` and >1 slices):
      slice *i* owns a contiguous device block; excluding it rebuilds
      the mesh over the remaining blocks.  The per-dp-shard batch stays
      constant, so global batch (and throughput) scale with surviving
      devices.
    - **logical** (uneven split): the mesh keeps every device and an
      exclusion shrinks the global batch proportionally instead — the
      capacity loss is modeled even when the topology cannot be
      physically partitioned (single-host test rigs).

    ``exclude_slice``/``rejoin_slice`` are idempotent, matching the
    coordinator's crash-replay contract.
    """

    def __init__(
        self,
        cfg: CanaryConfig,
        devices: Optional[Sequence[jax.Device]] = None,
        n_slices: int = 2,
        seed: int = 0,
        precompile: bool = True,
    ) -> None:
        if n_slices <= 0:
            raise ValueError(f"n_slices must be positive, got {n_slices}")
        self.base_cfg = cfg
        devs = list(devices) if devices is not None else list(jax.devices())
        self.devices = devs
        self.n_slices = n_slices
        self.physical = n_slices > 1 and len(devs) % n_slices == 0
        if self.physical:
            per = len(devs) // n_slices
            self.slice_devices = [
                devs[i * per : (i + 1) * per] for i in range(n_slices)
            ]
        else:
            self.slice_devices = [list(devs) for _ in range(n_slices)]
        base_dp = len(devs) // make_mesh(devs).shape["tp"]
        self._per_dp_batch = max(1, cfg.batch // base_dp)
        self.excluded: set[int] = set()
        self._bundles: dict[frozenset, _ElasticBundle] = {}
        rng = jax.random.PRNGKey(seed)
        self._host_params = jax.tree.map(np.asarray, init_params(rng, cfg))
        self.resize_events: list[dict] = []
        self.step_times = []
        self.losses = []
        self._batch_rng = np.random.default_rng(seed)
        self._activate(frozenset(), self._host_params, None)
        if precompile:
            self.precompile_exclusions()
        self.window_start = time.monotonic()

    # -- bundles --

    def _build_bundle(self, excl: frozenset) -> _ElasticBundle:
        if len(excl) >= self.n_slices:
            raise ValueError("cannot exclude every slice of the workload")
        if self.physical:
            devs = [
                d
                for i in range(self.n_slices)
                if i not in excl
                for d in self.slice_devices[i]
            ]
            mesh = make_mesh(devs)
            batch = mesh.shape["dp"] * self._per_dp_batch
        else:
            mesh = make_mesh(self.devices)
            active = self.n_slices - len(excl)
            batch = mesh.shape["dp"] * max(
                1, self._per_dp_batch * active // self.n_slices
            )
        cfg = replace(self.base_cfg, batch=batch)
        jitted, opt, sp, sb, so = make_sharded_train_step(mesh, cfg)
        return _ElasticBundle(mesh, cfg, jitted, opt, sp, sb, so)

    def _bundle_for(self, excl: frozenset) -> _ElasticBundle:
        if excl not in self._bundles:
            self._bundles[excl] = self._build_bundle(excl)
        return self._bundles[excl]

    def precompile_exclusions(self, exclusion_sets=None) -> None:
        """Compile the bundles resizes will switch to, so the switch
        itself pays no XLA compile.  Default: each single-slice
        exclusion (the shapes a rolling upgrade visits)."""
        sets = (
            [frozenset(s) for s in exclusion_sets]
            if exclusion_sets is not None
            else [frozenset({i}) for i in range(self.n_slices)]
        )
        for excl in sets:
            bundle = self._bundle_for(excl)
            p = bundle.shard_params(self._host_params)
            o = bundle.shard_opt_state(p, bundle.opt.init(self._host_params))
            batch = bundle.shard_batch(
                jnp.zeros(
                    (bundle.cfg.batch, bundle.cfg.seq_len + 1), jnp.int32
                )
            )
            # Two chained steps: the first compiles the freshly-placed
            # signature, the second the output-fed-back signature (step
            # outputs carry compiler-chosen shardings that differ from
            # device_put's, and a first post-resize step would otherwise
            # pay a recompile on its SECOND iteration).
            p, o, loss = bundle.jitted(p, o, batch)
            batch = bundle.shard_batch(
                jnp.zeros(
                    (bundle.cfg.batch, bundle.cfg.seq_len + 1), jnp.int32
                )
            )
            p, o, loss = bundle.jitted(p, o, batch)
            jax.block_until_ready(loss)

    def _activate(self, excl: frozenset, host_params, host_opt) -> None:
        bundle = self._bundle_for(excl)
        self.mesh = bundle.mesh
        self.cfg = bundle.cfg
        self.params = bundle.shard_params(host_params)
        if host_opt is None:
            host_opt = bundle.opt.init(host_params)
        self.opt_state = bundle.shard_opt_state(self.params, host_opt)
        self._step = bundle.jitted
        self._shard_batch = bundle.shard_batch

    # -- resizes --

    @property
    def active_slices(self) -> int:
        return self.n_slices - len(self.excluded)

    def active_device_count(self) -> int:
        return int(np.prod(tuple(self.mesh.shape.values())))

    def _resize(self, new_excl: frozenset, direction: str, index: int) -> None:
        t0 = time.monotonic()
        host_p = jax.tree.map(np.asarray, self.params)
        host_o = jax.tree.map(np.asarray, self.opt_state)
        self.excluded = set(new_excl)
        self._activate(new_excl, host_p, host_o)
        self.resize_events.append(
            {
                "direction": direction,
                "slice": index,
                "seconds": time.monotonic() - t0,
            }
        )

    def exclude_slice(self, index: int) -> None:
        if not 0 <= index < self.n_slices:
            raise ValueError(f"slice index {index} out of range")
        if index in self.excluded:
            return
        self._resize(frozenset(self.excluded | {index}), "down", index)

    def rejoin_slice(self, index: int) -> None:
        if index not in self.excluded:
            return
        self._resize(frozenset(self.excluded - {index}), "up", index)
