"""Workload coordination: the elastic-roll negotiation subsystem.

The upgrade engine's side of the protocol lives in
``upgrade/upgrade_state.py`` (``process_negotiation_groups`` /
``process_rejoin_resize_groups``); this package is the WORKLOAD side —
the agent a training job runs so the operator can reshape its mesh
around a slice under maintenance instead of draining it (Tenplex-style
elasticity, PAPERS.md):

- :mod:`protocol` — annotation key semantics and pure parse helpers
  shared by both sides (the node annotations ARE the wire);
- :mod:`workload` — :class:`WorkloadCoordinator`, the job-side agent
  that registers on its slices, answers exclusion offers, drives the
  runtime's resize, and stamps completion;
- :mod:`elastic` — glue between slice identity and device indices, plus
  runtime adapters for the elastic workloads in ``workloads/``.
"""

from k8s_operator_libs_tpu.coordination.protocol import (  # noqa: F401
    RESPONSE_ACCEPT,
    RESPONSE_DECLINE,
    NegotiationView,
    negotiation_view,
)
from k8s_operator_libs_tpu.coordination.elastic import (  # noqa: F401
    RecordingRuntime,
    RunnerElasticRuntime,
    partition_devices,
)
from k8s_operator_libs_tpu.coordination.workload import (  # noqa: F401
    WorkloadCoordinator,
)
