"""Pure helpers for the elastic-roll annotation protocol.

The protocol has no dedicated API object: node annotations are the wire.
The controller (``upgrade_state.py``) and the workload agent
(:mod:`k8s_operator_libs_tpu.coordination.workload`) each read the other
side's stamps from the same node objects, so every transition survives a
crash of either party — the annotations replay the conversation.

Key roles (all formatted per-provider via :class:`UpgradeKeys`):

========================  =======  ====================================
annotation                writer   meaning
========================  =======  ====================================
``elastic-workload``      job      workload id; marks the slice as
                                   coordination-capable at admission
``elastic-offer``         ctrl     epoch the exclusion offer was posted
``elastic-response``      job      ``accept`` | ``decline``
``elastic-resize-complete``  job   epoch the shrink finished
``elastic-excluded``      ctrl     ``true`` while the slice is out of
                                   the mesh (budget-exempt marker)
``elastic-rejoin-offer``  ctrl     epoch the rejoin offer was posted
``elastic-rejoin-complete``  job   epoch the regrow finished
========================  =======  ====================================
"""

from dataclasses import dataclass
from typing import Iterable, Optional

from k8s_operator_libs_tpu.upgrade.consts import (
    ELASTIC_RESPONSE_ACCEPT,
    ELASTIC_RESPONSE_DECLINE,
    NULL_STRING,
    TRUE_STRING,
)
from k8s_operator_libs_tpu.upgrade.durable import parse_epoch
from k8s_operator_libs_tpu.upgrade.util import UpgradeKeys

# Re-exported so workload-side code never imports from upgrade.consts
# directly (keeps the coordination package the single import surface for
# job authors).
RESPONSE_ACCEPT = ELASTIC_RESPONSE_ACCEPT
RESPONSE_DECLINE = ELASTIC_RESPONSE_DECLINE


def annotation_value(node, key: str) -> str:
    """Read one annotation, treating the ``"null"`` tombstone as empty."""
    meta = getattr(node, "metadata", None)
    annotations = getattr(meta, "annotations", None) or {}
    value = annotations.get(key, "")
    if value == NULL_STRING:
        return ""
    return value


@dataclass(frozen=True)
class NegotiationView:
    """One slice's negotiation state as read from its nodes.

    Each field is the first non-empty value across the slice's nodes —
    both sides stamp every member, so a partial write (crash mid-patch)
    still yields the stamped value.
    """

    workload: str
    offer_epoch: Optional[int]
    response: str
    resize_complete_epoch: Optional[int]
    excluded: bool
    rejoin_offer_epoch: Optional[int]
    rejoin_complete_epoch: Optional[int]

    @property
    def offered(self) -> bool:
        return self.offer_epoch is not None

    @property
    def responded(self) -> bool:
        return self.response in (RESPONSE_ACCEPT, RESPONSE_DECLINE)

    @property
    def rejoin_offered(self) -> bool:
        return self.rejoin_offer_epoch is not None


def _first_value(nodes: Iterable, key: str) -> str:
    for node in nodes:
        value = annotation_value(node, key)
        if value:
            return value
    return ""


def negotiation_view(nodes: Iterable, keys: UpgradeKeys) -> NegotiationView:
    """Fold a slice's node annotations into one :class:`NegotiationView`."""
    nodes = list(nodes)
    return NegotiationView(
        workload=_first_value(nodes, keys.elastic_workload_annotation),
        offer_epoch=parse_epoch(_first_value(nodes, keys.elastic_offer_annotation)),
        response=_first_value(nodes, keys.elastic_response_annotation),
        resize_complete_epoch=parse_epoch(
            _first_value(nodes, keys.elastic_resize_complete_annotation)
        ),
        excluded=_first_value(nodes, keys.elastic_excluded_annotation) == TRUE_STRING,
        rejoin_offer_epoch=parse_epoch(
            _first_value(nodes, keys.elastic_rejoin_offer_annotation)
        ),
        rejoin_complete_epoch=parse_epoch(
            _first_value(nodes, keys.elastic_rejoin_complete_annotation)
        ),
    )
