"""Glue between slice identity and elastic runtimes.

The coordinator speaks in slice ids (strings from the node topology
labels); elastic runtimes speak in device groups or slice indices. This
module holds the small adapters between the two so neither side imports
the other's vocabulary.
"""

from typing import Callable, Dict, List, Optional, Sequence


def partition_devices(devices: Sequence, n_slices: int) -> List[List]:
    """Split a flat device list into ``n_slices`` contiguous groups.

    Mirrors how a multi-slice mesh lays devices out slice-major (ICI
    within a group, DCN across groups). The device count must divide
    evenly — an uneven split would silently skew dp-shard sizes.
    """
    if n_slices <= 0:
        raise ValueError(f"n_slices must be positive, got {n_slices}")
    if len(devices) % n_slices != 0:
        raise ValueError(
            f"{len(devices)} devices do not divide into {n_slices} slices"
        )
    per = len(devices) // n_slices
    return [list(devices[i * per : (i + 1) * per]) for i in range(n_slices)]


class RecordingRuntime:
    """Fake elastic runtime for engine tests — records calls, can fail.

    ``exclude``/``rejoin`` are idempotent like the real runtimes: the
    coordinator may replay either after a crash.
    """

    def __init__(self, fail_exclude: bool = False):
        self.fail_exclude = fail_exclude
        self.excluded: List[str] = []
        self.rejoined: List[str] = []
        self.calls: List[str] = []

    def exclude(self, slice_id: str) -> None:
        self.calls.append(f"exclude:{slice_id}")
        if self.fail_exclude:
            raise RuntimeError(f"resize failed for {slice_id}")
        if slice_id not in self.excluded:
            self.excluded.append(slice_id)

    def rejoin(self, slice_id: str) -> None:
        self.calls.append(f"rejoin:{slice_id}")
        if slice_id in self.excluded:
            self.excluded.remove(slice_id)
        if slice_id not in self.rejoined:
            self.rejoined.append(slice_id)


class RunnerElasticRuntime:
    """Adapt a slice-index runner (ElasticCanaryRunner) to slice ids.

    ``slice_index_of`` maps the operator's slice id to the runner's
    slice index (position in its device partition). Unknown ids raise:
    an offer for a slice the workload does not own means registration
    and topology disagree, which must surface, not be absorbed.
    """

    def __init__(
        self,
        runner,
        slice_index_of: Dict[str, int],
        on_resize: Optional[Callable[[str, str], None]] = None,
    ):
        self.runner = runner
        self.slice_index_of = dict(slice_index_of)
        self.on_resize = on_resize

    def _index(self, slice_id: str) -> int:
        if slice_id not in self.slice_index_of:
            raise KeyError(f"slice {slice_id!r} is not part of this workload")
        return self.slice_index_of[slice_id]

    def exclude(self, slice_id: str) -> None:
        self.runner.exclude_slice(self._index(slice_id))
        if self.on_resize is not None:
            self.on_resize(slice_id, "down")

    def rejoin(self, slice_id: str) -> None:
        self.runner.rejoin_slice(self._index(slice_id))
        if self.on_resize is not None:
            self.on_resize(slice_id, "up")
