"""Job-side agent for the elastic-roll negotiation protocol.

A training job that can reshape its mesh runs one
:class:`WorkloadCoordinator` (on its coordinator host, next to the jax
process). The agent:

1. ``register()`` — stamps the ``elastic-workload`` annotation on every
   node of every slice it owns, which is what makes the controller
   route those slices through ``negotiate-required`` instead of
   cordoning them cold;
2. ``poll_once()`` — reads each slice's negotiation annotations, and

   - on a fresh exclusion offer: consults ``accept_policy``; on accept
     stamps ``elastic-response=accept``, drives ``runtime.exclude``,
     then stamps ``elastic-resize-complete``; on decline stamps
     ``elastic-response=decline`` and walks away (the controller falls
     back to the drain path);
   - on a rejoin offer: drives ``runtime.rejoin`` and stamps
     ``elastic-rejoin-complete``.

Crash-safety mirrors the controller's: every decision is stamped before
the next step runs, and ``runtime.exclude``/``rejoin`` are idempotent,
so replaying ``poll_once`` after a crash resumes mid-negotiation
(accept stamped but resize unfinished → the resize reruns; resize
stamped → nothing to do). A resize that raises is reported as a decline
so the controller falls back to draining rather than waiting out the
offer timeout.
"""

import logging
import time
from typing import Callable, Dict, List, Optional

from k8s_operator_libs_tpu.coordination.protocol import (
    RESPONSE_ACCEPT,
    RESPONSE_DECLINE,
    NegotiationView,
    negotiation_view,
)
from k8s_operator_libs_tpu.upgrade.util import UpgradeKeys

logger = logging.getLogger(__name__)


class WorkloadCoordinator:
    def __init__(
        self,
        client,
        keys: UpgradeKeys,
        workload_id: str,
        slice_nodes: Dict[str, List[str]],
        runtime,
        accept_policy: Optional[Callable[[str], bool]] = None,
        now: Callable[[], float] = time.time,
    ):
        """``slice_nodes`` maps slice id -> node names the job occupies;
        ``runtime`` needs ``exclude(slice_id)`` / ``rejoin(slice_id)``;
        ``accept_policy`` decides per-slice whether to take an offer
        (default: accept everything)."""
        self.client = client
        self.keys = keys
        self.workload_id = workload_id
        self.slice_nodes = {s: list(n) for s, n in slice_nodes.items()}
        self.runtime = runtime
        self.accept_policy = accept_policy or (lambda slice_id: True)
        self.now = now
        # Slices this agent has finished shrinking away; used only for
        # reporting — the annotations remain the source of truth.
        self.excluded_slices: List[str] = []

    # -- annotation plumbing --

    def _nodes(self, slice_id: str) -> List:
        nodes = []
        for name in self.slice_nodes[slice_id]:
            node = self.client.get_node(name, cached=False)
            if node is not None:
                nodes.append(node)
        return nodes

    def _stamp(self, slice_id: str, key: str, value: str) -> None:
        for name in self.slice_nodes[slice_id]:
            self.client.patch_node_annotations(name, {key: value})

    def _view(self, slice_id: str) -> NegotiationView:
        return negotiation_view(self._nodes(slice_id), self.keys)

    # -- protocol steps --

    def register(self) -> None:
        for slice_id in self.slice_nodes:
            self._stamp(
                slice_id, self.keys.elastic_workload_annotation, self.workload_id
            )

    def poll_once(self) -> Dict[str, str]:
        """One negotiation sweep; returns {slice_id: action taken}."""
        actions: Dict[str, str] = {}
        for slice_id in self.slice_nodes:
            view = self._view(slice_id)
            action = self._step_slice(slice_id, view)
            if action:
                actions[slice_id] = action
        return actions

    def _step_slice(self, slice_id: str, view: NegotiationView) -> str:
        # Rejoin takes precedence: a rejoin offer means the exclusion
        # cycle is over and the controller wants the slice back.
        if view.rejoin_offered and view.rejoin_complete_epoch is None:
            self.runtime.rejoin(slice_id)
            self._stamp(
                slice_id,
                self.keys.elastic_rejoin_complete_annotation,
                str(int(self.now())),
            )
            if slice_id in self.excluded_slices:
                self.excluded_slices.remove(slice_id)
            return "rejoin-complete"

        if not view.offered or view.excluded:
            return ""
        if view.response == RESPONSE_DECLINE:
            return ""
        if view.response == RESPONSE_ACCEPT and view.resize_complete_epoch is not None:
            return ""

        if view.response != RESPONSE_ACCEPT:
            if not self.accept_policy(slice_id):
                self._stamp(
                    slice_id,
                    self.keys.elastic_response_annotation,
                    RESPONSE_DECLINE,
                )
                return "declined"
            self._stamp(
                slice_id, self.keys.elastic_response_annotation, RESPONSE_ACCEPT
            )

        # Accept stamped (now or by a pre-crash incarnation) but the
        # resize has not completed — run it.
        try:
            self.runtime.exclude(slice_id)
        except Exception:
            logger.exception("elastic resize failed for slice %s", slice_id)
            self._stamp(
                slice_id, self.keys.elastic_response_annotation, RESPONSE_DECLINE
            )
            return "resize-failed"
        self._stamp(
            slice_id,
            self.keys.elastic_resize_complete_annotation,
            str(int(self.now())),
        )
        if slice_id not in self.excluded_slices:
            self.excluded_slices.append(slice_id)
        return "resize-complete"
