"""CRD schema generation: the controller-gen analogue.

The reference generates its CRD machinery with controller-gen (deepcopy in
``api/upgrade/v1alpha1/zz_generated.deepcopy.go:29``, driven by ``make
generate``, reference ``Makefile:60-66``) and relies on kubebuilder markers
(``api/upgrade/v1alpha1/upgrade_spec.go:27-110``) for defaults/validation,
which consumer operators compile into CRD OpenAPI schemas.  Here the spec
types are dataclasses, so the same artifacts are *derived* instead of
template-generated:

- :func:`spec_schema` introspects a ``_SpecBase`` dataclass into an
  OpenAPI v3 **structural schema** (types from annotations, defaults from
  field defaults, descriptions from the ``#`` comments above each field —
  the moral equivalent of controller-gen reading Go doc comments, and the
  validation markers from :data:`_CONSTRAINTS`).
- :func:`crd_manifest` wraps it into a full
  ``apiextensions.k8s.io/v1 CustomResourceDefinition`` for
  ``TPUUpgradePolicy`` (written to ``config/crd/`` by ``tools/gen_crd.py``,
  checked for drift in CI like the reference's go-check job,
  ``.github/workflows/ci.yaml:33-41``).
- :func:`validate_object` is a miniature structural-schema validator so
  the controller rejects a malformed policy file with apiserver-style
  messages instead of silently dropping unknown fields.
"""

from __future__ import annotations

import inspect
import re
from dataclasses import MISSING, fields
from typing import Any, Union, get_args, get_origin, get_type_hints

from k8s_operator_libs_tpu.api.v1alpha1 import (
    IntOrString,
    PlanningSpec,
    SliceTopologySpec,
    TPUUpgradePolicySpec,
    _SpecBase,
    _camel,
    _JSON_NAME_OVERRIDES,
)
from k8s_operator_libs_tpu.artifacts.dag import GATE_MODES, SKEW_MODES

# ---------------------------------------------------------------------------
# Validation markers — the kubebuilder-marker analogue, keyed by
# (dataclass name, python field name).  Kept here, next to the generator,
# so the CRD and the runtime validator can never disagree.
# ---------------------------------------------------------------------------

_CONSTRAINTS: dict[tuple[str, str], dict[str, Any]] = {
    # Reference upgrade_spec.go:33-38 (+kubebuilder:validation:Minimum=0).
    ("DriverUpgradePolicySpec", "max_parallel_upgrades"): {"minimum": 0},
    ("TPUUpgradePolicySpec", "max_parallel_upgrades"): {"minimum": 0},
    ("WaitForCompletionSpec", "timeout_second"): {"minimum": 0},
    ("PodDeletionSpec", "timeout_second"): {"minimum": 0},
    ("DrainSpec", "timeout_second"): {"minimum": 0},
    ("TPUUpgradePolicySpec", "unavailability_unit"): {
        "enum": list(TPUUpgradePolicySpec.UNAVAILABILITY_UNITS)
    },
    ("TPUUpgradePolicySpec", "stuck_threshold_second"): {"minimum": 0},
    # Derived from the runtime rule so the CRD can't drift from validate()
    # (empty string = unset is also admitted).
    ("SliceTopologySpec", "topology"): {
        "pattern": SliceTopologySpec._TOPOLOGY_RE.pattern + "|^$"
    },
    ("SliceTopologySpec", "hosts_per_slice"): {"minimum": 0},
    ("SliceHealthGateSpec", "all_reduce_timeout_second"): {"minimum": 0},
    ("SliceHealthGateSpec", "timeout_second"): {"minimum": 0},
    ("SliceHealthGateSpec", "min_reformation_fraction"): {
        "minimum": 0.0,
        "maximum": 1.0,
    },
    ("EvictionEscalationSpec", "evict_timeout_second"): {"minimum": 0},
    ("EvictionEscalationSpec", "delete_timeout_second"): {"minimum": 0},
    ("SliceQuarantineSpec", "ready_dwell_second"): {"minimum": 0},
    ("ElasticCoordinationSpec", "offer_timeout_second"): {"minimum": 0},
    ("ElasticCoordinationSpec", "rejoin_timeout_second"): {"minimum": 0},
    ("PoolSpec", "name"): {"pattern": "^.+$"},
    ("PoolSpec", "max_parallel_upgrades"): {"minimum": 0},
    ("PlanningSpec", "drift_threshold_second"): {"minimum": 0},
    ("PlanningSpec", "replan_interval_second"): {"minimum": 0},
    ("PlanningSpec", "max_replans"): {"minimum": 0},
    ("PlanningSpec", "admission_mode"): {
        "enum": list(PlanningSpec.ADMISSION_MODES)
    },
    ("FederationClusterSpec", "name"): {"pattern": "^.+$"},
    ("FederationClusterSpec", "region"): {"pattern": "^.+$"},
    ("FederationCanarySpec", "region"): {"pattern": "^.+$"},
    ("FederationCanarySpec", "soak_second"): {"minimum": 0},
    ("FederationSpec", "max_parallel_upgrades"): {"minimum": 0},
    ("FederationSpec", "degraded_after_probes"): {"minimum": 1},
    ("FederationSpec", "partitioned_after_probes"): {"minimum": 1},
    ("FederationSpec", "heal_probes"): {"minimum": 1},
    ("FederationSpec", "lease_duration_second"): {"minimum": 0},
    ("ArtifactSpec", "name"): {"pattern": "^[a-z0-9]([a-z0-9.-]*[a-z0-9])?$"},
    ("ArtifactSpec", "gate"): {"enum": list(GATE_MODES)},
    ("ArtifactEdgeSpec", "before"): {"pattern": "^.+$"},
    ("ArtifactEdgeSpec", "after"): {"pattern": "^.+$"},
    ("ArtifactEdgeSpec", "skew"): {"enum": list(SKEW_MODES)},
}


# ---------------------------------------------------------------------------
# Field descriptions from source comments
# ---------------------------------------------------------------------------

_FIELD_DEF_RE = re.compile(r"^\s+(\w+)\s*:\s*[^=#]+(?:=.*)?$")


def _field_comments(cls: type) -> dict[str, str]:
    """Collect the ``#`` comment block directly above each field definition,
    walking the MRO so inherited fields keep their descriptions."""
    out: dict[str, str] = {}
    for klass in reversed(cls.__mro__):
        if not hasattr(klass, "__dataclass_fields__"):
            continue
        try:
            src = inspect.getsource(klass)
        except (OSError, TypeError):  # pragma: no cover - source unavailable
            continue
        pending: list[str] = []
        for line in src.splitlines():
            stripped = line.strip()
            if stripped.startswith("#"):
                pending.append(stripped.lstrip("#").strip())
                continue
            m = _FIELD_DEF_RE.match(line)
            if m and m.group(1) in klass.__dataclass_fields__:
                if pending:
                    out[m.group(1)] = " ".join(pending)
            pending = []
    return out


def _doc_first_paragraph(cls: type) -> str:
    doc = inspect.getdoc(cls) or ""
    return doc.split("\n\n")[0].replace("\n", " ").strip()


# ---------------------------------------------------------------------------
# Schema generation
# ---------------------------------------------------------------------------


def _unwrap_optional(hint: Any) -> Any:
    if get_origin(hint) is Union:
        args = [a for a in get_args(hint) if a is not type(None)]
        if len(args) == 1:
            return args[0]
    return hint


def _default_json(value: Any) -> Any:
    if isinstance(value, _SpecBase):
        return value.to_dict()
    if isinstance(value, IntOrString):
        return value.value
    return value


def spec_schema(cls: type = TPUUpgradePolicySpec) -> dict[str, Any]:
    """OpenAPI v3 structural schema for a ``_SpecBase`` dataclass."""
    hints = get_type_hints(cls)
    comments = _field_comments(cls)
    props: dict[str, Any] = {}
    for f in fields(cls):
        hint = _unwrap_optional(hints[f.name])
        key = _JSON_NAME_OVERRIDES.get(f.name, _camel(f.name))
        origin = get_origin(hint)
        if origin is list:
            (item_hint,) = get_args(hint)
            if isinstance(item_hint, type) and issubclass(
                item_hint, _SpecBase
            ):
                items = spec_schema(item_hint)
            elif item_hint is str:
                items = {"type": "string"}
            else:  # pragma: no cover - no such list item types today
                raise TypeError(
                    f"{cls.__name__}.{f.name}: unmapped list item "
                    f"type {item_hint!r}"
                )
            sub = {"type": "array", "items": items}
        elif origin is dict:
            # Only string->string maps appear today (node selectors).
            sub = {
                "type": "object",
                "additionalProperties": {"type": "string"},
            }
        elif isinstance(hint, type) and issubclass(hint, _SpecBase):
            sub = spec_schema(hint)
        elif hint is IntOrString:
            # apiextensions IntOrString marker (reference
            # upgrade_spec.go:39-45 uses apimachinery intstr).
            sub = {"x-kubernetes-int-or-string": True}
        elif hint is bool:
            sub = {"type": "boolean"}
        elif hint is int:
            sub = {"type": "integer"}
        elif hint is float:
            sub = {"type": "number"}
        elif hint is str:
            sub = {"type": "string"}
        else:  # pragma: no cover - no such field types today
            raise TypeError(f"{cls.__name__}.{f.name}: unmapped type {hint!r}")
        sub.update(_CONSTRAINTS.get((cls.__name__, f.name), {}))
        if f.name in comments:
            sub.setdefault("description", comments[f.name])
        default: Any = MISSING
        if f.default is not MISSING:
            default = f.default
        elif f.default_factory is not MISSING:  # type: ignore[misc]
            default = f.default_factory()  # type: ignore[misc]
        if default is not MISSING and default is not None:
            sub["default"] = _default_json(default)
        props[key] = sub
    schema: dict[str, Any] = {"type": "object", "properties": props}
    desc = _doc_first_paragraph(cls)
    if desc:
        schema["description"] = desc
    return schema


POLICY_GROUP = "upgrade.tpu.google.com"
POLICY_VERSION = "v1alpha1"
POLICY_PLURAL = "tpuupgradepolicies"
POLICY_KIND = "TPUUpgradePolicy"


def crd_manifest(
    group: str = POLICY_GROUP,
    kind: str = POLICY_KIND,
    plural: str = POLICY_PLURAL,
    version: str = POLICY_VERSION,
    spec_cls: type = TPUUpgradePolicySpec,
) -> dict[str, Any]:
    """Full CustomResourceDefinition manifest embedding the policy schema."""
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": f"{plural}.{group}"},
        "spec": {
            "group": group,
            "names": {
                "kind": kind,
                "listKind": f"{kind}List",
                "plural": plural,
                "singular": kind.lower(),
            },
            "scope": "Namespaced",
            "versions": [
                {
                    "name": version,
                    "served": True,
                    "storage": True,
                    "subresources": {"status": {}},
                    # `kubectl get tpuupgradepolicy` shows roll progress
                    # from the status the controller publishes.
                    "additionalPrinterColumns": [
                        {
                            "name": "Auto",
                            "type": "boolean",
                            "jsonPath": ".spec.autoUpgrade",
                        },
                        {
                            "name": "Done",
                            "type": "integer",
                            "jsonPath": ".status.upgradesDone",
                        },
                        {
                            "name": "In-Progress",
                            "type": "integer",
                            "jsonPath": ".status.upgradesInProgress",
                        },
                        {
                            "name": "Failed",
                            "type": "integer",
                            "jsonPath": ".status.upgradesFailed",
                        },
                        {
                            "name": "Age",
                            "type": "date",
                            "jsonPath": ".metadata.creationTimestamp",
                        },
                    ],
                    "schema": {
                        "openAPIV3Schema": {
                            "type": "object",
                            "properties": {
                                "apiVersion": {"type": "string"},
                                "kind": {"type": "string"},
                                "metadata": {"type": "object"},
                                "spec": spec_schema(spec_cls),
                                "status": {
                                    "type": "object",
                                    "x-kubernetes-preserve-unknown-fields": True,
                                },
                            },
                        }
                    },
                }
            ],
        },
    }


def register_policy_crd(cluster) -> None:
    """Install the TPUUpgradePolicy CRD on a cluster/store (the runtime
    analogue of ``kubectl apply -f config/crd/``): enables the CR routes
    and wires the generated schema in as the admission validator, so an
    invalid CR is rejected 422 with field paths."""
    schema = spec_schema(TPUUpgradePolicySpec)

    def _validate(obj: dict) -> list[str]:
        return validate_object(obj.get("spec") or {}, schema)

    cluster.register_custom_resource(
        POLICY_GROUP, POLICY_VERSION, POLICY_PLURAL, validator=_validate
    )


# ---------------------------------------------------------------------------
# Miniature structural-schema validator
# ---------------------------------------------------------------------------


def validate_object(
    obj: Any, schema: dict[str, Any], path: str = "spec"
) -> list[str]:
    """Validate ``obj`` against a schema produced above.

    Returns apiserver-style error strings (empty list = valid).  Stricter
    than apiserver pruning on one point: unknown fields are *errors*, not
    silently dropped — a typoed key in a local policy file should fail
    loudly (``from_dict`` tolerates unknowns for wire compatibility,
    v1alpha1.py:119).
    """
    errors: list[str] = []
    if schema.get("x-kubernetes-int-or-string"):
        if not isinstance(obj, (int, str)) or isinstance(obj, bool):
            errors.append(f"{path}: must be an integer or a string")
        return errors
    typ = schema.get("type")
    if typ == "array":
        if not isinstance(obj, list):
            return [f"{path}: must be an array, got {type(obj).__name__}"]
        items = schema.get("items", {})
        for i, item in enumerate(obj):
            errors.extend(validate_object(item, items, f"{path}[{i}]"))
        return errors
    if typ == "object":
        if not isinstance(obj, dict):
            return [f"{path}: must be an object, got {type(obj).__name__}"]
        if schema.get("x-kubernetes-preserve-unknown-fields"):
            return errors
        extra = schema.get("additionalProperties")
        if extra is not None and "properties" not in schema:
            # Map type (e.g. a node selector): every value validates
            # against the additionalProperties schema, any key admitted.
            for key, val in obj.items():
                errors.extend(validate_object(val, extra, f"{path}.{key}"))
            return errors
        props = schema.get("properties", {})
        for key, val in obj.items():
            sub = props.get(key)
            if sub is None:
                errors.append(f'{path}.{key}: unknown field "{key}"')
            elif val is not None:
                errors.extend(validate_object(val, sub, f"{path}.{key}"))
        return errors
    if typ == "boolean" and not isinstance(obj, bool):
        return [f"{path}: must be a boolean, got {type(obj).__name__}"]
    if typ == "integer" and (isinstance(obj, bool) or not isinstance(obj, int)):
        return [f"{path}: must be an integer, got {type(obj).__name__}"]
    if typ == "number" and (
        isinstance(obj, bool) or not isinstance(obj, (int, float))
    ):
        return [f"{path}: must be a number, got {type(obj).__name__}"]
    if typ == "string" and not isinstance(obj, str):
        return [f"{path}: must be a string, got {type(obj).__name__}"]
    if "enum" in schema and obj not in schema["enum"]:
        errors.append(
            f"{path}: unsupported value {obj!r}, expected one of "
            + ", ".join(repr(e) for e in schema["enum"])
        )
    if "pattern" in schema and isinstance(obj, str):
        if not re.match(schema["pattern"], obj):
            errors.append(
                f"{path}: {obj!r} does not match pattern {schema['pattern']!r}"
            )
    if isinstance(obj, (int, float)) and not isinstance(obj, bool):
        if "minimum" in schema and obj < schema["minimum"]:
            errors.append(
                f"{path}: must be greater than or equal to {schema['minimum']}"
            )
        if "maximum" in schema and obj > schema["maximum"]:
            errors.append(
                f"{path}: must be less than or equal to {schema['maximum']}"
            )
    return errors
