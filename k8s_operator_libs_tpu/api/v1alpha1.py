"""v1alpha1 policy API: CRD-embeddable upgrade policy types.

Capability parity with the reference's
``api/upgrade/v1alpha1/upgrade_spec.go:27-110`` (DriverUpgradePolicySpec,
WaitForCompletionSpec, PodDeletionSpec, DrainSpec with kubebuilder
defaults/validation) and ``zz_generated.deepcopy.go`` (deep-copy), plus the
TPU-native extensions specified in SURVEY.md §7 step 1: slice topology,
slice-atomicity mode, ICI health gate, and slice-granular unavailability.

Types serialize to/from the same camelCase JSON shape a consumer operator
would embed in its CRD, so a policy YAML written for the reference loads
unchanged into :class:`DriverUpgradePolicySpec`.
"""

from __future__ import annotations

import copy
import math
import re
from dataclasses import dataclass, field, fields
from typing import Any, Optional, Union


class ValidationError(ValueError):
    """Raised when a spec violates its (kubebuilder-style) validation rules."""


# ---------------------------------------------------------------------------
# IntOrString — analogue of k8s.io/apimachinery/pkg/util/intstr
# ---------------------------------------------------------------------------

_PERCENT_RE = re.compile(r"^(\d+)%$")


@dataclass(frozen=True)
class IntOrString:
    """An int count or a percentage string like ``"25%"``.

    Mirrors apimachinery's intstr type as used by MaxUnavailable
    (reference upgrade_spec.go:39-45).
    """

    value: Union[int, str]

    def __post_init__(self) -> None:
        if isinstance(self.value, str) and not _PERCENT_RE.match(self.value):
            raise ValidationError(
                f"invalid IntOrString {self.value!r}: string form must be 'N%'"
            )
        if isinstance(self.value, int) and self.value < 0:
            raise ValidationError("IntOrString int form must be >= 0")

    def scaled_value(self, total: int, round_up: bool = True) -> int:
        """Resolve to an absolute count against ``total``.

        Analogue of ``intstr.GetScaledValueFromIntOrPercent`` as called at
        reference upgrade_state.go:395-401 (percentage rounds up).
        """
        if isinstance(self.value, int):
            return self.value
        pct = int(_PERCENT_RE.match(self.value).group(1))
        if round_up:
            return math.ceil(pct * total / 100)
        return math.floor(pct * total / 100)

    @classmethod
    def parse(cls, raw: Union[int, str, "IntOrString"]) -> "IntOrString":
        if isinstance(raw, IntOrString):
            return raw
        return cls(raw)


# ---------------------------------------------------------------------------
# Spec base with camelCase JSON round-trip + deep copy
# ---------------------------------------------------------------------------


def _camel(name: str) -> str:
    parts = name.split("_")
    return parts[0] + "".join(p.title() for p in parts[1:])


_JSON_NAME_OVERRIDES = {
    # Reference upgrade_spec.go:48: field DrainSpec serializes as "drain".
    "drain_spec": "drain",
    # Reference upgrade_spec.go:63,77,104: TimeoutSecond -> "timeoutSeconds".
    "timeout_second": "timeoutSeconds",
    "stuck_threshold_second": "stuckThresholdSeconds",
    "evict_timeout_second": "evictTimeoutSeconds",
    "delete_timeout_second": "deleteTimeoutSeconds",
    "ready_dwell_second": "readyDwellSeconds",
    "pdb_grace_second": "pdbGraceSeconds",
    "offer_timeout_second": "offerTimeoutSeconds",
    "rejoin_timeout_second": "rejoinTimeoutSeconds",
    "drift_threshold_second": "driftThresholdSeconds",
    "replan_interval_second": "replanIntervalSeconds",
    "soak_second": "soakSeconds",
    "lease_duration_second": "leaseDurationSeconds",
}


class _SpecBase:
    """camelCase JSON (de)serialization + deep-copy for all spec types."""

    def to_dict(self) -> dict[str, Any]:
        out: dict[str, Any] = {}
        for f in fields(self):
            v = getattr(self, f.name)
            if v is None:
                continue
            if isinstance(v, list) and not v:
                continue  # empty list = unset, like None
            key = _JSON_NAME_OVERRIDES.get(f.name, _camel(f.name))
            if isinstance(v, _SpecBase):
                out[key] = v.to_dict()
            elif isinstance(v, IntOrString):
                out[key] = v.value
            elif isinstance(v, list):
                out[key] = [
                    x.to_dict() if isinstance(x, _SpecBase) else x for x in v
                ]
            else:
                out[key] = v
        return out

    @classmethod
    def from_dict(cls, data: dict[str, Any]) -> "Any":
        kwargs: dict[str, Any] = {}
        by_json_name = {}
        for f in fields(cls):
            by_json_name[_JSON_NAME_OVERRIDES.get(f.name, _camel(f.name))] = f
        for key, raw in (data or {}).items():
            f = by_json_name.get(key)
            if f is None:
                continue  # tolerate unknown fields like the apiserver does
            if raw is None:
                # Explicit null = unset: a structural-schema apiserver
                # prunes nulls and applies the field default.
                continue
            typ = _NESTED_TYPES.get((cls.__name__, f.name))
            if typ is not None and isinstance(raw, list):
                kwargs[f.name] = [typ.from_dict(item) for item in raw]
            elif typ is not None and raw is not None:
                kwargs[f.name] = typ.from_dict(raw)
            elif f.name == "max_unavailable" and raw is not None:
                kwargs[f.name] = IntOrString.parse(raw)
            else:
                kwargs[f.name] = raw
        return cls(**kwargs)

    def deep_copy(self):
        """Analogue of the controller-gen DeepCopy (zz_generated.deepcopy.go)."""
        return copy.deepcopy(self)

    def validate(self) -> None:  # overridden where rules exist
        pass


# ---------------------------------------------------------------------------
# Reference-parity specs
# ---------------------------------------------------------------------------


@dataclass
class WaitForCompletionSpec(_SpecBase):
    """Wait-for-job-completion configuration (upgrade_spec.go:51-64)."""

    pod_selector: str = ""
    # 0 means wait forever.
    timeout_second: int = 0

    def validate(self) -> None:
        if self.timeout_second < 0:
            raise ValidationError("waitForCompletion.timeoutSeconds must be >= 0")


@dataclass
class PodDeletionSpec(_SpecBase):
    """Workload pod deletion configuration (upgrade_spec.go:66-83)."""

    force: bool = False
    timeout_second: int = 300
    delete_empty_dir: bool = False

    def validate(self) -> None:
        if self.timeout_second < 0:
            raise ValidationError("podDeletion.timeoutSeconds must be >= 0")


@dataclass
class EvictionEscalationSpec(_SpecBase):
    """Eviction escalation ladder (new; no reference analogue).

    When a drain's eviction stalls — a PodDisruptionBudget that never
    releases, or a pod held Terminating by a finalizer — the ladder
    escalates evict → delete → force-delete (grace 0), each rung gated
    by its own timeout.  Disabled by default; the force rung is
    separately opt-in because force-deleting a pod whose kubelet is
    still alive can leave containers running on the ICI domain.
    """

    enable: bool = False
    # Seconds a pod may resist eviction before escalating to delete.
    evict_timeout_second: int = 300
    # Seconds a delete may dangle (stuck Terminating) before force.
    delete_timeout_second: int = 300
    # Allow the final rung: delete with gracePeriodSeconds=0.
    allow_force_delete: bool = False
    # PDB-aware hold: extra seconds a pod whose evictions are rejected
    # by a PodDisruptionBudget may stay at the evict rung PAST
    # evictTimeoutSeconds before escalating to a PDB-bypassing delete —
    # the budget releasing is plausibly imminent, so keep asking instead
    # of timing out blind.  0 disables the hold.
    pdb_grace_second: int = 0

    def validate(self) -> None:
        if self.evict_timeout_second < 0 or self.delete_timeout_second < 0:
            raise ValidationError(
                "evictionEscalation timeouts must be >= 0"
            )
        if self.pdb_grace_second < 0:
            raise ValidationError(
                "evictionEscalation.pdbGraceSeconds must be >= 0"
            )


@dataclass
class DrainSpec(_SpecBase):
    """Node drain configuration (upgrade_spec.go:85-110), extended with
    the opt-in eviction escalation ladder."""

    enable: bool = False
    force: bool = False
    pod_selector: str = ""
    timeout_second: int = 300
    delete_empty_dir: bool = False
    eviction_escalation: Optional[EvictionEscalationSpec] = None

    def validate(self) -> None:
        if self.timeout_second < 0:
            raise ValidationError("drain.timeoutSeconds must be >= 0")
        if self.eviction_escalation is not None:
            self.eviction_escalation.validate()


@dataclass
class DriverUpgradePolicySpec(_SpecBase):
    """Automatic-upgrade policy (upgrade_spec.go:24-49).

    Defaults mirror the reference's kubebuilder markers: autoUpgrade=false,
    maxParallelUpgrades=1 (0 = unlimited), maxUnavailable="25%".
    """

    auto_upgrade: bool = False
    max_parallel_upgrades: int = 1
    max_unavailable: Optional[IntOrString] = field(
        default_factory=lambda: IntOrString("25%")
    )
    pod_deletion: Optional[PodDeletionSpec] = None
    wait_for_completion: Optional[WaitForCompletionSpec] = None
    drain_spec: Optional[DrainSpec] = None

    def validate(self) -> None:
        if self.max_parallel_upgrades < 0:
            raise ValidationError("maxParallelUpgrades must be >= 0")
        for sub in (self.pod_deletion, self.wait_for_completion, self.drain_spec):
            if sub is not None:
                sub.validate()


# ---------------------------------------------------------------------------
# TPU-native extensions (new; SURVEY.md §7 step 1)
# ---------------------------------------------------------------------------


@dataclass
class SliceTopologySpec(_SpecBase):
    """Explicit slice-topology override.

    Normally slice membership is discovered from GKE TPU node labels
    (cloud.google.com/gke-tpu-topology et al.); this spec lets a consumer
    pin the expectation so discovery drift fails loudly.
    """

    # e.g. "tpu-v5p-slice"
    accelerator: str = ""
    # Chip topology string, e.g. "2x2x4" (v5p-16: 8 chips? no — chips) —
    # product of dims = chips in the slice.
    topology: str = ""
    # Hosts forming one ICI domain; 0 = derive from topology/accelerator.
    hosts_per_slice: int = 0

    _TOPOLOGY_RE = re.compile(r"^\d+x\d+(x\d+)?$")

    def validate(self) -> None:
        if self.topology and not self._TOPOLOGY_RE.match(self.topology):
            raise ValidationError(
                f"topology {self.topology!r} must look like '2x2x4'"
            )
        if self.hosts_per_slice < 0:
            raise ValidationError("hostsPerSlice must be >= 0")

    def chips(self) -> int:
        if not self.topology:
            return 0
        dims = [int(d) for d in self.topology.split("x")]
        n = 1
        for d in dims:
            n *= d
        return n


@dataclass
class SliceHealthGateSpec(_SpecBase):
    """ICI/XLA health gate run in the validation state (new component).

    Replaces the reference's out-of-repo nvidia-smi validation pods
    (SURVEY.md §5 'Collective-health probing'): "validated" means the slice
    re-formed completely and an XLA all-reduce over ICI completes.
    """

    enable: bool = True
    # Seconds to wait for one all-reduce probe before declaring it hung.
    all_reduce_timeout_second: int = 60
    # Fraction of expected devices that must re-enumerate; north star = 1.0.
    min_reformation_fraction: float = 1.0
    # Also probe DCN reachability between slices of one multi-slice group.
    dcn_check: bool = False
    # Overall validation deadline before the slice is marked failed
    # (reference validation_manager.go:32 uses a fixed 600s).
    timeout_second: int = 600
    # Route confirmed fleet-health stragglers (sustained below-baseline
    # probe telemetry) into the slice-quarantine path.  Off by default:
    # the telemetry plane is observe-only unless the operator opts in.
    quarantine_stragglers: bool = False

    def validate(self) -> None:
        if not (0.0 <= self.min_reformation_fraction <= 1.0):
            raise ValidationError("minReformationFraction must be in [0, 1]")
        if self.all_reduce_timeout_second < 0 or self.timeout_second < 0:
            raise ValidationError("health gate timeouts must be >= 0")


@dataclass
class SliceQuarantineSpec(_SpecBase):
    """Data-plane fault handling for in-flight slices (new component).

    When a member of an in-flight slice goes NotReady or vanishes, the
    whole slice parks in the ``quarantined`` state: it stops charging
    the unavailability budget and holds its position until every host
    stays Ready for ``ready_dwell_second`` (hysteresis — a flapping
    kubelet must not thrash cordon/uncordon), then resumes the exact
    state it left.  Enabled by default: parking a slice on dead
    hardware is strictly safer than letting it pin budget forever.
    """

    enable: bool = True
    # Seconds every host must stay Ready before the slice rejoins the
    # roll.  The dwell clock restarts on any readiness flap.
    ready_dwell_second: int = 300
    # Cap on quarantine cycles per slice: hardware that keeps flapping
    # across dwell windows demotes to upgrade-failed (with a
    # QuarantineCycleLimit event) once it has been parked this many
    # times, instead of park/rejoin thrashing forever.  0 = unlimited.
    max_cycles: int = 3

    def validate(self) -> None:
        if self.ready_dwell_second < 0:
            raise ValidationError(
                "sliceQuarantine.readyDwellSeconds must be >= 0"
            )
        if self.max_cycles < 0:
            raise ValidationError(
                "sliceQuarantine.maxCycles must be >= 0"
            )


@dataclass
class ElasticCoordinationSpec(_SpecBase):
    """Workload-negotiated mesh reshaping for zero-downtime rolls (new
    component, Tenplex-style elasticity).

    With coordination enabled, an admitted slice whose nodes carry a
    workload registration annotation is offered for exclusion instead of
    being cordoned outright: the workload resizes its mesh away from the
    slice (checkpoint-free, host-side snapshot + re-shard), the roll
    proceeds with zero workload downtime, and after uncordon the slice is
    offered back for a rejoin-resize.  Decline or timeout falls back to
    the pre-existing drain path — coordination only adds capability,
    never removes safety.  Disabled by default: it requires an elastic
    workload agent (coordination.WorkloadCoordinator) in the job.
    """

    enable: bool = False
    # Seconds the controller waits for the workload's accept/decline +
    # resize-complete before falling back to the drain path.
    offer_timeout_second: int = 60
    # Seconds the controller waits after uncordon for the rejoin-resize
    # before declaring the group done anyway (the workload can rejoin
    # later on its own schedule; the roll must not hang on it).
    rejoin_timeout_second: int = 300

    def validate(self) -> None:
        if self.offer_timeout_second < 0:
            raise ValidationError(
                "elastic.offerTimeoutSeconds must be >= 0"
            )
        if self.rejoin_timeout_second < 0:
            raise ValidationError(
                "elastic.rejoinTimeoutSeconds must be >= 0"
            )


@dataclass
class MaintenanceWindowSpec(_SpecBase):
    """Cron-style UTC maintenance window for one pool (new component).

    The expression is a standard 5-field cron (minute hour day-of-month
    month day-of-week, UTC) read as a *membership test*: the window is
    open at an instant iff every field matches, so ``"* 2-5 * * 6,0"``
    means 02:00-05:59 UTC on weekends.  Outside the window the pool's
    groups hold in a budget-free ``window-wait`` condition — no state
    transitions, no budget charge — and resume where they stopped when
    the window opens.
    """

    # 5-field cron membership expression, UTC.  "* * * * *" = always open.
    cron: str = "* * * * *"

    def validate(self) -> None:
        from k8s_operator_libs_tpu.fleet.windows import validate_window

        try:
            validate_window(self.cron)
        except ValueError as e:
            raise ValidationError(f"maintenanceWindow.cron: {e}") from e


@dataclass
class PoolSpec(_SpecBase):
    """One pool of a heterogeneous fleet (new component).

    A pool is a labelled subset of the managed nodes — typically one
    device generation — with its own roll envelope: target driver
    version, budget overrides, and an optional maintenance window.
    Budgets compose as a hierarchy: an admission must fit the FLEET caps
    and the pool's own caps simultaneously.
    """

    # Pool identity (required, unique within the policy).
    name: str = ""
    # Label selector matching this pool's nodes (all pairs must match),
    # e.g. {"cloud.google.com/gke-tpu-accelerator": "tpu-v4-podslice"}.
    node_selector: dict[str, str] = field(default_factory=dict)
    # Target driver version for this pool's DaemonSet (informational +
    # surfaced in status; the DaemonSet template hash remains the
    # authoritative "outdated" predicate).
    driver_version: str = ""
    # Per-pool maxUnavailable override; unset inherits the fleet cap.
    max_unavailable: Optional[IntOrString] = None
    # Per-pool maxParallelUpgrades override; unset inherits, 0 = unlimited
    # within the pool (the fleet cap still applies).
    max_parallel_upgrades: Optional[int] = None
    # Optional maintenance window; unset = always open.
    maintenance_window: Optional[MaintenanceWindowSpec] = None

    def validate(self) -> None:
        if not self.name:
            raise ValidationError("pool.name must be non-empty")
        if (
            self.max_parallel_upgrades is not None
            and self.max_parallel_upgrades < 0
        ):
            raise ValidationError(
                f"pool {self.name!r}: maxParallelUpgrades must be >= 0"
            )
        if self.maintenance_window is not None:
            try:
                self.maintenance_window.validate()
            except ValidationError as e:
                raise ValidationError(f"pool {self.name!r}: {e}") from e


@dataclass
class PlanningSpec(_SpecBase):
    """Predictive rollout planning knobs (new component).

    Tunes the drift watchdog that anchors an active roll to its
    analytic plan: how far reality may diverge from the projection
    before the controller re-plans, how often it may re-plan, and a
    ceiling on automatic re-plans per roll.  Planning itself is always
    on and read-only — these knobs only shape the watchdog's reaction.
    """

    # Drift (seconds behind projection) beyond which the watchdog
    # re-plans from the live snapshot.
    drift_threshold_second: int = 300
    # Minimum seconds between automatic re-plans.
    replan_interval_second: int = 60
    # Ceiling on automatic re-plans per roll (planning must never
    # become the hot path on a pathological fleet).
    max_replans: int = 5
    # How the admission pass orders chargeable groups.  "greedy" keeps
    # the historical generation-then-id order; "packed" lets admission
    # consult the watchdog's anchored plan and first-fit-decreasing
    # pack each wave (falls back to greedy whenever no fresh plan is
    # anchored).  Packing never relaxes budgets, DCN anti-affinity,
    # maintenance windows, or oldest-generation-first ordering.
    admission_mode: str = "greedy"

    ADMISSION_MODES = ("greedy", "packed")

    def validate(self) -> None:
        if self.drift_threshold_second < 0:
            raise ValidationError(
                "planning.driftThresholdSeconds must be >= 0"
            )
        if self.replan_interval_second < 0:
            raise ValidationError(
                "planning.replanIntervalSeconds must be >= 0"
            )
        if self.max_replans < 0:
            raise ValidationError("planning.maxReplans must be >= 0")
        if self.admission_mode not in self.ADMISSION_MODES:
            raise ValidationError(
                "planning.admissionMode must be 'greedy' or 'packed'"
            )


@dataclass
class FederationClusterSpec(_SpecBase):
    """One member cluster of a federated roll."""

    # Unique cluster name (the budget-hierarchy key).
    name: str = ""
    # Region the cluster belongs to (the canary/promotion unit).
    region: str = ""

    def validate(self) -> None:
        if not self.name:
            raise ValidationError("federation cluster: name is required")
        if not self.region:
            raise ValidationError(
                f"federation cluster {self.name!r}: region is required"
            )


@dataclass
class FederationCanarySpec(_SpecBase):
    """Regional canary gate for federated rolls."""

    # Region that rolls first (must match a cluster's region).
    region: str = ""
    # Seconds the canary region's health baselines must stay clean
    # after its roll completes before promotion to remaining regions.
    soak_second: int = 300

    def validate(self) -> None:
        if not self.region:
            raise ValidationError("federation.canary.region is required")
        if self.soak_second < 0:
            raise ValidationError(
                "federation.canary.soakSeconds must be >= 0"
            )


@dataclass
class FederationSpec(_SpecBase):
    """Federated (multi-cluster) roll configuration.

    Declares the member clusters, the canary region and soak, the
    GLOBAL unavailability budget (checked-and-charged above every
    cluster's own caps: global ∧ cluster ∧ pool), and the health-probe
    ladder that drives fail-static degradation (Reachable → Degraded →
    Partitioned).  See docs/federation.md.
    """

    enable: bool = False
    # Member clusters; each name must be unique.
    clusters: list[FederationClusterSpec] = field(default_factory=list)
    # Regional canary gate (required when enabled).
    canary: Optional[FederationCanarySpec] = None
    # GLOBAL maxUnavailable across every cluster (int or percentage of
    # the federation's total units); unset = no global cap beyond the
    # per-cluster policies.
    max_unavailable: Optional[IntOrString] = None
    # Global in-flight group ceiling across clusters (0 = unlimited).
    max_parallel_upgrades: int = 0
    # Consecutive failed health probes before a cluster is Degraded.
    degraded_after_probes: int = 1
    # Consecutive failed probes before Partitioned (an open circuit
    # breaker escalates straight here).
    partitioned_after_probes: int = 3
    # Consecutive clean probes a Partitioned cluster needs to step back
    # down the ladder (hysteresis against flapping WAN links).
    heal_probes: int = 2
    # Observer-clock staleness bound for member controller leases.
    lease_duration_second: int = 30

    def validate(self) -> None:
        if not self.enable:
            return
        if not self.clusters:
            raise ValidationError(
                "federation.enable requires at least one cluster"
            )
        seen: set[str] = set()
        regions: set[str] = set()
        for cluster in self.clusters:
            cluster.validate()
            if cluster.name in seen:
                raise ValidationError(
                    f"duplicate federation cluster name {cluster.name!r}"
                )
            seen.add(cluster.name)
            regions.add(cluster.region)
        if self.canary is None:
            raise ValidationError(
                "federation.enable requires federation.canary"
            )
        self.canary.validate()
        if self.canary.region not in regions:
            raise ValidationError(
                f"federation.canary.region {self.canary.region!r} "
                f"matches no cluster's region"
            )
        if self.max_parallel_upgrades < 0:
            raise ValidationError(
                "federation.maxParallelUpgrades must be >= 0"
            )
        if self.degraded_after_probes < 1:
            raise ValidationError(
                "federation.degradedAfterProbes must be >= 1"
            )
        if self.partitioned_after_probes < self.degraded_after_probes:
            raise ValidationError(
                "federation.partitionedAfterProbes must be >= "
                "degradedAfterProbes"
            )
        if self.heal_probes < 1:
            raise ValidationError("federation.healProbes must be >= 1")
        if self.lease_duration_second < 0:
            raise ValidationError(
                "federation.leaseDurationSeconds must be >= 0"
            )
        huge = 1 << 30
        if (
            self.max_unavailable is not None
            and self.max_unavailable.scaled_value(huge, round_up=True) == 0
        ):
            raise ValidationError(
                "federation.maxUnavailable admits zero units: the global "
                "roll can never start (plan-infeasible)"
            )


@dataclass
class ArtifactSpec(_SpecBase):
    """One artifact of a composable driver stack (device driver, network
    driver, device plugin, ...) managed as a node of the upgrade DAG."""

    # Unique artifact name (the DAG node id).
    name: str = ""
    # DaemonSet selector: pods/DaemonSets carrying these labels belong
    # to this artifact.
    match_labels: dict[str, str] = field(default_factory=dict)
    # Version the roll targets (compared by edges' requires constraints).
    target_version: str = ""
    # Per-artifact validation gate run inside the drain window before
    # the stack may advance past this artifact: "" (none) or
    # "network-path" (DCN reachability + ICI link state).
    gate: str = ""

    def validate(self) -> None:
        if not self.name:
            raise ValidationError("artifact: name is required")
        if not self.match_labels:
            raise ValidationError(
                f"artifact {self.name!r}: matchLabels is required"
            )


@dataclass
class ArtifactEdgeSpec(_SpecBase):
    """Dependency edge ``before -> after`` of the artifact DAG."""

    # Upstream artifact (must restart no later than `after`).
    before: str = ""
    # Downstream artifact.
    after: str = ""
    # Version-compatibility constraint the upstream's targetVersion must
    # satisfy (">=1.2", "==535.104.05", bare version = exact; empty =
    # unconstrained).  Checked at admission against declared targets.
    requires: str = ""
    # "lockstep": both ends restart in the same step of the shared
    # window.  "pinned-order": `after` may not restart until `before`
    # is fully synced (and gated, if it declares a gate).
    skew: str = "lockstep"

    def validate(self) -> None:
        if not self.before or not self.after:
            raise ValidationError(
                "artifact edge: before and after are required"
            )


@dataclass
class ArtifactDAGSpec(_SpecBase):
    """The policy's composable driver stack: artifacts + edges.

    Structural validation (cycles, dangling edges, skew conflicts,
    unsatisfiable constraints) lives in
    :class:`k8s_operator_libs_tpu.artifacts.dag.ArtifactDAG` and runs
    through ``_validate_feasibility`` — an invalid stack rejects the
    policy at admission.  A single-item stack is the classic
    one-DaemonSet path, byte for byte.
    """

    items: list[ArtifactSpec] = field(default_factory=list)
    edges: list[ArtifactEdgeSpec] = field(default_factory=list)

    def validate(self) -> None:
        for item in self.items:
            item.validate()
        for edge in self.edges:
            edge.validate()


@dataclass
class TPUUpgradePolicySpec(DriverUpgradePolicySpec):
    """Slice-aware upgrade policy for TPU node pools.

    Extends the reference policy with the TPU north-star fields:

    - ``slice_atomic``: all hosts of one ICI domain transition as a unit —
      the torus is never split (SURVEY.md §7 step 2);
    - ``unavailability_unit``: whether maxParallelUpgrades/maxUnavailable
      count slices or individual hosts;
    - ``health_gate``: the ICI/XLA validation gate;
    - ``dcn_anti_affinity``: never take two slices of the same DCN
      (multi-slice data-parallel) group down simultaneously.
    """

    UNAVAILABILITY_UNITS = ("slice", "node")

    slice_atomic: bool = True
    # "slice" or "node".
    unavailability_unit: str = "slice"
    topology: Optional[SliceTopologySpec] = None
    health_gate: Optional[SliceHealthGateSpec] = field(
        default_factory=SliceHealthGateSpec
    )
    dcn_anti_affinity: bool = True
    # Seconds a group may dwell in one in-progress state before the
    # engine emits stuck-state Warning events with the progress-blocker
    # reason (0 disables).  Distinct from the validation timeout: this is
    # telemetry, not a transition.
    stuck_threshold_second: int = 300
    # Pipelined validation ("optimistic uncordon"): as soon as a slice's
    # driver pods are back in sync, its hosts are uncordoned and the
    # workload readmitted WHILE the health gate still runs; a slice in
    # that phase is schedulable, so it stops consuming parallel slots and
    # unavailability budget and the next slice's drain overlaps its
    # validation.  A failed/timed-out gate re-cordons the slice and marks
    # it upgrade-failed.  Tradeoff (opt-in): the workload may run briefly
    # on a slice the gate later rejects — acceptable when the continuous
    # per-host probe agents already vouch for basic chip health, and
    # required to meet a <2 min budget on multi-slice pools.
    pipeline_validation: bool = False
    # Data-plane fault handling: quarantine in-flight slices that lose a
    # host instead of charging the budget while hardware is dead.
    slice_quarantine: Optional[SliceQuarantineSpec] = field(
        default_factory=SliceQuarantineSpec
    )
    # Elastic roll coordination: negotiate workload mesh reshaping before
    # cordoning a slice (None/disabled = today's drain rolls unchanged).
    elastic: Optional[ElasticCoordinationSpec] = None
    # Heterogeneous-fleet pools: per-generation node subsets, each with
    # its own driver target, budget overrides, and maintenance window.
    # Empty = the whole fleet is one implicit pool (prior behavior).
    pools: list[PoolSpec] = field(default_factory=list)
    # Predictive rollout planning / drift-watchdog knobs; None = planner
    # defaults (planning is always on — it is read-only).
    planning: Optional[PlanningSpec] = None
    # Federated (multi-cluster) roll: member clusters, regional canary
    # gate, global budget, partition-tolerance ladder.  None/disabled =
    # single-cluster behavior unchanged.
    federation: Optional[FederationSpec] = None
    # Multi-artifact upgrade DAG: the composable driver stack this
    # policy rolls under ONE cordon/drain window per node.  None or a
    # single item = the classic one-DaemonSet behavior unchanged.
    artifacts: Optional[ArtifactDAGSpec] = None

    def validate(self) -> None:
        super().validate()
        if self.stuck_threshold_second < 0:
            raise ValidationError("stuckThresholdSeconds must be >= 0")
        if self.unavailability_unit not in self.UNAVAILABILITY_UNITS:
            raise ValidationError(
                "unavailabilityUnit must be 'slice' or 'node', got "
                f"{self.unavailability_unit!r}"
            )
        if self.topology is not None:
            self.topology.validate()
        if self.health_gate is not None:
            self.health_gate.validate()
        if self.slice_quarantine is not None:
            self.slice_quarantine.validate()
        if self.elastic is not None:
            self.elastic.validate()
        if self.planning is not None:
            self.planning.validate()
        if self.federation is not None:
            self.federation.validate()
        if self.artifacts is not None:
            self.artifacts.validate()
        seen_pools: set[str] = set()
        for pool in self.pools:
            pool.validate()
            if pool.name in seen_pools:
                raise ValidationError(f"duplicate pool name {pool.name!r}")
            seen_pools.add(pool.name)
        self._validate_feasibility()

    def _validate_feasibility(self) -> None:
        """Admission-time plan feasibility: reject a policy whose roll
        can PROVABLY never finish — a budget that admits zero units
        regardless of fleet size, or a maintenance window whose cron is
        syntactically valid but never matches a real instant (Feb 31).
        Fleet-dependent deadlocks (a slice whose node cost exceeds a
        nonzero cap) are a runtime planner/watchdog verdict — they
        depend on the observed fleet, not the policy alone."""
        from k8s_operator_libs_tpu.fleet.windows import next_open

        huge = 1 << 30  # any positive percentage of this rounds up >= 1
        if (
            self.auto_upgrade
            and self.max_unavailable is not None
            and self.max_unavailable.scaled_value(huge, round_up=True) == 0
        ):
            raise ValidationError(
                "maxUnavailable admits zero units: the roll can never "
                "start (plan-infeasible)"
            )
        for pool in self.pools:
            if (
                pool.max_unavailable is not None
                and pool.max_unavailable.scaled_value(huge, round_up=True)
                == 0
            ):
                raise ValidationError(
                    f"pool {pool.name!r}: maxUnavailable admits zero "
                    "units — the pool can never be upgraded "
                    "(plan-infeasible)"
                )
            window = pool.maintenance_window
            if window is not None and window.cron:
                try:
                    opens = next_open(window.cron)
                except ValueError:
                    continue  # pool.validate() already rejected syntax
                if opens is None:
                    raise ValidationError(
                        f"pool {pool.name!r}: maintenanceWindow.cron "
                        f"{window.cron!r} never opens (plan-infeasible)"
                    )
        if self.artifacts is not None and self.artifacts.items:
            # Structural DAG feasibility: cycles, dangling/self edges,
            # lockstep/pinned-order conflicts, unsatisfiable version
            # constraints.  Deferred import — artifacts.dag is pure
            # graph code but api must stay importable standalone.
            from k8s_operator_libs_tpu.artifacts.dag import (
                ArtifactDAG,
                ArtifactDAGError,
            )

            try:
                ArtifactDAG.from_spec(self.artifacts).validate()
            except ArtifactDAGError as e:
                raise ValidationError(f"artifacts: {e}") from e


# Nested-type registry for from_dict (maps (class, field) -> spec type).
_NESTED_TYPES: dict[tuple[str, str], Any] = {
    ("DriverUpgradePolicySpec", "pod_deletion"): PodDeletionSpec,
    ("DriverUpgradePolicySpec", "wait_for_completion"): WaitForCompletionSpec,
    ("DriverUpgradePolicySpec", "drain_spec"): DrainSpec,
    ("DrainSpec", "eviction_escalation"): EvictionEscalationSpec,
    ("TPUUpgradePolicySpec", "pod_deletion"): PodDeletionSpec,
    ("TPUUpgradePolicySpec", "wait_for_completion"): WaitForCompletionSpec,
    ("TPUUpgradePolicySpec", "drain_spec"): DrainSpec,
    ("TPUUpgradePolicySpec", "topology"): SliceTopologySpec,
    ("TPUUpgradePolicySpec", "health_gate"): SliceHealthGateSpec,
    ("TPUUpgradePolicySpec", "slice_quarantine"): SliceQuarantineSpec,
    ("TPUUpgradePolicySpec", "elastic"): ElasticCoordinationSpec,
    ("TPUUpgradePolicySpec", "planning"): PlanningSpec,
    ("TPUUpgradePolicySpec", "federation"): FederationSpec,
    ("FederationSpec", "canary"): FederationCanarySpec,
    ("TPUUpgradePolicySpec", "artifacts"): ArtifactDAGSpec,
    # List-of-nested: from_dict maps each element through the type.
    ("TPUUpgradePolicySpec", "pools"): PoolSpec,
    ("FederationSpec", "clusters"): FederationClusterSpec,
    ("ArtifactDAGSpec", "items"): ArtifactSpec,
    ("ArtifactDAGSpec", "edges"): ArtifactEdgeSpec,
    ("PoolSpec", "maintenance_window"): MaintenanceWindowSpec,
}
