"""CRD-embeddable policy types (analogue of the reference's ``api/upgrade``)."""

from k8s_operator_libs_tpu.api.v1alpha1 import (  # noqa: F401
    ArtifactDAGSpec,
    ArtifactEdgeSpec,
    ArtifactSpec,
    DrainSpec,
    DriverUpgradePolicySpec,
    ElasticCoordinationSpec,
    EvictionEscalationSpec,
    FederationCanarySpec,
    FederationClusterSpec,
    FederationSpec,
    IntOrString,
    PlanningSpec,
    PodDeletionSpec,
    SliceHealthGateSpec,
    SliceQuarantineSpec,
    SliceTopologySpec,
    TPUUpgradePolicySpec,
    WaitForCompletionSpec,
)
from k8s_operator_libs_tpu.api.schema import (  # noqa: F401
    crd_manifest,
    spec_schema,
    validate_object,
)
