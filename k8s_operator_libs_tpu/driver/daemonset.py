"""libtpu device-plugin DaemonSet: spec builder + reconciler.

The genuinely new "thin TPU device-plugin reconciler" from the north star
(BASELINE.json): where the reference assumes consumer operators deploy an
NVIDIA driver container, this module *owns* the driver DaemonSet —
building a deterministic spec for the libtpu/device-plugin pod and
reconciling the live object toward it.

Design points:

- **OnDelete update strategy**: when the template changes, the DS
  controller records a new ControllerRevision but does NOT restart pods;
  the upgrade state machine detects outdated pods via revision hashes
  (pod_manager parity with reference pod_manager.go:87-121) and rolls
  them slice-atomically.  The DS controller must never split a torus on
  its own.
- **Template hashing**: the reconciler annotates the DaemonSet with a
  content hash of the desired template; drift (image bump, env change)
  is detected by hash comparison, so reconcile is cheap and idempotent.
- **Safe-load init container**: optional; runs
  ``python -m k8s_operator_libs_tpu.driver.safe_load_init``, which holds
  libtpu load until the controller has quiesced the slice (§3.5 protocol).
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field
from typing import Optional

from k8s_operator_libs_tpu.consts import get_logger
from k8s_operator_libs_tpu.k8s.client import NotFoundError
from k8s_operator_libs_tpu.k8s.objects import (
    DaemonSet,
    DaemonSetSpec,
    LabelSelectorSpec,
    ObjectMeta,
    PodTemplateSpec,
)
from k8s_operator_libs_tpu.topology.slices import GKE_TPU_ACCELERATOR_LABEL

logger = get_logger(__name__)

TEMPLATE_HASH_ANNOTATION = "tpu.google.com/driver-template-hash"


@dataclass
class DriverDaemonSetSpec:
    """Desired state of the libtpu driver / device-plugin DaemonSet."""

    name: str = "libtpu-device-plugin"
    namespace: str = "kube-system"
    image: str = "registry.local/libtpu-device-plugin"
    version: str = "latest"
    driver_name: str = "libtpu"
    # Schedule onto every TPU node (any accelerator type) by default; set
    # to restrict to one accelerator family.
    accelerator: Optional[str] = None
    safe_load: bool = True
    env: dict[str, str] = field(default_factory=dict)
    extra_labels: dict[str, str] = field(default_factory=dict)
    # ServiceAccount the pods run under.  Both pod kinds talk to the
    # apiserver (the safe-load init container sets/polls its node
    # annotation; the agent publishes health reports), so on an RBAC
    # cluster the default SA would 403.  config/manifests/ creates this
    # account bound to the node-reporter ClusterRole.
    service_account: str = "tpu-node-reporter"

    @property
    def selector_labels(self) -> dict[str, str]:
        """The IMMUTABLE pod selector: a stable minimal subset (the
        apiserver rejects any spec.selector change for the DaemonSet's
        lifetime, so extra_labels must never leak in here)."""
        return {"app": f"{self.driver_name}-driver"}

    @property
    def labels(self) -> dict[str, str]:
        return {
            **self.selector_labels,
            "app.kubernetes.io/managed-by": "tpu-operator-libs",
            **self.extra_labels,
        }

    def pod_spec(self) -> dict:
        return _pod_spec(self)

    # DaemonSet rolling semantics: the driver DS is OnDelete — the
    # upgrade state machine rolls its pods slice-atomically.
    update_strategy = "OnDelete"


def _base_pod(spec: DriverDaemonSetSpec) -> tuple[dict, list]:
    """Shared TPU-host pod skeleton + env list: priority, host network,
    the google.com/tpu taint toleration, survival of the cordon the
    upgrade itself performs, NODE_NAME downward API, optional
    accelerator pinning.  Both the driver and agent pods build on this —
    a taint or env fix must land in exactly one place."""
    env = [{"name": k, "value": v} for k, v in sorted(spec.env.items())]
    env.append(
        {
            "name": "NODE_NAME",
            "valueFrom": {"fieldRef": {"fieldPath": "spec.nodeName"}},
        }
    )
    pod: dict = {
        "priorityClassName": "system-node-critical",
        "serviceAccountName": spec.service_account,
        "hostNetwork": True,
        "tolerations": [
            # TPU nodes carry the google.com/tpu taint; driver and agent
            # (like any device plugin) must land there anyway — and must
            # also survive the cordon their own upgrade performs.
            {"key": "google.com/tpu", "operator": "Exists"},
            {"key": "node.kubernetes.io/unschedulable",
             "operator": "Exists", "effect": "NoSchedule"},
        ],
    }
    if spec.accelerator:
        pod["nodeSelector"] = {GKE_TPU_ACCELERATOR_LABEL: spec.accelerator}
    return pod, env


def _pod_spec(spec: DriverDaemonSetSpec) -> dict:
    """Raw podSpec JSON for the driver pod (serialized verbatim by the
    REST client)."""
    pod, env = _base_pod(spec)
    pod["containers"] = [
        {
            "name": "device-plugin",
            "image": f"{spec.image}:{spec.version}",
            "env": env,
            "securityContext": {"privileged": True},
            "volumeMounts": [
                {"name": "device-plugin-dir",
                 "mountPath": "/var/lib/kubelet/device-plugins"},
                {"name": "libtpu-dir", "mountPath": "/usr/lib/libtpu"},
            ],
        }
    ]
    pod["volumes"] = [
        {"name": "device-plugin-dir",
         "hostPath": {"path": "/var/lib/kubelet/device-plugins"}},
        {"name": "libtpu-dir",
         "hostPath": {"path": "/usr/lib/libtpu",
                      "type": "DirectoryOrCreate"}},
    ]
    if spec.safe_load:
        pod["initContainers"] = [
            {
                "name": "safe-load",
                "image": f"{spec.image}:{spec.version}",
                "command": [
                    "python",
                    "-m",
                    "k8s_operator_libs_tpu.driver.safe_load_init",
                ],
                "env": env + [
                    {"name": "DRIVER_NAME", "value": spec.driver_name}
                ],
            }
        ]
    return pod


def template_hash(spec: DriverDaemonSetSpec) -> str:
    """Content hash of everything that defines the pod template."""
    blob = json.dumps(
        {"pod": spec.pod_spec(), "labels": spec.labels},
        sort_keys=True,
        separators=(",", ":"),
    )
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def build_daemon_set(spec: DriverDaemonSetSpec) -> DaemonSet:
    return DaemonSet(
        metadata=ObjectMeta(
            name=spec.name,
            namespace=spec.namespace,
            labels=spec.labels,
            annotations={TEMPLATE_HASH_ANNOTATION: template_hash(spec)},
        ),
        spec=DaemonSetSpec(
            selector=LabelSelectorSpec(dict(spec.selector_labels)),
            template=PodTemplateSpec(
                labels=dict(spec.labels),
                pod_spec=spec.pod_spec(),
            ),
            update_strategy=spec.update_strategy,
        ),
    )


@dataclass
class AgentDaemonSetSpec(DriverDaemonSetSpec):
    """Desired state of the per-host health-probe-agent DaemonSet.

    One agent pod per TPU host (``health.agent``): it probes the local
    chips (or the whole torus under ``jax.distributed``) and publishes
    per-host HealthReport annotations the validation gate aggregates.
    ``driver_revision`` is stamped into the pod env: when the controller
    observes a new driver ControllerRevision it re-reconciles this spec,
    the template hash changes, and the agents restart probing under —
    and reporting — the new revision (reports pinned to the old revision
    can never validate the new driver)."""

    name: str = "libtpu-health-agent"
    probe_interval_s: float = 30.0
    deep: bool = False
    driver_revision: str = ""
    # "host[:port]" peer-slice endpoints across the DCN; when set the
    # agents run the dcn_reachability check (SliceHealthGateSpec.dcn_check
    # gates on it).  In a JobSet deployment these are the peer slices'
    # headless-service addresses.
    dcn_peers: tuple[str, ...] = ()
    # This pool's DCN group name plus every group expected in the
    # cross-slice jax.distributed world; when both are set the agents run
    # the dcn_collective check — a cross-slice XLA psum, the gate the
    # north star asks for ("XLA all-reduce reachability") and strictly
    # stronger than TCP reachability.
    dcn_group: str = ""
    dcn_expected_groups: tuple[str, ...] = ()

    # RollingUpdate is the point: a template change (new DRIVER_REVISION)
    # must restart the agent pods, or they would keep publishing reports
    # pinned to the old revision and the gate could never pass.  Agent
    # restarts don't touch the torus — only the driver DS is OnDelete.
    update_strategy = "RollingUpdate"

    @property
    def selector_labels(self) -> dict[str, str]:
        return {"app": f"{self.driver_name}-health-agent"}

    def pod_spec(self) -> dict:
        pod, env = _base_pod(self)
        env += [
            {"name": "DRIVER_REVISION", "value": self.driver_revision},
            {
                "name": "HEALTH_PROBE_INTERVAL_S",
                "value": str(self.probe_interval_s),
            },
        ]
        if self.deep:
            env.append({"name": "HEALTH_DEEP_PROBE", "value": "1"})
        if self.dcn_peers:
            env.append(
                {"name": "HEALTH_DCN_PEERS", "value": ",".join(self.dcn_peers)}
            )
        if self.dcn_group:
            env.append(
                {"name": "HEALTH_DCN_GROUP", "value": self.dcn_group}
            )
        if self.dcn_expected_groups:
            env.append(
                {
                    "name": "HEALTH_DCN_GROUPS",
                    "value": ",".join(self.dcn_expected_groups),
                }
            )
        pod["containers"] = [
            {
                "name": "health-agent",
                "image": f"{self.image}:{self.version}",
                "command": [
                    "python",
                    "-m",
                    "k8s_operator_libs_tpu.health.agent",
                ],
                "env": env,
                # Device access for the JAX probe battery.
                "securityContext": {"privileged": True},
                "volumeMounts": [
                    {"name": "libtpu-dir",
                     "mountPath": "/usr/lib/libtpu"},
                ],
            }
        ]
        pod["volumes"] = [
            {"name": "libtpu-dir",
             "hostPath": {"path": "/usr/lib/libtpu",
                          "type": "DirectoryOrCreate"}},
        ]
        return pod


class DriverSetReconciler:
    """Idempotently drive the live DaemonSet toward the desired spec."""

    def __init__(self, client, spec: DriverDaemonSetSpec) -> None:
        self.client = client
        self.spec = spec

    def reconcile(self) -> str:
        """Returns one of "created" | "updated" | "unchanged"."""
        desired = build_daemon_set(self.spec)
        want_hash = desired.metadata.annotations[TEMPLATE_HASH_ANNOTATION]
        try:
            live = self.client.get_daemon_set(
                self.spec.namespace, self.spec.name
            )
        except NotFoundError:
            self.client.create_daemon_set(desired)
            logger.info(
                "created driver DaemonSet %s/%s (template %s)",
                self.spec.namespace,
                self.spec.name,
                want_hash,
            )
            return "created"
        live_hash = live.metadata.annotations.get(TEMPLATE_HASH_ANNOTATION)
        if live_hash == want_hash:
            return "unchanged"
        # Preserve identity/metadata the apiserver owns, and NEVER rewrite
        # spec.selector — it is immutable for the DaemonSet's lifetime and
        # a changed selector would 422 every reconcile forever.
        live.metadata.labels = desired.metadata.labels
        live.metadata.annotations[TEMPLATE_HASH_ANNOTATION] = want_hash
        desired.spec.selector = live.spec.selector
        live.spec = desired.spec
        self.client.update_daemon_set(live)
        logger.info(
            "updated driver DaemonSet %s/%s: template %s -> %s "
            "(OnDelete: pods roll via the upgrade state machine)",
            self.spec.namespace,
            self.spec.name,
            live_hash,
            want_hash,
        )
        return "updated"
