"""Driver-side components: the libtpu DaemonSet and its node-side agents.

The reference assumes an out-of-repo NVIDIA driver container managed by
consumer operators; the TPU north star replaces that with an in-repo
**libtpu device-plugin reconciler** (BASELINE.json) plus the node-side
half of the safe-load handshake (reference docs/automatic-ofed-upgrade.md:57-63
describes the protocol; the init container itself lives outside the
reference repo — here it is first-class):

- :mod:`daemonset` — spec builder + reconciler for the libtpu driver /
  device-plugin DaemonSet (OnDelete update strategy so the upgrade state
  machine, not the DS controller, rolls the pods);
- :mod:`safe_load_init` — the init-container entrypoint that blocks
  libtpu load until the controller confirms the slice is quiesced.
"""

from k8s_operator_libs_tpu.driver.daemonset import (
    AgentDaemonSetSpec,
    DriverDaemonSetSpec,
    DriverSetReconciler,
    build_daemon_set,
)
from k8s_operator_libs_tpu.driver.safe_load_init import (
    announce_and_wait,
)

__all__ = [
    "AgentDaemonSetSpec",
    "DriverDaemonSetSpec",
    "DriverSetReconciler",
    "announce_and_wait",
    "build_daemon_set",
]
