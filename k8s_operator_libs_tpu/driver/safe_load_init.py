"""Safe-load init container: hold libtpu load until the slice is quiesced.

Node-side half of the safe-load handshake (controller side:
``upgrade.safe_driver_load_manager``; protocol shape per reference
docs/automatic-ofed-upgrade.md:43-66 and SURVEY.md §3.5):

1. on start, set the ``…driver-wait-for-safe-load`` annotation on this
   node — the upgrade state machine sees it and forces the node's slice
   through the full cordon/wait/delete/drain pipeline;
2. block while the annotation exists;
3. the controller removes the annotation once the slice is quiesced
   (instead of restarting the pod) — we exit 0 and the main driver
   container loads libtpu onto a quiet torus.

Crash-safety: setting the annotation is idempotent (re-running after a
restart re-announces), and if the controller already removed it between
our write and first poll we exit immediately.
"""

from __future__ import annotations

import os
import time
from typing import Optional

from k8s_operator_libs_tpu.consts import get_logger
from k8s_operator_libs_tpu.upgrade.consts import TRUE_STRING
from k8s_operator_libs_tpu.upgrade.util import UpgradeKeys

logger = get_logger(__name__)

DEFAULT_POLL_S = 5.0


def announce_and_wait(
    client,
    node_name: str,
    keys: Optional[UpgradeKeys] = None,
    poll_interval_s: float = DEFAULT_POLL_S,
    timeout_s: float = 0.0,
) -> bool:
    """Set the safe-load annotation, then block until the controller
    removes it.  Returns True when unblocked; False on timeout
    (timeout_s == 0 waits forever — init containers are restarted by the
    kubelet, so no exit is safer than a premature driver load)."""
    keys = keys or UpgradeKeys()
    annotation = keys.safe_load_annotation
    client.patch_node_annotations(node_name, {annotation: TRUE_STRING})
    logger.info(
        "node %s waiting for safe driver load (%s)", node_name, annotation
    )
    deadline = time.monotonic() + timeout_s if timeout_s > 0 else None
    while True:
        node = client.get_node(node_name, cached=False)
        if annotation not in node.annotations:
            logger.info("node %s unblocked; loading driver", node_name)
            return True
        if deadline is not None and time.monotonic() > deadline:
            logger.warning(
                "node %s safe-load wait timed out after %.0fs",
                node_name,
                timeout_s,
            )
            return False
        time.sleep(poll_interval_s)


def main() -> None:
    from k8s_operator_libs_tpu.k8s import get_default_client

    node_name = os.environ.get("NODE_NAME", "")
    if not node_name:
        raise SystemExit("NODE_NAME is required")
    keys = UpgradeKeys(
        driver_name=os.environ.get("DRIVER_NAME", "libtpu")
    )
    poll = float(os.environ.get("SAFE_LOAD_POLL_S", str(DEFAULT_POLL_S)))
    if not announce_and_wait(
        get_default_client(), node_name, keys, poll_interval_s=poll
    ):
        raise SystemExit(1)


if __name__ == "__main__":
    main()
