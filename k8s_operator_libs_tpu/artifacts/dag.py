"""Artifact dependency DAGs: composable driver stacks under one window.

Real TPU fleets never roll libtpu alone: the device driver, the network
driver and the device plugin form a *stack* whose pieces must upgrade
together under per-edge version-compatibility constraints (the K8s
Network Driver Model's composable-driver picture, PAPERS.md).  This
module is the pure-graph core of that generalization:

- :class:`ArtifactDAG` is built from the policy's ``artifacts`` stanza
  (duck-typed — this module never imports ``api.v1alpha1``, which
  imports *us* for admission validation) and validated once at
  admission: duplicate/empty names, dangling or self edges, cycles,
  lockstep/pinned-order conflicts and unsatisfiable version
  constraints all reject the policy through the existing
  ``_validate_feasibility`` path.
- ``lockstep`` edges merge their endpoints into one restart *step*:
  the artifacts' pods restart in the same pass, inside the same
  cordon/drain window.  ``pinned-order`` edges serialize: the
  downstream artifact's pods may not restart until the upstream
  artifact is fully synced (and its gate, if any, has passed).
- :meth:`topo_order` is deterministic (Kahn's algorithm, ties broken
  by the spec's item order), which is what lets the engine map the
  FIRST artifact in topological order onto the existing
  ``driver_pod``/``driver_daemon_set`` fields — a DAG of size 1 *is*
  the classic single-artifact code path, byte for byte.
- :meth:`rollback_order` is the reverse topological order, the unwind
  sequence a failed mid-stack roll reports artifact by artifact.

The DAG never touches the cluster: it is a validated shape the engine,
planner, twin and tracer all consult, the same read-only doctrine as
``planning/``.
"""

from __future__ import annotations

from typing import Optional

SKEW_LOCKSTEP = "lockstep"
SKEW_PINNED_ORDER = "pinned-order"
SKEW_MODES = (SKEW_LOCKSTEP, SKEW_PINNED_ORDER)

# Artifact gates: "" (none) or the fused battery's network-path checks
# (DCN reachability + ICI link state), which gate only the networking
# artifact's edge.
GATE_NONE = ""
GATE_NETWORK_PATH = "network-path"
GATE_MODES = (GATE_NONE, GATE_NETWORK_PATH)


class ArtifactDAGError(ValueError):
    """The artifacts stanza does not describe a usable DAG.  Raised at
    admission (``TPUUpgradePolicySpec._validate_feasibility``) so an
    invalid stack rejects the policy instead of wedging a roll."""


def _parse_version(v: str) -> tuple:
    """Dotted-numeric version -> comparable tuple.  Non-numeric
    components compare as strings after the numeric prefix (enough for
    driver tags like ``1.2.3`` or ``535.104.05``)."""
    parts: list = []
    for piece in str(v).split("."):
        try:
            parts.append((0, int(piece)))
        except ValueError:
            parts.append((1, piece))
    return tuple(parts)


_OPS = (">=", "<=", "==", "!=", ">", "<")


def constraint_satisfied(requires: str, version: str) -> bool:
    """Evaluate a ``requires`` constraint (``">=1.2"`` style) against a
    target version.  An empty constraint always holds; an unparseable
    one never does (it must reject at admission, not surprise mid-roll).
    """
    requires = (requires or "").strip()
    if not requires:
        return True
    for op in _OPS:
        if requires.startswith(op):
            want = requires[len(op):].strip()
            if not want:
                return False
            a, b = _parse_version(version), _parse_version(want)
            return {
                ">=": a >= b,
                "<=": a <= b,
                "==": a == b,
                "!=": a != b,
                ">": a > b,
                "<": a < b,
            }[op]
    # Bare version = exact match.
    return _parse_version(version) == _parse_version(requires)


class ArtifactDAG:
    """Validated artifact dependency DAG for one upgrade policy.

    Construction never raises; call :meth:`validate` (admission does)
    to surface :class:`ArtifactDAGError`.  All orders are deterministic
    so every controller incarnation — and the planner's projection —
    steps the stack identically.
    """

    def __init__(self, items, edges) -> None:
        # Duck-typed items/edges: anything with .name/.match_labels/
        # .target_version/.gate and .before/.after/.requires/.skew.
        self.items = list(items or [])
        self.edges = list(edges or [])
        self._index = {
            getattr(a, "name", ""): i for i, a in enumerate(self.items)
        }

    @classmethod
    def from_spec(cls, spec) -> Optional["ArtifactDAG"]:
        """Build from a policy's ``artifacts`` stanza (or None)."""
        if spec is None:
            return None
        return cls(getattr(spec, "items", None), getattr(spec, "edges", None))

    # -- basic shape ---------------------------------------------------------

    def size(self) -> int:
        return len(self.items)

    def names(self) -> list[str]:
        return [getattr(a, "name", "") for a in self.items]

    def artifact(self, name: str):
        i = self._index.get(name)
        return self.items[i] if i is not None else None

    def is_multi(self) -> bool:
        """Does this DAG actually change engine behavior?  A size-0/1
        DAG IS the classic single-artifact path."""
        return len(self.items) > 1

    # -- validation ----------------------------------------------------------

    def validate(self) -> None:
        names = self.names()
        seen: set[str] = set()
        for name in names:
            if not name:
                raise ArtifactDAGError("artifact with empty name")
            if name in seen:
                raise ArtifactDAGError(f"duplicate artifact name {name!r}")
            seen.add(name)
        for a in self.items:
            gate = getattr(a, "gate", "") or ""
            if gate not in GATE_MODES:
                raise ArtifactDAGError(
                    f"artifact {getattr(a, 'name', '')!r}: unknown gate "
                    f"{gate!r} (expected one of {GATE_MODES})"
                )
            if not getattr(a, "match_labels", None):
                raise ArtifactDAGError(
                    f"artifact {getattr(a, 'name', '')!r}: empty "
                    "DaemonSet selector (matchLabels)"
                )
        for e in self.edges:
            before = getattr(e, "before", "")
            after = getattr(e, "after", "")
            skew = getattr(e, "skew", SKEW_LOCKSTEP) or SKEW_LOCKSTEP
            if before not in seen or after not in seen:
                raise ArtifactDAGError(
                    f"dangling edge {before!r} -> {after!r}: both ends "
                    "must name declared artifacts"
                )
            if before == after:
                raise ArtifactDAGError(f"self-edge on artifact {before!r}")
            if skew not in SKEW_MODES:
                raise ArtifactDAGError(
                    f"edge {before!r} -> {after!r}: unknown skew "
                    f"{skew!r} (expected one of {SKEW_MODES})"
                )
            requires = getattr(e, "requires", "") or ""
            if requires:
                upstream = self.artifact(before)
                version = getattr(upstream, "target_version", "") or ""
                if not constraint_satisfied(requires, version):
                    raise ArtifactDAGError(
                        f"unsatisfiable constraint on edge {before!r} -> "
                        f"{after!r}: requires {requires!r} but "
                        f"{before!r} targets version {version!r}"
                    )
        # Cycle detection runs over the CONDENSED graph (lockstep
        # components merged): it simultaneously catches pinned-order
        # cycles and lockstep/pinned-order conflicts (a pinned-order
        # edge between two artifacts forced into one lockstep step is a
        # cycle of the condensation).
        self._levels()

    # -- stepping structure --------------------------------------------------

    def _components(self) -> dict[str, int]:
        """Union lockstep-connected artifacts into restart components.
        Returns name -> component id (root item index)."""
        parent = list(range(len(self.items)))

        def find(i: int) -> int:
            while parent[i] != i:
                parent[i] = parent[parent[i]]
                i = parent[i]
            return i

        for e in self.edges:
            skew = getattr(e, "skew", SKEW_LOCKSTEP) or SKEW_LOCKSTEP
            if skew != SKEW_LOCKSTEP:
                continue
            b = self._index.get(getattr(e, "before", ""))
            a = self._index.get(getattr(e, "after", ""))
            if b is None or a is None:
                continue
            rb, ra = find(b), find(a)
            if rb != ra:
                # Deterministic root: smaller item index wins.
                lo, hi = (rb, ra) if rb < ra else (ra, rb)
                parent[hi] = lo
        return {
            getattr(a, "name", ""): find(i)
            for i, a in enumerate(self.items)
        }

    def _levels(self) -> dict[str, int]:
        """name -> 1-based restart step.  Lockstep components share a
        step; pinned-order edges force strictly later steps; unrelated
        components may share a step (they restart in the same pass).
        Raises :class:`ArtifactDAGError` on a cycle."""
        comp = self._components()
        comp_ids = sorted(set(comp.values()))
        succ: dict[int, set[int]] = {c: set() for c in comp_ids}
        indeg: dict[int, int] = {c: 0 for c in comp_ids}
        for e in self.edges:
            skew = getattr(e, "skew", SKEW_LOCKSTEP) or SKEW_LOCKSTEP
            if skew != SKEW_PINNED_ORDER:
                continue
            b = comp.get(getattr(e, "before", ""))
            a = comp.get(getattr(e, "after", ""))
            if b is None or a is None:
                continue
            if b == a:
                raise ArtifactDAGError(
                    f"edge {getattr(e, 'before', '')!r} -> "
                    f"{getattr(e, 'after', '')!r} is pinned-order but its "
                    "ends are lockstep-connected (conflicting skew)"
                )
            if a not in succ[b]:
                succ[b].add(a)
                indeg[a] += 1
        level: dict[int, int] = {}
        ready = [c for c in comp_ids if indeg[c] == 0]
        for c in ready:
            level[c] = 1
        out = 0
        while ready:
            # Kahn over components, deterministic order.
            ready.sort()
            c = ready.pop(0)
            out += 1
            for n in sorted(succ[c]):
                level[n] = max(level.get(n, 1), level[c] + 1)
                indeg[n] -= 1
                if indeg[n] == 0:
                    ready.append(n)
        if out != len(comp_ids):
            raise ArtifactDAGError(
                "artifact dependency cycle (pinned-order edges form a "
                "loop across restart steps)"
            )
        return {name: level[c] for name, c in comp.items()}

    def levels(self) -> dict[str, int]:
        """Validated name -> 1-based restart step."""
        return self._levels()

    def serialized_steps(self) -> int:
        """Number of serialized restart steps inside one window — what
        an n-artifact stack costs over a single artifact.  The planner
        charges ``(serialized_steps - 1)`` extra pod-restart clocks per
        group; lockstep stacks collapse back toward 1."""
        lv = self._levels()
        return max(lv.values()) if lv else 1

    def topo_order(self) -> list[str]:
        """Artifacts in restart order: ascending step, ties broken by
        the spec's item order.  ``topo_order()[0]`` is the PRIMARY
        artifact — the engine maps it onto the existing driver
        DaemonSet fields."""
        lv = self._levels()
        return sorted(self.names(), key=lambda n: (lv[n], self._index[n]))

    def rollback_order(self) -> list[str]:
        """Reverse topological order: the unwind sequence a failed
        mid-stack roll reports, newest work first."""
        return list(reversed(self.topo_order()))

    def primary(self) -> Optional[str]:
        order = self.topo_order()
        return order[0] if order else None

    def gated_artifacts(self) -> list[str]:
        """Artifacts whose completion is gated by the fused battery's
        network-path checks."""
        return [
            getattr(a, "name", "")
            for a in self.items
            if (getattr(a, "gate", "") or "") == GATE_NETWORK_PATH
        ]


def artifact_dag_of(policy) -> Optional[ArtifactDAG]:
    """The policy's effective multi-artifact DAG, or None when the
    policy has no ``artifacts`` stanza OR the stanza holds a single
    artifact (the classic path; size-1 parity is the contract)."""
    spec = getattr(policy, "artifacts", None)
    dag = ArtifactDAG.from_spec(spec)
    if dag is None or not dag.is_multi():
        return None
    return dag
