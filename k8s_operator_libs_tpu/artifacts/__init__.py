"""Multi-artifact upgrade DAGs: composable driver stacks rolled under
one cordon/drain window.  See docs/multi-artifact-dags.md."""

from k8s_operator_libs_tpu.artifacts.dag import (
    ArtifactDAG,
    ArtifactDAGError,
    GATE_MODES,
    GATE_NETWORK_PATH,
    GATE_NONE,
    SKEW_LOCKSTEP,
    SKEW_MODES,
    SKEW_PINNED_ORDER,
    artifact_dag_of,
    constraint_satisfied,
)
from k8s_operator_libs_tpu.artifacts.gates import (
    GateResult,
    NetworkPathGateProber,
)

__all__ = [
    "ArtifactDAG",
    "ArtifactDAGError",
    "GATE_MODES",
    "GATE_NETWORK_PATH",
    "GATE_NONE",
    "GateResult",
    "NetworkPathGateProber",
    "SKEW_LOCKSTEP",
    "SKEW_MODES",
    "SKEW_PINNED_ORDER",
    "artifact_dag_of",
    "constraint_satisfied",
]
