"""Per-artifact validation gates inside the drain window.

A ``network-path`` gated artifact (typically the network driver) may not
be counted synced — and the stack may not advance past its restart step
— until the data paths it owns are verified back: DCN reachability and
ICI link state, the fused probe battery's network-path checks
(:func:`k8s_operator_libs_tpu.health.fused.run_network_path_checks`).

The engine consults a duck-typed prober: any object with
``probe(group, artifact_name) -> GateResult``-shaped return (``.passed``
bool + ``.detail`` str).  With no prober configured the gate passes
vacuously — the fake tier and unit tests run without JAX devices, and
a cluster operator opts into real gating by wiring a prober exactly the
way validation probers are wired today.  Gate verdicts are *in-memory
only*: a controller restart re-probes, which is the safe direction
(re-verifying a link costs milliseconds warm; trusting a stale verdict
could advance a stack over a dead link).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from k8s_operator_libs_tpu.consts import get_logger

logger = get_logger(__name__)


@dataclass
class GateResult:
    """Verdict of one artifact gate probe."""

    passed: bool
    detail: str = ""
    # Per-check name -> ok, for events/metrics.
    checks: dict[str, bool] = field(default_factory=dict)


class NetworkPathGateProber:
    """Gate prober backed by the fused battery's network-path checks.

    ``runner`` is injected for tests (and for agent-side transports);
    the default lazily imports :mod:`health.fused` so the controller
    process never pays a JAX import unless a gated artifact exists AND
    this prober is wired.
    """

    def __init__(self, runner=None, expected_processes: Optional[int] = None):
        self._runner = runner
        self._expected_processes = expected_processes

    def _run(self):
        if self._runner is not None:
            return self._runner()
        import jax  # deferred: only a wired prober pays this

        from k8s_operator_libs_tpu.health.fused import (
            run_network_path_checks,
        )

        return run_network_path_checks(
            jax.devices(), expected_processes=self._expected_processes
        )

    def probe(self, group, artifact_name: str) -> GateResult:
        """Fail-closed: an infrastructure fault is gate-not-passed
        (the stack simply holds at this step and re-probes next pass),
        never gate-passed."""
        try:
            results = list(self._run())
        except Exception as e:  # noqa: BLE001 — hold the gate, don't crash
            logger.warning(
                "network-path gate probe for artifact %s of group %s "
                "failed to run: %s",
                artifact_name,
                getattr(group, "id", group),
                e,
            )
            return GateResult(False, f"probe error: {e}")
        checks = {r.name: bool(r.ok) for r in results}
        failed = [r for r in results if not r.ok]
        if failed:
            return GateResult(
                False,
                "; ".join(f"{r.name}: {r.detail}" for r in failed),
                checks,
            )
        return GateResult(
            True,
            ", ".join(sorted(checks)) + " verified",
            checks,
        )
