"""Critical-path makespan attribution over a completed roll trace.

Answers the operator question "the roll took 40 minutes — where did
they go?" by walking the span tree backward from roll completion:

- at every point in time the walk picks the **latest-finishing
  activity** (phase or wait span) that explains the interval ending at
  the current frontier, preferring wait spans over phase spans when
  both cover it (a wait is the more specific explanation);
- the chosen interval's seconds are charged to that activity's
  **bucket** — phase-time, budget-wait, window-hold, quarantine,
  negotiation, API-retry — and uncovered gaps are charged to idle;
- the frontier jumps to the chosen activity's start and the walk
  repeats until it reaches the roll start.

By construction the bucket totals sum **exactly** to the measured
makespan (each frontier decrement charges precisely its length), which
is what lets the acceptance gate check ``sum(buckets) == makespan``.

The per-phase actuals are then compared against the
``PhaseClocks``/plan projection, and the top drift contributors are
published into CR status (``makespanBreakdown``), metrics, and the
``make trace`` / status-CLI rendering.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional

from k8s_operator_libs_tpu.obs.trace import (
    KIND_GROUP,
    KIND_PHASE,
    KIND_ROLL,
    KIND_WAIT,
    WAIT_API_RETRY,
    WAIT_BUDGET,
    WAIT_NEGOTIATE,
    WAIT_QUARANTINE,
    WAIT_RUNG_PREFIX,
    WAIT_WINDOW,
    CompletedTrace,
    Span,
)

# Makespan buckets (ISSUE order) + the structural remainder.
BUCKET_PHASE = "phase"
BUCKET_BUDGET = "budget_wait"
BUCKET_WINDOW = "window_hold"
BUCKET_QUARANTINE = "quarantine"
BUCKET_NEGOTIATION = "negotiation"
BUCKET_API_RETRY = "api_retry"
BUCKET_IDLE = "idle"
ALL_BUCKETS = (
    BUCKET_PHASE,
    BUCKET_BUDGET,
    BUCKET_WINDOW,
    BUCKET_QUARANTINE,
    BUCKET_NEGOTIATION,
    BUCKET_API_RETRY,
    BUCKET_IDLE,
)

_WAIT_BUCKET = {
    WAIT_BUDGET: BUCKET_BUDGET,
    WAIT_WINDOW: BUCKET_WINDOW,
    WAIT_QUARANTINE: BUCKET_QUARANTINE,
    WAIT_NEGOTIATE: BUCKET_NEGOTIATION,
    WAIT_API_RETRY: BUCKET_API_RETRY,
}

_BUCKET_CAMEL = {
    BUCKET_PHASE: "phaseSeconds",
    BUCKET_BUDGET: "budgetWaitSeconds",
    BUCKET_WINDOW: "windowHoldSeconds",
    BUCKET_QUARANTINE: "quarantineSeconds",
    BUCKET_NEGOTIATION: "negotiationSeconds",
    BUCKET_API_RETRY: "apiRetrySeconds",
    BUCKET_IDLE: "idleSeconds",
}


def bucket_of(span: Span) -> Optional[str]:
    """Bucket for an activity span; None for structural spans."""
    if span.kind == KIND_PHASE:
        return BUCKET_PHASE
    if span.kind != KIND_WAIT:
        return None
    reason = span.name
    if reason.startswith("wait:"):
        reason = reason[len("wait:"):]
    if reason.startswith(WAIT_RUNG_PREFIX):
        # Eviction-ladder rungs are drain work, finer-grained: they
        # refine WHERE phase time went, not a different bucket.
        return BUCKET_PHASE
    return _WAIT_BUCKET.get(reason, BUCKET_PHASE)


def _pool_of_span(span: Span) -> str:
    # Deterministic ids are "<trace>/<pool>/..." paths; trace ids never
    # contain '/'.
    parts = span.span_id.split("/")
    return parts[1] if len(parts) > 1 else ""


@dataclass
class PathSegment:
    """One critical-path interval attributed to a span (or to idle)."""

    span_id: Optional[str]
    name: str
    bucket: str
    start: float
    end: float

    @property
    def seconds(self) -> float:
        return max(0.0, self.end - self.start)


@dataclass
class Attribution:
    trace_id: str
    makespan: float
    buckets: dict = field(default_factory=dict)
    segments: list = field(default_factory=list)  # list[PathSegment]
    # (pool, phase state value) -> [per-group durations]
    phase_samples: dict = field(default_factory=dict)
    group_count: int = 0

    def bucket_total(self) -> float:
        return sum(self.buckets.values())


def analyze(trace: CompletedTrace) -> Attribution:
    """Walk the completed span tree; charge every makespan second to a
    bucket.  Bucket totals sum exactly to the makespan."""
    out = Attribution(trace_id=trace.trace_id, makespan=trace.makespan)
    out.buckets = {b: 0.0 for b in ALL_BUCKETS}
    start, end = trace.start, trace.end
    activities = []
    for span in trace.spans:
        if span.kind == KIND_GROUP:
            out.group_count += 1
        if span.kind == KIND_PHASE and span.end is not None:
            key = (_pool_of_span(span), span.name)
            out.phase_samples.setdefault(key, []).append(
                span.duration()
            )
        b = bucket_of(span)
        if b is None or span.end is None:
            continue
        a_start = max(span.start, start)
        a_end = min(span.end, end)
        if a_end <= start or a_start >= end:
            continue
        activities.append((a_start, a_end, b, span))
    if end <= start:
        return out
    frontier = end
    eps = 1e-9
    max_steps = 4 * len(activities) + 16
    steps = 0
    while frontier > start + eps and steps < max_steps:
        steps += 1
        best = None
        best_key = None
        for (a_start, a_end, b, span) in activities:
            if a_start >= frontier - eps:
                continue
            cover = min(a_end, frontier)
            if cover <= start:
                continue
            # Latest-finishing first; prefer waits; then earliest start
            # (one long segment beats many slivers).
            key = (cover, span.kind == KIND_WAIT, -a_start)
            if best_key is None or key > best_key:
                best_key = key
                best = (a_start, a_end, b, span)
        if best is None:
            out.buckets[BUCKET_IDLE] += frontier - start
            out.segments.append(
                PathSegment(None, "idle", BUCKET_IDLE, start, frontier)
            )
            frontier = start
            break
        a_start, a_end, b, span = best
        cover = min(a_end, frontier)
        if cover < frontier - eps:
            out.buckets[BUCKET_IDLE] += frontier - cover
            out.segments.append(
                PathSegment(None, "idle", BUCKET_IDLE, cover, frontier)
            )
        seg_start = max(a_start, start)
        out.buckets[b] += cover - seg_start
        out.segments.append(
            PathSegment(span.span_id, span.name, b, seg_start, cover)
        )
        frontier = seg_start
    if frontier > start + eps:
        # Step-capped (pathological tree): close the books as idle so
        # the sum-to-makespan invariant still holds.
        out.buckets[BUCKET_IDLE] += frontier - start
        out.segments.append(
            PathSegment(None, "idle", BUCKET_IDLE, start, frontier)
        )
    out.segments.reverse()  # chronological
    return out


@dataclass
class DriftContributor:
    pool: str
    phase: str
    expected_s: float
    actual_s: float
    samples: int

    @property
    def excess_s(self) -> float:
        """Total seconds of drift this (pool, phase) contributed."""
        return (self.actual_s - self.expected_s) * self.samples


def phase_drift(
    attribution: Attribution,
    expected: Callable[[str, str], Optional[float]],
    top: int = 5,
) -> list:
    """Compare per-(pool, phase) actual means against the projection.

    ``expected(pool, state_value)`` returns the projected seconds for a
    group in that phase (PhaseClocks/plan), or None when unprojected.
    Returns the ``top`` contributors ordered by absolute total excess.
    """
    contributors = []
    for (pool, phase), samples in attribution.phase_samples.items():
        if not samples:
            continue
        try:
            exp = expected(pool, phase)
        except Exception:  # noqa: BLE001 — projections are advisory
            exp = None
        if exp is None:
            continue
        actual = sum(samples) / len(samples)
        contributors.append(
            DriftContributor(
                pool=pool or "default",
                phase=phase,
                expected_s=exp,
                actual_s=actual,
                samples=len(samples),
            )
        )
    contributors.sort(key=lambda c: abs(c.excess_s), reverse=True)
    return contributors[:top]


def expected_from_tracker(clock_tracker, base=None):
    """Adapt a ``PhaseClockTracker`` into the ``expected(pool, state)``
    callable :func:`phase_drift` wants (None when the tracker lacks a
    clock for that phase)."""
    from k8s_operator_libs_tpu.planning.clocks import PHASE_OF_STATE

    def expected(pool: str, state_value: str) -> Optional[float]:
        attr = PHASE_OF_STATE.get(state_value)
        if attr is None:
            return None
        pool_key = "" if pool in ("", "default") else pool
        clocks = clock_tracker.clocks_for(pool_key, base)
        return getattr(clocks, attr, None)

    return expected


def makespan_breakdown(
    attribution: Attribution,
    drift: Optional[list] = None,
    top_segments: int = 5,
) -> dict:
    """CR-status-shaped ``makespanBreakdown`` block."""
    segs = sorted(
        (s for s in attribution.segments if s.span_id is not None),
        key=lambda s: s.seconds,
        reverse=True,
    )[:top_segments]
    out = {
        "traceId": attribution.trace_id,
        "makespanSeconds": round(attribution.makespan, 3),
        "groups": attribution.group_count,
        "buckets": {
            _BUCKET_CAMEL[b]: round(v, 3)
            for b, v in attribution.buckets.items()
        },
        "criticalPath": [
            {
                "span": s.name,
                "bucket": _BUCKET_CAMEL[s.bucket],
                "seconds": round(s.seconds, 3),
            }
            for s in segs
        ],
    }
    if drift:
        out["topDrift"] = [
            {
                "pool": c.pool,
                "phase": c.phase,
                "expectedSeconds": round(c.expected_s, 3),
                "actualSeconds": round(c.actual_s, 3),
                "excessSeconds": round(c.excess_s, 3),
            }
            for c in drift
        ]
    return out


def render_tree(trace: CompletedTrace, max_spans: int = 400) -> str:
    """ASCII rendering of a completed roll's span tree (``make trace``
    and the status CLI)."""
    children: dict[Optional[str], list[Span]] = {}
    by_id = {s.span_id: s for s in trace.spans}
    for span in trace.spans:
        parent = span.parent_id if span.parent_id in by_id else None
        children.setdefault(parent, []).append(span)
    for kids in children.values():
        kids.sort(key=lambda s: (s.start, s.span_id))
    lines: list[str] = []
    origin = trace.start

    def emit(span: Span, depth: int) -> None:
        if len(lines) >= max_spans:
            return
        dur = span.duration(trace.end)
        mark = "" if span.end is not None else "  [OPEN]"
        offset = span.start - origin
        extra = ""
        if span.kind == KIND_WAIT:
            extra = ""
        elif span.attrs.get("reopened"):
            extra = "  (reopened)"
        lines.append(
            f"{'  ' * depth}{span.kind:<6} {span.name:<28} "
            f"+{offset:8.3f}s  {dur:8.3f}s{mark}{extra}"
        )
        for kid in children.get(span.span_id, ()):
            emit(kid, depth + 1)

    roots = children.get(None, [])
    roots.sort(key=lambda s: (s.kind != KIND_ROLL, s.start))
    for root in roots:
        emit(root, 0)
    if len(lines) >= max_spans:
        lines.append(f"... ({len(trace.spans)} spans total, truncated)")
    return "\n".join(lines)


def render_breakdown(breakdown: dict) -> str:
    """Human rendering of a ``makespanBreakdown`` block."""
    lines = [
        f"trace     {breakdown.get('traceId', '?')}",
        f"makespan  {breakdown.get('makespanSeconds', 0.0):.3f}s over "
        f"{breakdown.get('groups', 0)} group(s)",
        "buckets:",
    ]
    for key, val in (breakdown.get("buckets") or {}).items():
        lines.append(f"  {key:<22} {val:10.3f}s")
    path = breakdown.get("criticalPath") or []
    if path:
        lines.append("critical path (top contributors):")
        for seg in path:
            lines.append(
                f"  {seg['span']:<28} {seg['seconds']:8.3f}s"
                f"  [{seg['bucket']}]"
            )
    drift = breakdown.get("topDrift") or []
    if drift:
        lines.append("top drift vs projection:")
        for c in drift:
            lines.append(
                f"  {c['pool']}/{c['phase']:<24} expected "
                f"{c['expectedSeconds']:7.3f}s actual "
                f"{c['actualSeconds']:7.3f}s excess "
                f"{c['excessSeconds']:+8.3f}s"
            )
    return "\n".join(lines)
