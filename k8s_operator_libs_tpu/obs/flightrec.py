"""Flight recorder: a ring of recent facts + black-box dumps on failure.

Aviation-style black box for the controller: a fixed-size in-memory
ring buffer absorbs a cheap note per interesting fact (informer deltas,
admission decisions, budget verdicts, API errors, span openings), and a
*trigger* — stuck-detector fire, ``fleet_roll_infeasible``, quarantine,
circuit-open, crash-adoption — freezes the ring together with the
active span tree, informer cache ages, and ledger state into one
redacted JSON snapshot on a bounded on-disk spool.

Contracts:

- ``note()`` is O(1) and fail-open — it can run on the reconcile hot
  path with tracing's < 5% overhead budget.
- Dumps are throttled per trigger reason so an event storm (every tick
  re-fires infeasibility) cannot write the disk full; the spool itself
  enforces a total byte cap by deleting oldest-first.
- Snapshots are redacted: values under secret-shaped keys (token,
  secret, password, authorization, bearer) are replaced before
  anything touches disk.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Callable, Optional

from k8s_operator_libs_tpu.consts import get_logger

logger = get_logger(__name__)

DEFAULT_RING_CAPACITY = 512
DEFAULT_SPOOL_CAP_BYTES = 4 * 1024 * 1024
DEFAULT_THROTTLE_S = 60.0

# Trigger reasons (metrics label values; free-form reasons also work).
TRIGGER_STUCK = "stuck"
TRIGGER_INFEASIBLE = "infeasible"
TRIGGER_QUARANTINE = "quarantine"
TRIGGER_CIRCUIT_OPEN = "circuit_open"
TRIGGER_ADOPTION = "adoption"

_SECRET_MARKERS = ("token", "secret", "password", "authorization", "bearer")
_REDACTED = "[REDACTED]"


def redact(obj):
    """Recursively replace values under secret-shaped keys."""
    if isinstance(obj, dict):
        out = {}
        for k, v in obj.items():
            key = str(k)
            lowered = key.lower()
            if any(m in lowered for m in _SECRET_MARKERS):
                out[key] = _REDACTED
            else:
                out[key] = redact(v)
        return out
    if isinstance(obj, (list, tuple)):
        return [redact(v) for v in obj]
    return obj


class FlightRecorder:
    """Bounded ring + throttled, byte-capped black-box spool.

    ``snapshot_providers`` is a name → zero-arg callable map; each is
    invoked (fail-open) at dump time so the snapshot always reflects
    the moment of the trigger, not construction time.  The trace
    recorder, informer, and budget ledger register themselves here.
    """

    def __init__(
        self,
        capacity: int = DEFAULT_RING_CAPACITY,
        spool_dir: Optional[str] = None,
        spool_cap_bytes: int = DEFAULT_SPOOL_CAP_BYTES,
        throttle_s: float = DEFAULT_THROTTLE_S,
        clock: Optional[Callable[[], float]] = None,
    ) -> None:
        self._ring: deque = deque(maxlen=max(1, capacity))
        self.spool_dir = spool_dir
        self.spool_cap_bytes = spool_cap_bytes
        self.throttle_s = throttle_s
        self._clock = clock or time.monotonic
        self._lock = threading.Lock()
        self._last_dump: dict[str, float] = {}
        self._seq = 0
        self.snapshot_providers: dict[str, Callable[[], object]] = {}
        # Counters (exported via metrics.observe_trace).
        self.dumps_total: dict[str, int] = {}
        self.throttled_total = 0
        self.note_drops = 0

    # ------------------------------------------------------------------
    # hot path
    # ------------------------------------------------------------------

    def note(self, kind: str, **fields) -> None:
        """Append one fact to the ring.  O(1), lock-lite, fail-open."""
        try:
            entry = {"t": round(time.time(), 3), "kind": kind}
            if fields:
                entry.update(fields)
            self._ring.append(entry)
        except Exception:  # noqa: BLE001 — observe-only
            self.note_drops += 1

    # ------------------------------------------------------------------
    # triggers
    # ------------------------------------------------------------------

    def trigger(self, reason: str, **context) -> Optional[str]:
        """Dump a black-box snapshot for ``reason`` unless throttled.
        Returns the spool path written, or None."""
        try:
            now = self._clock()
            with self._lock:
                last = self._last_dump.get(reason)
                if last is not None and now - last < self.throttle_s:
                    self.throttled_total += 1
                    return None
                self._last_dump[reason] = now
                self._seq += 1
                seq = self._seq
            snapshot = self._build_snapshot(reason, context)
            path = self._spool_write(reason, seq, snapshot)
            with self._lock:
                self.dumps_total[reason] = (
                    self.dumps_total.get(reason, 0) + 1
                )
            return path
        except Exception as e:  # noqa: BLE001 — a failing black box
            # must never take down the flight it was recording.
            logger.debug("flight recorder trigger(%s) failed: %s", reason, e)
            return None

    def _build_snapshot(self, reason: str, context: dict) -> dict:
        snapshot = {
            "reason": reason,
            "at_epoch": round(time.time(), 3),
            "context": context,
            "ring": list(self._ring),
        }
        for name, provider in list(self.snapshot_providers.items()):
            try:
                snapshot[name] = provider()
            except Exception as e:  # noqa: BLE001 — partial snapshots
                # beat no snapshot
                snapshot[name] = {"error": str(e)}
        return redact(snapshot)

    # ------------------------------------------------------------------
    # spool
    # ------------------------------------------------------------------

    def _spool_write(self, reason: str, seq: int, snapshot: dict) -> Optional[str]:
        if not self.spool_dir:
            return None
        os.makedirs(self.spool_dir, exist_ok=True)
        safe_reason = "".join(
            c if c.isalnum() or c in "-_" else "_" for c in reason
        )
        name = f"blackbox-{int(time.time())}-{seq:06d}-{safe_reason}.json"
        path = os.path.join(self.spool_dir, name)
        data = json.dumps(snapshot, default=str, separators=(",", ":"))
        encoded = data.encode("utf-8", errors="replace")
        if len(encoded) > self.spool_cap_bytes:
            # One snapshot larger than the whole spool: shed the ring
            # (the bulkiest section) and keep the structural parts.
            snapshot = dict(snapshot)
            snapshot["ring"] = [
                {"dropped": "ring shed: snapshot exceeded spool cap"}
            ]
            encoded = json.dumps(
                snapshot, default=str, separators=(",", ":")
            ).encode("utf-8", errors="replace")
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(encoded)
        os.replace(tmp, path)
        self._enforce_spool_cap()
        return path

    def _enforce_spool_cap(self) -> None:
        """Delete oldest dumps until the spool fits its byte cap."""
        try:
            entries = []
            for name in os.listdir(self.spool_dir):
                if not name.startswith("blackbox-"):
                    continue
                full = os.path.join(self.spool_dir, name)
                try:
                    st = os.stat(full)
                except OSError:
                    continue
                entries.append((st.st_mtime, name, full, st.st_size))
            entries.sort()
            total = sum(size for (_, _, _, size) in entries)
            while entries and total > self.spool_cap_bytes:
                _, _, full, size = entries.pop(0)
                try:
                    os.remove(full)
                    total -= size
                except OSError:
                    break
        except Exception as e:  # noqa: BLE001 — cap enforcement is
            # best-effort; a failure here only risks spool growth.
            logger.debug("flight recorder spool cap enforcement: %s", e)

    def spool_bytes(self) -> int:
        """Current spool footprint (bench/metrics)."""
        if not self.spool_dir or not os.path.isdir(self.spool_dir):
            return 0
        total = 0
        try:
            for name in os.listdir(self.spool_dir):
                if not name.startswith("blackbox-"):
                    continue
                try:
                    total += os.stat(
                        os.path.join(self.spool_dir, name)
                    ).st_size
                except OSError:
                    continue
        except OSError:
            return total
        return total

    def spool_files(self) -> list[str]:
        if not self.spool_dir or not os.path.isdir(self.spool_dir):
            return []
        return sorted(
            os.path.join(self.spool_dir, n)
            for n in os.listdir(self.spool_dir)
            if n.startswith("blackbox-") and n.endswith(".json")
        )

    def ring_size(self) -> int:
        return len(self._ring)
