"""Fleet health telemetry plane: durable per-node probe history,
robust baselines, health scores and straggler verdicts.

Every probe battery (fused or classic) measures real throughput — MXU
TFLOPs, HBM GB/s, ICI bus bandwidth, battery execute time — and until
now threw the numbers away the moment they cleared a static floor.
This module keeps them:

- **Capture**: the validation manager hands every ProbeResult's
  measured per-node stats to :meth:`TelemetryPlane.observe_validation`
  (fail-open — telemetry can never fail a gate).
- **Durability**: each node's last K samples ride the existing
  combined state-label patch as one bounded ring annotation
  (:meth:`annotation_source` is a provider transition-annotation
  source, the same mechanism as the trace anchor), so history costs
  **zero extra API write verbs** and survives controller restarts:
  :meth:`adopt_node` re-seeds rings from annotations on adoption,
  deduplicating by sample sequence number.  The ring is longitudinal —
  unlike the trace anchor it is never cleared on terminal states.
- **Baselines & verdicts**: :meth:`recompute` folds ring medians into
  per-(generation, pool) median+MAD baselines (obs/baseline.py) and
  maintains a per-node consecutive-battery streak; a node flags as a
  straggler only after ``confirm_batteries`` consecutive samples beyond
  ``z_threshold`` robust sigmas — one slow battery never flags.

Everything is observe-only by default.  The design rules match the
rest of ``obs/``: fail-open (a telemetry bug degrades to missing data,
never to a wedged roll — ``drops`` counts swallowed errors), no wall
clocks in verdict math, and no new upgrade states.
"""

from __future__ import annotations

import functools
import json
import threading
import time
from typing import Callable, Dict, List, Mapping, Optional, Tuple

from k8s_operator_libs_tpu.consts import get_logger
from k8s_operator_libs_tpu.obs.baseline import (
    DEFAULT_MIN_COHORT,
    BaselineStat,
    compute_baselines,
    health_score,
    median,
    node_badness,
)

logger = get_logger(__name__)

# Ring wire format version (annotation payload).
RING_VERSION = 1

# Stat → probe check attribution for the probe_measured metric family.
# Stats outside this map are attributed to the battery as a whole.
STAT_CHECK: Dict[str, str] = {
    "tflops": "mxu_matmul",
    "mfu": "mxu_matmul",
    "gbps": "hbm_bandwidth",
    "busbw_gbps": "ici_allreduce",
}
_BATTERY_CHECK = "fused_battery"


def _failopen(method):
    """Observability must never take down the roll: swallow, count,
    keep going (same contract as obs/trace.py)."""

    @functools.wraps(method)
    def wrapper(self, *args, **kwargs):
        try:
            return method(self, *args, **kwargs)
        except Exception:  # noqa: BLE001 — deliberate fail-open
            self.drops += 1
            logger.debug(
                "telemetry drop in %s", method.__name__, exc_info=True
            )
            return None

    return wrapper


def format_ring(samples: List[Tuple[int, float, Dict[str, float]]]) -> str:
    """Serialize a ring to its compact annotation payload."""
    return json.dumps(
        {
            "v": RING_VERSION,
            "s": [
                [
                    int(seq),
                    round(float(epoch), 3),
                    {k: round(float(v), 3) for k, v in metrics.items()},
                ]
                for seq, epoch, metrics in samples
            ],
        },
        separators=(",", ":"),
        sort_keys=True,
    )


def parse_ring(raw: object) -> List[Tuple[int, float, Dict[str, float]]]:
    """Parse a ring annotation; garbage reads as an empty history
    (adoption is fail-open — a corrupt annotation must not wedge)."""
    if not raw or not isinstance(raw, str):
        return []
    try:
        data = json.loads(raw)
        samples = data.get("s") or []
        out = []
        for entry in samples:
            seq, epoch, metrics = entry[0], entry[1], entry[2]
            out.append(
                (
                    int(seq),
                    float(epoch),
                    {
                        str(k): float(v)
                        for k, v in dict(metrics).items()
                    },
                )
            )
        out.sort(key=lambda s: s[0])
        return out
    except (ValueError, TypeError, KeyError, IndexError, AttributeError):
        return []


class TelemetryPlane:
    """Longitudinal per-node health from measured probe telemetry."""

    def __init__(
        self,
        history_len: int = 8,
        z_threshold: float = 3.0,
        confirm_batteries: int = 3,
        min_cohort: int = DEFAULT_MIN_COHORT,
        epoch_clock: Callable[[], float] = time.time,
    ) -> None:
        self.history_len = history_len
        self.z_threshold = z_threshold
        self.confirm_batteries = confirm_batteries
        self.min_cohort = min_cohort
        self.epoch_clock = epoch_clock
        # Set by the manager's wiring (UpgradeKeys.telemetry_history_
        # annotation); None leaves the plane in-memory only.
        self.annotation_key: Optional[str] = None
        # Swallowed-error count (fail-open contract).
        self.drops = 0
        self.samples_total = 0
        self._lock = threading.RLock()
        # node → sorted [(seq, epoch, {stat: value})], bounded.
        self._rings: Dict[str, List[Tuple[int, float, Dict[str, float]]]] = {}
        self._next_seq: Dict[str, int] = {}
        # Nodes whose ring has samples not yet persisted to the
        # annotation (a crash before the next transition loses at most
        # these — fail-open by design).
        self._dirty: set = set()
        self._node_pool: Dict[str, str] = {}
        self._node_generation: Dict[str, str] = {}
        # Verdict state (rebuilt from rings by recompute()).
        self._streak: Dict[str, int] = {}
        self._last_scored_seq: Dict[str, int] = {}
        self._scores: Dict[str, float] = {}
        self._badness: Dict[str, Dict[str, float]] = {}
        self._confirmed: Dict[str, dict] = {}
        self._reported: set = set()
        self._baselines: Dict[
            Tuple[str, str], Dict[str, BaselineStat]
        ] = {}

    # ------------------------------------------------------------------
    # capture

    def seed_pools(self, node_pool: Mapping[str, str]) -> None:
        """Refresh node → pool attribution (same feed as the phase
        clocks and the trace recorder get each full pass)."""
        with self._lock:
            self._node_pool.update(
                {str(k): str(v or "") for k, v in node_pool.items()}
            )

    @_failopen
    def observe_validation(self, group, result) -> None:
        """Validation-manager sink: record one battery's measured
        per-node stats.  Called for every probe verdict (healthy or
        not) on both the sync and async paths."""
        telemetry = getattr(result, "telemetry", None)
        if not telemetry:
            return
        generations = {}
        for node in getattr(group, "nodes", []) or []:
            labels = getattr(node, "labels", None) or {}
            gen = labels.get(_accelerator_label(), "")
            if gen:
                generations[node.name] = gen
        now = self.epoch_clock()
        for node_name, stats in telemetry.items():
            if not stats:
                continue
            self.ingest(
                node_name,
                stats,
                generation=generations.get(node_name, ""),
                now_epoch=now,
            )

    def ingest(
        self,
        node_name: str,
        metrics: Mapping[str, float],
        generation: str = "",
        pool: Optional[str] = None,
        now_epoch: Optional[float] = None,
    ) -> None:
        """Append one measured sample to a node's ring (in memory; the
        annotation persists at the node's next transition)."""
        clean: Dict[str, float] = {}
        for k, v in dict(metrics).items():
            try:
                clean[str(k)] = float(v)
            except (TypeError, ValueError):
                continue
        if not clean:
            return
        epoch = self.epoch_clock() if now_epoch is None else now_epoch
        with self._lock:
            ring = self._rings.setdefault(node_name, [])
            seq = self._next_seq.get(node_name)
            if seq is None:
                seq = (ring[-1][0] + 1) if ring else 1
            ring.append((seq, float(epoch), clean))
            del ring[: -self.history_len]
            self._next_seq[node_name] = seq + 1
            self._dirty.add(node_name)
            self.samples_total += 1
            if generation:
                self._node_generation[node_name] = generation
            if pool is not None:
                self._node_pool[node_name] = pool

    # ------------------------------------------------------------------
    # durability (rides the combined transition patch)

    @_failopen
    def annotation_source(self, node, new_state) -> Optional[dict]:
        """Provider transition-annotation source: when this node's ring
        has unpersisted samples, ride them on the state-label patch the
        provider is about to stage anyway — zero extra write verbs.
        Unlike the trace anchor the ring is longitudinal: it persists
        through terminal states and is never deleted."""
        key = self.annotation_key
        if key is None:
            return {}
        with self._lock:
            name = getattr(node, "name", None)
            if name not in self._dirty:
                return {}
            ring = self._rings.get(name)
            if not ring:
                self._dirty.discard(name)
                return {}
            self._dirty.discard(name)
            return {key: format_ring(ring)}

    @_failopen
    def adopt_node(self, node) -> bool:
        """Re-seed one node's ring from its durable annotation (crash /
        leader-handoff adoption).  Merges by sequence number: samples
        already in memory are never duplicated and newer in-memory
        samples are never clobbered.  Returns True when any sample was
        adopted."""
        key = self.annotation_key
        if key is None:
            return False
        raw = (getattr(node, "annotations", None) or {}).get(key)
        adopted = parse_ring(raw)
        if not adopted:
            return False
        name = node.name
        with self._lock:
            ring = self._rings.get(name, [])
            have = {seq for seq, _, _ in ring}
            merged = ring + [s for s in adopted if s[0] not in have]
            merged.sort(key=lambda s: s[0])
            del merged[: -self.history_len]
            self._rings[name] = merged
            self._next_seq[name] = merged[-1][0] + 1 if merged else 1
        return True

    # ------------------------------------------------------------------
    # baselines & verdicts

    def recompute(self) -> None:
        """Fold rings into cohort baselines and update scores, streaks
        and straggler confirmations.  Idempotent per sample: a ring
        sample feeds a node's streak exactly once (tracked by sequence
        number), so calling this every pass is safe."""
        with self._lock:
            reps: Dict[str, Dict[str, float]] = {}
            cohorts: Dict[str, Tuple[str, str]] = {}
            for name, ring in self._rings.items():
                if not ring:
                    continue
                stats: Dict[str, List[float]] = {}
                for _, _, metrics in ring:
                    for k, v in metrics.items():
                        stats.setdefault(k, []).append(v)
                reps[name] = {k: median(v) for k, v in stats.items()}
                cohorts[name] = (
                    self._node_generation.get(name, ""),
                    self._node_pool.get(name, ""),
                )
            self._baselines = compute_baselines(
                reps, cohorts, min_cohort=self.min_cohort
            )
            self._scores = {}
            self._badness = {}
            confirmed: Dict[str, dict] = {}
            for name, ring in self._rings.items():
                baseline = self._baselines.get(cohorts.get(name))
                if not baseline:
                    # Cohort too small (or unknown): no verdicts, and
                    # any running streak is void.
                    self._streak.pop(name, None)
                    continue
                worst, per_stat = node_badness(
                    reps.get(name, {}), baseline
                )
                self._scores[name] = round(health_score(worst), 1)
                self._badness[name] = per_stat
                # Streak: each NEW sample (by seq) beyond the threshold
                # extends it; one at-baseline sample resets it.  Replay
                # from the ring so an adopted history rebuilds the same
                # streak a crashed controller had accumulated.
                last_scored = self._last_scored_seq.get(name, 0)
                streak = self._streak.get(name, 0)
                for seq, _, metrics in ring:
                    if seq <= last_scored:
                        continue
                    sample_worst, _ = node_badness(metrics, baseline)
                    streak = (
                        streak + 1
                        if sample_worst > self.z_threshold
                        else 0
                    )
                    last_scored = seq
                self._streak[name] = streak
                self._last_scored_seq[name] = last_scored
                if streak >= self.confirm_batteries:
                    worst_stat = max(
                        per_stat, key=per_stat.get, default=""
                    )
                    confirmed[name] = {
                        "node": name,
                        "generation": cohorts[name][0],
                        "pool": cohorts[name][1],
                        "score": self._scores[name],
                        "streak": streak,
                        "worstStat": worst_stat,
                        "z": round(per_stat.get(worst_stat, 0.0), 2),
                    }
            self._confirmed = confirmed
            self._reported &= set(confirmed)

    def is_straggler(self, node_name: str) -> bool:
        with self._lock:
            return node_name in self._confirmed

    def consume_straggler(self, node_name: str) -> bool:
        """Acknowledge a confirmed straggler (quarantine routing): the
        streak resets so re-confirmation needs ``confirm_batteries``
        fresh batteries — a parked node cannot be re-parked by the same
        stale verdict the moment it rejoins."""
        with self._lock:
            was = node_name in self._confirmed
            self._confirmed.pop(node_name, None)
            self._reported.discard(node_name)
            self._streak[node_name] = 0
            return was

    def new_confirmations(self) -> List[dict]:
        """Stragglers confirmed since the last call (event dedup: the
        NodeHealthDegraded Warning fires once per confirmation, not
        once per pass)."""
        with self._lock:
            fresh = [
                dict(v)
                for k, v in sorted(self._confirmed.items())
                if k not in self._reported
            ]
            self._reported |= set(self._confirmed)
            return fresh

    def stragglers_by_pool(self) -> Dict[str, List[str]]:
        """Confirmed stragglers grouped by pool (planner surface: the
        phase clocks annotate 'this pool's ETA is inflated by ...')."""
        with self._lock:
            out: Dict[str, List[str]] = {}
            for name, info in self._confirmed.items():
                out.setdefault(info.get("pool", ""), []).append(name)
            return {k: sorted(v) for k, v in out.items()}

    # ------------------------------------------------------------------
    # publication

    def to_status(self) -> dict:
        """CR status block: ``healthSummary`` + ``stragglers``.  Output
        only — baselines re-derive from the rings on adoption, so
        nothing here is ever read back."""
        with self._lock:
            cohorts = []
            for (gen, pool), baseline in sorted(self._baselines.items()):
                cohorts.append(
                    {
                        "generation": gen,
                        "pool": pool,
                        "nodes": max(
                            (b.count for b in baseline.values()),
                            default=0,
                        ),
                        "baseline": {
                            stat: {
                                "median": round(b.median, 3),
                                "mad": round(b.mad, 3),
                            }
                            for stat, b in sorted(baseline.items())
                        },
                    }
                )
            summary: dict = {}
            if cohorts:
                summary["cohorts"] = cohorts
            if self._scores:
                summary["scoredNodes"] = len(self._scores)
                summary["meanScore"] = round(
                    sum(self._scores.values()) / len(self._scores), 1
                )
            out: dict = {}
            if summary:
                out["healthSummary"] = summary
            stragglers = [
                dict(v) for _, v in sorted(self._confirmed.items())
            ]
            if stragglers:
                out["stragglers"] = stragglers
            return out

    def metrics_view(self) -> dict:
        """Everything UpgradeMetrics.observe_telemetry publishes:
        per-node scores, per-cohort straggler counts, and fleet-median
        measured stats attributed to their probe check."""
        with self._lock:
            straggler_counts: Dict[Tuple[str, str], int] = {}
            for info in self._confirmed.values():
                key = (info.get("generation", ""), info.get("pool", ""))
                straggler_counts[key] = straggler_counts.get(key, 0) + 1
            measured: Dict[Tuple[str, str], float] = {}
            per_stat: Dict[str, List[float]] = {}
            for ring in self._rings.values():
                if not ring:
                    continue
                for k, v in ring[-1][2].items():
                    per_stat.setdefault(k, []).append(v)
            for stat, values in per_stat.items():
                check = STAT_CHECK.get(stat)
                if check is None:
                    if not stat.startswith("battery_"):
                        continue
                    check = _BATTERY_CHECK
                measured[(check, stat)] = round(median(values), 3)
            return {
                "scores": dict(self._scores),
                "stragglers": straggler_counts,
                "measured": measured,
                "samples_total": self.samples_total,
                "drops": self.drops,
            }

    def export(self) -> dict:
        """Flight-recorder snapshot: compact, bounded, redactable."""
        with self._lock:
            return {
                "nodes": len(self._rings),
                "samples_total": self.samples_total,
                "drops": self.drops,
                "cohorts": [
                    {"generation": g, "pool": p, "stats": sorted(b)}
                    for (g, p), b in sorted(self._baselines.items())
                ],
                "stragglers": [
                    dict(v) for _, v in sorted(self._confirmed.items())
                ],
                "streaks": {
                    k: v
                    for k, v in sorted(self._streak.items())
                    if v > 0
                },
            }


def _accelerator_label() -> str:
    from k8s_operator_libs_tpu.upgrade.consts import (
        GKE_TPU_ACCELERATOR_LABEL,
    )

    return GKE_TPU_ACCELERATOR_LABEL
