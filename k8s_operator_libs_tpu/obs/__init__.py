"""Roll tracing, flight recorder & fleet health telemetry (observability
layer).

Four read-mostly, fail-open parts:

- :mod:`trace` — span model + recorder: every fleet roll becomes one
  causal span tree (roll → pool → wave → slice-group → node → phase,
  plus wait spans), recorded at the engine's existing choke points,
  crash-durable via the node-annotation write plane, continued across
  controller failover by ``manager.adopt()``.
- :mod:`flightrec` — black box: a fixed-size ring of recent facts and
  a throttled, byte-capped on-disk spool of redacted JSON snapshots
  dumped when something goes wrong (stuck detector, infeasibility,
  quarantine, circuit-open, crash-adoption).
- :mod:`critical` — critical-path makespan attribution: on roll
  completion, bucket the makespan into phase-time vs budget-wait vs
  window-hold vs quarantine vs API-retry, compare per-phase actuals
  against the PhaseClocks projection, and publish the top drift
  contributors (CR ``makespanBreakdown``, metrics, ``make trace``).
- :mod:`telemetry` + :mod:`baseline` — fleet health: every probe
  battery's measured side channel (TFLOPs, HBM GB/s, ICI bus BW,
  execute time) lands in a bounded per-node ring riding the combined
  transition patch (zero extra writes, re-adopted across restarts),
  folds into per-(generation, pool) median+MAD baselines, and yields
  health scores plus sustained-deviation straggler verdicts —
  observe-only unless ``healthGate.quarantineStragglers`` opts in.

Observability is observe-only by contract: every entry point fails
open, so a recorder or telemetry failure can never block a state
transition (drops are counted into ``trace_drops_total`` /
``telemetry_drops_total`` instead).  See docs/observability.md.
"""

from k8s_operator_libs_tpu.obs.trace import (  # noqa: F401
    CompletedTrace,
    Span,
    TraceRecorder,
    format_anchor,
    parse_anchor,
)
from k8s_operator_libs_tpu.obs.flightrec import (  # noqa: F401
    FlightRecorder,
    redact,
)
from k8s_operator_libs_tpu.obs.baseline import (  # noqa: F401
    BaselineStat,
    compute_baselines,
    health_score,
    node_badness,
)
from k8s_operator_libs_tpu.obs.telemetry import (  # noqa: F401
    TelemetryPlane,
    format_ring,
    parse_ring,
)
from k8s_operator_libs_tpu.obs.critical import (  # noqa: F401
    Attribution,
    analyze,
    expected_from_tracker,
    makespan_breakdown,
    phase_drift,
    render_breakdown,
    render_tree,
)
