"""Robust fleet baselines for measured probe telemetry.

Pure math, no I/O and no Kubernetes types: the telemetry plane
(obs/telemetry.py) feeds per-node representative stats in and gets
per-(generation, pool) baselines, per-node badness and health scores
back.  Everything here is deliberately boring and deterministic so the
straggler verdict is explainable from the CR status alone:

- **median + MAD** per cohort and stat.  Median absolute deviation is
  the textbook robust scale estimate — a single degraded node cannot
  drag the baseline toward itself the way a mean/stddev pair would,
  which is exactly the failure mode a straggler detector must not have.
- **robust z-score** ``0.6745 * (x - median) / MAD`` (the 0.6745
  factor makes MAD consistent with the standard deviation under
  normality, so the configured threshold reads like a familiar
  z-score).
- **orientation map**: throughput stats (TFLOPs, GB/s, bus GB/s, MFU)
  are lower-is-worse; latency stats (battery execute ms) are
  higher-is-worse.  Stats outside the map ride the history and the
  ``probe_measured`` metric family but never feed a verdict — an
  unknown key must not be able to quarantine a node.
- **minimum-cohort guard**: a cohort smaller than ``min_cohort`` nodes
  produces no baseline and therefore no verdicts — two nodes cannot
  meaningfully out-vote each other.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterable, Mapping, Optional, Tuple

# Consistency factor: MAD * 1.4826 ≈ stddev under normality, i.e.
# z = 0.6745 * (x - median) / MAD.
MAD_TO_SIGMA = 0.6745

# Stat name → orientation.  -1: lower-is-worse (throughput), +1:
# higher-is-worse (latency/duration).  Anything absent is informational
# only and never contributes to badness.
STAT_ORIENTATION: Dict[str, int] = {
    "tflops": -1,
    "mfu": -1,
    "gbps": -1,
    "busbw_gbps": -1,
    "battery_execute_ms": +1,
}

# Default minimum cohort size before a (generation, pool) baseline is
# trusted for verdicts.
DEFAULT_MIN_COHORT = 4


def median(values: Iterable[float]) -> float:
    """Plain middle-value median (mean of the middle pair for even n).

    Raises ValueError on an empty input — callers guard with the
    min-cohort check first.
    """
    ordered = sorted(float(v) for v in values)
    n = len(ordered)
    if n == 0:
        raise ValueError("median of empty sequence")
    mid = n // 2
    if n % 2:
        return ordered[mid]
    return (ordered[mid - 1] + ordered[mid]) / 2.0


def mad(values: Iterable[float], med: Optional[float] = None) -> float:
    """Median absolute deviation around ``med`` (computed if omitted)."""
    vals = [float(v) for v in values]
    if med is None:
        med = median(vals)
    return median(abs(v - med) for v in vals)


@dataclass(frozen=True)
class BaselineStat:
    """One cohort's robust location/scale for one measured stat."""

    median: float
    mad: float
    count: int

    def zscore(self, value: float) -> float:
        """Robust z of ``value`` against this baseline.

        A zero MAD (identical cohort) gets a tiny relative floor so the
        division is defined: identical nodes score z == 0 exactly, while
        a node 25% off an otherwise-identical cohort still produces a
        huge |z| and flags.  The floor scales with the median so the
        units of the stat don't matter.
        """
        scale = max(self.mad, abs(self.median) * 1e-6 + 1e-9)
        return MAD_TO_SIGMA * (value - self.median) / scale


def compute_baselines(
    node_stats: Mapping[str, Mapping[str, float]],
    node_cohort: Mapping[str, Tuple[str, str]],
    min_cohort: int = DEFAULT_MIN_COHORT,
) -> Dict[Tuple[str, str], Dict[str, BaselineStat]]:
    """Fold per-node representative stats into per-cohort baselines.

    ``node_stats``: node name → {stat: value} (each node's ring median).
    ``node_cohort``: node name → (generation, pool).  Nodes missing
    from the cohort map are skipped.  Cohorts with fewer than
    ``min_cohort`` contributing nodes for a stat produce no baseline
    for that stat.
    """
    per_cohort: Dict[Tuple[str, str], Dict[str, list]] = {}
    for node, stats in node_stats.items():
        cohort = node_cohort.get(node)
        if cohort is None:
            continue
        bucket = per_cohort.setdefault(cohort, {})
        for stat, value in stats.items():
            try:
                bucket.setdefault(stat, []).append(float(value))
            except (TypeError, ValueError):
                continue
    out: Dict[Tuple[str, str], Dict[str, BaselineStat]] = {}
    for cohort, stats in per_cohort.items():
        folded: Dict[str, BaselineStat] = {}
        for stat, values in stats.items():
            if len(values) < min_cohort:
                continue
            med = median(values)
            folded[stat] = BaselineStat(
                median=med, mad=mad(values, med), count=len(values)
            )
        if folded:
            out[cohort] = folded
    return out


def node_badness(
    stats: Mapping[str, float],
    baseline: Mapping[str, BaselineStat],
) -> Tuple[float, Dict[str, float]]:
    """Per-node badness against a cohort baseline.

    Badness per stat is the robust z oriented so that positive means
    *worse than the cohort* regardless of whether the stat is a
    throughput (lower-is-worse) or a duration (higher-is-worse).
    Returns ``(worst_badness, {stat: badness})`` over the oriented
    stats only; both are empty/0.0 when nothing overlaps the baseline.
    """
    per_stat: Dict[str, float] = {}
    for stat, value in stats.items():
        orientation = STAT_ORIENTATION.get(stat)
        if orientation is None:
            continue
        base = baseline.get(stat)
        if base is None:
            continue
        try:
            z = base.zscore(float(value))
        except (TypeError, ValueError):
            continue
        per_stat[stat] = orientation * z
    worst = max(per_stat.values(), default=0.0)
    return worst, per_stat


def health_score(badness: float) -> float:
    """Map badness to a 0–100 health score.

    At-or-better-than-baseline scores 100; each badness unit (robust
    sigma) costs 12.5 points, bottoming out at 0 beyond 8 sigma.  The
    scale is chosen so the default straggler threshold (3 sigma) reads
    as a 62.5 score — visibly degraded but not yet zero.
    """
    return max(0.0, 100.0 - 12.5 * max(0.0, badness))
