"""Span model + recorder: every roll becomes a causal span tree.

The engine already has one choke point per interesting fact: the
provider's ``transition_observer`` sees every group-level state flip,
the admission pass knows which groups it charged, the window/quarantine
/negotiation processors know why a group is parked, and the drain
helper knows which eviction rung each node occupies.  The
:class:`TraceRecorder` listens at exactly those points and grows a
bounded in-memory span tree::

    roll (trace root)
      pool
        wave-N            (one per pool per admission pass that charged)
          slice-group
            phase         (cordon, drain, validation, ... — one per
                           occupied state, closed by the next flip)
            wait          (budget-denied/queued, window-held, quarantine
                           dwell, elastic negotiation)
            node
              wait        (eviction rung ladder: evict -> delete ->
                           force-delete)

Design rules, all load-bearing:

- **Observe-only, fail-open.**  Every public method is wrapped so a
  recorder bug can never block a state transition; failures count into
  ``drops`` (exported as ``trace_drops_total``) instead of raising.
- **Deterministic ids.**  ``trace_id = roll-<epoch>``; span ids are
  ``<trace>/<pool>/<group>/<name>`` paths.  Re-recording the same fact
  after a crash lands on the same id and is a no-op, which is what
  makes adoption idempotent.  A *legitimately* repeated span (second
  quarantine cycle) gets an ``#n`` occurrence suffix.
- **Monotonic timestamps.**  Span clocks are ``time.monotonic`` so they
  are immune to wall-clock steps; the durable anchor carries wall
  epochs and is rebased through
  :func:`~k8s_operator_libs_tpu.upgrade.durable.monotonic_from_epoch`
  on adoption (the same idiom as the eviction-rung store).
- **Crash durability rides existing writes.**  ``annotation_source``
  returns the anchor annotation patch that the provider merges into
  the SAME node intent as the state label — zero extra API writes, so
  the write-hygiene bench pins hold with tracing on.
"""

from __future__ import annotations

import functools
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Iterable, Optional

from k8s_operator_libs_tpu.consts import get_logger
from k8s_operator_libs_tpu.upgrade.consts import UpgradeState
from k8s_operator_libs_tpu.upgrade.durable import monotonic_from_epoch

logger = get_logger(__name__)

# Span kinds (tree levels + leaf activities).
KIND_ROLL = "roll"
KIND_POOL = "pool"
KIND_WAVE = "wave"
KIND_GROUP = "group"
KIND_NODE = "node"
KIND_PHASE = "phase"
KIND_WAIT = "wait"
# Per-artifact step inside a phase (multi-artifact stacks): nested under
# the group's open phase span.  Deliberately NOT a makespan bucket —
# critical.py buckets only PHASE/WAIT kinds, so the phase spans keep
# summing exactly to the makespan with artifact nesting present.
KIND_ARTIFACT = "artifact"

# Wait-span reasons (the critical-path buckets key off these).
WAIT_BUDGET = "budget"
WAIT_WINDOW = "window"
WAIT_QUARANTINE = "quarantine"
WAIT_NEGOTIATE = "negotiate"
WAIT_API_RETRY = "api_retry"
WAIT_RUNG_PREFIX = "evict:"  # + rung name (evict/delete/force-delete)

# Serialized name for the pool-less bucket ("" internally) — matches
# planning/clocks.py so trace pools line up with phase-clock pools.
DEFAULT_POOL_KEY = "default"

_TERMINAL = (UpgradeState.DONE.value, UpgradeState.UNKNOWN.value)
_QUEUED = UpgradeState.UPGRADE_REQUIRED.value
_QUARANTINED = UpgradeState.QUARANTINED.value

# Anchor annotation value: "<trace_id>|<state>|<epoch>".
_ANCHOR_SEP = "|"


def format_anchor(trace_id: str, state_value: str, epoch: float) -> str:
    return f"{trace_id}{_ANCHOR_SEP}{state_value}{_ANCHOR_SEP}{epoch:.3f}"


def parse_anchor(value: Optional[str]) -> Optional[tuple[str, str, float]]:
    """Parse a durable anchor annotation; garbage reads as absent."""
    if not value:
        return None
    parts = value.split(_ANCHOR_SEP)
    if len(parts) != 3:
        return None
    trace_id, state_value, epoch_s = parts
    if not trace_id or not state_value:
        return None
    try:
        epoch = float(epoch_s)
    except ValueError:
        return None
    return trace_id, state_value, epoch


@dataclass
class Span:
    """One timed activity.  ``start``/``end`` are process-monotonic."""

    span_id: str
    trace_id: str
    parent_id: Optional[str]
    kind: str
    name: str
    start: float
    end: Optional[float] = None
    attrs: dict = field(default_factory=dict)

    @property
    def open(self) -> bool:
        return self.end is None

    def duration(self, now: Optional[float] = None) -> float:
        stop = self.end
        if stop is None:
            stop = time.monotonic() if now is None else now
        return max(0.0, stop - self.start)

    def to_dict(self, origin: float = 0.0) -> dict:
        return {
            "id": self.span_id,
            "parent": self.parent_id,
            "kind": self.kind,
            "name": self.name,
            "start_s": round(self.start - origin, 6),
            "end_s": (
                None if self.end is None else round(self.end - origin, 6)
            ),
            "attrs": dict(self.attrs),
        }


@dataclass
class CompletedTrace:
    """Immutable snapshot handed to obs/critical.py on roll completion."""

    trace_id: str
    start: float
    end: float
    spans: list  # list[Span], the roll span first

    @property
    def makespan(self) -> float:
        return max(0.0, self.end - self.start)

    def roll_span(self) -> Optional[Span]:
        for s in self.spans:
            if s.kind == KIND_ROLL:
                return s
        return None


def _failopen(method: Callable) -> Callable:
    """Observe-only contract: a recorder failure must never block a
    state transition.  Any exception is swallowed, counted into
    ``drops``, and logged at debug."""

    @functools.wraps(method)
    def wrapper(self, *args, **kwargs):
        try:
            return method(self, *args, **kwargs)
        except Exception as e:  # noqa: BLE001 — fail-open by contract
            self.drops += 1
            logger.debug("trace recorder %s failed: %s", method.__name__, e)
            return None

    return wrapper


class TraceRecorder:
    """Bounded, thread-safe, fail-open span recorder for fleet rolls.

    One instance per manager; tracks at most one active roll trace at a
    time (the controller is the single admission point for a fleet, so
    concurrent rolls collapse into one trace with per-pool subtrees).
    """

    def __init__(
        self,
        max_spans: int = 8192,
        clock: Optional[Callable[[], float]] = None,
        epoch_clock: Optional[Callable[[], float]] = None,
    ) -> None:
        self.max_spans = max_spans
        self._clock = clock or time.monotonic
        self._epoch = epoch_clock or time.time
        self._lock = threading.RLock()
        # Fail-open accounting (exported as trace_drops_total).
        self.drops = 0
        # Completed rolls, newest last (bounded).
        self.completed: list[CompletedTrace] = []
        self.max_completed = 4
        # Optional: flight recorder notified of span openings (ring
        # deltas); duck-typed, fail-open.
        self.flight_recorder = None
        self._reset_locked()

    # ------------------------------------------------------------------
    # internal state
    # ------------------------------------------------------------------

    def _reset_locked(self) -> None:
        self.trace_id: Optional[str] = None
        self._roll_id: Optional[str] = None
        self._roll_started_epoch: Optional[float] = None
        self._spans: dict[str, Span] = {}
        # group key (lexicographically-first node name) -> last state
        self._group_state: dict[str, str] = {}
        # group key -> open phase span id
        self._group_phase: dict[str, str] = {}
        # (group key, artifact name) -> open artifact span id
        self._group_artifact: dict[tuple[str, str], str] = {}
        # (group key, wait reason) -> open wait span id
        self._group_wait: dict[tuple[str, str], str] = {}
        # node name -> (group key, open rung-wait span id or None)
        self._node_rung: dict[str, tuple[str, Optional[str]]] = {}
        self._node_group: dict[str, str] = {}
        self._node_pool: dict[str, str] = {}
        self._group_pool: dict[str, str] = {}
        # occurrence counters for repeated deterministic ids
        self._occurrence: dict[str, int] = {}
        # wave bookkeeping: pool -> wave ordinal / last admission pass
        self._pool_wave: dict[str, int] = {}
        self._pool_wave_pass: dict[str, int] = {}
        self._pass_token = 0

    def _new_span(
        self,
        span_id: str,
        parent_id: Optional[str],
        kind: str,
        name: str,
        start: float,
        attrs: Optional[dict] = None,
    ) -> Optional[Span]:
        """Insert a span; deterministic-id no-op if it is already open,
        ``#n``-suffixed re-occurrence if it exists closed."""
        existing = self._spans.get(span_id)
        if existing is not None:
            if existing.open:
                return existing  # idempotent re-record (crash replay)
            n = self._occurrence.get(span_id, 1) + 1
            self._occurrence[span_id] = n
            span_id = f"{span_id}#{n}"
            again = self._spans.get(span_id)
            if again is not None and again.open:
                return again
        if len(self._spans) >= self.max_spans:
            self.drops += 1
            return None
        span = Span(
            span_id=span_id,
            trace_id=self.trace_id or "",
            parent_id=parent_id,
            kind=kind,
            name=name,
            start=start,
            attrs=dict(attrs or {}),
        )
        self._spans[span_id] = span
        fr = self.flight_recorder
        if fr is not None:
            try:
                fr.note("span", kind=kind, name=name, id=span_id)
            except Exception:  # noqa: BLE001 — observe-only
                pass
        return span

    def _pool_of(self, group_key: str) -> str:
        pool = self._group_pool.get(group_key)
        if pool is None:
            pool = self._node_pool.get(group_key, "")
            self._group_pool[group_key] = pool
        return pool

    def _ensure_roll_locked(self, now: float, trace_id: Optional[str] = None):
        if self.trace_id is not None:
            return self._spans.get(self._roll_id)
        epoch = self._epoch()
        if trace_id is None:
            trace_id = f"roll-{int(epoch)}"
        self.trace_id = trace_id
        self._roll_started_epoch = epoch
        self._roll_id = trace_id
        return self._new_span(trace_id, None, KIND_ROLL, trace_id, now)

    def _ensure_pool_locked(self, pool: str, now: float) -> Optional[str]:
        name = pool or DEFAULT_POOL_KEY
        span_id = f"{self.trace_id}/{name}"
        if span_id not in self._spans:
            self._new_span(span_id, self._roll_id, KIND_POOL, name, now)
        return span_id if span_id in self._spans else self._roll_id

    def _ensure_group_locked(self, group_key: str, now: float) -> str:
        pool = self._pool_of(group_key)
        pool_name = pool or DEFAULT_POOL_KEY
        span_id = f"{self.trace_id}/{pool_name}/{group_key}"
        if span_id in self._spans:
            return span_id
        pool_id = self._ensure_pool_locked(pool, now)
        created = self._new_span(span_id, pool_id, KIND_GROUP, group_key, now)
        return span_id if created is not None else pool_id

    def _assign_wave_locked(self, group_key: str, now: float) -> None:
        """Admission: hang the group under this pass's wave span.  The
        group span usually predates admission (created when the group
        queued), so assignment is a reparent, not a create."""
        pool = self._pool_of(group_key)
        pool_name = pool or DEFAULT_POOL_KEY
        group_id = self._ensure_group_locked(group_key, now)
        gspan = self._spans.get(group_id)
        if gspan is None or gspan.kind != KIND_GROUP:
            return
        if gspan.parent_id and "/wave-" in gspan.parent_id:
            return  # already assigned (crash replay)
        pool_id = self._ensure_pool_locked(pool, now)
        # Groups charged in the same admission pass share one wave span
        # per pool.
        if self._pool_wave_pass.get(pool) != self._pass_token:
            self._pool_wave[pool] = self._pool_wave.get(pool, 0) + 1
            self._pool_wave_pass[pool] = self._pass_token
        wave_n = self._pool_wave.get(pool, 1)
        wave_id = f"{self.trace_id}/{pool_name}/wave-{wave_n}"
        if wave_id not in self._spans:
            self._new_span(wave_id, pool_id, KIND_WAVE, f"wave-{wave_n}", now)
        if wave_id in self._spans:
            gspan.parent_id = wave_id
            gspan.attrs.setdefault("wave", wave_n)

    def _group_span_id(self, group_key: str) -> Optional[str]:
        pool_name = self._pool_of(group_key) or DEFAULT_POOL_KEY
        span_id = f"{self.trace_id}/{pool_name}/{group_key}"
        return span_id if span_id in self._spans else None

    def _close_phase_locked(self, group_key: str, now: float) -> None:
        # Artifact steps nest under the phase: a rotating phase takes its
        # open artifact spans with it.
        for (gkey, artifact) in list(self._group_artifact):
            if gkey != group_key:
                continue
            aid = self._group_artifact.pop((gkey, artifact))
            aspan = self._spans.get(aid)
            if aspan is not None and aspan.open:
                aspan.end = now
        span_id = self._group_phase.pop(group_key, None)
        if span_id is not None:
            span = self._spans.get(span_id)
            if span is not None and span.open:
                span.end = now

    def _close_wait_locked(
        self, group_key: str, reason: str, now: float
    ) -> None:
        span_id = self._group_wait.pop((group_key, reason), None)
        if span_id is not None:
            span = self._spans.get(span_id)
            if span is not None and span.open:
                span.end = now

    def _open_wait_locked(
        self,
        group_key: str,
        reason: str,
        now: float,
        parent_id: Optional[str] = None,
        attrs: Optional[dict] = None,
    ) -> None:
        if (group_key, reason) in self._group_wait:
            return  # already waiting for this reason
        if parent_id is None:
            parent_id = self._ensure_group_locked(group_key, now)
        pool_name = self._pool_of(group_key) or DEFAULT_POOL_KEY
        span_id = f"{self.trace_id}/{pool_name}/{group_key}/wait:{reason}"
        span = self._new_span(
            span_id, parent_id, KIND_WAIT, f"wait:{reason}", now, attrs
        )
        if span is not None:
            self._group_wait[(group_key, reason)] = span.span_id

    def _close_node_rungs_locked(self, group_key: str, now: float) -> None:
        for node, (gkey, wait_id) in list(self._node_rung.items()):
            if gkey != group_key:
                continue
            if wait_id is not None:
                span = self._spans.get(wait_id)
                if span is not None and span.open:
                    span.end = now
            node_span_id = self._node_span_id(node, group_key)
            if node_span_id is not None:
                span = self._spans.get(node_span_id)
                if span is not None and span.open:
                    span.end = now
            del self._node_rung[node]

    def _node_span_id(self, node: str, group_key: str) -> Optional[str]:
        pool_name = self._pool_of(group_key) or DEFAULT_POOL_KEY
        span_id = f"{self.trace_id}/{pool_name}/{group_key}/{node}"
        return span_id if span_id in self._spans else None

    def _close_group_locked(self, group_key: str, now: float) -> None:
        self._close_phase_locked(group_key, now)
        for (gkey, reason) in list(self._group_wait):
            if gkey == group_key:
                self._close_wait_locked(gkey, reason, now)
        self._close_node_rungs_locked(group_key, now)
        span_id = self._group_span_id(group_key)
        if span_id is not None:
            span = self._spans[span_id]
            if span.open:
                span.end = now

    @staticmethod
    def _group_key_of(nodes: Iterable) -> Optional[str]:
        names = sorted(
            n.name for n in nodes if getattr(n, "name", None) is not None
        )
        return names[0] if names else None

    def _gkey(self, group_or_nodes) -> Optional[str]:
        nodes = getattr(group_or_nodes, "nodes", None)
        if nodes is not None:
            return self._group_key_of(nodes)
        if isinstance(group_or_nodes, str):
            return group_or_nodes
        try:
            return self._group_key_of(group_or_nodes)
        except TypeError:
            return None

    # ------------------------------------------------------------------
    # wiring (controller/manager)
    # ------------------------------------------------------------------

    @_failopen
    def seed_pools(self, node_pool: dict[str, str]) -> None:
        """Refresh node→pool attribution (mirrors PhaseClockTracker)."""
        with self._lock:
            self._node_pool.update(node_pool)

    # ------------------------------------------------------------------
    # observation: provider transition_observer choke point
    # ------------------------------------------------------------------

    @_failopen
    def observe_group_transition(
        self, nodes: Iterable, new_state, now: Optional[float] = None
    ) -> None:
        """One group-level transition (fired BEFORE labels change).

        This single callback drives the whole tree: the first non-DONE
        transition begins the roll trace; entering ``upgrade-required``
        opens the budget/queue wait; entering a phase state closes that
        wait (admission) and rotates the phase span; quarantine opens
        the dwell wait; DONE closes the group.
        """
        names = sorted(
            n.name for n in nodes if getattr(n, "name", None) is not None
        )
        if not names:
            return
        group_key = names[0]
        ts = self._clock() if now is None else now
        new_value = getattr(new_state, "value", new_state)
        with self._lock:
            prev = self._group_state.get(group_key)
            if prev == new_value:
                return  # idempotent re-issue (crash replay, re-drive)
            if self.trace_id is None:
                if new_value in _TERMINAL:
                    return  # cleanup traffic outside any roll
                self._ensure_roll_locked(ts)
            self._group_state[group_key] = new_value
            for n in names:
                self._node_group[n] = group_key
            if new_value in _TERMINAL:
                self._close_group_locked(group_key, ts)
                return
            admitted = prev == _QUEUED
            group_id = self._ensure_group_locked(group_key, ts)
            if new_value == _QUEUED:
                self._close_phase_locked(group_key, ts)
                self._open_wait_locked(
                    group_key, WAIT_BUDGET, ts, parent_id=group_id
                )
                return
            if admitted:
                self._close_wait_locked(group_key, WAIT_BUDGET, ts)
                self._assign_wave_locked(group_key, ts)
            if new_value == _QUARANTINED:
                self._close_phase_locked(group_key, ts)
                self._close_node_rungs_locked(group_key, ts)
                self._open_wait_locked(
                    group_key, WAIT_QUARANTINE, ts, parent_id=group_id
                )
                return
            if prev == _QUARANTINED:
                self._close_wait_locked(group_key, WAIT_QUARANTINE, ts)
            # Rotate the phase span: close the occupied phase, open the
            # entered one.  (Leaving DRAIN also retires rung ladders.)
            self._close_phase_locked(group_key, ts)
            self._close_node_rungs_locked(group_key, ts)
            pool_name = self._pool_of(group_key) or DEFAULT_POOL_KEY
            span_id = f"{self.trace_id}/{pool_name}/{group_key}/{new_value}"
            span = self._new_span(
                span_id, group_id, KIND_PHASE, new_value, ts
            )
            if span is not None:
                self._group_phase[group_key] = span.span_id

    # ------------------------------------------------------------------
    # engine hooks
    # ------------------------------------------------------------------

    @_failopen
    def begin_admission_pass(self) -> None:
        """Wave boundary: groups admitted in one pass share a wave."""
        with self._lock:
            self._pass_token += 1

    @_failopen
    def begin_wait(self, group_or_nodes, reason: str, **attrs) -> None:
        group_key = self._gkey(group_or_nodes)
        if group_key is None:
            return
        with self._lock:
            if self.trace_id is None:
                return
            self._open_wait_locked(
                group_key, reason, self._clock(), attrs=attrs or None
            )

    @_failopen
    def end_wait(self, group_or_nodes, reason: str) -> None:
        group_key = self._gkey(group_or_nodes)
        if group_key is None:
            return
        with self._lock:
            if self.trace_id is None:
                return
            self._close_wait_locked(group_key, reason, self._clock())

    @_failopen
    def rung_entered(self, node_name: str, rung: str) -> None:
        """Eviction-ladder hook (DrainHelper): one node span per host,
        one wait span per rung occupancy."""
        with self._lock:
            if self.trace_id is None:
                return
            group_key = self._node_group.get(node_name)
            if group_key is None:
                return
            ts = self._clock()
            prev = self._node_rung.get(node_name)
            if prev is not None and prev[1] is not None:
                span = self._spans.get(prev[1])
                if span is not None:
                    if span.open and span.name == f"wait:{WAIT_RUNG_PREFIX}{rung}":
                        return  # idempotent re-entry of the same rung
                    if span.open:
                        span.end = ts
            group_id = self._ensure_group_locked(group_key, ts)
            pool_name = self._pool_of(group_key) or DEFAULT_POOL_KEY
            node_id = f"{self.trace_id}/{pool_name}/{group_key}/{node_name}"
            if node_id not in self._spans:
                self._new_span(node_id, group_id, KIND_NODE, node_name, ts)
            parent = node_id if node_id in self._spans else group_id
            wait_name = f"{WAIT_RUNG_PREFIX}{rung}"
            wait_id = f"{node_id}/wait:{wait_name}"
            span = self._new_span(
                wait_id, parent, KIND_WAIT, f"wait:{wait_name}", ts
            )
            self._node_rung[node_name] = (
                group_key,
                span.span_id if span is not None else None,
            )

    @_failopen
    def artifact_step(
        self, group_or_nodes, artifact: str, done: bool = False
    ) -> None:
        """Multi-artifact stack hook: one nested span per artifact step
        under the group's OPEN phase span (pod-restart today), opened
        when the engine starts restarting that artifact's pods and
        closed when the artifact is fully synced (``done=True``).  The
        span kind is excluded from makespan bucketing by construction
        (critical.py walks PHASE/WAIT only), so nesting artifact steps
        never perturbs the buckets-sum-exactly invariant."""
        group_key = self._gkey(group_or_nodes)
        if group_key is None:
            return
        with self._lock:
            if self.trace_id is None:
                return
            ts = self._clock()
            key = (group_key, artifact)
            open_id = self._group_artifact.get(key)
            if done:
                if open_id is not None:
                    span = self._spans.get(open_id)
                    if span is not None and span.open:
                        span.end = ts
                    del self._group_artifact[key]
                return
            if open_id is not None:
                span = self._spans.get(open_id)
                if span is not None and span.open:
                    return  # idempotent re-issue while the step runs
            parent = self._group_phase.get(group_key)
            if parent is None or parent not in self._spans:
                parent = self._group_span_id(group_key)
            if parent is None:
                return
            span = self._new_span(
                f"{parent}/artifact:{artifact}",
                parent,
                KIND_ARTIFACT,
                f"artifact:{artifact}",
                ts,
            )
            if span is not None:
                self._group_artifact[key] = span.span_id

    @_failopen
    def note_gate(self, group_or_nodes, detail: str) -> None:
        """Validation-gate hook: annotate the open validation phase span
        with the latest rejection detail (bounded, last-writer-wins)."""
        group_key = self._gkey(group_or_nodes)
        if group_key is None:
            return
        with self._lock:
            span_id = self._group_phase.get(group_key)
            span = self._spans.get(span_id) if span_id else None
            if span is not None and span.open:
                span.attrs["gate_rejection"] = str(detail)[:200]
                span.attrs["gate_rejections"] = (
                    int(span.attrs.get("gate_rejections", 0)) + 1
                )

    @_failopen
    def note_api_retry(self, group_or_nodes, seconds: float) -> None:
        """Charge API retry/backoff time to the group (closed wait span,
        recorded after the fact — retries are measured, not predicted)."""
        group_key = self._gkey(group_or_nodes)
        if group_key is None or seconds <= 0:
            return
        with self._lock:
            if self.trace_id is None:
                return
            ts = self._clock()
            parent = self._ensure_group_locked(group_key, ts)
            pool_name = self._pool_of(group_key) or DEFAULT_POOL_KEY
            base = (
                f"{self.trace_id}/{pool_name}/{group_key}"
                f"/wait:{WAIT_API_RETRY}"
            )
            span = self._new_span(
                base,
                parent,
                KIND_WAIT,
                f"wait:{WAIT_API_RETRY}",
                ts - seconds,
            )
            if span is not None and span.open:
                span.end = ts

    # ------------------------------------------------------------------
    # crash durability
    # ------------------------------------------------------------------

    @_failopen
    def annotation_source(self, node, new_state) -> dict:
        """Durable anchor patch merged into the state-label intent by the
        provider (``transition_annotation_source``).  Same idiom as
        AnnotationRungStore: wall epochs in, rebased on adoption."""
        key = getattr(self, "annotation_key", None)
        if key is None:
            return {}
        new_value = getattr(new_state, "value", new_state)
        if new_value in _TERMINAL:
            # Roll over for this group: delete the anchor in the same
            # patch that flips the label to done.
            return {key: None}
        with self._lock:
            if self.trace_id is None:
                return {}
            return {
                key: format_anchor(self.trace_id, new_value, self._epoch())
            }

    @_failopen
    def reopen_group(
        self,
        group_or_nodes,
        anchor_value: Optional[str],
        pool: Optional[str] = None,
        adopted_by: Optional[str] = None,
        now_epoch: Optional[float] = None,
    ) -> bool:
        """Adoption path: continue the persisted trace for one in-flight
        group under a restarted controller.

        Re-opens the roll/pool/group spans plus the group's current
        phase (or wait) span with starts rebased from the persisted wall
        epochs, and primes the dedupe state so the engine's idempotent
        re-drive of the same transition records nothing new.  Returns
        True when a span was re-opened.
        """
        parsed = parse_anchor(anchor_value)
        if parsed is None:
            return False
        trace_id, state_value, epoch = parsed
        group_key = self._gkey(group_or_nodes)
        if group_key is None:
            return False
        nodes = getattr(group_or_nodes, "nodes", None)
        now_ep = int(self._epoch() if now_epoch is None else now_epoch)
        phase_start = monotonic_from_epoch(int(epoch), now_ep)
        with self._lock:
            if self.trace_id is not None and self.trace_id != trace_id:
                # A different roll's leftovers: ignore rather than graft
                # a foreign subtree onto the active trace.
                return False
            if self.trace_id is None:
                # Rebase the roll start from the epoch baked into the
                # trace id (trace ids are deterministic: roll-<epoch>).
                roll_epoch = None
                _, _, tail = trace_id.rpartition("-")
                try:
                    roll_epoch = int(tail)
                except ValueError:
                    roll_epoch = None
                roll_start = (
                    monotonic_from_epoch(roll_epoch, now_ep)
                    if roll_epoch is not None
                    else phase_start
                )
                self._ensure_roll_locked(roll_start, trace_id=trace_id)
            if self._group_state.get(group_key) == state_value:
                return False  # already continued (idempotent re-adopt)
            if pool is not None:
                self._group_pool[group_key] = pool
            if nodes is not None:
                for n in nodes:
                    name = getattr(n, "name", None)
                    if name is not None:
                        self._node_group[name] = group_key
            else:
                self._node_group[group_key] = group_key
            self._group_state[group_key] = state_value
            group_id = self._ensure_group_locked(group_key, phase_start)
            gspan = self._spans.get(group_id)
            if gspan is not None:
                gspan.attrs.setdefault("reopened", True)
                if adopted_by:
                    gspan.attrs["adopted_by"] = adopted_by
            if state_value in _TERMINAL:
                self._close_group_locked(group_key, phase_start)
                return True
            if state_value == _QUEUED:
                self._open_wait_locked(
                    group_key, WAIT_BUDGET, phase_start, parent_id=group_id
                )
                return True
            if state_value == _QUARANTINED:
                self._open_wait_locked(
                    group_key,
                    WAIT_QUARANTINE,
                    phase_start,
                    parent_id=group_id,
                )
                return True
            pool_name = self._pool_of(group_key) or DEFAULT_POOL_KEY
            span_id = (
                f"{self.trace_id}/{pool_name}/{group_key}/{state_value}"
            )
            span = self._new_span(
                span_id, group_id, KIND_PHASE, state_value, phase_start
            )
            if span is not None:
                span.attrs.setdefault("reopened", True)
                self._group_phase[group_key] = span.span_id
            return True

    # ------------------------------------------------------------------
    # roll lifecycle
    # ------------------------------------------------------------------

    @_failopen
    def maybe_end_roll(self, now: Optional[float] = None):
        """Close the trace when every observed group has reached a
        terminal state (called at the end of each full engine pass).
        Returns the :class:`CompletedTrace` on the closing call."""
        with self._lock:
            if self.trace_id is None or not self._group_state:
                return None
            if any(
                state not in _TERMINAL
                for state in self._group_state.values()
            ):
                return None
            ts = self._clock() if now is None else now
            # Everything should already be closed (DONE closes groups);
            # force-close stragglers so a completed trace can never
            # contain an open span.
            forced = 0
            for span in self._spans.values():
                if span.open and span.kind != KIND_ROLL:
                    span.end = ts
                    forced += 1
            roll = self._spans.get(self._roll_id)
            if roll is None:
                self._reset_locked()
                return None
            roll.end = ts
            if forced:
                roll.attrs["force_closed_spans"] = forced
            completed = CompletedTrace(
                trace_id=self.trace_id,
                start=roll.start,
                end=ts,
                spans=list(self._spans.values()),
            )
            self.completed.append(completed)
            del self.completed[: -self.max_completed]
            self._reset_locked()
            return completed

    # ------------------------------------------------------------------
    # introspection (status CLI, flight recorder, tests)
    # ------------------------------------------------------------------

    @property
    def active(self) -> bool:
        return self.trace_id is not None

    def active_trace_id(self) -> Optional[str]:
        return self.trace_id

    def last_completed(self) -> Optional[CompletedTrace]:
        return self.completed[-1] if self.completed else None

    def spans(self) -> list:
        with self._lock:
            return list(self._spans.values())

    def open_span_count(self) -> int:
        with self._lock:
            return sum(1 for s in self._spans.values() if s.open)

    def export(self) -> dict:
        """JSON-shaped snapshot of the ACTIVE trace (flight recorder)."""
        with self._lock:
            roll = self._spans.get(self._roll_id) if self._roll_id else None
            origin = roll.start if roll is not None else 0.0
            return {
                "trace_id": self.trace_id,
                "open_spans": sum(
                    1 for s in self._spans.values() if s.open
                ),
                "drops": self.drops,
                "spans": [s.to_dict(origin) for s in self._spans.values()],
            }
