"""Node-side probe agent.

Runs inside the validation DaemonSet, one pod per TPU host (host networking,
``spec.nodeName`` downward-API env).  Each cycle it runs the JAX probe
battery and publishes the resulting
:class:`~k8s_operator_libs_tpu.health.report.HealthReport` as a node
annotation, where the controller-side
:class:`~k8s_operator_libs_tpu.health.slice_prober.NodeReportProber`
aggregates per-host reports into the slice verdict.

For a multi-host slice the agents coordinate through ``jax.distributed``
(GKE injects ``TPU_WORKER_HOSTNAMES`` / ``MEGASCALE_COORDINATOR_ADDRESS``
style env; we honor JAX's standard auto-detection): then
``jax.devices()`` spans the whole torus and the ICI all-reduce probe *is*
the slice re-formation check.  Single-host agents probe their local chips
only and set ``slice_wide=False``.
"""

from __future__ import annotations

import os
import time
from typing import Optional, Sequence

import jax

from k8s_operator_libs_tpu.consts import get_logger
from k8s_operator_libs_tpu.health.probes import run_host_probe
from k8s_operator_libs_tpu.health.report import HealthReport
from k8s_operator_libs_tpu.upgrade.util import UpgradeKeys

logger = get_logger(__name__)

# Set by the downward API in the agent DaemonSet spec.
NODE_NAME_ENV = "NODE_NAME"
# Driver revision the agent probes under; injected by the controller via
# the DaemonSet template (so it changes exactly when the driver does).
DRIVER_REVISION_ENV = "DRIVER_REVISION"


# GKE's TPU coordinator port convention (worker 0 hosts the jax
# coordination service; jax's own GkeTpuCluster detector uses the same).
GKE_COORDINATOR_PORT = 8476


def maybe_initialize_distributed(backend: Optional[str] = None) -> bool:
    """Initialize ``jax.distributed`` when multi-host env is present.

    GKE TPU pods are injected with ``TPU_WORKER_HOSTNAMES`` +
    ``TPU_WORKER_ID`` (and megascale coordinator env on multi-slice).
    When those fully determine the cluster (>1 hostname and a worker id)
    we initialize EXPLICITLY — coordinator = worker 0, process_id =
    worker id — with jax's own environment auto-detection deactivated,
    so a partially-matching cloud environment can't override the
    contract.  An explicit coordinator address alone falls back to jax
    auto-detection for the remaining parameters.

    Returns True when the process participates in a multi-process JAX
    runtime for ``backend`` (then ``jax.devices()`` spans the whole
    slice and the ICI all-reduce probe is the re-formation check)."""
    hostnames = [
        h
        for h in os.environ.get("TPU_WORKER_HOSTNAMES", "").split(",")
        if h.strip()
    ]
    explicit = (
        os.environ.get("JAX_COORDINATOR_ADDRESS")
        or os.environ.get("COORDINATOR_ADDRESS")
    )
    # Multi-slice (megascale): TPU_WORKER_HOSTNAMES/TPU_WORKER_ID are
    # PER-SLICE, so the explicit branch below would compute a wrong
    # global topology (duplicate process_ids across slices, per-slice
    # num_processes) — only jax's own cluster detection knows how to
    # offset by slice id.  Never use the megascale (DCN) coordinator as
    # the jax coordination service address.
    megascale = bool(os.environ.get("MEGASCALE_COORDINATOR_ADDRESS"))
    if explicit or megascale or len(hostnames) > 1:
        kwargs: dict = {}
        worker_id = os.environ.get("TPU_WORKER_ID", "")
        if not megascale and len(hostnames) > 1 and worker_id.isdigit():
            kwargs = {
                "coordinator_address": (
                    explicit
                    or f"{hostnames[0]}:{GKE_COORDINATOR_PORT}"
                ),
                "num_processes": len(hostnames),
                "process_id": int(worker_id),
                "cluster_detection_method": "deactivate",
            }
        try:
            jax.distributed.initialize(**kwargs)
        except RuntimeError as e:
            # Already initialized (idempotent re-entry) is fine.
            if "already" not in str(e).lower():
                raise
    return jax.process_count(backend) > 1


class HealthAgent:
    """Probe-and-publish loop for one TPU host."""

    def __init__(
        self,
        client,
        node_name: str,
        keys: Optional[UpgradeKeys] = None,
        driver_revision: str = "",
        devices: Optional[Sequence[jax.Device]] = None,
        slice_wide: bool = False,
        matmul_n: int = 4096,
        hbm_mib: int = 1024,
        allreduce_elems: int = 1 << 20,
        deep: bool = False,
        max_iters: Optional[int] = None,
        dcn_peers: Optional[Sequence[str]] = None,
        dcn_group: str = "",
        dcn_expected_groups: Optional[Sequence[str]] = None,
        fused: Optional[bool] = None,
    ) -> None:
        self.client = client
        self.node_name = node_name
        self.keys = keys or UpgradeKeys()
        self.driver_revision = driver_revision
        self.devices = list(devices) if devices is not None else None
        self.slice_wide = slice_wide
        self.matmul_n = matmul_n
        self.hbm_mib = hbm_mib
        self.allreduce_elems = allreduce_elems
        self.deep = deep
        # Sustained-measurement iteration cap.  None = the probes'
        # escalating default (best accuracy; right for a production agent
        # that owns an idle quiesced host).  Bounded values trade
        # precision for a hard ceiling on battery wall-time — for rigs
        # where the agent shares a chip with a workload (the 1-chip
        # bench) a pass/fail verdict against a 50%-of-spec floor does
        # not need deep escalation.
        self.max_iters = max_iters
        # "host[:port]" peer-slice endpoints across the DCN; when set the
        # battery includes dcn_reachability (BASELINE config 5).
        self.dcn_peers = list(dcn_peers) if dcn_peers else None
        # This host's DCN group + the groups expected in the collective
        # world; when set the battery includes dcn_collective — the
        # cross-slice XLA all-reduce the health gate prefers over TCP
        # reachability (north star: "XLA all-reduce reachability").
        self.dcn_group = dcn_group
        self.dcn_expected_groups = (
            list(dcn_expected_groups) if dcn_expected_groups else None
        )
        # Fused single-dispatch battery (health.fused); None resolves
        # the K8S_TPU_FUSED_BATTERY env default (on).  The fused program
        # is fully static, so multi-host slice_wide agents enqueue
        # identical SPMD programs — and every agent of a slice shares
        # the topology-keyed compile across probe cycles.
        self.fused = fused

    def probe_once(self) -> HealthReport:
        kwargs = {} if self.max_iters is None else {"max_iters": self.max_iters}
        checks = run_host_probe(
            self.devices,
            matmul_n=self.matmul_n,
            hbm_mib=self.hbm_mib,
            allreduce_elems=self.allreduce_elems,
            deep=self.deep,
            dcn_peers=self.dcn_peers,
            dcn_group=self.dcn_group,
            dcn_expected_groups=self.dcn_expected_groups,
            fused=self.fused,
            **kwargs,
        )
        # Derive the visible-device count from the enumeration check
        # rather than re-calling jax.devices(): when libtpu is broken (the
        # exact failure this agent exists to report) re-enumeration raises
        # and the unhealthy report would never be published — the
        # controller would only see staleness, losing attribution.
        devs = 0
        for check in checks:
            if check.name == "device_enumeration":
                devs = int(check.metrics.get("devices", 0.0))
                break
        return HealthReport(
            node_name=self.node_name,
            driver_revision=self.driver_revision,
            checks=checks,
            timestamp=time.time(),
            visible_devices=devs,
            slice_wide=self.slice_wide,
        )

    def publish(self, report: HealthReport) -> None:
        self.client.patch_node_annotations(
            self.node_name,
            {self.keys.health_report_annotation: report.to_json()},
        )

    def run_once(self) -> HealthReport:
        report = self.probe_once()
        self.publish(report)
        logger.info(
            "published health report for %s: healthy=%s",
            self.node_name,
            report.healthy,
        )
        return report

    def run_forever(self, interval_s: float = 30.0) -> None:
        """Probe/publish until the process is killed (DaemonSet lifecycle).
        Probe failures are published, not raised: an unhealthy report *is*
        the signal the controller needs."""
        while True:
            try:
                self.run_once()
            except Exception:  # noqa: BLE001 — agent must stay alive
                logger.exception("health probe cycle failed")
            time.sleep(interval_s)


def csv_env(name: str) -> Optional[list]:
    """Comma-separated env var -> stripped non-empty entries, or None.

    Shared by this entrypoint and the multihost test worker (which
    exists to model THIS agent — parsing drift between them would
    silently change what the test exercises)."""
    entries = [
        e.strip() for e in os.environ.get(name, "").split(",") if e.strip()
    ]
    return entries or None


def main() -> None:
    """Entrypoint for the agent container:
    ``python -m k8s_operator_libs_tpu.health.agent``."""
    from k8s_operator_libs_tpu.k8s import get_default_client

    node_name = os.environ.get(NODE_NAME_ENV, "")
    if not node_name:
        raise SystemExit(f"{NODE_NAME_ENV} is required")
    slice_wide = maybe_initialize_distributed()
    agent = HealthAgent(
        client=get_default_client(),
        node_name=node_name,
        driver_revision=os.environ.get(DRIVER_REVISION_ENV, ""),
        slice_wide=slice_wide,
        deep=os.environ.get("HEALTH_DEEP_PROBE", "") == "1",
        dcn_peers=csv_env("HEALTH_DCN_PEERS"),
        dcn_group=os.environ.get("HEALTH_DCN_GROUP", ""),
        dcn_expected_groups=csv_env("HEALTH_DCN_GROUPS"),
    )
    interval = float(os.environ.get("HEALTH_PROBE_INTERVAL_S", "30"))
    agent.run_forever(interval)


if __name__ == "__main__":
    main()
