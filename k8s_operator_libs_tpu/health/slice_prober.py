"""Controller-side slice health probers.

Both classes implement the ``SliceProber`` protocol consumed by
``upgrade.validation_manager.ValidationManager`` (the TPU redesign of the
reference's pod-Ready-only check, validation_manager.go:71-136):

- :class:`LocalDeviceProber` runs the JAX probe battery in-process on the
  devices visible to the controller.  This is the single-host path
  (BASELINE config 3: controller and the v5e host are one machine) and
  the bench/dry-run path.
- :class:`NodeReportProber` is the production multi-host path: each TPU
  host runs a probe-agent pod (``health.agent``) that publishes a
  :class:`~k8s_operator_libs_tpu.health.report.HealthReport` node
  annotation; this prober aggregates the per-host reports into one slice
  verdict — every host must have a fresh report, probed under the
  *current* driver revision, with every check passing and the expected
  chip count visible.  "Validated" therefore means 100 % slice
  re-formation plus a completed ICI collective (the north star), not
  merely "a pod is Ready".
"""

from __future__ import annotations

import time
from typing import Optional, Sequence

import jax

from k8s_operator_libs_tpu.consts import get_logger
from k8s_operator_libs_tpu.health.probes import run_host_probe
from k8s_operator_libs_tpu.health.report import (
    HealthReport,
    measured_node_stats,
)
from k8s_operator_libs_tpu.upgrade.types import UpgradeGroup
from k8s_operator_libs_tpu.upgrade.util import UpgradeKeys
from k8s_operator_libs_tpu.upgrade.validation_manager import ProbeResult

logger = get_logger(__name__)

# A report older than this can't validate: the driver pod restarted more
# recently than the probe ran, or the agent is wedged.
DEFAULT_MAX_REPORT_AGE_S = 600.0


class LocalDeviceProber:
    """Run the probe battery in-process on locally-visible devices."""

    # Real XLA device work (seconds even with the fused battery's warm
    # path on big topologies): ValidationManager dispatches this prober
    # to a worker thread so the battery never blocks a reconcile tick —
    # validation of group N+1 overlaps uncordon of group N.
    async_probe = True

    def __init__(
        self,
        devices: Optional[Sequence[jax.Device]] = None,
        expected_devices: int = 0,
        matmul_n: int = 4096,
        hbm_mib: int = 1024,
        allreduce_elems: int = 1 << 20,
        # None resolves the K8S_TPU_FUSED_BATTERY env default (on): one
        # compiled XLA dispatch for the whole battery, compile cached by
        # topology (health.fused), unfused probes as automatic fallback.
        fused: Optional[bool] = None,
    ) -> None:
        self.devices = list(devices) if devices is not None else None
        self.expected_devices = expected_devices
        self.matmul_n = matmul_n
        self.hbm_mib = hbm_mib
        self.allreduce_elems = allreduce_elems
        self.fused = fused

    def probe(self, group: UpgradeGroup) -> ProbeResult:
        checks = run_host_probe(
            self.devices,
            expected_devices=self.expected_devices,
            matmul_n=self.matmul_n,
            hbm_mib=self.hbm_mib,
            allreduce_elems=self.allreduce_elems,
            fused=self.fused,
        )
        # Measured side-channel stats for the telemetry plane: the
        # battery ran once in-process, so every member host gets the
        # same sample (single-host path — controller and devices are
        # one machine).
        stats = measured_node_stats(checks)
        telemetry = (
            {n.name: dict(stats) for n in group.nodes} if stats else None
        )
        failed = [c for c in checks if not c.ok]
        if failed:
            detail = "; ".join(f"{c.name}: {c.detail}" for c in failed)
            logger.info("group %s local probe failed: %s", group.id, detail)
            return ProbeResult(False, detail, telemetry=telemetry)
        return ProbeResult(
            True,
            f"all {len(checks)} local device checks passed",
            telemetry=telemetry,
        )


def expected_chips_per_host(group: UpgradeGroup) -> int:
    """Chips each host of this group should enumerate (0 = unknown, don't
    enforce): the explicit chips-per-host label override first, then the
    accelerator table, then the topology's chips over expected hosts."""
    if group.slice_info is None:
        return 0
    return group.slice_info.host_chips()


class NodeReportProber:
    """Aggregate per-host HealthReport annotations into a slice verdict."""

    def __init__(
        self,
        keys: UpgradeKeys,
        max_report_age_s: float = DEFAULT_MAX_REPORT_AGE_S,
        # Resolve the driver revision a report must match; wired to
        # PodManager.get_daemonset_controller_revision_hash by the caller.
        revision_resolver=None,
        # Optional floor on reported HBM bandwidth / ICI bus bandwidth;
        # 0 disables (enumeration+correctness checks still apply).
        min_hbm_gbps: float = 0.0,
        min_ici_busbw_gbps: float = 0.0,
        # When > 0 and no explicit min_hbm_gbps is given, derive the HBM
        # floor per group as this fraction of the slice accelerator's
        # published spec (hw.chip_spec) — the default production wiring,
        # so the silent-HBM-degradation mode the probe exists to catch
        # actually gates.  Unknown accelerators leave the floor off.
        hbm_floor_fraction: float = 0.0,
        # Resolve HBM/ICI floors from the fleet GenerationProfile
        # registry when no explicit value is configured — so a v5e pool
        # is gated at v5e spec, a v5p pool at v5p spec, from the same
        # policy.  Off by default (reference behavior: unset floor =
        # floor disabled).
        generation_floors: bool = False,
    ) -> None:
        self.keys = keys
        self.max_report_age_s = max_report_age_s
        self.revision_resolver = revision_resolver
        self.min_hbm_gbps = min_hbm_gbps
        self.min_ici_busbw_gbps = min_ici_busbw_gbps
        self.hbm_floor_fraction = hbm_floor_fraction
        self.generation_floors = generation_floors
        # Require a DCN check (dcn_collective — the cross-slice XLA
        # all-reduce — or the TCP dcn_reachability fallback) in every
        # report for groups that belong to a DCN (multi-slice) group.
        # Pushed from SliceHealthGateSpec.dcn_check by apply_state; a
        # failed DCN check already rejects via the generic failed-checks
        # path — this flag additionally rejects reports that MISSED the
        # check (agent not configured), so "gate on DCN" can't silently
        # no-op.
        self.require_dcn_check = False

    def _required_revision(self, group: UpgradeGroup) -> str:
        if self.revision_resolver is None:
            return ""
        for member in group.members:
            if member.driver_daemon_set is not None:
                return self.revision_resolver(member.driver_daemon_set) or ""
        return ""

    def _group_profile(self, group: UpgradeGroup):
        """The group's GenerationProfile, or None (CPU test meshes)."""
        if group.slice_info is None:
            return None
        from k8s_operator_libs_tpu.fleet.profiles import generation_profile

        return generation_profile(group.slice_info.accelerator)

    def _hbm_floor(self, group: UpgradeGroup) -> float:
        """Effective HBM floor for this group: explicit wins; else the
        policy fraction (or the profile's own floor under
        ``generation_floors``) of the generation's published spec."""
        if self.min_hbm_gbps:
            return self.min_hbm_gbps
        if not self.hbm_floor_fraction and not self.generation_floors:
            return 0.0
        profile = self._group_profile(group)
        if profile is None:
            return 0.0
        if self.hbm_floor_fraction:
            return profile.hbm_floor(self.hbm_floor_fraction)
        return profile.hbm_floor()

    def _ici_floor(self, group: UpgradeGroup) -> float:
        """Effective ICI bus-bandwidth floor: explicit wins; else the
        generation's profile floor under ``generation_floors``."""
        if self.min_ici_busbw_gbps or not self.generation_floors:
            return self.min_ici_busbw_gbps
        profile = self._group_profile(group)
        if profile is None:
            return 0.0
        return profile.ici_floor()

    def _check_report(
        self, report: HealthReport, group: UpgradeGroup, required_rev: str,
        now: float, hbm_floor: float = 0.0,
        ici_floor: Optional[float] = None,
    ) -> Optional[str]:
        """Return a rejection reason, or None if the report is acceptable.

        ``now`` is the staleness reference point.  Callers clamp it to the
        gate's start time when one is recorded: a report must have been
        fresh when the gate OPENED, not stay fresh while it runs — once
        the workload is readmitted (pipelined validation) libtpu's
        exclusive device lock stops the agent from probing, so demanding
        continued freshness would time out every pipelined gate on real
        multi-host slices (the device-contention constraint)."""
        if ici_floor is None:
            ici_floor = self.min_ici_busbw_gbps
        if required_rev and report.driver_revision != required_rev:
            return (
                f"report is for driver revision "
                f"{report.driver_revision or '<none>'}, want {required_rev}"
            )
        age = report.age_seconds(now)
        if self.max_report_age_s and age > self.max_report_age_s:
            return f"report is stale ({age:.0f}s old)"
        if not report.checks:
            return "report has no checks"
        failed = report.failed_checks()
        if failed:
            return "; ".join(f"{c.name}: {c.detail}" for c in failed)
        chips = expected_chips_per_host(group)
        if report.slice_wide and group.slice_info is not None:
            # Agent probed the whole torus: it must have seen every chip
            # of the slice — this IS the 100 % re-formation predicate.
            # (slice_info.chips is always >0, so this check never silently
            # disables for unmapped accelerator types.)
            want = group.slice_info.chips
            if want and report.visible_devices != want:
                return (
                    f"slice-wide probe saw {report.visible_devices} chips, "
                    f"torus has {want}"
                )
        elif chips and report.visible_devices != chips:
            return (
                f"host enumerates {report.visible_devices} chips, "
                f"expected {chips}"
            )
        if (
            self.require_dcn_check
            and group.slice_info is not None
            and group.slice_info.dcn_group is not None
            and not any(
                c.name in ("dcn_collective", "dcn_reachability")
                for c in report.checks
            )
        ):
            return (
                "dcn_check is enabled but the report carries no "
                "dcn_collective/dcn_reachability check (agent not "
                "configured with HEALTH_DCN_GROUP(S)/HEALTH_DCN_PEERS?)"
            )
        for check in report.checks:
            # A check with no measured figure (timing_inconclusive: host
            # noise defeated the sustained estimator, though correctness
            # verified) neither passes nor fails a floor — the next agent
            # sweep will carry a number; rejecting would let one noisy
            # measurement flip a slice verdict.
            if (
                hbm_floor
                and check.name == "hbm_bandwidth"
                and "gbps" in check.metrics
                and check.metrics["gbps"] < hbm_floor
            ):
                return (
                    f"HBM bandwidth {check.metrics['gbps']:.1f} "
                    f"GB/s below floor {hbm_floor:.1f}"
                )
            if (
                ici_floor
                and check.name == "ici_allreduce"
                and "busbw_gbps" in check.metrics
                and check.metrics["busbw_gbps"] < ici_floor
            ):
                return (
                    f"ICI bus bandwidth "
                    f"{check.metrics['busbw_gbps']:.1f} GB/s below "
                    f"floor {ici_floor:.1f}"
                )
        return None

    def probe(self, group: UpgradeGroup) -> ProbeResult:
        key = self.keys.health_report_annotation
        start_key = self.keys.validation_start_time_annotation
        required_rev = self._required_revision(group)
        now = time.time()
        hbm_floor = self._hbm_floor(group)
        ici_floor = self._ici_floor(group)
        # Measured per-node telemetry collected as reports parse — kept
        # even on a failing verdict (a slow-but-parsing host is exactly
        # the sample the straggler baseline needs).
        telemetry: dict[str, dict[str, float]] = {}
        for node in group.nodes:
            raw = node.annotations.get(key)
            if not raw:
                return ProbeResult(
                    False,
                    f"no health report from node {node.name}",
                    telemetry=telemetry or None,
                )
            try:
                report = HealthReport.from_json(raw)
            except ValueError as e:
                return ProbeResult(
                    False,
                    f"node {node.name}: {e}",
                    telemetry=telemetry or None,
                )
            stats = measured_node_stats(report.checks)
            if stats:
                telemetry[node.name] = stats
            # Staleness reference: the gate's start time when stamped (the
            # workload may have re-locked the devices since — see
            # _check_report), else now.
            raw_start = node.annotations.get(start_key, "")
            ref = min(now, float(raw_start)) if raw_start.isdigit() else now
            reason = self._check_report(
                report, group, required_rev, ref, hbm_floor, ici_floor
            )
            if reason is not None:
                return ProbeResult(
                    False,
                    f"node {node.name}: {reason}",
                    telemetry=telemetry or None,
                )
        return ProbeResult(
            True,
            f"all {group.size()} host report(s) healthy"
            + (f" @ revision {required_rev}" if required_rev else ""),
            telemetry=telemetry or None,
        )
