"""TPU slice health backend.

The genuinely new first-class component relative to the reference
(SURVEY.md §2.3, §5, §7 step 5): the reference's ValidationManager can only
check that an out-of-repo validation pod is Ready
(validation_manager.go:71-136) — the actual health check (nvidia-smi) lives
in consumer operators.  Here the health check is in-repo and TPU-native:

- :mod:`probes` — JAX/XLA probe computations: device enumeration, MXU
  matmul with an analytic result check, HBM-bandwidth streaming, ICI
  all-reduce (psum over a device mesh) and per-link ring (ppermute)
  verification;
- :mod:`report` — the serializable per-host :class:`HealthReport` that a
  node agent publishes as a node annotation;
- :mod:`agent` — the node-side probe agent (runs in the validation
  DaemonSet, one pod per TPU host, optionally `jax.distributed` across the
  slice);
- :mod:`slice_prober` — controller-side probers implementing the
  ``SliceProber`` protocol consumed by
  ``upgrade.validation_manager.ValidationManager``.
"""

from k8s_operator_libs_tpu.health.probes import (
    CheckResult,
    device_inventory,
    dcn_collective_probe,
    dcn_reachability_probe,
    hbm_bandwidth_probe,
    ici_allreduce_probe,
    ici_ring_attention_probe,
    ici_ring_probe,
    matmul_probe,
    run_host_probe,
)
from k8s_operator_libs_tpu.health.report import (
    HEALTH_CHECKS_ALL,
    HealthReport,
)
from k8s_operator_libs_tpu.health.slice_prober import (
    LocalDeviceProber,
    NodeReportProber,
)

__all__ = [
    "CheckResult",
    "HealthReport",
    "HEALTH_CHECKS_ALL",
    "LocalDeviceProber",
    "NodeReportProber",
    "device_inventory",
    "dcn_collective_probe",
    "dcn_reachability_probe",
    "hbm_bandwidth_probe",
    "ici_allreduce_probe",
    "ici_ring_attention_probe",
    "ici_ring_probe",
    "matmul_probe",
    "run_host_probe",
]
