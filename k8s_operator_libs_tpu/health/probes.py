"""JAX/XLA health-probe computations.

Each probe is a small, jit-compiled XLA program with a host-side
verification of an analytically-known result, so a probe failure
distinguishes "the math came out wrong" (broken chip / driver) from "the
program didn't run" (device lost / hang → exception or timeout handled by
the caller).  The probes map one-to-one onto the failure domains of a TPU
host after a libtpu upgrade:

- **device enumeration** — libtpu loaded and all chips visible (the
  TPU-native replacement for the reference's out-of-repo nvidia-smi
  validation pod, SURVEY.md §2.3);
- **MXU matmul** — the systolic array multiplies correctly (bf16 inputs,
  f32 accumulation, large static shapes so XLA tiles onto the MXU);
- **HBM bandwidth** — a streaming read+write loop achieves sane bandwidth
  (catches the degraded-HBM failure mode that enumerates fine);
- **ICI all-reduce** — `psum` over every chip of the mesh completes and is
  numerically exact: "the slice re-formed" (BASELINE north star's 100 %
  slice re-formation gate);
- **ICI ring** — `ppermute` by +1 verifies each directed neighbor link
  individually, so a single flaky ICI link is attributable, not just a
  slow/global all-reduce failure.

Probes run identically on TPU and on a virtual multi-device CPU backend
(tests, dry-runs): only the XLA target differs.  All control flow is
static; verification happens on host after ``block_until_ready``.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from k8s_operator_libs_tpu.consts import get_logger

logger = get_logger(__name__)

# One ICI mesh axis: a slice is one torus; the probe reduces over all of it.
ICI_AXIS = "ici"

# jax moved shard_map out of jax.experimental at different points across
# the versions this library runs against; resolve once, newest spelling
# first, so every probe (and the fused battery) shares one symbol.
try:
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover — older jax
    from jax.experimental.shard_map import shard_map


@dataclass
class CheckResult:
    """Outcome of one probe."""

    name: str
    ok: bool
    latency_ms: float = 0.0
    detail: str = ""
    # Free-form numeric side channel (e.g. tflops, gbps) for metrics/bench.
    metrics: dict[str, float] = field(default_factory=dict)

    def as_dict(self) -> dict:
        return {
            "name": self.name,
            "ok": self.ok,
            "latency_ms": round(self.latency_ms, 3),
            "detail": self.detail,
            "metrics": {k: round(v, 3) for k, v in self.metrics.items()},
        }

    @staticmethod
    def from_dict(d: dict) -> "CheckResult":
        return CheckResult(
            name=d.get("name", ""),
            ok=bool(d.get("ok", False)),
            latency_ms=float(d.get("latency_ms", 0.0)),
            detail=d.get("detail", ""),
            metrics=dict(d.get("metrics", {})),
        )


@dataclass(frozen=True)
class GenerationFloors:
    """The probe gates one generation is judged against — resolved from
    the fleet ``GenerationProfile`` registry, never from global
    constants, so a v5e host is not held to v5p bandwidth."""

    generation: str
    mxu_tflops: float
    hbm_gbps: float
    ici_busbw_gbps: float
    allreduce_latency_ms: float


def resolve_floors(device_kind: str) -> Optional[GenerationFloors]:
    """Per-generation probe floors for a device-kind string or GKE
    accelerator label; None when the generation is unknown (CPU test
    meshes) — callers then skip floor gating, same contract as
    ``hw.chip_spec``."""
    from k8s_operator_libs_tpu.fleet.profiles import generation_profile

    profile = generation_profile(device_kind)
    if profile is None:
        return None
    return GenerationFloors(
        generation=profile.name,
        mxu_tflops=profile.mxu_floor(),
        hbm_gbps=profile.hbm_floor(),
        ici_busbw_gbps=profile.ici_floor(),
        allreduce_latency_ms=profile.allreduce_latency_ceiling_ms,
    )


def _timed(fn, *args) -> tuple[float, object]:
    """Run ``fn`` once for compile warmup, then time one synchronous call."""
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) * 1e3, out


# Sustained measurement window.  A single-dispatch timing is dominated by
# host->device dispatch/round-trip cost and can read orders of magnitude
# off the hardware's real throughput (low when a fixed round trip
# dominates a small op; absurdly HIGH when the runtime's
# block_until_ready does not actually wait, as on tunneled remote
# backends) — either way useless for threshold policies.
def _min_time_from_env() -> float:
    raw = os.environ.get("K8S_TPU_PROBE_MIN_TIME_S", "")
    try:
        return float(raw) if raw else 0.05
    except ValueError:
        logger.warning(
            "ignoring malformed K8S_TPU_PROBE_MIN_TIME_S=%r "
            "(want seconds as a float); using 0.05",
            raw,
        )
        return 0.05


DEFAULT_MIN_TIME_S = _min_time_from_env()
_MAX_SUSTAINED_ITERS = 2048
# Initial k1 is capped low (fast probes stay fast); the differential
# check below escalates toward _MAX_SUSTAINED_ITERS//4 only when the
# measured slope doesn't hold enough device work to trust.
_INIT_SUSTAINED_ITERS = 256


# Injectable for unit tests (a fake must not leak to other perf_counter
# callers in the process — jax's own dispatch uses the stdlib one).
_perf_counter = time.perf_counter


def _median(xs: list) -> float:
    xs = sorted(xs)
    mid = len(xs) // 2
    return xs[mid] if len(xs) % 2 else (xs[mid - 1] + xs[mid]) / 2


class InconclusiveTiming(RuntimeError):
    """Sustained-rate measurement failed to produce a valid slope.

    Not a health failure: the computation ran and its *content* is
    verifiable (``out``/``applied`` carry the final chained value and
    application count) — only the throughput figure is missing.  Probes
    catch this and report a passing-but-unmeasured check, so one noisy
    host can't flip a health verdict (ADVICE r2: a hard failure here fed
    false negatives into the validation gate and failed-group recovery).
    """

    def __init__(self, msg: str, out: object, applied: int) -> None:
        super().__init__(msg)
        self.out = out
        self.applied = applied


def _sync_readback(out) -> None:
    """Force execution by reading one element back to the host.

    ``block_until_ready`` is not trustworthy on every backend (remote
    tunnels ack the enqueue, not the execution); a host readback cannot
    complete without the producing computation.  On a multi-process
    global array only this process's shards are host-readable."""
    leaf = jax.tree_util.tree_leaves(out)[0]
    if not getattr(leaf, "is_fully_addressable", True):
        np.asarray(leaf.addressable_shards[0].data)
    elif getattr(leaf, "ndim", 0):
        np.asarray(leaf[(slice(0, 1),) * leaf.ndim])
    else:
        np.asarray(leaf)


def _addressable_numpy(out) -> np.ndarray:
    """This process's view of a (possibly multi-process) array: the full
    array when addressable, else the concatenation of local shards."""
    if getattr(out, "is_fully_addressable", True):
        return np.asarray(out)
    return np.concatenate(
        [np.asarray(s.data) for s in out.addressable_shards]
    )


def _timed_sustained(
    fn,
    args: tuple,
    min_time_s: float = DEFAULT_MIN_TIME_S,
    chain: bool = False,
    max_iters: int = _MAX_SUSTAINED_ITERS,
    flush_every: int = 0,
    deterministic: bool = False,
) -> tuple[float, object, int]:
    """(per-iteration latency ms, last output, chained iterations).

    Measures *sustained* per-op time as the slope between two loop
    lengths: run k1 iterations (one readback sync), run 4·k1 iterations
    (one readback sync), and divide the time difference by the iteration
    difference — any fixed cost (compile residue, dispatch round trip,
    readback) appears in both runs and cancels exactly, leaving pipelined
    device throughput.  ``chain=True`` feeds each output back as the
    first argument so every iteration depends on the previous one and
    nothing can be elided; the returned iteration count is the total
    number of applications on the chained value (for analytic content
    checks)."""
    state = {"out": None, "applied": 0}

    def run(iters: int, start) -> float:
        cur = start
        out = None
        t0 = _perf_counter()
        for i in range(iters):
            out = fn(*cur)
            if chain:
                cur = (out, *args[1:])
            # flush_every bounds the in-flight queue where that matters
            # (hundreds of un-synced multi-device executions exhaust
            # host-backend resources).  It is 0 for single-device probes:
            # chained ops keep only two buffers live, and every flush
            # costs a full round trip on remote backends — throttling
            # the very throughput being measured.
            if flush_every and (i + 1) % flush_every == 0:
                jax.block_until_ready(out)
        _sync_readback(out)
        elapsed = _perf_counter() - t0
        state["out"] = out
        state["applied"] += iters
        return elapsed

    def start_args():
        return (state["out"], *args[1:]) if chain else args

    # Warm/compile.
    state["out"] = fn(*args)
    _sync_readback(state["out"])
    state["applied"] = 1
    # Pilot run to size k1 so the short run holds >= min_time_s of work.
    # Floor at 16: remote backends only reach pipelined throughput past
    # ~16 queued ops (shallow queues pay a round trip per op, which the
    # slope would then faithfully — but uselessly — report).
    #
    # ``deterministic`` pins the whole call schedule to constants
    # instead of local timing.  REQUIRED when ``fn`` contains a
    # collective executed SPMD across processes (multi-host slice_wide
    # probing): every process must enqueue exactly the same number of
    # collective executions, and a timing-derived k1 — or a
    # timing-dependent early break below — would let two hosts disagree
    # and deadlock the slice mid-probe.
    pilot_s = run(2, start_args())
    if deterministic:
        k1 = 16
    else:
        per_est = max(pilot_s / 2, 1e-7)
        init_cap = min(_INIT_SUSTAINED_ITERS, max_iters // 4)
        k1 = max(16, min(init_cap, int(min_time_s / per_est) + 1))
    k2 = 4 * k1
    # One k1-length warm run: the first measured runs after process
    # start are systematically skewed on tunneled backends (the
    # runtime's stream/flush machinery is still warming), which shows up
    # as a consistently non-monotonic first slope pair.  Its elapsed
    # time also RE-SIZES k1: the pilot's per-op estimate is dominated by
    # the fixed dispatch/readback cost on remote backends, which
    # under-sizes k1 for fast ops (an n=4096 matmul is ~0.7 ms on the
    # MXU vs tens of ms of tunnel round trip), drowning the slope in
    # transport jitter.
    warm_s = run(k1, start_args())
    if not deterministic:
        per_warm = max(warm_s / k1, 1e-9)
        resized = int(min_time_s / per_warm) + 1
        if resized > k1:
            k1 = min(max_iters // 4, resized)
            k2 = 4 * k1
    # Measure three slope pairs and take the MEDIAN of the valid
    # (monotonic) slopes.  One noisy measurement must not flip a health
    # verdict in EITHER direction: a host stall during the long run
    # deflates throughput (false floor failure — the r2 flakiness), a
    # stall during the short run inflates it (a >100 % MFU fiction that
    # sails over every floor).  The median of three rejects a single
    # contaminated pair on both sides.
    #
    # A slope is TRUSTED only when its numerator — the k2−k1 differential,
    # which is pure device work (fixed costs cancel) — holds at least
    # min_time_s.  A monotonic-but-tiny differential is indistinguishable
    # from transport jitter and reads as absurd throughput (the r3 bench's
    # over-peak MXU figure).  Untrusted or all-invalid measurements
    # ESCALATE: quadruple the run length (amortizing the jitter) and
    # re-measure, up to the iteration cap.  Never under ``deterministic``:
    # escalation is a timing-dependent decision and SPMD processes must
    # enqueue identical collective counts.  At the cap, valid slopes are
    # accepted as-is (callers still reject over-spec figures); with no
    # valid pair at all the measurement is inconclusive — clamping an
    # invalid slope would report fiction as a passing figure.
    slopes: list[float] = []
    pairs: list[tuple[float, float]] = []
    while True:
        slopes.clear()
        pairs.clear()
        diffs: list[float] = []
        for _ in range(3):
            t1 = run(k1, start_args())
            t2 = run(k2, start_args())
            pairs.append((t1, t2))
            if t2 > t1:
                slopes.append((t2 - t1) / (k2 - k1))
                diffs.append(t2 - t1)
        at_cap = deterministic or k1 >= max_iters // 4
        if slopes:
            med_diff = _median(diffs)
            # Trust needs BOTH enough differential device work and
            # mutually consistent slopes: at a too-short window every
            # pair can be monotonic yet noise-skewed the same way (a
            # 2-3x-under-rate figure the median happily reports).
            # Disagreeing slopes at a long-enough window mean the
            # environment is noisy at every scale — escalate further.
            consistent = (
                len(slopes) == 3 and max(slopes) <= 1.5 * min(slopes)
            )
            if at_cap or (med_diff >= min_time_s and consistent):
                break
            # Jump straight to the run length whose differential holds
            # min_time_s (each escalation round costs 8 host round trips
            # on remote backends — a ×4 ladder would pay that per rung).
            needed = int(k1 * min_time_s / max(med_diff, 1e-9)) + 1
            k1 = min(max_iters // 4, max(k1 * 4, needed))
        elif at_cap:
            raise InconclusiveTiming(
                f"unstable timing: {k1}- vs {k2}-iteration runs were "
                f"non-monotonic in all {len(pairs)} attempts ({pairs}); "
                "cannot measure sustained rate",
                state["out"],
                state["applied"],
            )
        else:
            k1 = min(k1 * 4, max_iters // 4)
        k2 = 4 * k1
    return _median(slopes) * 1e3, state["out"], state["applied"]


def device_inventory(
    devices: Optional[Sequence[jax.Device]] = None,
    expected_devices: int = 0,
) -> CheckResult:
    """Enumerate accelerator devices: libtpu loaded, chips visible.

    ``expected_devices`` > 0 additionally asserts the count (per-host chip
    count from the slice topology, or global chip count under
    ``jax.distributed``)."""
    t0 = time.perf_counter()
    try:
        devs = list(devices) if devices is not None else list(jax.devices())
    except RuntimeError as e:  # no backend at all — driver not loaded
        return CheckResult(
            "device_enumeration", False, 0.0, f"device enumeration failed: {e}"
        )
    latency_ms = (time.perf_counter() - t0) * 1e3
    kinds = sorted({d.device_kind for d in devs})
    ok = len(devs) > 0
    detail = f"{len(devs)} device(s): {', '.join(kinds)}"
    if expected_devices and len(devs) != expected_devices:
        ok = False
        detail += f" (expected {expected_devices})"
    return CheckResult(
        "device_enumeration",
        ok,
        latency_ms,
        detail,
        {"devices": float(len(devs))},
    )


def matmul_probe(
    device: Optional[jax.Device] = None,
    n: int = 4096,
    dtype=jnp.bfloat16,
    min_time_s: float = DEFAULT_MIN_TIME_S,
    max_iters: int = _MAX_SUSTAINED_ITERS,
) -> CheckResult:
    """MXU correctness + sustained throughput with an analytic result.

    A is filled with ``0.5`` and B with ``1/n`` ⇒ every element of
    ``A @ B`` equals ``n * 0.5 * (1/n) = 0.5`` exactly (for power-of-two
    ``n`` both constants are exact in bf16 and accumulation is forced to
    f32), so the product can be *chained* — ``C ← C @ B`` keeps every
    value at exactly 0.5 — giving a dependent back-to-back matmul stream
    whose per-iteration time is real MXU throughput, and any deviation
    anywhere in the chain is a compute fault, not rounding.  Reports
    sustained TFLOPS and MFU against the chip's spec."""
    if n & (n - 1):
        # A failing check, not an exception: run_host_probe's contract is
        # that every probe yields an attributable CheckResult, and a
        # misconfigured battery must still publish a report.
        return CheckResult(
            "mxu_matmul", False, 0.0,
            f"matmul_probe needs power-of-two n for exact chained "
            f"verification, got {n}",
        )
    if device is None:
        device = jax.devices()[0]
    a_val, b_val = 0.5, 1.0 / n
    expected = np.float32(a_val)  # invariant under each chained matmul

    @jax.jit
    def mm(c, b):
        return jnp.matmul(
            c, b, preferred_element_type=jnp.float32
        ).astype(dtype)

    inconclusive = ""
    try:
        a = jax.device_put(jnp.full((n, n), a_val, dtype=dtype), device)
        b = jax.device_put(jnp.full((n, n), b_val, dtype=dtype), device)
        latency_ms, out, iters = _timed_sustained(
            mm, (a, b), min_time_s=min_time_s, chain=True,
            max_iters=max_iters,
        )
        got = np.asarray(out).astype(np.float32)
    except InconclusiveTiming as e:
        # Correctness is still verifiable from the chained output; only
        # the throughput figure is missing.
        latency_ms, out, iters = 0.0, e.out, e.applied
        got = np.asarray(out).astype(np.float32)
        inconclusive = str(e)
    except Exception as e:  # noqa: BLE001 — any device fault fails the check
        return CheckResult("mxu_matmul", False, 0.0, f"matmul failed: {e}")
    exact = bool(np.all(got == expected))
    if not exact:
        return CheckResult(
            "mxu_matmul", False, latency_ms,
            f"matmul result mismatch: expected {expected}, got "
            f"[{got.min()}, {got.max()}]",
            {"n": float(n), "iters": float(iters)},
        )
    if inconclusive:
        return CheckResult(
            "mxu_matmul", True, 0.0,
            f"exact over {iters} chained matmuls (n={n}); throughput "
            f"unmeasured: {inconclusive}",
            {"n": float(n), "iters": float(iters), "timing_inconclusive": 1.0},
        )
    tflops = (2.0 * n * n * n) / (latency_ms * 1e-3) / 1e12
    from k8s_operator_libs_tpu.hw import mfu as _mfu

    metrics = {"tflops": tflops, "n": float(n), "iters": float(iters)}
    mfu_frac = _mfu(tflops, device.device_kind)
    if mfu_frac is not None:
        if mfu_frac > 1.0:
            # Physically impossible — residual timing contamination the
            # median didn't filter.  An over-spec figure must never be
            # REPORTED (it's fiction that trivially clears every floor);
            # correctness stands, throughput is unmeasured.
            return CheckResult(
                "mxu_matmul", True, 0.0,
                f"exact over {iters} chained matmuls (n={n}); measured "
                f"{tflops:.1f} TFLOPS exceeds the chip's peak — timing "
                "unreliable, throughput unmeasured",
                {
                    "n": float(n),
                    "iters": float(iters),
                    "timing_inconclusive": 1.0,
                },
            )
        metrics["mfu"] = mfu_frac
    return CheckResult(
        "mxu_matmul",
        True,
        latency_ms,
        f"exact; {tflops:.1f} TFLOPS sustained over {iters} chained "
        f"matmuls (n={n})",
        metrics,
    )


def hbm_bandwidth_probe(
    device: Optional[jax.Device] = None,
    mib: int = 1024,
    min_time_s: float = DEFAULT_MIN_TIME_S,
    max_iters: int = _MAX_SUSTAINED_ITERS,
) -> CheckResult:
    """Sustained HBM stream: chained ``x ← x + 1`` over a ``mib``-MiB f32
    array (default 1 GiB — large enough that one pass is pure HBM
    traffic, not cache).

    Catches the silently-degraded-HBM failure mode.  Chaining makes every
    iteration depend on the previous one's memory, so XLA cannot elide
    work, and the final value is the exact iteration count — a content
    check over the whole accumulation, not a single add."""
    if device is None:
        device = jax.devices()[0]
    elems = (mib * 1024 * 1024) // 4

    @jax.jit
    def stream(x):
        return x + 1.0

    inconclusive = ""
    try:
        x = jax.device_put(jnp.zeros((elems,), jnp.float32), device)
        latency_ms, out, iters = _timed_sustained(
            stream, (x,), min_time_s=min_time_s, chain=True,
            max_iters=max_iters,
        )
        sample = np.asarray(out[:8])
    except InconclusiveTiming as e:
        latency_ms, out, iters = 0.0, e.out, e.applied
        sample = np.asarray(out[:8])
        inconclusive = str(e)
    except Exception as e:  # noqa: BLE001
        return CheckResult("hbm_bandwidth", False, 0.0, f"stream failed: {e}")
    # The chained value accumulates exactly one add per application,
    # starting from zeros; `iters` is the total application count.
    expected = float(iters)
    if not np.all(sample == expected):
        return CheckResult(
            "hbm_bandwidth", False, latency_ms,
            f"stream content mismatch: expected {expected}, got "
            f"{sample[:4]}",
            {"mib": float(mib), "iters": float(iters)},
        )
    if inconclusive:
        return CheckResult(
            "hbm_bandwidth", True, 0.0,
            f"content exact over {mib} MiB x {iters} passes; bandwidth "
            f"unmeasured: {inconclusive}",
            {
                "mib": float(mib),
                "iters": float(iters),
                "timing_inconclusive": 1.0,
            },
        )
    nbytes = elems * 4 * 2  # read + write per iteration
    gbps = nbytes / (latency_ms * 1e-3) / 1e9
    from k8s_operator_libs_tpu.hw import chip_spec as _chip_spec

    spec = _chip_spec(device.device_kind)
    if spec is not None and gbps > 1.05 * spec.hbm_gbps:
        # Over physical bandwidth: fiction, not a measurement (same
        # rationale as the matmul probe's >100 % MFU clamp).
        return CheckResult(
            "hbm_bandwidth", True, 0.0,
            f"content exact over {mib} MiB x {iters} passes; measured "
            f"{gbps:.1f} GB/s exceeds the chip's {spec.hbm_gbps:.0f} GB/s "
            "spec — timing unreliable, bandwidth unmeasured",
            {
                "mib": float(mib),
                "iters": float(iters),
                "timing_inconclusive": 1.0,
            },
        )
    return CheckResult(
        "hbm_bandwidth",
        True,
        latency_ms,
        f"{gbps:.1f} GB/s sustained over {mib} MiB x {iters} passes",
        {"gbps": gbps, "mib": float(mib), "iters": float(iters)},
    )


def _make_ici_mesh(devices: Sequence[jax.Device]) -> Mesh:
    return Mesh(np.asarray(devices), (ICI_AXIS,))


def ici_allreduce_probe(
    devices: Optional[Sequence[jax.Device]] = None,
    per_device_elems: int = 1 << 20,
    min_time_s: float = DEFAULT_MIN_TIME_S,
    max_iters: int = _MAX_SUSTAINED_ITERS,
) -> CheckResult:
    """All-reduce (`psum`) across every chip of the slice mesh.

    Device ``i`` contributes the constant ``i+1`` ⇒ every shard of the
    result must equal ``n(n+1)/2`` exactly.  Success means the torus
    re-formed end-to-end — the north-star "100 % slice re-formation"
    predicate.  Bus bandwidth is measured over a sustained run (the same
    input re-reduced back to back), so the figure reflects link
    throughput, not dispatch latency."""
    devs = list(devices) if devices is not None else list(jax.devices())
    n = len(devs)
    if n < 2:
        return CheckResult(
            "ici_allreduce", True, 0.0, "single device; no ICI to probe",
            {"devices": float(n)},
        )
    mesh = _make_ici_mesh(devs)
    expected = n * (n + 1) / 2.0
    # Multi-process mesh (slice_wide probing): every process runs this
    # probe SPMD, so the measurement schedule must be deterministic — a
    # locally-timed schedule would desynchronize collective counts
    # across hosts and hang the slice.
    multi_process = len({d.process_index for d in devs}) > 1

    def body(x):
        return jax.lax.psum(x, ICI_AXIS)

    fn = jax.jit(
        shard_map(
            body, mesh=mesh, in_specs=P(ICI_AXIS), out_specs=P(ICI_AXIS)
        )
    )
    inconclusive = ""
    try:
        # ramp: rows of constant (i+1), row i sharded onto device i.
        host = np.repeat(
            np.arange(1.0, n + 1.0, dtype=np.float32)[:, None],
            per_device_elems,
            axis=1,
        )
        x = jax.make_array_from_callback(
            host.shape,
            NamedSharding(mesh, P(ICI_AXIS)),
            lambda idx: host[idx],
        )
        latency_ms, out, iters = _timed_sustained(
            fn, (x,), min_time_s=min_time_s, flush_every=16,
            deterministic=multi_process, max_iters=max_iters,
        )
        got = _addressable_numpy(out)
    except InconclusiveTiming as e:
        latency_ms, out, iters = 0.0, e.out, e.applied
        got = _addressable_numpy(out)
        inconclusive = str(e)
    except Exception as e:  # noqa: BLE001
        return CheckResult(
            "ici_allreduce", False, 0.0, f"all-reduce failed: {e}"
        )
    if not np.all(got == expected):
        return CheckResult(
            "ici_allreduce", False, latency_ms,
            f"psum mismatch: expected {expected}, got "
            f"[{got.min()}, {got.max()}]",
            {"devices": float(n), "iters": float(iters)},
        )
    if inconclusive:
        return CheckResult(
            "ici_allreduce", True, 0.0,
            f"psum over {n} devices exact ({iters} rounds); bus bandwidth "
            f"unmeasured: {inconclusive}",
            {
                "devices": float(n),
                "iters": float(iters),
                "timing_inconclusive": 1.0,
            },
        )
    # Ring all-reduce moves 2(n-1)/n of the buffer over each link.
    shard_bytes = per_device_elems * 4
    busbw = (2.0 * (n - 1) / n) * shard_bytes / (latency_ms * 1e-3) / 1e9
    return CheckResult(
        "ici_allreduce",
        True,
        latency_ms,
        f"psum over {n} devices exact; {busbw:.1f} GB/s bus bandwidth "
        f"sustained over {iters} rounds",
        {"devices": float(n), "busbw_gbps": busbw, "iters": float(iters)},
    )


def ici_ring_probe(
    devices: Optional[Sequence[jax.Device]] = None,
) -> CheckResult:
    """Per-link verification: ``ppermute`` every shard to its +1 ring
    neighbor; shard ``i`` must then hold ``i-1 (mod n)``.

    A failure here names the broken *link* (the first position whose
    received value is wrong), where the all-reduce probe can only say "the
    collective didn't complete"."""
    devs = list(devices) if devices is not None else list(jax.devices())
    n = len(devs)
    if n < 2:
        return CheckResult(
            "ici_ring", True, 0.0, "single device; no links to probe",
            {"devices": float(n)},
        )
    mesh = _make_ici_mesh(devs)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(x):
        return jax.lax.ppermute(x, ICI_AXIS, perm)

    fn = jax.jit(
        shard_map(
            body, mesh=mesh, in_specs=P(ICI_AXIS), out_specs=P(ICI_AXIS)
        )
    )
    try:
        host = np.arange(n, dtype=np.float32)[:, None]
        x = jax.make_array_from_callback(
            host.shape, NamedSharding(mesh, P(ICI_AXIS)),
            lambda idx: host[idx],
        )
        latency_ms, out = _timed(fn, x)
        # Verify shard-wise by GLOBAL position: under jax.distributed
        # each process can read only its own shards, but their .index
        # carries the global row, so every directed link is still checked
        # (each host verifies the links that deliver INTO its chips).
        bad: list[tuple[int, float]] = []
        checked = 0
        for shard in out.addressable_shards:
            row = shard.index[0].start or 0
            vals = np.asarray(shard.data)[:, 0]
            for off, got_v in enumerate(vals):
                checked += 1
                want = float((row + off - 1) % n)
                if got_v != want:
                    bad.append((row + off, float(got_v)))
    except Exception as e:  # noqa: BLE001
        return CheckResult("ici_ring", False, 0.0, f"ppermute failed: {e}")
    if bad:
        first, got_v = bad[0]
        return CheckResult(
            "ici_ring",
            False,
            latency_ms,
            f"link {(first - 1) % n}->{first} delivered {got_v}, "
            f"expected {float((first - 1) % n)}",
            {"devices": float(n), "bad_links": float(len(bad))},
        )
    return CheckResult(
        "ici_ring",
        True,
        latency_ms,
        f"all {checked} locally-received ring link(s) verified "
        f"({n}-device ring)",
        {"devices": float(n)},
    )


def ici_ring_attention_probe(
    devices: Optional[Sequence[jax.Device]] = None,
    seq_per_device: int = 128,
) -> CheckResult:
    """Deep ICI soak: ring attention over the full mesh.

    One psum proves the torus formed; a ring-attention pass keeps every
    directed link under sustained, overlapping load for n rounds — the
    traffic shape of real long-context training — and verifies the
    result against single-device full attention.  Optional (slower than
    the quick gate); enable for post-incident validation or periodic
    deep checks."""
    from k8s_operator_libs_tpu.workloads.ring_attention import (
        ring_attention_soak,
    )

    devs = list(devices) if devices is not None else list(jax.devices())
    if len(devs) < 2:
        return CheckResult(
            "ici_ring_attention", True, 0.0,
            "single device; no ring to soak",
            {"devices": float(len(devs))},
        )
    try:
        res = ring_attention_soak(devs, seq_per_device=seq_per_device)
    except Exception as e:  # noqa: BLE001
        return CheckResult(
            "ici_ring_attention", False, 0.0, f"ring attention failed: {e}"
        )
    return CheckResult(
        "ici_ring_attention",
        bool(res["ok"]),
        float(res["latency_ms"]),
        (
            f"seq {res['global_seq']} over {res['devices']} devices, "
            f"max err {res['max_err']:.2e}"
        ),
        {
            "devices": float(res["devices"]),
            "link_gbps": float(res["link_gbps"]),
            "global_seq": float(res["global_seq"]),
        },
    )


# Default TPU runtime gRPC port (what peer-slice hosts listen on).
DCN_DEFAULT_PORT = 8471


def dcn_reachability_probe(
    peers: Sequence[str], timeout_s: float = 2.0
) -> CheckResult:
    """TCP reachability to peer-slice hosts across the DCN.

    In a multi-slice deployment (DCN data-parallel, BASELINE config 5)
    every host must reach the peer slices' hosts or the whole JobSet
    stalls at the next cross-slice collective.  ICI probes can't see
    this — the slice itself re-forms fine with a broken DCN path — so
    it's a separate check, gated by SliceHealthGateSpec.dcn_check.
    ``peers`` are "host[:port]" (default port: the TPU runtime's gRPC
    port); reachability is a TCP connect, the same signal a gRPC channel
    setup would give, without needing the peer mid-collective.
    """
    import socket
    from concurrent.futures import ThreadPoolExecutor

    def parse(peer: str) -> tuple[str, int]:
        # "host", "host:port", "[v6]:port", or a bare IPv6 literal.
        if peer.startswith("["):
            host, _, rest = peer[1:].partition("]")
            port = rest.lstrip(":")
        elif peer.count(":") > 1:
            host, port = peer, ""
        else:
            host, _, port = peer.partition(":")
        return host, int(port or DCN_DEFAULT_PORT)

    def connect(peer: str) -> Optional[str]:
        try:
            with socket.create_connection(parse(peer), timeout=timeout_s):
                return None
        except (OSError, ValueError) as e:
            return f"{peer} ({e})"

    t0 = time.perf_counter()
    # Concurrent connects: total probe time stays ~one timeout even with
    # many unreachable peers (a partitioned DCN must not make the probe
    # itself so slow that reports go stale and mask the real failure).
    with ThreadPoolExecutor(max_workers=min(32, max(1, len(peers)))) as pool:
        failures = list(pool.map(connect, peers))
    unreachable = [f for f in failures if f is not None]
    elapsed_ms = (time.perf_counter() - t0) * 1e3
    reachable = len(peers) - len(unreachable)
    detail = f"{reachable}/{len(peers)} DCN peer(s) reachable"
    if unreachable:
        detail += ": unreachable " + "; ".join(unreachable)
    return CheckResult(
        "dcn_reachability",
        not unreachable,
        elapsed_ms,
        detail,
        metrics={"peers": float(len(peers)), "reachable": float(reachable)},
    )


def dcn_collective_probe(
    devices: Optional[Sequence[jax.Device]] = None,
    dcn_group: str = "",
    expected_groups: Optional[Sequence[str]] = None,
) -> CheckResult:
    """A cross-slice XLA all-reduce over the DCN — the north star's
    "XLA all-reduce reachability", strictly stronger than
    :func:`dcn_reachability_probe`: a port can answer while the
    collective transport is broken (stale gRPC state, a peer slice that
    never joined the world, an asymmetric route), and only a COMPLETING
    psum whose result carries every peer slice's contribution proves the
    multi-slice JobSet can actually step.

    Every process contributes a one-hot vector over the sorted expected
    DCN group names at its own group's index; after a ``psum`` across
    the full ``jax.distributed`` world, entry g is the number of devices
    whose host claims group g.  Verdict: every expected group
    contributed at least once.  A peer slice that is reachable by TCP
    but absent from the collective world shows up as a zero — the exact
    failure the TCP probe cannot see."""
    try:
        devs = list(devices) if devices is not None else list(jax.devices())
    except RuntimeError as e:
        return CheckResult(
            "dcn_collective", False, 0.0, f"device enumeration failed: {e}"
        )
    if not dcn_group:
        return CheckResult(
            "dcn_collective", False, 0.0,
            "no DCN group configured for this host (HEALTH_DCN_GROUP)",
        )
    groups = sorted(set(expected_groups or ()) | {dcn_group})
    if len(groups) < 2:
        return CheckResult(
            "dcn_collective", False, 0.0,
            f"need >=2 expected DCN groups, have {groups} — a single-group "
            "collective proves nothing about the DCN",
        )
    n = len(devs)
    n_processes = len({d.process_index for d in devs})
    if n_processes < 2:
        return CheckResult(
            "dcn_collective", False, 0.0,
            f"distributed world spans {n_processes} process(es); the "
            "cross-slice world never formed",
            metrics={"processes": float(n_processes)},
        )
    mesh = Mesh(np.asarray(devs), ("dcn",))
    onehot = np.zeros(len(groups), dtype=np.float32)
    onehot[groups.index(dcn_group)] = 1.0
    # Each process materializes only ITS addressable rows, filled with
    # ITS group's one-hot; remote rows come from their own processes.
    host = np.tile(onehot, (n, 1))

    def body(x):
        return jax.lax.psum(x, "dcn")

    t0 = time.perf_counter()
    try:
        x = jax.make_array_from_callback(
            host.shape,
            NamedSharding(mesh, P("dcn")),
            lambda idx: host[idx],
        )
        fn = jax.jit(
            shard_map(body, mesh=mesh, in_specs=P("dcn"), out_specs=P())
        )
        counts = np.asarray(
            _addressable_numpy(jax.block_until_ready(fn(x)))
        ).reshape(-1)[: len(groups)]
    except Exception as e:  # noqa: BLE001 — a broken DCN raises mid-psum
        return CheckResult(
            "dcn_collective", False,
            (time.perf_counter() - t0) * 1e3,
            f"cross-slice psum failed: {e}",
        )
    elapsed_ms = (time.perf_counter() - t0) * 1e3
    contributions = {g: int(c) for g, c in zip(groups, counts)}
    missing = [g for g, c in contributions.items() if c < 1]
    detail = "cross-slice psum completed; contributions: " + " ".join(
        f"{g}={c}" for g, c in contributions.items()
    )
    if missing:
        detail = (
            "DCN collective missing contribution(s) from: "
            + ", ".join(missing) + "; " + detail
        )
    return CheckResult(
        "dcn_collective",
        not missing,
        elapsed_ms,
        detail,
        metrics={
            "groups": float(len(groups)),
            "participating": float(len(groups) - len(missing)),
            "processes": float(n_processes),
        },
    )


def fused_battery_enabled() -> bool:
    """Fused battery default: on unless K8S_TPU_FUSED_BATTERY disables
    it (the unfused path is the always-available fallback)."""
    raw = os.environ.get("K8S_TPU_FUSED_BATTERY", "1").strip().lower()
    return raw not in ("0", "false", "no", "off")


def run_host_probe(
    devices: Optional[Sequence[jax.Device]] = None,
    expected_devices: int = 0,
    matmul_n: int = 4096,
    hbm_mib: int = 1024,
    allreduce_elems: int = 1 << 20,
    skip_ici: bool = False,
    deep: bool = False,
    min_time_s: float = DEFAULT_MIN_TIME_S,
    max_iters: int = _MAX_SUSTAINED_ITERS,
    dcn_peers: Optional[Sequence[str]] = None,
    dcn_group: str = "",
    dcn_expected_groups: Optional[Sequence[str]] = None,
    on_check=None,
    fused: Optional[bool] = None,
) -> list[CheckResult]:
    """Run the full probe battery; returns every check's result.

    Production defaults are sized for *sustained* measurement (n=4096
    matmuls, 1 GiB HBM stream, ≥50 ms device time per probe) so the
    reported TFLOPS/GB/s figures are comparable to chip spec and usable
    as health floors; tests/CI pass small overrides.

    ``fused`` selects the single-dispatch fused battery
    (health.fused: one compiled XLA program for matmul + HBM + ICI,
    topology-keyed compile cache).  ``None`` resolves the
    K8S_TPU_FUSED_BATTERY env default (on); any fused-path fault falls
    back to the unfused probes below, so fusing can only ever add
    speed, never subtract coverage.  Fused checks carry no throughput
    figures (a single dispatch can't run the sustained estimator) —
    downstream floors treat that like ``timing_inconclusive``.

    ``on_check`` (optional ``CheckResult -> None``) is invoked as each
    check completes — a progress/liveness hook for callers running the
    battery under a stall watchdog (the bench) or emitting per-check
    telemetry.

    Fail-fast on enumeration (nothing else can run without devices), then
    run every remaining probe even if one fails — the per-check results
    are what make a slice-health verdict attributable."""
    results: list[CheckResult] = []

    def add(check: CheckResult) -> None:
        results.append(check)
        if on_check is not None:
            on_check(check)

    try:
        devs = list(devices) if devices is not None else list(jax.devices())
    except RuntimeError as e:  # no backend at all — driver not loaded
        add(
            CheckResult(
                "device_enumeration",
                False,
                0.0,
                f"device enumeration failed: {e}",
            )
        )
        return results
    add(device_inventory(devs, expected_devices))
    if not devs:
        return results
    if fused is None:
        fused = fused_battery_enabled()
    fused_checks: Optional[list[CheckResult]] = None
    if fused:
        try:
            from k8s_operator_libs_tpu.health.fused import run_fused_battery

            fused_checks = run_fused_battery(
                devs,
                matmul_n=matmul_n,
                hbm_mib=hbm_mib,
                allreduce_elems=allreduce_elems,
                skip_ici=skip_ici,
            )
        except Exception as e:  # noqa: BLE001 — unfused is the fallback
            from k8s_operator_libs_tpu.health.fused import record_fallback

            record_fallback()
            logger.warning(
                "fused probe battery failed (%s); falling back to the "
                "unfused probes",
                e,
            )
            fused_checks = None
    if fused_checks is not None:
        for check in fused_checks:
            add(check)
    else:
        # Single-device probes must run on a device THIS process
        # addresses: under jax.distributed the global device list spans
        # hosts, and device_put onto a non-addressable device raises.
        # The process index must come from the device's own backend —
        # the DEFAULT backend can be a different registered plugin with
        # its own (single-process) view.
        local = [
            d for d in devs if d.process_index == d.client.process_index()
        ]
        probe_dev = local[0] if local else devs[0]
        battery_checks: list[CheckResult] = []
        t0 = time.perf_counter()
        battery_checks.append(
            matmul_probe(
                probe_dev,
                n=matmul_n,
                min_time_s=min_time_s,
                max_iters=max_iters,
            )
        )
        battery_checks.append(
            hbm_bandwidth_probe(
                probe_dev,
                mib=hbm_mib,
                min_time_s=min_time_s,
                max_iters=max_iters,
            )
        )
        if not skip_ici:
            battery_checks.append(
                ici_allreduce_probe(
                    devs,
                    per_device_elems=allreduce_elems,
                    min_time_s=min_time_s,
                    max_iters=max_iters,
                )
            )
            battery_checks.append(ici_ring_probe(devs))
        execute_ms = (time.perf_counter() - t0) * 1e3
        # Telemetry parity with the fused battery: stamp the same
        # battery_* side-channel keys (with ``fused: 0.0`` — falsy, so
        # fused-only consumers like fused_battery_telemetry still read
        # this report as unfused) and the generation's floor metadata,
        # so the telemetry plane is blind to which battery ran.  The
        # unfused battery has no compile step and no cache.
        parity = {
            "fused": 0.0,
            "battery_cache_hit": 0.0,
            "battery_compile_ms": 0.0,
            "battery_execute_ms": execute_ms,
        }
        kinds = sorted({d.device_kind for d in devs})
        floors = resolve_floors(",".join(kinds))
        if floors is not None:
            parity["floor_mxu_tflops"] = floors.mxu_tflops
            parity["floor_hbm_gbps"] = floors.hbm_gbps
            parity["floor_ici_busbw_gbps"] = floors.ici_busbw_gbps
        for check in battery_checks:
            check.metrics.update(parity)
            add(check)
    # The deep soak stays unfused: it is an optional post-incident /
    # periodic check with its own workload-shaped program, not part of
    # the quick gate the fusion accelerates.
    if not skip_ici and deep:
        add(ici_ring_attention_probe(devs))
    if dcn_peers:
        add(dcn_reachability_probe(dcn_peers))
    if dcn_expected_groups:
        # The collective gate (north star: "XLA all-reduce reachability")
        # — runs over the full jax.distributed world and proves every
        # peer DCN group's contribution lands; reachability above stays
        # as the cheap attribution aid when both are configured.
        add(
            dcn_collective_probe(
                devs, dcn_group=dcn_group,
                expected_groups=dcn_expected_groups,
            )
        )
    return results
