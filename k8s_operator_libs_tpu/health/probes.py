"""JAX/XLA health-probe computations.

Each probe is a small, jit-compiled XLA program with a host-side
verification of an analytically-known result, so a probe failure
distinguishes "the math came out wrong" (broken chip / driver) from "the
program didn't run" (device lost / hang → exception or timeout handled by
the caller).  The probes map one-to-one onto the failure domains of a TPU
host after a libtpu upgrade:

- **device enumeration** — libtpu loaded and all chips visible (the
  TPU-native replacement for the reference's out-of-repo nvidia-smi
  validation pod, SURVEY.md §2.3);
- **MXU matmul** — the systolic array multiplies correctly (bf16 inputs,
  f32 accumulation, large static shapes so XLA tiles onto the MXU);
- **HBM bandwidth** — a streaming read+write loop achieves sane bandwidth
  (catches the degraded-HBM failure mode that enumerates fine);
- **ICI all-reduce** — `psum` over every chip of the mesh completes and is
  numerically exact: "the slice re-formed" (BASELINE north star's 100 %
  slice re-formation gate);
- **ICI ring** — `ppermute` by +1 verifies each directed neighbor link
  individually, so a single flaky ICI link is attributable, not just a
  slow/global all-reduce failure.

Probes run identically on TPU and on a virtual multi-device CPU backend
(tests, dry-runs): only the XLA target differs.  All control flow is
static; verification happens on host after ``block_until_ready``.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from k8s_operator_libs_tpu.consts import get_logger

logger = get_logger(__name__)

# One ICI mesh axis: a slice is one torus; the probe reduces over all of it.
ICI_AXIS = "ici"


@dataclass
class CheckResult:
    """Outcome of one probe."""

    name: str
    ok: bool
    latency_ms: float = 0.0
    detail: str = ""
    # Free-form numeric side channel (e.g. tflops, gbps) for metrics/bench.
    metrics: dict[str, float] = field(default_factory=dict)

    def as_dict(self) -> dict:
        return {
            "name": self.name,
            "ok": self.ok,
            "latency_ms": round(self.latency_ms, 3),
            "detail": self.detail,
            "metrics": {k: round(v, 3) for k, v in self.metrics.items()},
        }

    @staticmethod
    def from_dict(d: dict) -> "CheckResult":
        return CheckResult(
            name=d.get("name", ""),
            ok=bool(d.get("ok", False)),
            latency_ms=float(d.get("latency_ms", 0.0)),
            detail=d.get("detail", ""),
            metrics=dict(d.get("metrics", {})),
        )


def _timed(fn, *args) -> tuple[float, object]:
    """Run ``fn`` once for compile warmup, then time one synchronous call."""
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) * 1e3, out


def device_inventory(
    devices: Optional[Sequence[jax.Device]] = None,
    expected_devices: int = 0,
) -> CheckResult:
    """Enumerate accelerator devices: libtpu loaded, chips visible.

    ``expected_devices`` > 0 additionally asserts the count (per-host chip
    count from the slice topology, or global chip count under
    ``jax.distributed``)."""
    t0 = time.perf_counter()
    try:
        devs = list(devices) if devices is not None else list(jax.devices())
    except RuntimeError as e:  # no backend at all — driver not loaded
        return CheckResult(
            "device_enumeration", False, 0.0, f"device enumeration failed: {e}"
        )
    latency_ms = (time.perf_counter() - t0) * 1e3
    kinds = sorted({d.device_kind for d in devs})
    ok = len(devs) > 0
    detail = f"{len(devs)} device(s): {', '.join(kinds)}"
    if expected_devices and len(devs) != expected_devices:
        ok = False
        detail += f" (expected {expected_devices})"
    return CheckResult(
        "device_enumeration",
        ok,
        latency_ms,
        detail,
        {"devices": float(len(devs))},
    )


def matmul_probe(
    device: Optional[jax.Device] = None, n: int = 2048, dtype=jnp.bfloat16
) -> CheckResult:
    """MXU correctness + throughput: ``C = A @ B`` with an analytic result.

    A is filled with ``a``, B with ``b`` ⇒ every C element equals
    ``n*a*b`` exactly (bf16 operands are exact for these small constants
    and accumulation is forced to f32 via ``preferred_element_type``), so
    any deviation is a real compute fault, not rounding."""
    if device is None:
        device = jax.devices()[0]
    a_val, b_val = 0.5, 0.25
    expected = n * a_val * b_val

    @jax.jit
    def mm(a, b):
        return jnp.matmul(a, b, preferred_element_type=jnp.float32)

    try:
        a = jax.device_put(jnp.full((n, n), a_val, dtype=dtype), device)
        b = jax.device_put(jnp.full((n, n), b_val, dtype=dtype), device)
        latency_ms, out = _timed(mm, a, b)
        got = np.asarray(out)
    except Exception as e:  # noqa: BLE001 — any device fault fails the check
        return CheckResult("mxu_matmul", False, 0.0, f"matmul failed: {e}")
    exact = bool(np.all(got == expected))
    tflops = (2.0 * n * n * n) / (latency_ms * 1e-3) / 1e12
    return CheckResult(
        "mxu_matmul",
        exact,
        latency_ms,
        "exact" if exact else
        f"matmul result mismatch: expected {expected}, got "
        f"[{got.min()}, {got.max()}]",
        {"tflops": tflops, "n": float(n)},
    )


def hbm_bandwidth_probe(
    device: Optional[jax.Device] = None, mib: int = 256
) -> CheckResult:
    """Streaming HBM read+write: ``y = x + 1`` over a ``mib``-MiB f32 array.

    Catches the silently-degraded-HBM failure mode.  The check itself
    verifies the add (content check on a sample), the bandwidth figure is
    surfaced as a metric for threshold policies in the prober."""
    if device is None:
        device = jax.devices()[0]
    elems = (mib * 1024 * 1024) // 4

    @jax.jit
    def stream(x):
        return x + 1.0

    try:
        x = jax.device_put(jnp.zeros((elems,), jnp.float32), device)
        latency_ms, out = _timed(stream, x)
        sample = np.asarray(out[:8])
    except Exception as e:  # noqa: BLE001
        return CheckResult("hbm_bandwidth", False, 0.0, f"stream failed: {e}")
    ok = bool(np.all(sample == 1.0))
    nbytes = elems * 4 * 2  # read + write
    gbps = nbytes / (latency_ms * 1e-3) / 1e9
    return CheckResult(
        "hbm_bandwidth",
        ok,
        latency_ms,
        f"{gbps:.1f} GB/s over {mib} MiB" if ok else "stream content mismatch",
        {"gbps": gbps, "mib": float(mib)},
    )


def _make_ici_mesh(devices: Sequence[jax.Device]) -> Mesh:
    return Mesh(np.asarray(devices), (ICI_AXIS,))


def ici_allreduce_probe(
    devices: Optional[Sequence[jax.Device]] = None,
    per_device_elems: int = 1 << 20,
) -> CheckResult:
    """All-reduce (`psum`) across every chip of the slice mesh.

    Device ``i`` contributes the constant ``i+1`` ⇒ every shard of the
    result must equal ``n(n+1)/2`` exactly.  Success means the torus
    re-formed end-to-end — the north-star "100 % slice re-formation"
    predicate.  Also reports ring-all-reduce bus bandwidth."""
    devs = list(devices) if devices is not None else list(jax.devices())
    n = len(devs)
    if n < 2:
        return CheckResult(
            "ici_allreduce", True, 0.0, "single device; no ICI to probe",
            {"devices": float(n)},
        )
    mesh = _make_ici_mesh(devs)
    expected = n * (n + 1) / 2.0

    def body(x):
        return jax.lax.psum(x, ICI_AXIS)

    fn = jax.jit(
        jax.shard_map(
            body, mesh=mesh, in_specs=P(ICI_AXIS), out_specs=P(ICI_AXIS)
        )
    )
    try:
        # ramp: rows of constant (i+1), row i sharded onto device i.
        host = np.repeat(
            np.arange(1.0, n + 1.0, dtype=np.float32)[:, None],
            per_device_elems,
            axis=1,
        )
        x = jax.device_put(host, NamedSharding(mesh, P(ICI_AXIS)))
        latency_ms, out = _timed(fn, x)
        got = np.asarray(out)
    except Exception as e:  # noqa: BLE001
        return CheckResult(
            "ici_allreduce", False, 0.0, f"all-reduce failed: {e}"
        )
    exact = bool(np.all(got == expected))
    # Ring all-reduce moves 2(n-1)/n of the buffer over each link.
    shard_bytes = per_device_elems * 4
    busbw = (2.0 * (n - 1) / n) * shard_bytes / (latency_ms * 1e-3) / 1e9
    return CheckResult(
        "ici_allreduce",
        exact,
        latency_ms,
        f"psum over {n} devices exact" if exact else
        f"psum mismatch: expected {expected}, got "
        f"[{got.min()}, {got.max()}]",
        {"devices": float(n), "busbw_gbps": busbw},
    )


def ici_ring_probe(
    devices: Optional[Sequence[jax.Device]] = None,
) -> CheckResult:
    """Per-link verification: ``ppermute`` every shard to its +1 ring
    neighbor; shard ``i`` must then hold ``i-1 (mod n)``.

    A failure here names the broken *link* (the first position whose
    received value is wrong), where the all-reduce probe can only say "the
    collective didn't complete"."""
    devs = list(devices) if devices is not None else list(jax.devices())
    n = len(devs)
    if n < 2:
        return CheckResult(
            "ici_ring", True, 0.0, "single device; no links to probe",
            {"devices": float(n)},
        )
    mesh = _make_ici_mesh(devs)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(x):
        return jax.lax.ppermute(x, ICI_AXIS, perm)

    fn = jax.jit(
        jax.shard_map(
            body, mesh=mesh, in_specs=P(ICI_AXIS), out_specs=P(ICI_AXIS)
        )
    )
    try:
        x = jax.device_put(
            np.arange(n, dtype=np.float32)[:, None],
            NamedSharding(mesh, P(ICI_AXIS)),
        )
        latency_ms, out = _timed(fn, x)
        got = np.asarray(out)[:, 0]
    except Exception as e:  # noqa: BLE001
        return CheckResult("ici_ring", False, 0.0, f"ppermute failed: {e}")
    expected = np.roll(np.arange(n, dtype=np.float32), 1)
    bad = np.nonzero(got != expected)[0]
    if bad.size:
        first = int(bad[0])
        return CheckResult(
            "ici_ring",
            False,
            latency_ms,
            f"link {(first - 1) % n}->{first} delivered {got[first]}, "
            f"expected {expected[first]}",
            {"devices": float(n), "bad_links": float(bad.size)},
        )
    return CheckResult(
        "ici_ring",
        True,
        latency_ms,
        f"all {n} ring links verified",
        {"devices": float(n)},
    )


def ici_ring_attention_probe(
    devices: Optional[Sequence[jax.Device]] = None,
    seq_per_device: int = 128,
) -> CheckResult:
    """Deep ICI soak: ring attention over the full mesh.

    One psum proves the torus formed; a ring-attention pass keeps every
    directed link under sustained, overlapping load for n rounds — the
    traffic shape of real long-context training — and verifies the
    result against single-device full attention.  Optional (slower than
    the quick gate); enable for post-incident validation or periodic
    deep checks."""
    from k8s_operator_libs_tpu.workloads.ring_attention import (
        ring_attention_soak,
    )

    devs = list(devices) if devices is not None else list(jax.devices())
    if len(devs) < 2:
        return CheckResult(
            "ici_ring_attention", True, 0.0,
            "single device; no ring to soak",
            {"devices": float(len(devs))},
        )
    try:
        res = ring_attention_soak(devs, seq_per_device=seq_per_device)
    except Exception as e:  # noqa: BLE001
        return CheckResult(
            "ici_ring_attention", False, 0.0, f"ring attention failed: {e}"
        )
    return CheckResult(
        "ici_ring_attention",
        bool(res["ok"]),
        float(res["latency_ms"]),
        (
            f"seq {res['global_seq']} over {res['devices']} devices, "
            f"max err {res['max_err']:.2e}"
        ),
        {
            "devices": float(res["devices"]),
            "link_gbps": float(res["link_gbps"]),
            "global_seq": float(res["global_seq"]),
        },
    )


def run_host_probe(
    devices: Optional[Sequence[jax.Device]] = None,
    expected_devices: int = 0,
    matmul_n: int = 2048,
    hbm_mib: int = 256,
    allreduce_elems: int = 1 << 20,
    skip_ici: bool = False,
    deep: bool = False,
) -> list[CheckResult]:
    """Run the full probe battery; returns every check's result.

    Fail-fast on enumeration (nothing else can run without devices), then
    run every remaining probe even if one fails — the per-check results
    are what make a slice-health verdict attributable."""
    try:
        devs = list(devices) if devices is not None else list(jax.devices())
    except RuntimeError as e:  # no backend at all — driver not loaded
        return [
            CheckResult(
                "device_enumeration",
                False,
                0.0,
                f"device enumeration failed: {e}",
            )
        ]
    results = [device_inventory(devs, expected_devices)]
    if not devs:
        return results
    # Single-device probes must run on a device THIS process addresses:
    # under jax.distributed the global device list spans hosts, and
    # device_put onto a non-addressable device raises.
    local = [d for d in devs if d.process_index == jax.process_index()]
    probe_dev = local[0] if local else devs[0]
    results.append(matmul_probe(probe_dev, n=matmul_n))
    results.append(hbm_bandwidth_probe(probe_dev, mib=hbm_mib))
    if not skip_ici:
        results.append(
            ici_allreduce_probe(devs, per_device_elems=allreduce_elems)
        )
        results.append(ici_ring_probe(devs))
        if deep:
            results.append(ici_ring_attention_probe(devs))
    return results
