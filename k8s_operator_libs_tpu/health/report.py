"""The per-host health report and its node-annotation wire format.

The reference persists all upgrade state *into the cluster* as node
labels/annotations so the stateless reconcile survives restarts
(SURVEY.md §5 "checkpoint/resume").  The health backend follows the same
pattern: each TPU host's probe agent publishes a :class:`HealthReport` as
a JSON node annotation, and the controller-side
:class:`~k8s_operator_libs_tpu.health.slice_prober.NodeReportProber`
aggregates the per-host reports into a slice verdict.  The report carries
the driver revision it was probed under, so a stale report from before
the driver restart can never validate the new driver.
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass, field

from k8s_operator_libs_tpu.health.probes import CheckResult

# Every check `run_host_probe` can emit, in emission order
# (ici_ring_attention only with deep=True; dcn_reachability only when
# the agent is configured with DCN peers).  The fused battery
# (health.fused) emits the same names with identical pass/fail
# semantics — only the throughput side-channel metrics differ.
HEALTH_CHECKS_ALL = (
    "device_enumeration",
    "mxu_matmul",
    "hbm_bandwidth",
    "ici_allreduce",
    "ici_ring",
    "ici_ring_attention",
    "dcn_reachability",
)


def fused_battery_telemetry(checks) -> dict[str, float]:
    """Battery telemetry carried in fused-check metrics, or {} when the
    report came from the unfused path.

    Keys (health.fused): ``battery_cache_hit``, ``battery_compile_ms``,
    ``battery_execute_ms`` — the cold-vs-warm split per report, consumed
    by the status CLI and the bench."""
    for c in checks:
        if c.metrics.get("fused"):
            return {
                k: v
                for k, v in c.metrics.items()
                if k == "fused" or k.startswith("battery_")
            }
    return {}


def battery_telemetry(checks) -> dict[str, float]:
    """Battery telemetry regardless of which battery ran.

    The fused path stamps ``fused: 1.0`` and the unfused path stamps
    ``fused: 0.0`` (both carry ``battery_*`` keys), so the telemetry
    plane is blind to which battery produced a report — the presence
    of the ``fused`` key marks a battery check, its value only says
    which implementation ran."""
    for c in checks:
        if "fused" in c.metrics:
            return {
                k: v
                for k, v in c.metrics.items()
                if k == "fused" or k.startswith("battery_")
            }
    return {}


def measured_node_stats(checks) -> dict[str, float]:
    """One host's measured side-channel stats across all its checks:
    throughput figures (``tflops``/``mfu``/``gbps``/``busbw_gbps``)
    plus the battery timing keys — the per-node sample the telemetry
    plane (obs/telemetry.py) folds into fleet baselines.  Shape-only
    keys (n/iters/devices/floors) are excluded; a timing-inconclusive
    check contributes nothing."""
    out: dict[str, float] = {}
    for c in checks:
        if c.metrics.get("timing_inconclusive"):
            continue
        for k in ("tflops", "mfu", "gbps", "busbw_gbps"):
            if k in c.metrics:
                out[k] = c.metrics[k]
    out.update(
        {
            k: v
            for k, v in battery_telemetry(checks).items()
            if k.startswith("battery_") and k != "battery_cache_hit"
        }
    )
    return out


@dataclass
class HealthReport:
    """One host's probe outcome, as published to its node annotation."""

    node_name: str = ""
    # ControllerRevision hash of the driver DaemonSet the probe ran under;
    # must match the current DS hash for the report to count.
    driver_revision: str = ""
    checks: list[CheckResult] = field(default_factory=list)
    # Unix seconds when the probe finished.
    timestamp: float = 0.0
    # Devices visible to this host's agent (per-host chip count, or the
    # global count when the agent runs jax.distributed across the slice).
    visible_devices: int = 0
    # True when the agent ran jax.distributed over the whole slice, i.e.
    # `ici_allreduce` spanned every chip of the torus, not one host.
    slice_wide: bool = False

    @property
    def healthy(self) -> bool:
        return bool(self.checks) and all(c.ok for c in self.checks)

    def failed_checks(self) -> list[CheckResult]:
        return [c for c in self.checks if not c.ok]

    def age_seconds(self, now: float | None = None) -> float:
        return (now if now is not None else time.time()) - self.timestamp

    def to_json(self) -> str:
        return json.dumps(
            {
                "node": self.node_name,
                "revision": self.driver_revision,
                "ts": round(self.timestamp, 3),
                "devices": self.visible_devices,
                "slice_wide": self.slice_wide,
                "checks": [c.as_dict() for c in self.checks],
            },
            separators=(",", ":"),
        )

    @staticmethod
    def from_json(raw: str) -> "HealthReport":
        """Parse an annotation value; raises ValueError on malformed input
        (callers treat that as "no report")."""
        # The annotation is writable by anything with node-patch access;
        # wrong-typed values must read as "malformed", never crash the
        # controller's reconcile loop.
        try:
            d = json.loads(raw)
            if not isinstance(d, dict):
                raise ValueError("not an object")
            return HealthReport(
                node_name=str(d.get("node", "")),
                driver_revision=str(d.get("revision", "")),
                timestamp=float(d.get("ts", 0.0)),
                visible_devices=int(d.get("devices", 0)),
                slice_wide=bool(d.get("slice_wide", False)),
                checks=[
                    CheckResult.from_dict(c) for c in d.get("checks", [])
                ],
            )
        except (ValueError, TypeError, AttributeError, KeyError) as e:
            raise ValueError(f"malformed health report: {e}") from e
