"""Fused XLA probe battery with a topology-keyed compile cache.

The unfused battery (:mod:`k8s_operator_libs_tpu.health.probes`) runs the
device-health checks as separate jit programs — device inventory, MXU
matmul, HBM stream, ICI all-reduce, ICI ring — each paying its own
compile, dispatch, and readback.  Warm, the battery costs 6-9 s per node,
and during a roll that cost is the wall-clock hog (every validated group
waits on it serially).

This module fuses the matmul + HBM + ICI checks into ONE compiled XLA
program: a single ``shard_map`` over the slice mesh whose body runs every
correctness chain and both collectives, and whose outputs are small
per-device verification scalars.  One dispatch, one readback, and —
because the program is fully static — one compile per *topology*:

- **one dispatch** — all hosts of a group launch the same SPMD program at
  once (slice-parallel), so the per-node cost is a single XLA execution
  instead of five serialized probe programs;
- **topology-keyed compile cache** — the compiled executable is cached
  keyed by (battery version, chip generation, device count, process
  layout, problem sizes), so node N+1 of the same topology pays zero
  compile time;
- **identical verdicts** — the single output decomposes back into the
  existing per-check :class:`~.probes.CheckResult` set (same names, same
  pass/fail semantics, same threshold behavior).  The fused program
  cannot run the sustained-slope estimator (that requires many timed
  dispatches — the very thing fusion removes), so fused checks carry no
  throughput figures; downstream floor logic already treats a missing
  figure as neither-pass-nor-fail (the ``timing_inconclusive``
  convention), which keeps threshold application identical.

Each fused check's ``metrics`` carry the battery telemetry —
``fused``, ``battery_cache_hit``, ``battery_compile_ms``,
``battery_execute_ms`` — so the cold-vs-warm split is visible per
:class:`CheckResult` (and, through the agent's report annotation, per
node in the status CLI).

All verification math reuses the analytic invariants of the unfused
probes (see probes.py): chained ``C ← C @ B`` stays exactly 0.5, chained
``x ← x + 1`` from zeros equals the iteration count, ``psum`` of ramp
constants equals n(n+1)/2, and a +1 ring ``ppermute`` leaves shard i
holding i-1 (mod n) — so any deviation is a compute/link fault, not
rounding.  The program is fully static (no timing-derived control flow),
so it is SPMD-safe under multi-process ``jax.distributed`` probing.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from k8s_operator_libs_tpu.consts import get_logger
from k8s_operator_libs_tpu.health.probes import (
    CheckResult,
    ICI_AXIS,
    resolve_floors,
    shard_map,
)

logger = get_logger(__name__)

# Bump when the fused program's math or output layout changes: a cached
# executable from an older battery must never serve a newer decomposition.
BATTERY_VERSION = 1

# Static chain lengths.  The fused battery verifies CORRECTNESS (exact
# analytic invariants over a dependent chain); a handful of iterations is
# enough to exercise the MXU/HBM paths end-to-end without making the
# single dispatch itself slow.  Static — never timing-derived — so every
# process of a multi-host slice compiles and enqueues the identical
# program.
MATMUL_CHAIN_ITERS = 8
HBM_CHAIN_ITERS = 8
PSUM_ROUNDS = 4


@dataclass(frozen=True)
class BatteryKey:
    """Compile-cache key: everything that shapes the fused XLA program.

    Two nodes with the same chip generation, device count, process
    layout, and probe sizes run byte-identical programs — the second one
    must pay zero compile time."""

    version: int
    device_kind: str
    device_count: int
    # Per-process device counts, sorted — the mesh/process layout (a
    # 4-host x 4-chip slice compiles a different SPMD program than a
    # single 16-chip host).
    process_layout: tuple[int, ...]
    matmul_n: int
    hbm_mib: int
    allreduce_elems: int
    skip_ici: bool


def battery_key(
    devices: Sequence[jax.Device],
    matmul_n: int,
    hbm_mib: int,
    allreduce_elems: int,
    skip_ici: bool,
) -> BatteryKey:
    per_process: dict[int, int] = {}
    for d in devices:
        per_process[d.process_index] = per_process.get(d.process_index, 0) + 1
    kinds = sorted({d.device_kind for d in devices})
    return BatteryKey(
        version=BATTERY_VERSION,
        device_kind=",".join(kinds),
        device_count=len(devices),
        process_layout=tuple(sorted(per_process.values())),
        matmul_n=matmul_n,
        hbm_mib=hbm_mib,
        allreduce_elems=allreduce_elems,
        skip_ici=skip_ici,
    )


@dataclass
class _CompiledBattery:
    """One cached, ready-to-launch fused battery."""

    key: BatteryKey
    mesh: Mesh
    fn: object  # AOT-compiled executable or jitted fallback
    aot: bool
    compile_ms: float
    input_shardings: tuple


_LOCK = threading.Lock()
_CACHE: dict[BatteryKey, _CompiledBattery] = {}
_STATS = {
    "compile_cache_hits": 0,
    "compile_cache_misses": 0,
    "fallbacks": 0,
    "last_compile_ms": 0.0,
    "last_execute_ms": 0.0,
}


def battery_stats() -> dict:
    """Snapshot of cache/timing counters (metrics + bench consumers)."""
    with _LOCK:
        stats = dict(_STATS)
        stats["cached_programs"] = float(len(_CACHE))
        return stats


def record_fallback() -> None:
    """Count one fused→unfused fallback (called by run_host_probe)."""
    with _LOCK:
        _STATS["fallbacks"] += 1


def reset_battery_cache() -> None:
    """Drop every cached executable and zero the counters (tests)."""
    with _LOCK:
        _CACHE.clear()
        for k in _STATS:
            _STATS[k] = 0.0 if k.startswith("last_") else 0


def _build_battery_fn(key: BatteryKey, mesh: Mesh):
    """Trace the fused program for ``key`` over ``mesh``.

    Inputs (a, b, x, ramp, ring) and outputs are described below; the
    body chains every probe computation so nothing can be elided, then
    reduces each check to small per-device verification scalars."""
    n = key.matmul_n
    n_dev = key.device_count
    probe_ici = not key.skip_ici and n_dev >= 2
    perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]

    def body(a, b, x, ramp, ring):
        # MXU: chained C ← C @ B keeps every element exactly 0.5
        # (power-of-two n, f32 accumulation) — per-device max abs error
        # against the invariant is the verification scalar.
        def mm_step(_, c):
            return jnp.matmul(
                c, b, preferred_element_type=jnp.float32
            ).astype(a.dtype)

        c = jax.lax.fori_loop(0, MATMUL_CHAIN_ITERS, mm_step, a)
        mm_err = jnp.max(
            jnp.abs(c.astype(jnp.float32) - jnp.float32(0.5))
        ).reshape(1)

        # HBM: chained x ← x + 1 from zeros; after the loop every
        # element must equal the iteration count exactly.
        def hbm_step(_, v):
            return v + 1.0

        x = jax.lax.fori_loop(0, HBM_CHAIN_ITERS, hbm_step, x)
        hbm_min = jnp.min(x).reshape(1)
        hbm_max = jnp.max(x).reshape(1)

        if probe_ici:
            # ICI all-reduce: chained psum rounds, each dependent on the
            # last so none can be elided.  s ← psum(s)/n maps the ramp
            # (device i holds i+1) to n(n+1)/2 / n = (n+1)/2 after round
            # one and is a fixed point thereafter — every value along
            # the chain is exactly representable in f32, so the final
            # shard value must equal (n+1)/2 exactly on every device.
            s = ramp
            for _ in range(PSUM_ROUNDS):
                s = jax.lax.psum(s, ICI_AXIS) / jnp.float32(n_dev)
            psum_out = s[:, :1]
            # ICI ring: ppermute by +1; shard i receives shard i-1's
            # value — each directed link verified individually.
            ring_out = jax.lax.ppermute(ring, ICI_AXIS, perm)
        else:
            psum_out = ramp[:, :1]
            ring_out = ring
        return mm_err, hbm_min, hbm_max, psum_out, ring_out

    rep = NamedSharding(mesh, P())
    shard = NamedSharding(mesh, P(ICI_AXIS))
    elems = max(1, (key.hbm_mib * 1024 * 1024) // 4)
    in_shapes = (
        jax.ShapeDtypeStruct((n, n), jnp.bfloat16, sharding=rep),
        jax.ShapeDtypeStruct((n, n), jnp.bfloat16, sharding=rep),
        jax.ShapeDtypeStruct((elems,), jnp.float32, sharding=rep),
        jax.ShapeDtypeStruct(
            (n_dev, key.allreduce_elems), jnp.float32, sharding=shard
        ),
        jax.ShapeDtypeStruct((n_dev, 1), jnp.float32, sharding=shard),
    )
    fn = jax.jit(
        shard_map(
            body,
            mesh=mesh,
            in_specs=(P(), P(), P(), P(ICI_AXIS), P(ICI_AXIS)),
            out_specs=(
                P(ICI_AXIS),
                P(ICI_AXIS),
                P(ICI_AXIS),
                P(ICI_AXIS),
                P(ICI_AXIS),
            ),
        )
    )
    return fn, in_shapes, (rep, shard)


def _get_compiled(
    key: BatteryKey, devices: Sequence[jax.Device]
) -> tuple[_CompiledBattery, bool]:
    """Fetch the compiled battery for ``key`` (compile on miss).

    Returns (battery, cache_hit).  Compile time is measured around the
    AOT lower+compile; when the backend can't AOT-compile a sharded
    program the jitted callable is kept and the first execution carries
    the compile (the timing split then attributes it to the execute
    phase of the cold call — still correct for the cache-hit story,
    since warm calls skip tracing either way)."""
    with _LOCK:
        cached = _CACHE.get(key)
        if cached is not None:
            _STATS["compile_cache_hits"] += 1
            return cached, True
    # Compile outside the lock: a 30 s XLA compile must not serialize
    # unrelated topologies.  A racing duplicate compile is benign — last
    # writer wins, both executables are identical.
    mesh = Mesh(np.asarray(list(devices)), (ICI_AXIS,))
    t0 = time.perf_counter()
    fn, in_shapes, shardings = _build_battery_fn(key, mesh)
    aot = False
    try:
        fn = fn.lower(*in_shapes).compile()
        aot = True
    except Exception as e:  # noqa: BLE001 — jit fallback keeps the fusion
        logger.info(
            "AOT compile of fused battery unavailable (%s); "
            "using jit-on-first-call",
            e,
        )
    compile_ms = (time.perf_counter() - t0) * 1e3
    battery = _CompiledBattery(
        key=key,
        mesh=mesh,
        fn=fn,
        aot=aot,
        compile_ms=compile_ms,
        input_shardings=shardings,
    )
    with _LOCK:
        _CACHE[key] = battery
        _STATS["compile_cache_misses"] += 1
        _STATS["last_compile_ms"] = compile_ms
    return battery, False


def _build_inputs(key: BatteryKey, battery: _CompiledBattery):
    rep, shard = battery.input_shardings
    n, n_dev = key.matmul_n, key.device_count
    elems = max(1, (key.hbm_mib * 1024 * 1024) // 4)
    a = jax.device_put(jnp.full((n, n), 0.5, jnp.bfloat16), rep)
    b = jax.device_put(jnp.full((n, n), 1.0 / n, jnp.bfloat16), rep)
    x = jax.device_put(jnp.zeros((elems,), jnp.float32), rep)
    ramp_host = np.repeat(
        np.arange(1.0, n_dev + 1.0, dtype=np.float32)[:, None],
        key.allreduce_elems,
        axis=1,
    )
    ramp = jax.make_array_from_callback(
        ramp_host.shape, shard, lambda idx: ramp_host[idx]
    )
    ring_host = np.arange(n_dev, dtype=np.float32)[:, None]
    ring = jax.make_array_from_callback(
        ring_host.shape, shard, lambda idx: ring_host[idx]
    )
    return a, b, x, ramp, ring


def _local_shard_rows(out) -> list[tuple[int, np.ndarray]]:
    """(global row index, values) for every locally-addressable shard —
    under multi-process jax.distributed each host verifies its own
    chips' outputs; single-process sees all of them."""
    rows: list[tuple[int, np.ndarray]] = []
    for s in out.addressable_shards:
        start = s.index[0].start or 0
        vals = np.asarray(s.data)
        vals = vals.reshape(vals.shape[0], -1)  # row-major, ≥1 col
        for off in range(vals.shape[0]):
            rows.append((start + off, vals[off]))
    return rows


# Problem sizes for the network-path battery: the smallest fused program
# that still exercises every ICI link and the cross-host launch path.
# Tiny on purpose — the network-path gate runs per artifact step inside
# the drain window, so it must cost milliseconds warm; it shares the
# topology-keyed compile cache with the full battery (distinct key, so
# neither evicts the other).
NETWORK_MATMUL_N = 128
NETWORK_HBM_MIB = 1
NETWORK_ALLREDUCE_ELEMS = 8


def run_network_path_checks(
    devices: Sequence[jax.Device],
    expected_processes: Optional[int] = None,
) -> list[CheckResult]:
    """Network-path checks gating the networking artifact's edge:
    ``dcn_reachability`` + ``ici_link_state``.

    A multi-artifact stack restarts the network driver *inside* the
    node's single drain window; before the stack may advance past that
    artifact the data paths it owns must be back.  Two checks:

    - **dcn_reachability** — every expected process (host) is visible
      through the distributed runtime.  DCN is the cross-host network;
      a host that cannot be enumerated cannot be reached.  Pure
      metadata, zero compile.
    - **ici_link_state** — the fused battery's ring ``ppermute`` at
      network-probe sizes: every directed ICI link carries one value
      and the receiver verifies it exactly.  Reuses the same fused
      program (small problem sizes, own compile-cache key), so warm
      gates pay one tiny dispatch.

    Returns CheckResults in the battery's conventions; raises on
    infrastructure faults (caller treats that as gate-not-passed, never
    as gate-passed)."""
    devs = list(devices)
    results: list[CheckResult] = []

    t0 = time.perf_counter()
    visible = jax.process_count()
    want = expected_processes if expected_processes else visible
    dcn_ms = (time.perf_counter() - t0) * 1e3
    if visible >= want:
        results.append(
            CheckResult(
                "dcn_reachability",
                True,
                dcn_ms,
                f"all {want} expected process(es) visible over DCN "
                f"({visible} enumerated)",
                {"expected": float(want), "visible": float(visible)},
            )
        )
    else:
        results.append(
            CheckResult(
                "dcn_reachability",
                False,
                dcn_ms,
                f"only {visible} of {want} expected process(es) visible "
                "over DCN",
                {"expected": float(want), "visible": float(visible)},
            )
        )

    ring = [
        r
        for r in run_fused_battery(
            devs,
            matmul_n=NETWORK_MATMUL_N,
            hbm_mib=NETWORK_HBM_MIB,
            allreduce_elems=NETWORK_ALLREDUCE_ELEMS,
        )
        if r.name == "ici_ring"
    ]
    if ring:
        src = ring[0]
        results.append(
            CheckResult(
                "ici_link_state",
                src.ok,
                src.latency_ms,
                src.detail,
                dict(src.metrics),
            )
        )
    else:  # skip_ici path cannot be taken here, but stay fail-closed
        results.append(
            CheckResult(
                "ici_link_state",
                False,
                0.0,
                "fused battery returned no ring verdict",
                {},
            )
        )
    return results


def run_fused_battery(
    devices: Sequence[jax.Device],
    matmul_n: int = 4096,
    hbm_mib: int = 1024,
    allreduce_elems: int = 1 << 20,
    skip_ici: bool = False,
) -> list[CheckResult]:
    """Run the fused battery; returns the mxu_matmul / hbm_bandwidth
    (+ ici_allreduce / ici_ring) CheckResults.

    Device enumeration stays with the caller (run_host_probe) — nothing
    here can run without devices, and the inventory check must publish
    even when the battery can't compile.  Raises on any infrastructure
    fault; the caller falls back to the unfused battery."""
    devs = list(devices)
    n_dev = len(devs)
    if matmul_n & (matmul_n - 1):
        raise ValueError(
            f"fused battery needs power-of-two matmul_n, got {matmul_n}"
        )
    key = battery_key(devs, matmul_n, hbm_mib, allreduce_elems, skip_ici)
    battery, cache_hit = _get_compiled(key, devs)

    inputs = _build_inputs(key, battery)
    t0 = time.perf_counter()
    mm_err, hbm_min, hbm_max, psum_out, ring_out = battery.fn(*inputs)
    # Host readback forces execution (block_until_ready is not
    # trustworthy on every backend — see probes._sync_readback); reading
    # the verification scalars IS the sync.
    mm_rows = _local_shard_rows(mm_err)
    hbm_min_rows = _local_shard_rows(hbm_min)
    hbm_max_rows = _local_shard_rows(hbm_max)
    psum_rows = _local_shard_rows(psum_out)
    ring_rows = _local_shard_rows(ring_out)
    execute_ms = (time.perf_counter() - t0) * 1e3
    with _LOCK:
        _STATS["last_execute_ms"] = execute_ms

    battery_metrics = {
        "fused": 1.0,
        "battery_cache_hit": 1.0 if cache_hit else 0.0,
        "battery_compile_ms": 0.0 if cache_hit else battery.compile_ms,
        "battery_execute_ms": execute_ms,
    }
    # Per-generation gate metadata (fleet GenerationProfile registry):
    # the fused battery verifies correctness without sustained figures,
    # so the floors this generation WOULD be judged against ride along
    # in the metrics — observability plus downstream gating without a
    # second registry lookup.  Mixed/unknown device kinds resolve to
    # None and the checks carry no floor keys, same missing-figure
    # convention as the throughput numbers themselves.
    floors = resolve_floors(key.device_kind)
    if floors is not None:
        battery_metrics["floor_mxu_tflops"] = floors.mxu_tflops
        battery_metrics["floor_hbm_gbps"] = floors.hbm_gbps
        battery_metrics["floor_ici_busbw_gbps"] = floors.ici_busbw_gbps

    def result(
        name: str, ok: bool, detail: str, extra: Optional[dict] = None
    ) -> CheckResult:
        metrics = dict(battery_metrics)
        if extra:
            metrics.update(extra)
        return CheckResult(name, ok, execute_ms, detail, metrics)

    results: list[CheckResult] = []

    # -- mxu_matmul: every local device's chain must be exactly 0.5 ----
    bad_mm = [(row, float(v.max())) for row, v in mm_rows if np.any(v != 0.0)]
    if bad_mm:
        row, err = bad_mm[0]
        results.append(
            result(
                "mxu_matmul",
                False,
                f"matmul result mismatch on device {row}: max abs error "
                f"{err} from expected 0.5 over {MATMUL_CHAIN_ITERS} "
                f"chained matmuls (n={matmul_n})",
                {"n": float(matmul_n), "iters": float(MATMUL_CHAIN_ITERS)},
            )
        )
    else:
        results.append(
            result(
                "mxu_matmul",
                True,
                f"exact over {MATMUL_CHAIN_ITERS} chained matmuls "
                f"(n={matmul_n}) on {len(mm_rows)} device(s); fused "
                "battery (throughput unmeasured)",
                {"n": float(matmul_n), "iters": float(MATMUL_CHAIN_ITERS)},
            )
        )

    # -- hbm_bandwidth: chained value == iteration count everywhere ----
    expected = float(HBM_CHAIN_ITERS)
    bad_hbm = [
        (row, float(v[0]))
        for rows in (hbm_min_rows, hbm_max_rows)
        for row, v in rows
        if float(v[0]) != expected
    ]
    if bad_hbm:
        row, got = bad_hbm[0]
        results.append(
            result(
                "hbm_bandwidth",
                False,
                f"stream content mismatch on device {row}: expected "
                f"{expected}, got {got}",
                {"mib": float(hbm_mib), "iters": float(HBM_CHAIN_ITERS)},
            )
        )
    else:
        results.append(
            result(
                "hbm_bandwidth",
                True,
                f"content exact over {hbm_mib} MiB x {HBM_CHAIN_ITERS} "
                "passes; fused battery (bandwidth unmeasured)",
                {"mib": float(hbm_mib), "iters": float(HBM_CHAIN_ITERS)},
            )
        )

    if skip_ici:
        return results

    # -- ici_allreduce ------------------------------------------------
    if n_dev < 2:
        results.append(
            result(
                "ici_allreduce",
                True,
                "single device; no ICI to probe",
                {"devices": float(n_dev)},
            )
        )
    else:
        want = (n_dev + 1) / 2.0  # fixed point of the chained psum
        bad_psum = [
            (row, float(v[0])) for row, v in psum_rows if float(v[0]) != want
        ]
        if bad_psum:
            row, got = bad_psum[0]
            results.append(
                result(
                    "ici_allreduce",
                    False,
                    f"psum mismatch on device {row}: expected {want}, "
                    f"got {got}",
                    {"devices": float(n_dev), "iters": float(PSUM_ROUNDS)},
                )
            )
        else:
            results.append(
                result(
                    "ici_allreduce",
                    True,
                    f"psum over {n_dev} devices exact ({PSUM_ROUNDS} "
                    "rounds); fused battery (bus bandwidth unmeasured)",
                    {"devices": float(n_dev), "iters": float(PSUM_ROUNDS)},
                )
            )

    # -- ici_ring -----------------------------------------------------
    if n_dev < 2:
        results.append(
            result(
                "ici_ring",
                True,
                "single device; no links to probe",
                {"devices": float(n_dev)},
            )
        )
    else:
        bad_ring = [
            (row, float(v[0]))
            for row, v in ring_rows
            if float(v[0]) != float((row - 1) % n_dev)
        ]
        if bad_ring:
            row, got = bad_ring[0]
            results.append(
                result(
                    "ici_ring",
                    False,
                    f"link {(row - 1) % n_dev}->{row} delivered {got}, "
                    f"expected {float((row - 1) % n_dev)}",
                    {
                        "devices": float(n_dev),
                        "bad_links": float(len(bad_ring)),
                    },
                )
            )
        else:
            results.append(
                result(
                    "ici_ring",
                    True,
                    f"all {len(ring_rows)} locally-received ring link(s) "
                    f"verified ({n_dev}-device ring)",
                    {"devices": float(n_dev)},
                )
            )
    return results
