"""Safe driver load: the "hold libtpu until the slice is quiesced" handshake.

Capability parity with the reference's ``SafeDriverLoadManager``
(safe_driver_load_manager.go:28-89) and its two-step protocol
(SURVEY.md §3.5): the driver pod's init container sets a
wait-for-safe-load annotation on its node and blocks; the state manager
detects it, forces the node through the full cordon/drain pipeline, and
finally *removes the annotation* instead of restarting the pod — the init
container unblocks and the driver loads onto a quiet node.

TPU semantics: libtpu load on ANY host of a multi-host slice re-initializes
the ICI fabric for the whole slice, so the handshake is group-scoped —
a slice is "waiting for safe load" if any host is, and unblocking happens
for all waiting hosts at once, only after the entire slice is quiesced.
"""

from __future__ import annotations


from k8s_operator_libs_tpu.consts import get_logger
from k8s_operator_libs_tpu.k8s.objects import Node
from k8s_operator_libs_tpu.upgrade.node_state_provider import (
    NodeUpgradeStateProvider,
)
from k8s_operator_libs_tpu.upgrade.types import UpgradeGroup
from k8s_operator_libs_tpu.upgrade.util import UpgradeKeys

logger = get_logger(__name__)


class SafeDriverLoadManager:
    def __init__(
        self,
        node_state_provider: NodeUpgradeStateProvider,
        keys: UpgradeKeys,
    ) -> None:
        self.provider = node_state_provider
        self.keys = keys

    def is_waiting_for_safe_driver_load(self, node: Node) -> bool:
        """True if the driver pod on the node set the safe-load annotation
        (safe_driver_load_manager.go:51-53)."""
        return bool(node.annotations.get(self.keys.safe_load_annotation))

    def is_group_waiting_for_safe_driver_load(self, group: UpgradeGroup) -> bool:
        return any(
            self.is_waiting_for_safe_driver_load(n) for n in group.nodes
        )

    def unblock_loading(self, node: Node) -> None:
        """Remove the safe-load annotation so the init container proceeds
        (safe_driver_load_manager.go:57-71)."""
        if not self.is_waiting_for_safe_driver_load(node):
            return
        self.provider.change_node_upgrade_annotation(
            node, self.keys.safe_load_annotation, "null"
        )

    def unblock_group_loading(self, group: UpgradeGroup) -> None:
        """Unblock every waiting host of a quiesced slice in one batch."""
        waiting = [
            n for n in group.nodes if self.is_waiting_for_safe_driver_load(n)
        ]
        if not waiting:
            return
        logger.info(
            "unblocking safe driver load for %d host(s) in group %s",
            len(waiting),
            group.id,
        )
        self.provider.change_nodes_upgrade_annotation(
            waiting, self.keys.safe_load_annotation, "null"
        )
