"""Validation manager: post-upgrade health gate.

Capability parity with the reference's ``ValidationManager``
(validation_manager.go:35-175): after the driver restarts, hold the unit in
``validation-required`` until validation succeeds, with a start-time
annotation and a timeout that fails the upgrade
(validation_manager.go:139-175, 600 s default).

TPU redesign: validation is a pluggable **slice health prober**.  The
reference can only check that a validation pod is Ready (the actual
nvidia-smi check lives in out-of-repo consumer operators, SURVEY.md §2.3);
here the prober interface is first-class and ships with:

- :class:`PodValidationProber` — reference-parity: pods matching
  ``pod_selector`` on every host of the group are Running+Ready;
- ``health.JaxSliceProber`` (see k8s_operator_libs_tpu/health) — the real
  TPU gate: device re-enumeration + MXU matmul + ICI all-reduce across the
  slice, "validated" = 100 % slice re-formation + collective completes.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Optional, Protocol

from k8s_operator_libs_tpu.consts import get_logger
from k8s_operator_libs_tpu.k8s.interface import KubeClient
from k8s_operator_libs_tpu.k8s.objects import Pod, PodPhase
from k8s_operator_libs_tpu.upgrade.consts import UpgradeState
from k8s_operator_libs_tpu.upgrade.node_state_provider import (
    NodeUpgradeStateProvider,
)
from k8s_operator_libs_tpu.upgrade.types import UpgradeGroup
from k8s_operator_libs_tpu.upgrade.util import (
    group_clock_start,
    EVENT_TYPE_NORMAL,
    EVENT_TYPE_WARNING,
    EventRecorder,
    UpgradeKeys,
    WorkerTracker,
    log_event,
)

logger = get_logger(__name__)

# Reference validation_manager.go:31-33.
VALIDATION_TIMEOUT_SECONDS_DEFAULT = 600


@dataclass
class ProbeResult:
    healthy: bool
    detail: str = ""
    # Measured side-channel telemetry per node ({node name: {stat:
    # value}}), populated by probers that have real numbers (the report
    # aggregator, the local device battery).  Observability only: the
    # verdict above is the gate; telemetry rides along to the fleet
    # telemetry plane (obs/telemetry.py) and never affects healthy.
    telemetry: Optional[dict] = None


class SliceProber(Protocol):
    """Anything that can render a health verdict for an upgrade group."""

    def probe(self, group: UpgradeGroup) -> ProbeResult: ...


class PodValidationProber:
    """Reference-parity prober: validation pods Ready on every host
    (validation_manager.go:71-136)."""

    def __init__(self, client: KubeClient, pod_selector: str) -> None:
        self.client = client
        self.pod_selector = pod_selector

    def _is_pod_ready(self, pod: Pod) -> bool:
        return (
            pod.status.phase == PodPhase.RUNNING and pod.all_containers_ready()
        )

    def probe(self, group: UpgradeGroup) -> ProbeResult:
        if not self.pod_selector:
            return ProbeResult(True, "no pod selector; validation disabled")
        for node in group.nodes:
            pods = self.client.list_pods(
                label_selector=self.pod_selector, node_name=node.name
            )
            if not pods:
                return ProbeResult(
                    False, f"no validation pods found on node {node.name}"
                )
            for pod in pods:
                if not self._is_pod_ready(pod):
                    return ProbeResult(
                        False,
                        f"validation pod {pod.name} on {node.name} not ready",
                    )
        return ProbeResult(True, "all validation pods ready")


class ValidationManager:
    def __init__(
        self,
        client: KubeClient,
        node_state_provider: NodeUpgradeStateProvider,
        keys: UpgradeKeys,
        prober: Optional[SliceProber] = None,
        event_recorder: Optional[EventRecorder] = None,
        timeout_seconds: int = VALIDATION_TIMEOUT_SECONDS_DEFAULT,
        escalation_stats=None,
    ) -> None:
        self.client = client
        self.provider = node_state_provider
        self.keys = keys
        self.prober = prober
        self.event_recorder = event_recorder
        self.timeout_seconds = timeout_seconds
        # Shared per-rung eviction counters (rollback evictions count
        # their evict-rung entries alongside the drain/pod managers').
        self.escalation_stats = escalation_stats
        # Last rejection reason per group id, consumed by the stuck-state
        # detector so a long validation wait is attributable in events.
        self.last_rejection: dict[str, str] = {}
        # Pipelined validation (optimistic uncordon): the group's hosts
        # were readmitted before the gate passed, so a timed-out gate
        # must take them back out of service.  Wired by the state
        # manager; set per apply_state from the policy.
        self.cordon_manager = None
        self.recordon_on_timeout = False
        # Sharded-mode companion to recordon_on_timeout: the pipelined
        # gate released the group's ledger claim at optimistic uncordon
        # (its hosts were serving again); when the gate times out the
        # hosts come back OUT of service, so the manager wires this to a
        # forced ledger re-claim — keeping unavailable_used honest until
        # the next full resync re-baselines from FAILED state.
        self.on_pipeline_recordon = None
        # Rollback workers evicting the readmitted workload (joinable via
        # wait_idle, test/bench convenience).
        self._tracker = WorkerTracker()
        # Drain settings for the rollback eviction; force=True because the
        # gate rejected the hardware outright — even unmanaged pods must
        # not keep running on it.
        self.rollback_drain_timeout_s = 300.0
        self.rollback_poll_interval_s = 1.0
        # group id -> blocker reason for rollback evictions that FAILED
        # (PDB, API fault): consumed by the stuck detector (a FAILED
        # group with workload pods still on gate-rejected hardware is an
        # outstanding safety action, not a settled terminal state) and by
        # retry_pending_rollbacks, which re-attempts on later passes.
        self.pending_rollback: dict[str, str] = {}
        # Groups with a live rollback worker (never stack two).
        self._rollback_active: set[str] = set()
        self._rollback_lock = threading.Lock()
        # Retry cadence: a FAST-failing blocker (apiserver 500s, auth
        # fault) would otherwise re-spawn — and re-event per node —
        # every reconcile pass, flooding the event stream the instant a
        # watch-driven controller wakes sub-second.  Same rationale as
        # the engine's recovery_probe_backoff_s.
        self.rollback_retry_backoff_s = 30.0
        self._rollback_last_attempt: dict[str, float] = {}
        # group id -> node names whose eviction failed on the last
        # attempt: the completion Normal event fires only for nodes that
        # actually had a failure to close out (not the whole group).
        self._rollback_failed_nodes: dict[str, list[str]] = {}
        # group id -> rollback attempt count (mirrored into the
        # rollback-attempts node annotation so it survives a controller
        # crash and surfaces in the status CLI).
        self.rollback_attempts: dict[str, int] = {}
        # Crash-safety hooks wired by the upgrade manager: leadership
        # fence for the async rollback workers + durable rung store for
        # their eviction ladders.  term_fence adds the adoption-stamp
        # term check (quorum read, worker entry only).
        self.fence = None
        self.term_fence = None
        self.rung_store = None
        # Roll tracing (obs/trace.py): fanned in by the state
        # manager; feeds eviction-rung entries into the span tree.
        self.trace_recorder = None
        # Fleet telemetry capture (obs/telemetry.py): wired by the state
        # manager to TelemetryPlane.observe_validation.  Called with
        # (group, result) for EVERY probe verdict — healthy or not, sync
        # or async — exactly once per battery.  Fail-open: a raising
        # sink never affects the gate.
        self.telemetry_sink = None
        # -- async (pipelined) probing ----------------------------------
        # A prober that marks itself ``async_probe = True`` (the fused
        # device battery — real XLA work, up to seconds even warm) runs
        # on a worker thread instead of on the reconcile thread:
        # validate() schedules the probe on first call and consumes the
        # verdict on a later pass, so one slice's battery never blocks
        # the tick — group N+1's validation overlaps group N's uncordon
        # (the existing pipeline slot math already keeps maxUnavailable
        # honest for VALIDATION_REQUIRED groups).  Cheap probers
        # (annotation aggregation, pod-Ready) stay synchronous.
        self._probe_lock = threading.Lock()
        self._probe_inflight: set[str] = set()
        self._probe_verdicts: dict[str, ProbeResult] = {}
        # Monotonically-increasing epoch per group: bumped whenever the
        # group leaves validation (timeout), so a verdict from a probe
        # scheduled before the exit can never pass a LATER gate entry.
        self._probe_epoch: dict[str, int] = {}
        # Gate wall-clock per group: first validate() call -> gate pass.
        # Terminal wall times land in validation_wall_s (metrics/bench:
        # the per-slice validation wall-time the fused battery shrinks).
        self._gate_started: dict[str, float] = {}
        self.validation_wall_s: dict[str, float] = {}

    # -- durable rollback clocks --------------------------------------------

    def _persist_rollback_attempt(self, group: UpgradeGroup) -> int:
        """Increment the group's rollback-attempts annotation and stamp
        the last-attempt epoch (best-effort: a lost write degrades to a
        restarted backoff window after a crash, never fails the pass)."""
        from k8s_operator_libs_tpu.upgrade.durable import parse_int

        attempts = max(
            (
                parse_int(
                    n.annotations.get(self.keys.rollback_attempts_annotation)
                )
                for n in group.nodes
            ),
            default=0,
        )
        attempts = max(attempts, self.rollback_attempts.get(group.id, 0)) + 1
        self.rollback_attempts[group.id] = attempts
        try:
            # One coalesced metadata patch per node (attempts + last-
            # attempt epoch together) — this runs on rollback worker
            # threads, which the thread-safe write plan now coalesces
            # just like the engine pass.
            with self.provider.batched():
                self.provider.change_nodes_upgrade_annotation(
                    group.nodes,
                    self.keys.rollback_attempts_annotation,
                    str(attempts),
                )
                self.provider.change_nodes_upgrade_annotation(
                    group.nodes,
                    self.keys.rollback_last_attempt_annotation,
                    str(int(time.time())),
                )
        except Exception as e:  # noqa: BLE001 — best-effort persistence
            logger.warning(
                "failed to persist rollback clock for group %s: %s",
                group.id,
                e,
            )
        return attempts

    def adopt(self, state) -> int:
        """Re-adoption pass (leader acquisition): rebuild the pending-
        rollback ledger from the persisted record instead of from zero.

        A FAILED group whose nodes carry a rollback-attempts annotation
        had an in-flight (or blocked) rollback eviction under the old
        leader; re-enter it in ``pending_rollback`` so
        :meth:`retry_pending_rollbacks` re-drives it, with the persisted
        last-attempt epoch rebased onto this process's monotonic clock so
        the backoff window CONTINUES rather than restarting.  Returns the
        number of groups adopted."""
        from k8s_operator_libs_tpu.upgrade.durable import (
            monotonic_from_epoch,
            parse_epoch,
            parse_int,
        )

        adopted = 0
        for group in state.groups_in(UpgradeState.FAILED):
            attempts = max(
                (
                    parse_int(
                        n.annotations.get(
                            self.keys.rollback_attempts_annotation
                        )
                    )
                    for n in group.nodes
                ),
                default=0,
            )
            if attempts <= 0:
                continue
            self.rollback_attempts[group.id] = max(
                attempts, self.rollback_attempts.get(group.id, 0)
            )
            if group.id not in self.pending_rollback:
                self.pending_rollback[group.id] = (
                    f"re-adopted after leader change ({attempts} prior "
                    "rollback attempt(s)); eviction completeness unknown"
                )
                adopted += 1
            last_epoch = max(
                (
                    parse_epoch(
                        n.annotations.get(
                            self.keys.rollback_last_attempt_annotation
                        )
                    )
                    or 0
                    for n in group.nodes
                ),
                default=0,
            )
            if last_epoch > 0:
                self._rollback_last_attempt[group.id] = monotonic_from_epoch(
                    last_epoch
                )
        return adopted

    def clear_pending_rollback(self, group_id: str) -> None:
        """Stop tracking a group's pending rollback eviction: clears the
        blocker record AND the retry-backoff stamp (and the failed-node
        list).  Popping only ``pending_rollback`` — the old recovery-path
        behavior — left the backoff stamp behind, silently delaying the
        group's NEXT failure's first rollback retry by a stale window."""
        self.pending_rollback.pop(group_id, None)
        self._rollback_last_attempt.pop(group_id, None)
        self._rollback_failed_nodes.pop(group_id, None)
        self.rollback_attempts.pop(group_id, None)

    def validate(self, group: UpgradeGroup) -> bool:
        """Probe the group; on failure run the timeout clock
        (validation_manager.go:94-115 lifted to groups).  Returns True when
        validation passed and the group may advance.

        Probers with ``async_probe = True`` are dispatched to a worker
        thread (see ``_probe_inflight`` in __init__): this call then
        returns False while the probe runs and consumes the verdict on a
        later reconcile pass — the timeout clock keeps ticking against
        the same start annotation either way."""
        if self.prober is None:
            return True
        self._gate_started.setdefault(group.id, time.monotonic())
        if getattr(self.prober, "async_probe", False):
            result = self._async_probe_result(group)
            if result is None:
                # In flight (or just scheduled): the gate stays open and
                # the timeout clock keeps running — a hung battery must
                # still fail the upgrade at the deadline.
                self._handle_timeout(group)
                return False
        else:
            result = self.prober.probe(group)
        if self.telemetry_sink is not None:
            # One battery = one capture, whatever the verdict (a slow
            # node that still clears the floor is exactly the sample the
            # straggler baseline needs).  Async verdicts are consumed
            # once, so this also fires once per battery on that path.
            try:
                self.telemetry_sink(group, result)
            except Exception:  # noqa: BLE001 — observability is fail-open
                logger.debug(
                    "telemetry sink failed for group %s",
                    group.id,
                    exc_info=True,
                )
        if not result.healthy:
            logger.info("group %s validation pending: %s", group.id, result.detail)
            self.last_rejection[group.id] = result.detail
            if self.trace_recorder is not None:
                self.trace_recorder.note_gate(group, result.detail)
            self._handle_timeout(group)
            return False
        self.last_rejection.pop(group.id, None)
        started = self._gate_started.pop(group.id, None)
        if started is not None:
            self.validation_wall_s[group.id] = time.monotonic() - started
        # Passed: clear the start-time annotation.
        self.provider.change_nodes_upgrade_annotation(
            [
                n
                for n in group.nodes
                if self.keys.validation_start_time_annotation in n.annotations
            ],
            self.keys.validation_start_time_annotation,
            "null",
        )
        return True

    def _async_probe_result(self, group: UpgradeGroup) -> Optional[ProbeResult]:
        """Consume a completed async verdict, or schedule a probe worker
        and return None while one is (now) in flight.

        An unhealthy verdict is consumed ONCE (the next pass schedules a
        fresh probe) — same retry cadence as the sync path, one probe
        per rejection, but off the reconcile thread."""
        with self._probe_lock:
            if group.id in self._probe_verdicts:
                return self._probe_verdicts.pop(group.id)
            if group.id in self._probe_inflight:
                return None
            self._probe_inflight.add(group.id)
            epoch = self._probe_epoch.get(group.id, 0)

        def _probe() -> None:
            try:
                result = self.prober.probe(group)
            except Exception as e:  # noqa: BLE001 — a crashed probe rejects
                result = ProbeResult(False, f"prober raised: {e}")
            with self._probe_lock:
                self._probe_inflight.discard(group.id)
                if self._probe_epoch.get(group.id, 0) == epoch:
                    self._probe_verdicts[group.id] = result
                # else: the group left validation (timeout) while this
                # probe ran — its verdict must not leak into a later
                # gate entry for the same group.

        try:
            self._tracker.spawn(_probe, name=f"validation-probe-{group.id}")
        except Exception as e:  # noqa: BLE001 — retry next pass
            # A failed spawn must not strand the in-flight claim (the
            # same leak shape as the rollback-spawn fix below); unlike
            # the rollback path this one swallows the error — validate()
            # runs on the reconcile thread and simply retries next pass.
            with self._probe_lock:
                self._probe_inflight.discard(group.id)
            logger.warning(
                "failed to spawn validation probe for group %s: %s",
                group.id,
                e,
            )
        return None

    def _discard_probe_state(self, group_id: str) -> None:
        """The group left validation: invalidate any in-flight probe
        (epoch bump) and drop an unconsumed verdict + the gate clock."""
        with self._probe_lock:
            self._probe_epoch[group_id] = (
                self._probe_epoch.get(group_id, 0) + 1
            )
            self._probe_verdicts.pop(group_id, None)
        self._gate_started.pop(group_id, None)

    def _handle_timeout(self, group: UpgradeGroup) -> None:
        key = self.keys.validation_start_time_annotation
        now = int(time.time())
        start = group_clock_start(self.provider, group, key, now)
        if start is None:
            return  # freshly stamped; clock evaluated next pass
        if self.timeout_seconds and now > start + self.timeout_seconds:
            logger.info("group %s validation timed out -> failed", group.id)
            # The group leaves validation: a stale rejection must not be
            # attributed to a future stall in a different phase, and a
            # still-running async probe's verdict must not pass a future
            # re-entry of the gate.
            self.last_rejection.pop(group.id, None)
            self._discard_probe_state(group.id)
            if self.recordon_on_timeout and self.cordon_manager is not None:
                # Optimistic-uncordon rollback: the workload was
                # readmitted before the gate; an unvalidated slice must
                # not keep serving it.  Cordon alone only blocks NEW
                # scheduling — the readmitted pods would keep running on
                # hardware the gate rejected — so also evict them (async:
                # eviction honors PDBs and can block; FAILED groups have
                # no drain processor to pick this up later).
                self.cordon_manager.cordon_nodes(group.nodes)
                self._schedule_rollback_eviction(group)
                if self.on_pipeline_recordon is not None:
                    self.on_pipeline_recordon(group)
            for node in group.nodes:
                log_event(
                    self.event_recorder,
                    node.name,
                    EVENT_TYPE_WARNING,
                    self.keys.event_reason,
                    "Validation timed out for the driver upgrade",
                )
            self.provider.change_nodes_upgrade_state(
                group.nodes, UpgradeState.FAILED
            )
            self.provider.change_nodes_upgrade_annotation(group.nodes, key, "null")

    def _schedule_rollback_eviction(self, group: UpgradeGroup) -> None:
        """Evict the workload pods readmitted by the optimistic uncordon.

        A failure (PDB-blocked eviction, API fault) is NOT log-and-
        forget: workload pods still running on hardware the gate
        rejected is an outstanding safety action.  Each failure
        publishes a Warning event per affected node, records the blocker
        in ``pending_rollback`` (surfaced through the stuck detector's
        ``slice_stuck_seconds`` + events), and the engine re-attempts on
        later passes via :meth:`retry_pending_rollbacks` — the drain is
        idempotent, so eviction completes once the blocker clears."""
        from k8s_operator_libs_tpu.k8s.drain import DrainHelper, FencedError

        if self.fence is not None and not self.fence():
            return  # deposed leader: the new leader re-adopts this work
        if self.term_fence is not None and not self.term_fence(group.nodes):
            return  # a higher term already adopted these nodes
        with self._rollback_lock:
            if group.id in self._rollback_active:
                return  # a worker is already evicting this group
            self._rollback_active.add(group.id)

        self._persist_rollback_attempt(group)
        helper = DrainHelper(
            self.client,
            force=True,
            ignore_all_daemon_sets=True,
            delete_empty_dir_data=True,
            timeout_s=self.rollback_drain_timeout_s,
            poll_interval_s=self.rollback_poll_interval_s,
            escalation_stats=self.escalation_stats,
            fence=self.fence,
            rung_store=self.rung_store,
            trace_hook=(
                self.trace_recorder.rung_entered
                if self.trace_recorder is not None
                else None
            ),
        )
        node_names = [n.name for n in group.nodes]
        had_failed_before = group.id in self.pending_rollback

        def _rollback() -> None:
            failures: list[tuple[str, Exception]] = []
            try:
                for name in node_names:
                    try:
                        helper.run_node_drain(name)
                    except FencedError:
                        # Leadership moved mid-rollback: stop acting.  The
                        # persisted rollback-attempts annotation lets the
                        # new leader re-adopt the unfinished eviction.
                        return
                    except Exception as e:  # noqa: BLE001 — retried later
                        failures.append((name, e))
                        logger.error(
                            "rollback eviction of node %s (group %s) "
                            "failed: %s — workload pods may still be "
                            "running on unvalidated hardware; will retry "
                            "while the group stays failed",
                            name,
                            group.id,
                            e,
                        )
                        log_event(
                            self.event_recorder,
                            name,
                            EVENT_TYPE_WARNING,
                            self.keys.event_reason,
                            "Rollback eviction after validation timeout "
                            f"failed: {e} — workload pods may still be "
                            "running on unvalidated hardware (will retry)",
                        )
                if failures:
                    self.pending_rollback[group.id] = (
                        "rollback eviction incomplete on "
                        f"{len(failures)}/{len(node_names)} node(s) "
                        f"({', '.join(n for n, _ in failures)}): "
                        f"{failures[0][1]}"
                    )
                    self._rollback_failed_nodes[group.id] = [
                        n for n, _ in failures
                    ]
                elif self.pending_rollback.pop(group.id, None) is not None:
                    # A previously-blocked eviction finally completed:
                    # close the loop for the operator watching events —
                    # on the nodes that actually had a failure to close
                    # out, not the whole group (nodes that drained clean
                    # on the first attempt never warned, so a completion
                    # Normal there would be noise with no Warning pair).
                    healed = self._rollback_failed_nodes.pop(
                        group.id, None
                    )
                    self._rollback_last_attempt.pop(group.id, None)
                    for name in healed if healed is not None else node_names:
                        log_event(
                            self.event_recorder,
                            name,
                            EVENT_TYPE_NORMAL,
                            self.keys.event_reason,
                            "Rollback eviction completed after earlier "
                            "failures; no workload pods remain on the "
                            "unvalidated hardware",
                        )
                if not failures:
                    # Eviction is complete: retire the durable rollback
                    # clocks so a later leader does not re-adopt finished
                    # work (best-effort; re-adopting a finished eviction
                    # is idempotent anyway).
                    try:
                        # Both clock deletes coalesce into one metadata
                        # patch per node via the write plan (this runs on
                        # a rollback worker thread).
                        with self.provider.batched():
                            for key in (
                                self.keys.rollback_attempts_annotation,
                                self.keys.rollback_last_attempt_annotation,
                            ):
                                self.provider.change_nodes_upgrade_annotation(
                                    [
                                        n
                                        for n in group.nodes
                                        if key in n.annotations
                                    ],
                                    key,
                                    "null",
                                )
                    except Exception as e:  # noqa: BLE001
                        logger.warning(
                            "failed to clear rollback clocks for group "
                            "%s: %s",
                            group.id,
                            e,
                        )
            finally:
                with self._rollback_lock:
                    self._rollback_active.discard(group.id)

        if had_failed_before:
            logger.info(
                "re-attempting blocked rollback eviction for group %s",
                group.id,
            )
        try:
            self._tracker.spawn(
                _rollback, name=f"validation-rollback-{group.id}"
            )
        except Exception:
            # A failed spawn (thread limit, interpreter shutdown) must
            # not strand the active claim: that would silently skip
            # every future retry for this group while workload pods sit
            # on gate-rejected hardware.
            with self._rollback_lock:
                self._rollback_active.discard(group.id)
            raise

    def retry_pending_rollbacks(self, state) -> None:
        """Re-attempt rollback evictions that previously failed, for
        groups still in FAILED (the engine calls this every pass).
        Groups that left FAILED (recovered after the gate passed, or
        relabeled by an operator) stop being tracked — recovery means
        the hardware was re-validated, so the eviction is moot."""
        if not self.pending_rollback:
            return
        failed = {g.id: g for g in state.groups_in(UpgradeState.FAILED)}
        now = time.monotonic()
        for gid in list(self.pending_rollback):
            group = failed.get(gid)
            if group is None:
                self.clear_pending_rollback(gid)
                continue
            last = self._rollback_last_attempt.get(gid)
            if (
                last is not None
                and now - last < self.rollback_retry_backoff_s
            ):
                continue
            self._rollback_last_attempt[gid] = now
            self._schedule_rollback_eviction(group)

    def wait_idle(self, timeout_s: float = 30.0) -> bool:
        """Join outstanding workers (rollback evictions + async probes)."""
        return self._tracker.wait_idle(timeout_s)
