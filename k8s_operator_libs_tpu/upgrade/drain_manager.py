"""Async drain manager.

Capability parity with the reference's ``DrainManager``
(drain_manager.go:48-155): asynchronous drain workers deduplicated across
reconcile passes by a :class:`StringSet`, cordon-then-drain, success moves
the unit to ``pod-restart-required`` and failure to ``upgrade-failed`` —
the "async actor + label mailbox" idiom (SURVEY.md §3.4).

TPU redesign: the schedulable unit is an :class:`UpgradeGroup` (one ICI
slice).  All hosts of a slice drain **concurrently inside one worker**, and
the state transition happens once, at the group barrier — all-or-nothing:
if any host fails to drain, the whole slice goes to ``upgrade-failed``
(the torus would be split either way; a half-drained slice is not a
usable TPU).  ``IgnoreAllDaemonSets`` stays true because the libtpu
driver/device-plugin itself runs as a DaemonSet (reference
drain_manager.go:80-81 has the same rationale for OFED pods).
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Optional, Sequence

from k8s_operator_libs_tpu.api.v1alpha1 import DrainSpec
from k8s_operator_libs_tpu.consts import get_logger
from k8s_operator_libs_tpu.k8s.interface import KubeClient
from k8s_operator_libs_tpu.k8s.drain import (
    DrainError,
    DrainHelper,
    EscalationStats,
    FencedError,
    escalation_from_spec,
)
from k8s_operator_libs_tpu.k8s.objects import Node
from k8s_operator_libs_tpu.upgrade.consts import UpgradeState
from k8s_operator_libs_tpu.upgrade.node_state_provider import (
    NodeUpgradeStateProvider,
)
from k8s_operator_libs_tpu.upgrade.types import NodeUpgradeState, UpgradeGroup
from k8s_operator_libs_tpu.upgrade.util import (
    EVENT_TYPE_NORMAL,
    EVENT_TYPE_WARNING,
    EventRecorder,
    StringSet,
    UpgradeKeys,
    WorkerTracker,
    log_event,
)

logger = get_logger(__name__)


@dataclass
class DrainConfiguration:
    """Drain spec + the groups to drain (reference DrainConfiguration,
    drain_manager.go:32-36, lifted to groups)."""

    spec: Optional[DrainSpec]
    groups: list[UpgradeGroup] = field(default_factory=list)


class DrainManager:
    def __init__(
        self,
        client: KubeClient,
        node_state_provider: NodeUpgradeStateProvider,
        keys: UpgradeKeys,
        event_recorder: Optional[EventRecorder] = None,
        max_hosts_concurrency: int = 32,
        poll_interval_s: float = 1.0,
        escalation_stats: Optional[EscalationStats] = None,
    ) -> None:
        self.client = client
        self.provider = node_state_provider
        self.keys = keys
        self.event_recorder = event_recorder
        self.max_hosts_concurrency = max_hosts_concurrency
        # Per-rung eviction-escalation counters, usually shared with the
        # other DrainHelper owners by the upgrade manager so one metrics
        # read covers every drain path.
        self.escalation_stats = escalation_stats
        # Apiserver-facing poll cadence for eviction/deletion waits; the
        # production default (1 s, kubectl-like) is deliberately NOT the
        # test default of the cache-sync polls — see ADVICE round 1.
        self.poll_interval_s = poll_interval_s
        # Crash-safety hooks wired by the upgrade manager: a leadership
        # fence every async worker consults before mutating, and the
        # annotation-backed store that persists each node's eviction-
        # ladder rung so a fresh leader resumes mid-escalation.
        # term_fence adds the adoption-stamp term check (quorum read,
        # worker entry + group barrier only).
        self.fence = None
        self.term_fence = None
        self.rung_store = None
        # Roll tracing (obs/trace.py): fanned in by the state
        # manager; feeds eviction-rung entries into the span tree.
        self.trace_recorder = None
        # Dedup of in-flight drains across reconcile passes
        # (drain_manager.go:103: drainingNodes StringSet), keyed by group id.
        self._draining = StringSet()
        self._tracker = WorkerTracker()
        # Last drain error per group id (policy or transient), consumed by
        # the stuck-state detector for attributable stall events.
        self.last_error: dict[str, str] = {}

    def schedule_groups_drain(self, config: DrainConfiguration) -> None:
        """Schedule async drain for each group not already draining."""
        if not config.groups:
            logger.info("Drain Manager: no groups scheduled to drain")
            return
        if config.spec is None:
            raise ValueError("drain spec should not be empty")
        if not config.spec.enable:
            logger.info("Drain Manager: drain is disabled")
            return

        for group in config.groups:
            if self._draining.has(group.id):
                logger.info("group %s already draining, skipping", group.id)
                continue
            self._draining.add(group.id)
            for node in group.nodes:
                log_event(
                    self.event_recorder,
                    node.name,
                    EVENT_TYPE_NORMAL,
                    self.keys.event_reason,
                    "Scheduling drain of the node",
                )
            self._tracker.spawn(
                lambda g=group, s=config.spec: self._drain_group(g, s),
                name=f"drain-{group.id}",
            )

    # Reference-parity shim: drain a list of nodes as singleton groups.
    def schedule_nodes_drain(
        self, spec: Optional[DrainSpec], nodes: Sequence[Node]
    ) -> None:
        groups = [
            UpgradeGroup(id=n.name, members=[NodeUpgradeState(node=n)])
            for n in nodes
        ]
        self.schedule_groups_drain(DrainConfiguration(spec=spec, groups=groups))

    def wait_idle(self, timeout_s: float = 30.0) -> bool:
        """Join outstanding drain workers (test/bench convenience; the
        reference relies on Eventually-style polling instead)."""
        return self._tracker.wait_idle(timeout_s)

    # -- worker -------------------------------------------------------------

    def _drain_group(self, group: UpgradeGroup, spec: DrainSpec) -> None:
        """Drain worker with failure CLASSIFICATION.

        The reference marks any drain error ``upgrade-failed``
        (drain_manager.go:111-127) and leaves recovery to a manual
        runbook.  Under a 2-minute downtime budget that is wrong for
        *transient* apiserver errors: only a policy-level
        :class:`DrainError` (undrainable pod per filters, PDB/timeout
        exhausted) fails the slice; any other exception leaves the group
        in ``drain-required`` so the next idempotent pass simply retries
        the drain."""
        try:
            if self.fence is not None and not self.fence():
                return  # deposed leader: abandon without acting
            if self.term_fence is not None and not self.term_fence(
                group.nodes
            ):
                return  # a higher term already adopted these nodes
            helper = DrainHelper(
                self.client,
                force=spec.force,
                ignore_all_daemon_sets=True,
                delete_empty_dir_data=spec.delete_empty_dir,
                timeout_s=float(spec.timeout_second),
                pod_selector=spec.pod_selector,
                poll_interval_s=self.poll_interval_s,
                escalation=escalation_from_spec(
                    getattr(spec, "eviction_escalation", None)
                ),
                escalation_stats=self.escalation_stats,
                fence=self.fence,
                rung_store=self.rung_store,
                trace_hook=(
                    self.trace_recorder.rung_entered
                    if self.trace_recorder is not None
                    else None
                ),
            )
            policy_failed: list[str] = []
            transient: list[str] = []
            # Phase 1: cordon every host first (no half-schedulable slice),
            # then drain hosts concurrently.
            for node in group.nodes:
                try:
                    helper.run_cordon_or_uncordon(node, True)
                except Exception as e:  # noqa: BLE001 — API error: retry
                    logger.error("failed to cordon %s: %s", node.name, e)
                    transient.append(node.name)
            # (Cordon errors are always transient — policy failures can
            # only arise in the drain phase below.)
            if not transient:
                with ThreadPoolExecutor(
                    max_workers=min(self.max_hosts_concurrency, group.size())
                ) as pool:
                    futures = {
                        pool.submit(helper.run_node_drain, node.name): node
                        for node in group.nodes
                    }
                    for fut, node in futures.items():
                        try:
                            fut.result()
                        except FencedError:
                            # Leadership moved mid-drain: abandon quietly.
                            # The new leader re-adopts from the persisted
                            # rungs; any transition here would race it.
                            return
                        except DrainError as e:
                            logger.error(
                                "failed to drain %s: %s", node.name, e
                            )
                            log_event(
                                self.event_recorder,
                                node.name,
                                EVENT_TYPE_WARNING,
                                self.keys.event_reason,
                                f"Failed to drain the node, {e}",
                            )
                            policy_failed.append(node.name)
                        except Exception as e:  # noqa: BLE001 — transient
                            logger.warning(
                                "transient error draining %s (will retry): "
                                "%s",
                                node.name,
                                e,
                            )
                            transient.append(node.name)

            # Group barrier: all-or-nothing transition — fenced, so a
            # deposed leader's worker cannot flip the slice after handoff.
            # The term fence re-checks here too: a successor elected
            # mid-drain has stamped its term by the time we transition.
            if self.fence is not None and not self.fence():
                return
            if self.term_fence is not None and not self.term_fence(
                group.nodes
            ):
                return
            if policy_failed:
                self.last_error[group.id] = (
                    f"drain policy failure on host(s) {policy_failed}"
                )
                self._set_group_state(group, UpgradeState.FAILED)
            elif transient:
                # No transition: the group stays drain-required and the
                # next reconcile pass re-schedules the (idempotent) drain.
                self.last_error[group.id] = (
                    f"transient drain errors on host(s) {transient}; retrying"
                )
                logger.info(
                    "group %s drain will be retried next pass "
                    "(transient errors on %s)",
                    group.id,
                    transient,
                )
            else:
                self.last_error.pop(group.id, None)
                for node in group.nodes:
                    log_event(
                        self.event_recorder,
                        node.name,
                        EVENT_TYPE_NORMAL,
                        self.keys.event_reason,
                        "Successfully drained the node",
                    )
                self._set_group_state(group, UpgradeState.POD_RESTART_REQUIRED)
        finally:
            self._draining.remove(group.id)

    def _set_group_state(self, group: UpgradeGroup, state: UpgradeState) -> None:
        try:
            self.provider.change_nodes_upgrade_state(group.nodes, state)
        except Exception as e:  # noqa: BLE001 — async actor: next pass re-drives
            logger.error("failed to set group %s state %s: %s", group.id, state, e)
