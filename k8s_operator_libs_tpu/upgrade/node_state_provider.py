"""Synchronized node mutation with read-your-writes guarantees.

Capability parity with the reference's ``NodeUpgradeStateProvider``
(node_upgrade_state_provider.go:33-216): per-node keyed mutex, label patch
for the upgrade state, merge-patch for annotations with the ``"null"``
delete convention, then **poll the (possibly stale) read cache until the
write is visible** — the trick that makes the stateless reconcile loop safe
when the controller cache lags the apiserver
(node_upgrade_state_provider.go:92-99).

TPU redesign on top of parity: **batched group transitions** riding the
transactional write plane (``k8s/writeplan.py``).  The reference pays
(patch + up-to-10s poll) serially per node; on a 16-host v5p-64 slice
that alone eats the <2 min downtime budget (SURVEY.md §7 'hard parts').
``change_nodes_upgrade_state`` issues all patches concurrently and then
polls all nodes concurrently, so a whole slice's label flip costs one
round-trip + one cache-sync wait, not N.

Every write is an *intent* staged into the shared, thread-safe
:class:`~k8s_operator_libs_tpu.k8s.writeplan.WritePlan` (which replaced
the old thread-local ``_WriteBatch``): the engine pass coalesces inside
``batched()`` scopes while drain/probe/validation worker threads flush
standalone intents through the same dedupe / fence / flow-control /
409-replay path, so their durable-clock patches coalesce too.  Writes
whose value already matches the cached object are suppressed at stage
time and counted in ``writes_suppressed_total``.
"""

from __future__ import annotations

import contextlib
import time
from typing import Optional, Sequence

from k8s_operator_libs_tpu.consts import get_logger
from k8s_operator_libs_tpu.k8s.client import NotFoundError
from k8s_operator_libs_tpu.k8s.interface import KubeClient
from k8s_operator_libs_tpu.k8s.objects import Node
from k8s_operator_libs_tpu.k8s.writeplan import NodeIntent, WritePlan
from k8s_operator_libs_tpu.upgrade.consts import NULL_STRING, UpgradeState
from k8s_operator_libs_tpu.upgrade.util import (
    EVENT_TYPE_NORMAL,
    EVENT_TYPE_WARNING,
    EventRecorder,
    KeyedMutex,
    UpgradeKeys,
    log_event,
    run_batch,
)

logger = get_logger(__name__)


class CacheSyncTimeout(RuntimeError):
    """The written value never became visible in the read cache."""


def node_ready(node: Node) -> bool:
    """Single source of truth for node readiness.

    A Ready condition with status ``Unknown`` (node-lifecycle controller
    lost contact with the kubelet) counts as NOT ready — same as
    ``False`` — because a slice cannot roll on a host whose state is
    unknowable.  Absent Ready condition counts as ready (matches
    reference upgrade_state.go:986-993 via Node.is_ready)."""
    return node.is_ready()


class NodeUpgradeStateProvider:
    """Synchronized node label/annotation writes with cache-sync waits."""

    def __init__(
        self,
        client: KubeClient,
        keys: UpgradeKeys,
        event_recorder: Optional[EventRecorder] = None,
        poll_interval_s: float = 1.0,
        poll_timeout_s: float = 10.0,
        max_concurrency: int = 32,
        max_staleness_s: float = 30.0,
        plan: Optional[WritePlan] = None,
    ) -> None:
        # Reference defaults: 1 s poll, 10 s timeout
        # (node_upgrade_state_provider.go:100-103).
        self.client = client
        self.keys = keys
        self.event_recorder = event_recorder
        self.poll_interval_s = poll_interval_s
        self.poll_timeout_s = poll_timeout_s
        self.max_concurrency = max_concurrency
        # Staleness guard for decision-feeding reads: build_state and
        # the managers act on what get_node returns (cordon, drain,
        # state transitions), so a cache older than this bound is
        # upgraded to a quorum read by the client.  The write-then-poll
        # waits below intentionally do NOT pass it — they are
        # convergence polls and the whole point is to read the cache.
        self.max_staleness_s = max_staleness_s
        self._node_mutex = KeyedMutex()
        # All writes route through the shared write plane: coalescing,
        # no-op suppression, flow control, fence-at-flush, 409 replay.
        self.plan = plan or WritePlan(
            client, max_concurrency=max_concurrency
        )
        # Transition observers (phase clocks, trace recorder, ...):
        # each is called once per GROUP transition with
        # (nodes, new_state) BEFORE the new labels are staged —
        # change_nodes_upgrade_state is the one choke point every
        # group-level transition goes through.  Read-only; observers
        # are exception-isolated from each other and a failing observer
        # must never block a transition.
        self._transition_observers: list = []
        # Durable annotation sources (obs/trace.py anchor, obs/
        # telemetry.py history ring): each returns an annotation patch
        # merged into the SAME intent as the state label — crash
        # durability that costs zero extra API writes.  Multicast like
        # the transition observers above; sources are exception-isolated
        # from each other.
        self._transition_annotation_sources: list = []

    # -- transition observers ------------------------------------------------

    @property
    def transition_observer(self):
        """Back-compat single-slot view: the first registered observer
        (None when the list is empty).  Assigning REPLACES the whole
        list — multi-observer users must go through
        :meth:`add_transition_observer`."""
        return (
            self._transition_observers[0]
            if self._transition_observers
            else None
        )

    @transition_observer.setter
    def transition_observer(self, fn) -> None:
        self._transition_observers = [] if fn is None else [fn]

    def add_transition_observer(self, fn) -> None:
        """Register an additional group-transition observer."""
        if fn is not None and fn not in self._transition_observers:
            self._transition_observers.append(fn)

    def remove_transition_observer(self, fn) -> None:
        try:
            self._transition_observers.remove(fn)
        except ValueError:
            pass

    def _fire_transition_observers(self, nodes, new_state) -> None:
        """Multicast with per-observer exception isolation: one raising
        observer never starves the others, and none can block the
        transition itself."""
        for observer in list(self._transition_observers):
            try:
                observer(nodes, new_state)
            except Exception:
                logger.exception("transition observer failed; continuing")

    @property
    def transition_annotation_source(self):
        """Back-compat single-slot view (same contract as
        ``transition_observer``): the first registered source, or None.
        Assigning replaces the whole list."""
        return (
            self._transition_annotation_sources[0]
            if self._transition_annotation_sources
            else None
        )

    @transition_annotation_source.setter
    def transition_annotation_source(self, fn) -> None:
        self._transition_annotation_sources = [] if fn is None else [fn]

    def add_transition_annotation_source(self, fn) -> None:
        """Register an additional durable-annotation source."""
        if fn is not None and fn not in self._transition_annotation_sources:
            self._transition_annotation_sources.append(fn)

    def remove_transition_annotation_source(self, fn) -> None:
        try:
            self._transition_annotation_sources.remove(fn)
        except ValueError:
            pass

    def _trace_annotations(self, node, new_state) -> dict:
        """Durable annotation patches riding the state-label intent
        (fail-open: observability must never block or dirty a
        transition).  Multicast: each source contributes its keys; a
        raising source is isolated and contributes nothing."""
        if not self._transition_annotation_sources:
            return {}
        extra: dict = {}
        for source in list(self._transition_annotation_sources):
            try:
                patch = source(node, new_state)
            except Exception:
                logger.exception("transition annotation source failed")
                continue
            if patch:
                extra.update(patch)
        if not extra:
            return {}
        # Suppress no-op writes against the cached object so an
        # idempotent re-drive stays write-free.
        out = {}
        for key, value in extra.items():
            current = node.metadata.annotations.get(key)
            if value is None and key not in node.metadata.annotations:
                continue
            if value is not None and current == value:
                continue
            out[key] = value
        return out

    # -- write coalescing ----------------------------------------------------

    @contextlib.contextmanager
    def batched(self):
        """Coalesce node writes into one patch per node via the write
        plan.

        Inside the context, ``change_node(s)_upgrade_state`` /
        ``change_node(s)_upgrade_annotation`` apply their mutation to the
        caller's Node objects immediately (read-your-writes within the
        pass) and stage the API write as a plan intent; on exit every
        node gets a single combined labels+annotations patch
        (``patch_node_metadata``) and one cache-sync wait.  A transition
        that today costs a label patch plus N annotation round trips per
        node collapses to one.

        Nested use joins the outer scope.  Scopes are per-thread over
        the shared plan, so concurrently-running workers stage into the
        same plan without cross-flushing each other's scopes.  If the
        body raises, this scope's staged intents are discarded (the old
        batch-drop semantics) — the next idempotent pass re-drives them.
        """
        scope = self.plan.begin_scope()
        if scope is None:
            yield self
            return
        ok = False
        try:
            yield self
            ok = True
        finally:
            names = self.plan.end_scope(scope)
            if not ok:
                self.plan.discard(names)
        self._flush_names(names)

    def _flush_names(self, names: list[str]) -> None:
        if not names:
            return

        def _post(intent: NodeIntent, fresh: Node) -> None:
            node = intent.node
            if node is None:
                return
            with self._node_mutex.lock(intent.name):
                self._wait_metadata_visible(
                    node, intent.labels, intent.annotations
                )

        def _on_error(intent: NodeIntent, exc: Exception) -> None:
            log_event(
                self.event_recorder,
                intent.name,
                EVENT_TYPE_WARNING,
                self.keys.event_reason,
                "Failed to apply coalesced node metadata patch",
            )

        self.plan.flush_nodes(names, post=_post, on_error=_on_error)

    def _wait_metadata_visible(
        self,
        node: Node,
        labels: dict[str, Optional[str]],
        annotations: dict[str, Optional[str]],
    ) -> None:
        """Poll the read cache until every batched key shows its patched
        value (None = absent) — the same write-then-poll contract as the
        single-key waits, amortized over the whole patch."""
        deadline = time.monotonic() + self.poll_timeout_s
        while True:
            try:
                fresh = self.client.get_node(node.name, cached=True)
            except NotFoundError:
                fresh = None
            if fresh is not None:
                ok = all(
                    fresh.labels.get(k) == v
                    if v is not None
                    else k not in fresh.labels
                    for k, v in labels.items()
                ) and all(
                    fresh.annotations.get(k) == v
                    if v is not None
                    else k not in fresh.annotations
                    for k, v in annotations.items()
                )
                if ok:
                    node.metadata = fresh.metadata
                    node.spec = fresh.spec
                    node.status = fresh.status
                    return
            if time.monotonic() >= deadline:
                raise CacheSyncTimeout(
                    f"node {node.name}: coalesced patch "
                    f"({len(labels)} labels, {len(annotations)} "
                    f"annotations) not visible within {self.poll_timeout_s}s"
                )
            time.sleep(
                min(self.poll_interval_s, max(0.0, deadline - time.monotonic()))
            )

    # -- reads -------------------------------------------------------------

    def get_node(self, node_name: str) -> Node:
        with self._node_mutex.lock(node_name):
            return self.client.get_node(
                node_name, cached=True, max_staleness_s=self.max_staleness_s
            )

    # -- single-node writes (reference parity) ------------------------------

    def change_node_upgrade_state(self, node: Node, new_state: UpgradeState) -> None:
        """Patch the state label and wait until the cache shows it."""
        # UNKNOWN means "label absent": a strategic-merge delete.
        value = new_state.value if new_state != UpgradeState.UNKNOWN else None
        key = self.keys.state_label
        current = node.metadata.labels.get(key)
        if (value is None and key not in node.metadata.labels) or (
            value is not None and current == value
        ):
            # No-op against the cached object: suppress the round trip.
            self.plan.note_suppressed()
            return
        trace_annotations = self._trace_annotations(node, new_state)
        if self.plan.in_scope():
            # Scoped: stage the intent and apply to the caller's object
            # immediately (read-your-writes within the pass); the API
            # write lands at scope exit.
            self.plan.stage(
                node.name,
                labels={key: value},
                annotations=trace_annotations or None,
                node=node,
            )
            if value is None:
                node.metadata.labels.pop(key, None)
            else:
                node.metadata.labels[key] = value
            for akey, avalue in trace_annotations.items():
                if avalue is None:
                    node.metadata.annotations.pop(akey, None)
                else:
                    node.metadata.annotations[akey] = avalue
            return
        intent = self.plan.stage(
            node.name,
            labels={key: value},
            annotations=trace_annotations or None,
            node=node,
        )
        with self._node_mutex.lock(node.name):
            try:
                flushed = self.plan.flush_intent(intent)
            except Exception:
                log_event(
                    self.event_recorder,
                    node.name,
                    EVENT_TYPE_WARNING,
                    self.keys.event_reason,
                    f"Failed to update node state label to {new_state.value}",
                )
                raise
            if flushed is None:
                return  # suppressed against the snapshot, or fenced
            self._wait_label_visible(node, key, new_state.value)

    def change_node_upgrade_annotation(
        self, node: Node, key: str, value: str
    ) -> None:
        """Patch an annotation; ``value == "null"`` deletes it
        (node_upgrade_state_provider.go:147-150)."""
        patch_value = None if value == NULL_STRING else value
        current = node.metadata.annotations.get(key)
        if (
            patch_value is None and key not in node.metadata.annotations
        ) or (patch_value is not None and current == patch_value):
            self.plan.note_suppressed()
            return
        if self.plan.in_scope():
            self.plan.stage(
                node.name, annotations={key: patch_value}, node=node
            )
            if patch_value is None:
                node.metadata.annotations.pop(key, None)
            else:
                node.metadata.annotations[key] = patch_value
            return
        intent = self.plan.stage(
            node.name, annotations={key: patch_value}, node=node
        )
        with self._node_mutex.lock(node.name):
            try:
                flushed = self.plan.flush_intent(intent)
            except Exception:
                log_event(
                    self.event_recorder,
                    node.name,
                    EVENT_TYPE_WARNING,
                    self.keys.event_reason,
                    f"Failed to update node annotation {key}={value}",
                )
                raise
            if flushed is None:
                return
            self._wait_annotation_visible(node, key, value)

    # -- batched group writes (TPU-native fast path) -------------------------

    def change_nodes_upgrade_state(
        self, nodes: Sequence[Node], new_state: UpgradeState
    ) -> None:
        """Atomically-intended batch: flip the state label on every node of
        a slice, concurrently, then wait for all writes to be visible.

        Raises on the first failure after all attempts complete, so a
        partially-written slice is re-driven by the next idempotent pass
        (the group's effective_state resolves to the earliest member)."""
        if nodes:
            self._fire_transition_observers(nodes, new_state)
        if self.plan.in_scope():
            # Inside a coalescing scope: fanning out to worker threads
            # would leave this thread's scope behind, so stage in-line
            # (recording an intent is cheap — round trips happen at
            # flush).
            for n in nodes:
                self.change_node_upgrade_state(n, new_state)
            return
        run_batch(
            [
                (lambda n=n: self.change_node_upgrade_state(n, new_state))
                for n in nodes
            ],
            self.max_concurrency,
        )

    def change_nodes_upgrade_annotation(
        self, nodes: Sequence[Node], key: str, value: str
    ) -> None:
        if self.plan.in_scope():
            for n in nodes:
                self.change_node_upgrade_annotation(n, key, value)
            return
        run_batch(
            [
                (lambda n=n: self.change_node_upgrade_annotation(n, key, value))
                for n in nodes
            ],
            self.max_concurrency,
        )

    # -- internals ----------------------------------------------------------

    def _wait_label_visible(
        self, node: Node, label_key: str, expected: str
    ) -> None:
        deadline = time.monotonic() + self.poll_timeout_s
        while True:
            try:
                fresh = self.client.get_node(node.name, cached=True)
            except NotFoundError:
                # Object not yet visible in the read cache — keep polling,
                # that is exactly the situation this loop exists for.
                fresh = None
            actual = fresh.labels.get(label_key, "") if fresh else None
            if fresh is not None and actual == expected:
                # Refresh caller's node object (the reference mutates the
                # passed *corev1.Node via Get into it).
                node.metadata = fresh.metadata
                node.spec = fresh.spec
                node.status = fresh.status
                log_event(
                    self.event_recorder,
                    node.name,
                    EVENT_TYPE_NORMAL,
                    self.keys.event_reason,
                    f"Successfully updated node state label to {expected}",
                )
                return
            if time.monotonic() >= deadline:
                log_event(
                    self.event_recorder,
                    node.name,
                    EVENT_TYPE_WARNING,
                    self.keys.event_reason,
                    f"Failed to update node state label to {expected}: "
                    "cache sync timeout",
                )
                raise CacheSyncTimeout(
                    f"node {node.name}: label {label_key}={expected!r} not "
                    f"visible within {self.poll_timeout_s}s (saw {actual!r})"
                )
            time.sleep(min(self.poll_interval_s, max(0.0, deadline - time.monotonic())))

    def _wait_annotation_visible(self, node: Node, key: str, value: str) -> None:
        deadline = time.monotonic() + self.poll_timeout_s
        while True:
            try:
                fresh = self.client.get_node(node.name, cached=True)
            except NotFoundError:
                fresh = None
            if fresh is None:
                ok = False
                actual = None
            else:
                actual = fresh.annotations.get(key)
                ok = (
                    (actual is None)
                    if value == NULL_STRING
                    else (actual == value)
                )
            if ok:
                node.metadata = fresh.metadata
                node.spec = fresh.spec
                node.status = fresh.status
                log_event(
                    self.event_recorder,
                    node.name,
                    EVENT_TYPE_NORMAL,
                    self.keys.event_reason,
                    f"Successfully updated node annotation {key}={value}",
                )
                return
            if time.monotonic() >= deadline:
                log_event(
                    self.event_recorder,
                    node.name,
                    EVENT_TYPE_WARNING,
                    self.keys.event_reason,
                    f"Failed to update node annotation {key}={value}: "
                    "cache sync timeout",
                )
                raise CacheSyncTimeout(
                    f"node {node.name}: annotation {key}={value!r} not visible "
                    f"within {self.poll_timeout_s}s"
                )
            time.sleep(min(self.poll_interval_s, max(0.0, deadline - time.monotonic())))
